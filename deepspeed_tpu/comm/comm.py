"""Distributed communication facade.

Capability parity with the reference's ``deepspeed/comm/comm.py`` (module-level
``init_distributed`` / ``all_reduce`` / ``all_gather`` / ``reduce_scatter`` /
``all_to_all_single`` / ``barrier`` plus the ``timed_op`` profiling decorator
and CommsLogger), rebuilt for XLA: collectives are ``jax.lax`` primitives that
only exist *inside* a compiled, mesh-mapped program, so this facade has two
faces:

1. **In-program collectives** — thin wrappers over ``jax.lax.psum`` /
   ``all_gather`` / ``psum_scatter`` / ``all_to_all`` / ``ppermute`` taking a
   mesh-axis name where the reference takes a process group. These are what
   engine/MoE/Ulysses code calls inside ``shard_map``. Each call records an
   event with the CommsLogger at trace time (XLA schedules the actual
   transfer; per-op wall times come from the profiler, matching how the
   reference's ``timed_op`` numbers are produced by CUDA events).

2. **Host-level process management** — ``init_distributed`` maps to
   ``jax.distributed.initialize`` (rendezvous via coordinator address instead
   of MASTER_ADDR/NCCL), ``get_rank``/``get_world_size`` map to
   ``jax.process_index``/``process_count``, and ``barrier`` outside jit is a
   tiny psum across all devices.

Reference: deepspeed/comm/comm.py:604 (init_distributed), :483 (all_reduce),
:228 (all_gather), :446 (reduce_scatter), :331 (all_to_all_single),
:406 (barrier), :101 (timed_op), utils/comms_logging.py:67 (CommsLogger).
"""

from __future__ import annotations

import os
import time
from dataclasses import dataclass, field
from enum import Enum
from typing import Any, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from ..utils.logging import log_dist, logger


class ReduceOp(Enum):
    SUM = "sum"
    AVG = "avg"
    MAX = "max"
    MIN = "min"
    PROD = "prod"


# ----------------------------------------------------------------------
# Comms logging (reference utils/comms_logging.py)

def _get_bw(comm_op: str, size_bytes: int, duration_s: float, n: int) -> tuple:
    """Algorithmic and bus bandwidth in GB/s. Mirrors reference
    ``calc_bw_log`` (utils/comms_logging.py:34)."""
    if duration_s <= 0:
        return 0.0, 0.0
    size_gb = size_bytes / 1e9
    algbw = size_gb / duration_s
    if comm_op in ("all_reduce", "reduce"):
        busbw = algbw * (2 * (n - 1) / n) if n > 0 else algbw
    elif comm_op in ("all_gather", "reduce_scatter", "all_to_all", "gather",
                     "sparse_allreduce"):
        busbw = algbw * ((n - 1) / n) if n > 0 else algbw
    else:
        busbw = algbw
    return algbw, busbw


@dataclass
class CommsLogger:
    """Records per-op counts/sizes at trace time; real latencies come from
    :func:`measure_comm_latencies`, which replays every recorded
    (op, size, axis) as a standalone timed program on the live mesh — the
    TPU analog of the reference's CUDA-event ``timed_op`` (comm.py:101),
    since XLA collectives only execute inside compiled programs.

    ``log_summary()`` prints the table like ``dist.log_summary`` in the
    reference (comm/comm.py:422), with algbw/busbw once measured.
    """

    enabled: bool = False
    verbose: bool = False
    records: Dict[str, Dict[int, List[float]]] = field(default_factory=dict)
    axes: Dict[tuple, str] = field(default_factory=dict)
    worlds: Dict[tuple, int] = field(default_factory=dict)
    # bytes-on-wire ledger (docs/communication.md): cumulative PHYSICAL
    # bytes per (op, logical_size) — differs from the logical payload only
    # for compressed collectives (comm/compressed.py), where the wire
    # carries int8/int4 + scales instead of the fp tensor
    wire: Dict[tuple, float] = field(default_factory=dict)

    def append(self, op_name: str, size_bytes: int, duration_s: float,
               world: int, axis_name: Optional[str] = None,
               wire_bytes: Optional[int] = None) -> None:
        if not self.enabled:
            return
        per_op = self.records.setdefault(op_name, {})
        per_op.setdefault(size_bytes, []).append(duration_s)
        if axis_name is not None:
            self.axes[(op_name, size_bytes)] = axis_name
        if world:
            self.worlds[(op_name, size_bytes)] = world
        wire = size_bytes if wire_bytes is None else int(wire_bytes)
        key = (op_name, size_bytes)
        self.wire[key] = self.wire.get(key, 0.0) + wire
        # unified telemetry: every recorded collective also lands in the
        # shared metrics registry, so comm volume shows up next to step
        # time in the exporters without a separate pipeline
        from ..telemetry.registry import get_registry

        reg = get_registry()
        reg.counter(f"comm/{op_name}/calls").inc()
        reg.counter(f"comm/{op_name}/bytes").inc(size_bytes)
        reg.counter(f"comm/{op_name}/wire_bytes").inc(wire)
        if wire < size_bytes:
            # compression ratio is a trace-time static (shapes + dtypes),
            # safe to observe here; per-op history for the exporters
            reg.histogram(f"comm/{op_name}/compression_ratio").observe(
                size_bytes / max(wire, 1))
        if self.verbose:
            algbw, busbw = _get_bw(op_name, size_bytes, duration_s, world)
            log_dist(
                f"comm op: {op_name} | msg size: {size_bytes} B | time: {duration_s * 1e3:.3f} ms"
                f" | algbw: {algbw:.2f} GB/s | busbw: {busbw:.2f} GB/s"
            )

    def backfill(self, op_name: str, size_bytes: int, duration_s: float) -> None:
        """Replace trace-time placeholder durations with a measured one."""
        durs = self.records.get(op_name, {}).get(size_bytes)
        if durs:
            self.records[op_name][size_bytes] = [duration_s] * len(durs)

    def log_summary(self) -> str:
        lines = [f"{'Comm. Op':<20}{'Message Size':>16}{'Count':>8}"
                 f"{'Total Lat(ms)':>16}{'Avg Lat(ms)':>14}"
                 f"{'algbw(GB/s)':>14}{'busbw(GB/s)':>14}"]
        for op, sizes in self.records.items():
            lines.append(op)
            for size, durs in sorted(sizes.items()):
                total = sum(durs) * 1e3
                avg = total / len(durs)
                world = self.worlds.get((op, size), 0)
                algbw, busbw = _get_bw(op, size, avg / 1e3, world)
                lines.append(f"{'':<20}{size:>16}{len(durs):>8}{total:>16.2f}"
                             f"{avg:>14.3f}{algbw:>14.2f}{busbw:>14.2f}")
        table = "\n".join(lines)
        logger.info(table)
        return table

    def snapshot_totals(self) -> Dict[str, Dict[str, float]]:
        """Aggregate per-op totals for StepStats: {op: {count, bytes,
        wire_bytes, time_s}}. Counts/bytes are trace-time facts (the
        collectives the compiled program contains); ``wire_bytes`` is the
        physical volume after compression (== ``bytes`` for uncompressed
        ops — the v2 schema field; archived v1 snapshots without it keep
        validating, see telemetry.spans.validate_step_record); time_s sums
        the recorded durations, which are real only after
        :func:`measure_comm_latencies` backfills them."""
        out: Dict[str, Dict[str, float]] = {}
        for op, sizes in self.records.items():
            count = bytes_total = wire_total = time_total = 0.0
            for size, durs in sizes.items():
                count += len(durs)
                bytes_total += size * len(durs)
                wire_total += self.wire.get((op, size), size * len(durs))
                time_total += sum(durs)
            out[op] = {"count": count, "bytes": bytes_total,
                       "wire_bytes": wire_total, "time_s": time_total}
        return out

    def reset(self) -> None:
        self.records.clear()
        self.axes.clear()
        self.worlds.clear()
        self.wire.clear()


_COMMS_LOGGER = CommsLogger()


def get_comms_logger() -> CommsLogger:
    return _COMMS_LOGGER


def configure_comms_logger(enabled: bool, verbose: bool = False) -> None:
    _COMMS_LOGGER.enabled = enabled
    _COMMS_LOGGER.verbose = verbose


def log_summary() -> str:
    return _COMMS_LOGGER.log_summary()


def _nbytes(x: Any) -> int:
    try:
        return int(np.prod(x.shape)) * jnp.dtype(x.dtype).itemsize
    except Exception:
        return 0


# chaos hook: resilience.chaos.install_fault_injector points this at the
# installed FaultInjector's on_collective (delay/fail injection for the
# fault-tolerance tests). None = zero overhead on every facade call.
_CHAOS_HOOK = None


def _record(op: str, x: Any, axis_name: Optional[str]) -> None:
    # Inside jit the transfer can't be timed at the call site (XLA schedules
    # it); record op/size/axis now, measure_comm_latencies() backfills real
    # durations via timed standalone replays.
    if _CHAOS_HOOK is not None:
        _CHAOS_HOOK(op)
    _COMMS_LOGGER.append(op, _nbytes(x), 0.0, 0, axis_name)


def record_collective(op: str, logical_bytes: int, wire_bytes: int,
                      axis_name: Optional[str] = None, world: int = 0) -> None:
    """Bytes-on-wire ledger entry for a facade-issued collective
    (comm/compressed.py): ``logical_bytes`` is what the uncompressed path
    would move per rank, ``wire_bytes`` the physical payload actually on
    the wire (quantized + scales). Routes through the same chaos hook and
    CommsLogger as the thin lax wrappers above."""
    if _CHAOS_HOOK is not None:
        _CHAOS_HOOK(op)
    _COMMS_LOGGER.append(op, int(logical_bytes), 0.0, world, axis_name,
                         wire_bytes=int(wire_bytes))


def measure_comm_latencies(mesh=None, iters: int = 10) -> str:
    """Replay every recorded collective on the live mesh and backfill real
    per-op latencies (reference timed_op comm.py:101 / comms benchmark
    suite). Each replay chains ``iters`` data-dependent repetitions inside
    ONE jitted shard_map and fences with a host fetch — dispatch overhead
    and async-dispatch illusions (block_until_ready is not a fence through
    the axon relay) are amortized away. Returns the updated summary table.
    """
    from ..parallel.mesh import get_topology

    mesh = mesh if mesh is not None else get_topology().mesh
    log = _COMMS_LOGGER

    def collective(op, axis):
        if op in ("all_reduce", "reduce",
                  # facade dense reduce hops (comm/compressed.py): the
                  # wire is a psum/pmean over the axis
                  "qgz_intra_reduce", "qgz_inter_reduce_dense"):
            return lambda x: jax.lax.psum(x, axis)
        if op in ("all_gather", "gather", "sparse_allreduce",
                  # facade gather hops: the wire is an all_gather of the
                  # (quantized) payload — the replay buffer is sized by
                  # the recorded WIRE bytes below, so latency reflects
                  # what the compressed program actually moves
                  "qwz_all_gather", "hpz_all_gather",
                  "qgz_inter_all_gather", "qgz_intra_all_gather"):
            # sparse_allreduce's wire cost IS its all_gathers (rows+indices,
            # recorded as one combined payload); the scatter-add is local
            return lambda x: jax.lax.all_gather(x, axis, axis=0, tiled=True)
        if op in ("reduce_scatter", "qgz_intra_reduce_scatter"):
            return lambda x: jax.lax.psum_scatter(x, axis, tiled=True)
        if op in ("all_to_all",
                  # facade quantized reduce-scatter hop: the wire is a
                  # chunk exchange (all_to_all) of the quantized payload
                  "qgz_inter_reduce_scatter"):
            return lambda x: jax.lax.all_to_all(x, axis, 0, 0, tiled=True)
        if op in ("broadcast", "scatter"):
            # scatter's wire IS a broadcast (see scatter()); replay as one
            return lambda x: jax.lax.psum(
                jnp.where(jax.lax.axis_index(axis) == 0, x, jnp.zeros_like(x)),
                axis)
        if op == "ppermute":
            return None  # perm is call-specific; skip replay
        return None

    from jax.sharding import PartitionSpec as P

    from ..parallel.mesh import shard_map_compat

    for op, sizes in list(log.records.items()):
        for size in list(sizes):
            axis = log.axes.get((op, size))
            if axis is None or axis not in mesh.axis_names:
                continue
            world = mesh.shape[axis]
            log.worlds[(op, size)] = world
            fn = collective(op, axis)
            # replay the PHYSICAL payload: for compressed facade ops the
            # wire ledger's per-call bytes, for dense ops wire == logical
            durs = log.records[op][size]
            wire_pc = log.wire.get((op, size), size * len(durs))
            wire_pc = wire_pc / max(len(durs), 1)
            n = max(int(wire_pc) // 4, world)
            n -= n % world or 0
            if fn is None or n < world:
                continue

            def replay(x, fn=fn):
                def body(_, x):
                    y = fn(x)
                    return x + 1e-30 * jnp.sum(y)  # data dep: no DCE/overlap
                return jax.lax.fori_loop(0, iters, body, x)

            spmd = shard_map_compat(replay, mesh=mesh, axis_names={axis},
                                 in_specs=P(axis), out_specs=P(axis),
                                 check_vma=False)
            run = jax.jit(lambda x: jnp.sum(spmd(x)))
            x = jnp.zeros((world * n,), jnp.float32)
            float(run(x))  # compile + warm
            best = float("inf")
            for _ in range(2):
                t0 = time.perf_counter()
                float(run(x))
                best = min(best, time.perf_counter() - t0)
            log.backfill(op, size, best / iters)
    return log.log_summary()


# ----------------------------------------------------------------------
# Host-level process management

_INITIALIZED = False


def init_distributed(dist_backend: str = "xla",
                     coordinator_address: Optional[str] = None,
                     num_processes: Optional[int] = None,
                     process_id: Optional[int] = None,
                     timeout: Optional[float] = None,
                     **_: Any) -> None:
    """Initialize multi-process JAX. Parity with reference
    ``init_distributed`` (comm/comm.py:604): idempotent, env-var driven.

    Single-process (one host owning its devices, incl. a full TPU slice via
    one controller) needs no rendezvous at all — matching how a TPU pod slice
    under a single JAX controller has no NCCL-style bootstrap.
    """
    global _INITIALIZED
    if _INITIALIZED:
        return
    coordinator_address = coordinator_address or os.environ.get("COORDINATOR_ADDRESS")
    num_processes = num_processes if num_processes is not None else int(os.environ.get("NUM_PROCESSES", "0") or 0)
    if coordinator_address and num_processes > 1:
        pid = process_id if process_id is not None else int(os.environ.get("PROCESS_ID", "0"))
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=pid,
        )
        log_dist(f"jax.distributed initialized: process {pid}/{num_processes} @ {coordinator_address}")
    _INITIALIZED = True


def is_initialized() -> bool:
    return _INITIALIZED


def get_rank() -> int:
    return jax.process_index()


def get_world_size() -> int:
    return jax.process_count()


def get_local_rank() -> int:
    return int(os.environ.get("LOCAL_RANK", "0"))


def barrier() -> None:
    """Cross-process barrier (reference comm/comm.py:406). A tiny all-reduce
    over every addressable device forces synchronization."""
    if _CHAOS_HOOK is not None:
        _CHAOS_HOOK("barrier")
    x = jnp.ones((jax.device_count(),))
    jax.block_until_ready(
        jax.pmap(lambda v: jax.lax.psum(v, "i"), axis_name="i")(x.reshape(jax.local_device_count(), -1)[:, 0])
        if jax.process_count() > 1
        else x.sum()
    )


# ----------------------------------------------------------------------
# In-program collectives (call inside shard_map/jit over a Mesh)

def all_reduce(x, axis_name: str, op: ReduceOp = ReduceOp.SUM):
    """lax.psum/pmax/... over a named mesh axis. Reference: comm.py:483."""
    _record("all_reduce", x, axis_name)
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        y = jax.lax.psum(x, axis_name)
        if op == ReduceOp.AVG:
            y = y / jax.lax.psum(1, axis_name)
        return y
    if op == ReduceOp.MAX:
        return jax.lax.pmax(x, axis_name)
    if op == ReduceOp.MIN:
        return jax.lax.pmin(x, axis_name)
    raise NotImplementedError(f"reduce op {op}")


def all_gather(x, axis_name: str, axis: int = 0, tiled: bool = True):
    """lax.all_gather over a named axis. Reference: comm.py:228."""
    _record("all_gather", x, axis_name)
    return jax.lax.all_gather(x, axis_name, axis=axis, tiled=tiled)


def reduce_scatter(x, axis_name: str, scatter_dimension: int = 0):
    """lax.psum_scatter. Reference: comm.py:446 (reduce_scatter_tensor)."""
    _record("reduce_scatter", x, axis_name)
    return jax.lax.psum_scatter(x, axis_name, scatter_dimension=scatter_dimension, tiled=True)


def all_to_all(x, axis_name: str, split_axis: int, concat_axis: int, tiled: bool = True):
    """lax.all_to_all. Reference: comm.py:331 (all_to_all_single)."""
    _record("all_to_all", x, axis_name)
    return jax.lax.all_to_all(x, axis_name, split_axis=split_axis, concat_axis=concat_axis, tiled=tiled)


def broadcast(x, axis_name: str, src_index: int = 0):
    """Broadcast the src shard's value to every member of the axis.

    Reference: comm.py:217 (broadcast). Implemented as select+psum so it
    lowers to one collective.
    """
    _record("broadcast", x, axis_name)
    idx = jax.lax.axis_index(axis_name)
    masked = jnp.where(idx == src_index, x, jnp.zeros_like(x))
    return jax.lax.psum(masked, axis_name)


def reduce(x, axis_name: str, dst_index: int = 0,
           op: ReduceOp = ReduceOp.SUM):
    """Reduce-to-one (reference comm.py reduce): every member computes the
    reduction, non-dst members get zeros — under SPMD a true single-owner
    reduce is a psum plus a mask, same wire cost."""
    _record("reduce", x, axis_name)
    if op in (ReduceOp.SUM, ReduceOp.AVG):
        y = jax.lax.psum(x, axis_name)
        if op == ReduceOp.AVG:
            y = y / jax.lax.psum(1, axis_name)
    elif op == ReduceOp.MAX:
        y = jax.lax.pmax(x, axis_name)
    elif op == ReduceOp.MIN:
        y = jax.lax.pmin(x, axis_name)
    else:
        raise NotImplementedError(f"reduce op {op}")
    idx = jax.lax.axis_index(axis_name)
    return jnp.where(idx == dst_index, y, jnp.zeros_like(y))


def gather(x, axis_name: str, dst_index: int = 0, axis: int = 0):
    """Gather-to-one (reference comm.py gather): all_gather, masked off on
    non-dst members."""
    _record("gather", x, axis_name)
    y = jax.lax.all_gather(x, axis_name, axis=axis, tiled=True)
    idx = jax.lax.axis_index(axis_name)
    return jnp.where(idx == dst_index, y, jnp.zeros_like(y))


def scatter(x, axis_name: str, src_index: int = 0, axis: int = 0):
    """Scatter-from-one (reference comm.py scatter): each member ends up
    with its chunk of the src member's tensor along ``axis``.

    NB: pure-SPMD collectives cannot express an asymmetric one-to-many
    send, so the wire carries a broadcast; the recorded payload is the
    algorithmic per-member chunk (what a point-to-point scatter would
    move)."""
    from ..parallel.mesh import collective_axis_size

    world = collective_axis_size(axis_name)  # static inside shard_map
    if x.shape[axis] % world:
        raise ValueError(
            f"scatter: dim {axis} size {x.shape[axis]} not divisible by "
            f"axis size {world} (torch scatter errors on unequal chunks too)")
    if _CHAOS_HOOK is not None:
        _CHAOS_HOOK("scatter")
    _COMMS_LOGGER.append("scatter", max(_nbytes(x) // world, 1), 0.0, 0,
                         axis_name)
    idx = jax.lax.axis_index(axis_name)
    masked = jnp.where(idx == src_index, x, jnp.zeros_like(x))
    full = jax.lax.psum(masked, axis_name)
    chunk = x.shape[axis] // world
    return jax.lax.dynamic_slice_in_dim(full, idx * chunk, chunk, axis=axis)


def sparse_allreduce(rows, indices, axis_name: str, dense_dim: int):
    """Sparse (embedding-)gradient allreduce: each rank contributes only the
    rows its batch touched — ``rows [k, d]`` at ``indices [k]`` — and the
    wire moves ``world*k*d`` elements instead of the dense ``vocab*d``.

    Reference: ``runtime/engine.py`` ``sparse_allreduce_bucket`` /
    ``sparse_gradients_enabled`` (torch SparseTensor allreduce for
    ``nn.Embedding``). Returns the dense [dense_dim, d] reduced gradient.
    Must run inside shard_map with ``axis_name`` manual; ``k`` must be
    equal across ranks (pad with a repeated index — scatter-add makes
    duplicate indices safe)."""
    # wire payload = rows AND indices (both all_gathered below)
    if _CHAOS_HOOK is not None:
        _CHAOS_HOOK("sparse_allreduce")
    _COMMS_LOGGER.append("sparse_allreduce",
                         _nbytes(rows) + _nbytes(indices), 0.0, 0, axis_name)
    rows_all = jax.lax.all_gather(rows, axis_name, axis=0, tiled=True)
    idx_all = jax.lax.all_gather(indices, axis_name, axis=0, tiled=True)
    dense = jnp.zeros((dense_dim,) + rows.shape[1:],
                      jnp.promote_types(rows.dtype, jnp.float32))
    return dense.at[idx_all].add(rows_all.astype(dense.dtype))


def ppermute(x, axis_name: str, perm):
    """Point-to-point shifts (send/recv parity for pipeline stages).

    Reference: send/recv in comm.py:356-:374 and runtime/pipe/p2p.py — on TPU
    neighbor exchange is a collective-permute riding ICI.
    """
    _record("ppermute", x, axis_name)
    return jax.lax.ppermute(x, axis_name, perm)
