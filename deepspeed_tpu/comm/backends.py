"""Pluggable kernel backends for the compressed-collectives facade
(docs/communication.md, "Kernel backends").

The facade (``comm/compressed.py``) made ZeRO-3 collectives cheap on the
wire; this seam makes them cheap in TIME by fusing the compression
bracket into the adjacent matmul and moving overlap from per-layer
fill/drain windows to per-tile pipelining. A backend implements three
fused compute–collective entry points whose semantics are *defined* by
the :class:`XlaCollectiveBackend`'s unfused composition of facade ops —
the fused :class:`PallasFusedBackend` must be bit-exact to it at the
same ``QuantSpec`` (and to dense with compression off), which the
interpret-mode parity suite (tests/test_fused_collectives.py) and the
``run_tests.sh`` fused gate enforce:

* ``all_gather_matmul`` — ``h @ all_gather(w_shard, dim)``: the Pallas
  backend runs a ring, dequantize+multiplying tile *i*
  (:func:`~deepspeed_tpu.ops.pallas.fused_collectives.dequant_matmul`)
  while tile *i+1*'s shard is in flight (``ring_permute`` issued before
  the kernel consumes). Bit-exactness holds because the gather dim is a
  NON-contraction dim of the matmul — each tile is an independent
  column slice of the product, so no fp32 accumulation is reordered.
  Contraction-dim shards take the fallback.
* ``matmul_reduce_scatter`` — the grad-producing matmul whose epilogue
  blockwise-quantizes the wire payload in-kernel
  (:func:`~...fused_collectives.matmul_quantize`), feeding the same
  ``quantized_chunk_exchange`` the facade reduction uses.
* ``matmul_all_reduce`` — the serving-decode MLP down-projection: the
  partial matmul's epilogue produces the (optionally quantized) chunks
  of a deterministic rank-ordered chunked all-reduce
  (``chunked_all_reduce``), so the decode all-reduce stops being pure
  exposed latency after the matmul.

Everything that cannot fuse (contraction-dim gathers, non-2D operands,
indivisible blocks, hierarchical inner hops) delegates to the fallback
backend and is metered through the existing ``comm/facade/fallbacks``
counter; engaged fusions count under ``comm/facade/fused``. Ledger
note: the fused all-gather books the same per-collective summary row as
the facade (so wire-ratio joins work across backends) plus per-hop
``<op>_ring`` rows for the physical ring traffic — per-op totals remain
comparable, and nothing sums across the two op names.

Backends contain no raw ``jax.lax`` collectives — every wire-moving
step routes through ``comm.compressed`` (the dslint ``comm-facade``
rule covers these modules too).
"""

from __future__ import annotations

from typing import Any, List, Optional

import jax
import jax.numpy as jnp

from ..ops.quantizer import pack_int4, quantize_blockwise
from . import compressed as cc
from .comm import record_collective


def _note_fused(op: str) -> None:
    from ..telemetry.registry import get_registry

    # trace-time static, like the facade's fallback counter: whether a
    # call fuses is a shape/config property of the traced program
    get_registry().counter("comm/facade/fused").inc()
    get_registry().counter(f"comm/facade/fused/{op}").inc()


class CollectiveBackend:
    """Protocol for the facade's kernel-backend seam. Subclasses must be
    usable inside a shard_map manual region (same contract as the facade
    functions they compose)."""

    name = "base"

    def all_gather_matmul(self, h: jnp.ndarray, w_shard: jnp.ndarray,
                          axis_name: str, *, dim: int = 1,
                          qspec: Optional[cc.QuantSpec] = None,
                          out_dtype=None, op: str = "qwz_all_gather",
                          stats: Optional[List[jnp.ndarray]] = None
                          ) -> jnp.ndarray:
        """``h [m, k] @ merge(all_gather(w_shard, dim))`` in fp32
        accumulation; ``dim`` is w's gathered dimension."""
        raise NotImplementedError

    def matmul_reduce_scatter(self, h: jnp.ndarray, g: jnp.ndarray, *,
                              outer_axis: str, outer_world: int,
                              inner_axis: Optional[str] = None,
                              inner_world: int = 1,
                              qspec: Optional[cc.QuantSpec] = None,
                              min_quant_size: int = 0,
                              stats: Optional[List[jnp.ndarray]] = None
                              ) -> jnp.ndarray:
        """Mean over the ZeRO group of the local weight gradient
        ``h.T @ g`` (``h [m, k]``, ``g [m, n]`` -> ``[k, n]``), moved
        through the hierarchical quantized reduction."""
        raise NotImplementedError

    def matmul_all_reduce(self, x: jnp.ndarray, w_shard: jnp.ndarray,
                          axis_name: str, *,
                          qspec: Optional[cc.QuantSpec] = None,
                          out_dtype=None,
                          op: str = "decode_mlp_all_reduce",
                          stats: Optional[List[jnp.ndarray]] = None
                          ) -> jnp.ndarray:
        """Sum over ``axis_name`` of the partial products
        ``x [m, k_shard] @ w_shard [k_shard, n]`` — the TP decode MLP
        down-projection — via the deterministic rank-ordered chunked
        all-reduce."""
        raise NotImplementedError


class XlaCollectiveBackend(CollectiveBackend):
    """The default backend: the unfused composition of facade collectives
    and XLA matmuls. This is the semantic REFERENCE for the seam — the
    parity suite asserts the fused backend against it bit-for-bit."""

    name = "xla"

    def all_gather_matmul(self, h, w_shard, axis_name, *, dim=1, qspec=None,
                          out_dtype=None, op="qwz_all_gather", stats=None):
        w_full = cc.quantized_all_gather(w_shard, axis_name, dim=dim,
                                         qspec=qspec, op=op, stats=stats)
        y = jax.lax.dot_general(h, w_full, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        return y.astype(out_dtype or h.dtype)

    def matmul_reduce_scatter(self, h, g, *, outer_axis, outer_world,
                              inner_axis=None, inner_world=1, qspec=None,
                              min_quant_size=0, stats=None):
        dw = jax.lax.dot_general(h, g, (((0,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        out = cc.hierarchical_pmean(
            dw.reshape(-1), outer_axis=outer_axis, outer_world=outer_world,
            inner_axis=inner_axis, inner_world=inner_world, qspec=qspec,
            min_quant_size=min_quant_size, stats=stats)
        return out.reshape(dw.shape)

    def matmul_all_reduce(self, x, w_shard, axis_name, *, qspec=None,
                          out_dtype=None, op="decode_mlp_all_reduce",
                          stats=None):
        y = jax.lax.dot_general(x, w_shard, (((1,), (0,)), ((), ())),
                                preferred_element_type=jnp.float32)
        out = cc.chunked_all_reduce(y, axis_name, qspec=qspec, op=op,
                                    reduce="sum", stats=stats)
        return out.astype(out_dtype or x.dtype)


class PallasFusedBackend(CollectiveBackend):
    """Fused compute–collective kernels (ops/pallas/fused_collectives.py)
    where shapes allow, the unfused backend otherwise. ``interpret``
    runs the kernels in Pallas interpret mode (the CPU testing path,
    like ops/pallas/flash_attention.py)."""

    name = "pallas"

    def __init__(self, fallback: Optional[CollectiveBackend] = None,
                 interpret: bool = False):
        self.fallback = fallback or XlaCollectiveBackend()
        self.interpret = interpret

    # -- fusability predicates (shape/config properties, trace-static) --
    def _gather_fusable(self, h, w_shard, dim, world) -> bool:
        # dim == 1 keeps the gather on a NON-contraction dim of h @ w:
        # each arriving tile is an independent column slice of the
        # product, so the fp32 accumulation order matches the unfused
        # matmul bit-for-bit. A dim-0 (contraction) shard would split
        # the accumulation across tiles — not bit-exact — so it falls
        # back instead. Mixed-dtype operands fall back too: the XLA
        # reference feeds the weight at ITS dtype into the dot, and a
        # ring tile cast to h's dtype would silently diverge.
        return (world > 1 and h.ndim == 2 and w_shard.ndim == 2
                and dim == 1 and h.shape[1] == w_shard.shape[0]
                and h.dtype == w_shard.dtype)

    def all_gather_matmul(self, h, w_shard, axis_name, *, dim=1, qspec=None,
                          out_dtype=None, op="qwz_all_gather", stats=None):
        from ..ops.pallas.fused_collectives import (dequant_matmul,
                                                    matmul_pallas)
        from ..parallel.mesh import collective_axis_size

        world = collective_axis_size(axis_name)
        if world <= 1:
            return self.fallback.all_gather_matmul(
                h, w_shard, axis_name, dim=dim, qspec=qspec,
                out_dtype=out_dtype, op=op, stats=stats)
        if not self._gather_fusable(h, w_shard, dim, world):
            # structural fusion fallback the facade itself won't meter
            cc._note_fallback(op)
            return self.fallback.all_gather_matmul(
                h, w_shard, axis_name, dim=dim, qspec=qspec,
                out_dtype=out_dtype, op=op, stats=stats)
        quantized = qspec is not None and qspec.divides(w_shard.size)
        if qspec is not None and not quantized:
            # indivisible shard: the facade's dense fallback meters this
            return self.fallback.all_gather_matmul(
                h, w_shard, axis_name, dim=dim, qspec=qspec,
                out_dtype=out_dtype, op=op, stats=stats)
        _note_fused(op)
        out_dtype = out_dtype or h.dtype
        m = h.shape[0]
        k, b = w_shard.shape
        logical = cc._nbytes(w_shard)
        me = jax.lax.axis_index(axis_name)
        out = jnp.zeros((m, world * b), jnp.float32)
        if quantized:
            # same per-collective summary row as the unfused facade, so
            # per-op ledger totals stay comparable across backends
            record_collective(op, logical, qspec.wire_nbytes(w_shard.size),
                              axis_name, world)
            flat = w_shard.reshape(-1).astype(jnp.float32)
            q, s, _ = quantize_blockwise(flat, bits=qspec.bits,
                                         block=qspec.block,
                                         manual_sharding=True)
            if stats is not None:
                from ..ops.quantizer import dequantize_blockwise

                deq = dequantize_blockwise(q, s, block=qspec.block,
                                           manual_sharding=True)
                stats.append(cc._rel_err(flat, deq))
            cur = (pack_int4(q) if qspec.bits == 4 else q, s)
        else:
            record_collective(op, logical, logical, axis_name, world)
            cur = (w_shard,)
        for step in range(world):
            nxt = None
            if step + 1 < world:
                # tile i+1's shard goes on the wire BEFORE tile i's
                # dequant+matmul kernel consumes anything — the per-tile
                # overlap the coarse block schedule cannot express
                nxt = tuple(
                    cc.ring_permute(t, axis_name, world=world,
                                    op=f"{op}_ring") for t in cur)
            if quantized:
                # dequantize at the shard's dtype — exactly what the
                # facade's merged w_full would carry into the matmul
                y = dequant_matmul(h, cur[0], cur[1], bits=qspec.bits,
                                   block=qspec.block, b=b,
                                   w_dtype=w_shard.dtype,
                                   interpret=self.interpret)
            else:
                # same dtype as h (checked by _gather_fusable) — exactly
                # the operand the XLA reference's dot consumes
                y = matmul_pallas(h, cur[0], interpret=self.interpret)
            r = jax.lax.rem(me - step + world, world)
            out = jax.lax.dynamic_update_slice(out, y, (0, r * b))
            cur = nxt
        return out.astype(out_dtype)

    def matmul_reduce_scatter(self, h, g, *, outer_axis, outer_world,
                              inner_axis=None, inner_world=1, qspec=None,
                              min_quant_size=0, stats=None):
        from ..ops.pallas.fused_collectives import matmul_quantize

        numel = h.shape[-1] * g.shape[-1] if h.ndim == 2 and g.ndim == 2 \
            else 0
        fusable = (h.ndim == 2 and g.ndim == 2 and h.shape[0] == g.shape[0]
                   and outer_world > 1 and qspec is not None
                   and inner_world <= 1
                   and numel >= max(min_quant_size, 1)
                   and qspec.divides(numel, outer_world))
        if not fusable:
            if (qspec is not None and inner_world > 1 and outer_world > 1
                    and h.ndim == 2 and g.ndim == 2):
                # hierarchical meshes keep the dense inner hop, which
                # must run BEFORE quantization — nothing to fuse into
                # the epilogue; the facade won't meter this itself
                cc._note_fallback("qgz_inter_reduce_scatter")
            return self.fallback.matmul_reduce_scatter(
                h, g, outer_axis=outer_axis, outer_world=outer_world,
                inner_axis=inner_axis, inner_world=inner_world, qspec=qspec,
                min_quant_size=min_quant_size, stats=stats)
        _note_fused("qgz_inter_reduce_scatter")
        payload, s = matmul_quantize(h, g, bits=qspec.bits,
                                     block=qspec.block, trans_a=True,
                                     interpret=self.interpret)
        out = cc.quantized_chunk_exchange(
            payload, s, n=numel, axis_name=outer_axis, world=outer_world,
            qspec=qspec, op_prefix="qgz_inter", reduce="mean", stats=stats)
        return out.reshape(h.shape[1], g.shape[1])

    def matmul_all_reduce(self, x, w_shard, axis_name, *, qspec=None,
                          out_dtype=None, op="decode_mlp_all_reduce",
                          stats=None):
        from ..ops.pallas.fused_collectives import (matmul_pallas,
                                                    matmul_quantize)
        from ..parallel.mesh import collective_axis_size

        world = collective_axis_size(axis_name)
        if not (x.ndim == 2 and w_shard.ndim == 2
                and x.shape[1] == w_shard.shape[0]):
            cc._note_fallback(op)
            return self.fallback.matmul_all_reduce(
                x, w_shard, axis_name, qspec=qspec, out_dtype=out_dtype,
                op=op, stats=stats)
        out_dtype = out_dtype or x.dtype
        n = x.shape[0] * w_shard.shape[1]
        if (world > 1 and qspec is not None and qspec.divides(n, world)):
            _note_fused(op)
            payload, s = matmul_quantize(x, w_shard, bits=qspec.bits,
                                         block=qspec.block, trans_a=False,
                                         interpret=self.interpret)
            out = cc.quantized_chunk_exchange(
                payload, s, n=n, axis_name=axis_name, world=world,
                qspec=qspec, op_prefix=op, reduce="sum", stats=stats)
            return out.reshape(x.shape[0], w_shard.shape[1]).astype(out_dtype)
        # dense (or indivisible, which chunked_all_reduce meters): the
        # partial matmul still fuses; the exchange is the shared
        # deterministic facade path, so XLA/Pallas stay bit-identical
        if world > 1:
            _note_fused(op)
        y = matmul_pallas(x, w_shard, interpret=self.interpret)
        out = cc.chunked_all_reduce(y, axis_name, qspec=qspec, op=op,
                                    reduce="sum", stats=stats)
        return out.astype(out_dtype)


def resolve_backend(name: Optional[str] = "auto", *,
                    interpret: Optional[bool] = None) -> CollectiveBackend:
    """Resolve a ``kernel_backend`` config value. ``"auto"`` picks the
    fused Pallas backend on TPU and the XLA backend elsewhere;
    ``"pallas"`` off-TPU runs the kernels in interpret mode (the CPU
    evidence-lane / testing configuration)."""
    from ..ops.attention import _on_tpu

    if name in (None, "auto"):
        name = "pallas" if _on_tpu() else "xla"
    if name == "xla":
        return XlaCollectiveBackend()
    if name == "pallas":
        on_tpu = _on_tpu()
        return PallasFusedBackend(
            interpret=(not on_tpu) if interpret is None else interpret)
    raise ValueError(f"unknown kernel backend {name!r} "
                     f"(expected 'auto', 'xla' or 'pallas')")
