"""Compression-aware collective facade: the shipped large-mesh ZeRO-3
communication path (docs/communication.md).

ZeRO++ (arxiv 2306.10209) cuts ZeRO-3 wire volume ~4x with three legs —
qwZ (blockwise-int8 weight all-gather), hpZ (secondary weight shard kept
inside the fast-ICI slice so per-layer gathers never cross the slow
links), qgZ (hierarchical int4/int8 gradient reduce-scatter: dense fp
inside the node, quantized across) — and T3 (arxiv 2401.16677) hides
most of what remains by fusing the per-block collectives into the
adjacent blocks' compute. This module is where both live for this repo:

* every ZeRO-3 hot-path collective the engine issues goes through a
  facade function here (the dslint ``comm-facade`` rule keeps raw
  ``jax.lax`` collectives out of ``parallel/zero.py`` /
  ``runtime/engine.py``);
* each facade call records a **bytes-on-wire ledger** entry with the
  CommsLogger — logical payload (what the uncompressed path would move)
  vs wire payload (quantized ints + scales) — so the compression claims
  are evidence, not configuration;
* each quantized collective carries a deterministic **error bound**
  (symmetric blockwise quant: per-element error <= scale/2, i.e. rel
  error vs the block absmax <= ``QuantSpec.rel_error_bound``) and an
  optional traced error-stats channel the engine folds into StepStats;
* anything that cannot be compressed (indivisible block, tiny leaf,
  axis of size 1, compression disabled) takes a **clean fallback** to
  the uncompressed collective, recorded in the same ledger with
  wire == logical and counted in ``comm/facade/fallbacks``.

The int4 wire format really is 4-bit on the wire: payloads are
nibble-packed (:func:`~deepspeed_tpu.ops.quantizer.pack_int4`) before
the collective, so the program moves half the elements — the ledger
reports what the compiled HLO actually transfers.

Reference surface: runtime/zero/stage3.py quantized all-gather/
reduce-scatter paths, utils/groups.py:356 (secondary groups),
blogs/zeropp/README.md positioning (quantize across the slow hop, stay
dense inside the node).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from ..ops.quantizer import (dequantize_blockwise, pack_int4,
                             quantize_blockwise, quantized_nbytes,
                             unpack_int4)
from .comm import record_collective


@dataclass(frozen=True)
class QuantSpec:
    """One quantized hop: bit width + block size of the symmetric
    blockwise quantization bracketing the collective."""

    bits: int = 8
    block: int = 256

    def __post_init__(self):
        if self.bits not in (4, 8):
            raise ValueError(f"QuantSpec.bits must be 4 or 8, got {self.bits}")
        if self.block <= 0 or self.block % 2:
            raise ValueError(f"QuantSpec.block must be positive and even, "
                             f"got {self.block}")

    @property
    def qmax(self) -> float:
        return 2.0 ** (self.bits - 1) - 1

    @property
    def rel_error_bound(self) -> float:
        """Deterministic per-element error bound relative to the block
        absmax: |x - deq(q(x))| <= scale/2 = absmax / (2*qmax)."""
        return 0.5 / self.qmax

    def wire_nbytes(self, numel: int) -> int:
        return quantized_nbytes(numel, self.bits, self.block)

    def divides(self, numel: int, world: int = 1) -> bool:
        """Whether ``numel`` elements can take this quantized hop across
        ``world`` ranks: chunking + blocking must come out even. (int4's
        pair-packing needs an even per-rank count, which block % 2 == 0
        — enforced at construction — already guarantees.)"""
        return numel > 0 and numel % (self.block * max(world, 1)) == 0


def _nbytes(x: Any) -> int:
    return int(np.prod(x.shape or (1,))) * jnp.dtype(x.dtype).itemsize


def _note_fallback(op: str) -> None:
    from ..telemetry.registry import get_registry

    # trace-time static: whether a collective falls back is a shape/config
    # property, so this counts once per traced program — the same
    # deliberate trace-time-counter pattern as the engine's _trace_counts
    get_registry().counter("comm/facade/fallbacks").inc()
    get_registry().counter(f"comm/facade/fallbacks/{op}").inc()


def _rel_err(x: jnp.ndarray, deq: jnp.ndarray) -> jnp.ndarray:
    """Traced max relative quantization error of one round trip, scaled
    to the tensor absmax (the documented bound is per-block; per-tensor
    is strictly looser, so bound violations still trip)."""
    denom = jnp.maximum(jnp.max(jnp.abs(x)), 1e-12)
    return jnp.max(jnp.abs(deq - x.astype(deq.dtype))) / denom


def _quant_roundtrip(x: jnp.ndarray, spec: QuantSpec,
                     dtype=jnp.float32) -> Tuple[jnp.ndarray, jnp.ndarray,
                                                 jnp.ndarray]:
    """(q int8, scales, deq) of a flat tensor — the pack/unpack bracket
    every quantized hop pays (what tpu_quant_comm_bench times)."""
    q, s, _ = quantize_blockwise(x, bits=spec.bits, block=spec.block,
                                 manual_sharding=True)
    deq = dequantize_blockwise(q, s, block=spec.block, dtype=dtype,
                               manual_sharding=True)
    return q, s, deq


def _merge_gathered(full: jnp.ndarray, world: int, shape: Tuple[int, ...],
                    dim: int) -> jnp.ndarray:
    """[world, *shape] -> shape with dim scaled by world, rank-major along
    ``dim`` (the tiled all_gather layout)."""
    out = jnp.moveaxis(full, 0, dim)
    return out.reshape(shape[:dim] + (world * shape[dim],) + shape[dim + 1:])


# ----------------------------------------------------------------------
# weight all-gather (qwZ)

def quantized_all_gather(x: jnp.ndarray, axis_name: str, *, dim: int = 0,
                         qspec: Optional[QuantSpec] = None,
                         op: str = "qwz_all_gather",
                         out_dtype=None,
                         stats: Optional[List[jnp.ndarray]] = None
                         ) -> jnp.ndarray:
    """All-gather ``x`` along mesh axis ``axis_name`` concatenating on
    ``dim``. With a ``qspec``, the wire carries blockwise-quantized ints
    (+ fp32 scales) — the qwZ leg; without one (or when the shard can't
    block-divide) the dense gather runs and the ledger books wire ==
    logical. Must run inside a shard_map region where ``axis_name`` is
    manual. ``stats`` (optional list) receives the traced max relative
    quantization error of the local round trip."""
    from ..parallel.mesh import collective_axis_size

    world = collective_axis_size(axis_name)
    if world <= 1:
        return x if out_dtype is None else x.astype(out_dtype)
    out_dtype = out_dtype or x.dtype
    logical = _nbytes(x)
    if qspec is None or not qspec.divides(x.size):
        if qspec is not None:
            _note_fallback(op)
        record_collective(op, logical, logical, axis_name, world)
        y = jax.lax.all_gather(x, axis_name, axis=dim, tiled=True)
        return y.astype(out_dtype)
    record_collective(op, logical, qspec.wire_nbytes(x.size), axis_name,
                      world)
    flat = x.reshape(-1).astype(jnp.float32)
    q, s, _ = quantize_blockwise(flat, bits=qspec.bits, block=qspec.block,
                                 manual_sharding=True)
    if stats is not None:
        deq = dequantize_blockwise(q, s, block=qspec.block,
                                   manual_sharding=True)
        stats.append(_rel_err(flat, deq))
    payload = pack_int4(q) if qspec.bits == 4 else q
    p_all = jax.lax.all_gather(payload, axis_name)            # [world, ...]
    s_all = jax.lax.all_gather(s, axis_name)                  # [world, n/block]
    q_all = (unpack_int4(p_all) if qspec.bits == 4
             else p_all.reshape(-1))
    deq_all = dequantize_blockwise(q_all, s_all.reshape(-1),
                                   block=qspec.block, dtype=out_dtype,
                                   manual_sharding=True)
    full = deq_all.reshape((world,) + x.shape)
    return _merge_gathered(full, world, x.shape, dim)


def gather_param_leaf(x: jnp.ndarray, spec, *,
                      outer_axes: Sequence[str] = ("data",),
                      qspec: Optional[QuantSpec] = None,
                      out_dtype=None,
                      stats: Optional[List[jnp.ndarray]] = None
                      ) -> jnp.ndarray:
    """Reassemble a full parameter leaf from its ZeRO-3 shard inside a
    manual shard_map region: per sharded dim, the inner (fast-ICI, hpZ)
    hops gather dense while hops crossing ``outer_axes`` move quantized
    payloads (qwZ). Minor axes of a tuple entry gather first so rank
    order composes like the GSPMD layout."""
    for d, entry in enumerate(tuple(spec)):
        if entry is None:
            continue
        axes = entry if isinstance(entry, tuple) else (entry,)
        for ax in reversed(axes):
            if ax in outer_axes:
                x = quantized_all_gather(x, ax, dim=d, qspec=qspec,
                                         op="qwz_all_gather",
                                         out_dtype=out_dtype, stats=stats)
            else:
                x = quantized_all_gather(x, ax, dim=d, qspec=None,
                                         op="hpz_all_gather",
                                         out_dtype=out_dtype)
    return x if out_dtype is None else x.astype(out_dtype)


def ste_quant_gather(x: jnp.ndarray, sharding, qspec: QuantSpec, dtype):
    """qwZ on the GSPMD (auto-sharded) path: fake-quantize through int8
    with the int8 tensor carrying the gather placement, so the compiler-
    inserted all-gather moves 1 byte/element. Straight-through estimator:
    the forward gathers quantized values, the backward passes the
    cotangent through unchanged — differentiating through round() would
    zero the gradient for all but the per-block argmax elements,
    silently freezing every quantized weight. (Moved from the engine's
    inline ste_quant; the facade records the wire ledger.)

    NB wire accounting: on this GSPMD path the gathered tensor is the
    int8 STORAGE format whatever the nominal bit width — nibble-packing
    would break the sharding-constraint trick — so the ledger books
    1 byte/element (+ scales) even for bits=4. True 4-bit wire needs the
    shard_map facade path (quantized_all_gather), which really packs."""
    record_collective("qwz_all_gather", _nbytes(x),
                      quantized_nbytes(x.size, 8, qspec.block))

    def primal(v):
        q, s, _ = quantize_blockwise(v, bits=qspec.bits, block=qspec.block)
        q = jax.lax.with_sharding_constraint(q, sharding)
        return dequantize_blockwise(q, s, block=qspec.block,
                                    dtype=dtype).reshape(v.shape)

    fq = jax.custom_vjp(primal)
    fq.defvjp(lambda v: (primal(v), None), lambda _, g: (g,))
    return fq(x)


# ----------------------------------------------------------------------
# gradient reduction (qgZ): hierarchical two-hop mean

def pmean(x: jnp.ndarray, axes) -> jnp.ndarray:
    """Dense mean-reduce over one or more mesh axes (losses, tiny
    tensors). Ledger-recorded as a plain all_reduce."""
    axes = (axes,) if isinstance(axes, str) else tuple(axes)
    record_collective("all_reduce", _nbytes(x), _nbytes(x),
                      axes[0])
    return jax.lax.pmean(x, axes)


def pmax(x: jnp.ndarray, axes) -> jnp.ndarray:
    """Dense max-reduce over one or more mesh axes (error-stat scalars:
    a per-rank local max is NOT replicated until reduced — declaring it
    so would hand the host an arbitrary shard's value)."""
    axes = (axes,) if isinstance(axes, str) else tuple(axes)
    record_collective("all_reduce", _nbytes(x), _nbytes(x),
                      axes[0])
    return jax.lax.pmax(x, axes)


def quantized_chunk_exchange(payload: jnp.ndarray, s: jnp.ndarray, *,
                             n: int, axis_name: str, world: int,
                             qspec: QuantSpec, op_prefix: str,
                             reduce: str = "mean",
                             stats: Optional[List[jnp.ndarray]] = None
                             ) -> jnp.ndarray:
    """The two quantized wire hops of a chunked reduction, operating on
    an ALREADY-quantized flat payload (wire format: nibble-packed for
    int4) with its fp32 block scales: all_to_all chunk exchange (the
    reduce-scatter hop), dense reduce of the dequantized chunk in FIXED
    rank order (axis 0 of the all_to_all result is the source rank, so
    the accumulation order is deterministic and identical on every
    rank), re-quantize, all_gather (the broadcast hop). Shared by the
    facade reduction (:func:`hierarchical_pmean`) and the fused kernel
    backends (comm/backends.py) so both paths move bit-identical wire
    payloads. ``n`` is the logical element count (payload is packed for
    int4); ``reduce`` picks mean (gradients) or sum (the decode MLP
    all-reduce)."""
    record_collective(f"{op_prefix}_reduce_scatter", n * 4,
                      qspec.wire_nbytes(n), axis_name, world)
    p_recv = jax.lax.all_to_all(payload.reshape(world, -1), axis_name,
                                0, 0, tiled=False)
    s_recv = jax.lax.all_to_all(s.reshape(world, -1), axis_name,
                                0, 0, tiled=False)
    chunk_n = n // world
    q_recv = (unpack_int4(p_recv) if qspec.bits == 4
              else p_recv.reshape(-1))
    vals = dequantize_blockwise(q_recv, s_recv.reshape(-1),
                                block=qspec.block, manual_sharding=True)
    vals = vals.reshape(world, chunk_n)
    chunk = (jnp.mean(vals, axis=0) if reduce == "mean"
             else jnp.sum(vals, axis=0))
    # broadcast hop: re-quantized reduced chunk, gathered by everyone
    record_collective(f"{op_prefix}_all_gather", chunk_n * 4,
                      qspec.wire_nbytes(chunk_n), axis_name, world)
    q2, s2, _ = quantize_blockwise(chunk, bits=qspec.bits, block=qspec.block,
                                   manual_sharding=True)
    if stats is not None:
        deq2 = dequantize_blockwise(q2, s2, block=qspec.block,
                                    manual_sharding=True)
        stats.append(_rel_err(chunk, deq2))
    payload2 = pack_int4(q2) if qspec.bits == 4 else q2
    p_all = jax.lax.all_gather(payload2, axis_name)
    s_all = jax.lax.all_gather(s2, axis_name)
    q_all = (unpack_int4(p_all) if qspec.bits == 4
             else p_all.reshape(-1))
    return dequantize_blockwise(q_all, s_all.reshape(-1), block=qspec.block,
                                manual_sharding=True).reshape(n)


def _quantized_pmean_1hop(x: jnp.ndarray, axis_name: str, world: int,
                          qspec: QuantSpec, op_prefix: str,
                          stats: Optional[List[jnp.ndarray]]) -> jnp.ndarray:
    """Quantized mean over one (slow) axis: quantize the local
    contribution, then the shared chunk exchange
    (:func:`quantized_chunk_exchange`) — both hops move quantized
    payloads, the qgZ wire shape. x: flat [n], n divisible by
    world*block (caller-checked)."""
    q, s, _ = quantize_blockwise(x, bits=qspec.bits, block=qspec.block,
                                 manual_sharding=True)
    if stats is not None:
        deq = dequantize_blockwise(q, s, block=qspec.block,
                                   manual_sharding=True)
        stats.append(_rel_err(x, deq))
    payload = pack_int4(q) if qspec.bits == 4 else q
    return quantized_chunk_exchange(
        payload, s, n=x.size, axis_name=axis_name, world=world, qspec=qspec,
        op_prefix=op_prefix, reduce="mean", stats=stats).reshape(x.shape)


def hierarchical_pmean(x: jnp.ndarray, *, outer_axis: str,
                       outer_world: int,
                       inner_axis: Optional[str] = None,
                       inner_world: int = 1,
                       qspec: Optional[QuantSpec] = None,
                       min_quant_size: int = 0,
                       stats: Optional[List[jnp.ndarray]] = None
                       ) -> jnp.ndarray:
    """Hierarchical gradient mean (qgZ). The shape that actually saves
    slow-link wire is *chunked*: reduce-SCATTER across the inner
    (fast-ICI) slice first so each inner rank holds a 1/inner_world fp
    chunk, run the quantized int8/int4 exchange across the outer
    (inter-slice) axis on that chunk only, then all-gather the reduced
    chunks back across the inner slice — inter-slice traffic is
    1/inner_world of the tensor per rank, matching ZeRO++'s hierarchy
    (an inner pmean followed by a full-tensor outer exchange would move
    inner_world x more across exactly the links compression exists to
    relieve). Degenerates cleanly: size-1 hops vanish, and
    indivisible/tiny tensors take the dense mean (inner pmean + dense
    outer pmean; ledger wire == logical, fallback counted). Must run
    inside a shard_map region where the named axes are manual."""
    hier = inner_axis is not None and inner_world > 1
    chunkable = x.size % max(inner_world, 1) == 0
    quantizable = (outer_world > 1 and qspec is not None
                   and x.size >= max(min_quant_size, 1)
                   and (not hier or chunkable)
                   and qspec.divides(x.size // (inner_world if hier else 1),
                                     outer_world))
    if not quantizable:
        y = x
        if hier:
            record_collective("qgz_intra_reduce", _nbytes(y), _nbytes(y),
                              inner_axis, inner_world)
            y = jax.lax.pmean(y, inner_axis)
        if outer_world <= 1:
            return y
        if qspec is not None:
            # counter op matches the ledger row the fallback records, so
            # comm/facade/fallbacks/<op> joins against comm/<op>/* rows
            _note_fallback("qgz_inter_reduce_dense")
        record_collective("qgz_inter_reduce_dense", _nbytes(y), _nbytes(y),
                          outer_axis, outer_world)
        return jax.lax.pmean(y, outer_axis)
    y = x
    if hier:
        # fast-ICI hop 1: fp reduce-scatter — each inner rank owns the
        # mean of its 1/inner_world chunk
        record_collective("qgz_intra_reduce_scatter", _nbytes(y), _nbytes(y),
                          inner_axis, inner_world)
        y = jax.lax.psum_scatter(y.reshape(-1), inner_axis,
                                 tiled=True) / inner_world
    # slow hop: quantized chunk-exchange mean across the outer axis
    y = _quantized_pmean_1hop(y.reshape(-1), outer_axis, outer_world, qspec,
                              "qgz_inter", stats)
    if hier:
        # fast-ICI hop 2: rebuild the full reduced tensor from the chunks
        record_collective("qgz_intra_all_gather", _nbytes(y), _nbytes(y),
                          inner_axis, inner_world)
        y = jax.lax.all_gather(y, inner_axis, axis=0, tiled=True)
    return y.reshape(x.shape)


def tree_hierarchical_pmean(grads: Any, *, outer_axis: str,
                            outer_world: int,
                            inner_axis: Optional[str] = None,
                            inner_world: int = 1,
                            qspec: Optional[QuantSpec] = None,
                            stats: Optional[List[jnp.ndarray]] = None
                            ) -> Any:
    """Leaf-wise :func:`hierarchical_pmean` over a gradient pytree; each
    leaf is flattened to fp32 for the reduction (the engine's gradient
    dtype discipline) and restored to its shape."""
    min_size = 4 * outer_world * (qspec.block if qspec else 1)

    def leaf(g):
        flat = g.reshape(-1).astype(jnp.float32)
        return hierarchical_pmean(
            flat, outer_axis=outer_axis, outer_world=outer_world,
            inner_axis=inner_axis, inner_world=inner_world,
            qspec=qspec, min_quant_size=min_size, stats=stats,
        ).reshape(g.shape)

    return jax.tree_util.tree_map(leaf, grads)


# ----------------------------------------------------------------------
# kernel-backend building blocks (comm/backends.py): the wire-moving
# primitives the fused Pallas backend composes with its kernels. They
# live here so the backends themselves contain no raw jax.lax
# collectives (the dslint comm-facade rule covers backend modules too).

def ring_permute(x: jnp.ndarray, axis_name: str, *, world: int,
                 op: str = "ring_permute") -> jnp.ndarray:
    """One ring hop: every rank sends ``x`` to its successor on
    ``axis_name`` and receives its predecessor's. The fused all-gather
    backend issues one of these per tile step — tile i+1's shard is in
    flight while tile i's dequant+matmul kernel runs. Ledger-recorded
    per hop with logical == wire == the payload bytes (the payload IS
    the wire format here; the compression claim lives in the caller's
    per-collective summary row, which books logical-vs-quantized)."""
    nbytes = _nbytes(x)
    record_collective(op, nbytes, nbytes, axis_name, world)
    perm = [(i, (i + 1) % world) for i in range(world)]
    return jax.lax.ppermute(x, axis_name, perm)


def chunked_all_reduce(y: jnp.ndarray, axis_name: str, *,
                       qspec: Optional[QuantSpec] = None,
                       op: str = "decode_mlp_all_reduce",
                       reduce: str = "sum",
                       stats: Optional[List[jnp.ndarray]] = None
                       ) -> jnp.ndarray:
    """Deterministic chunked all-reduce over one mesh axis: all_to_all
    chunk exchange, dense reduce of the received chunks in FIXED rank
    order, all_gather of the reduced chunk — the qgZ wire shape applied
    to a sum. With a ``qspec`` the exchanged chunks are blockwise-
    quantized (the serving-decode compression lever); without one the
    chunks move dense (wire == logical) but the rank-ordered
    accumulation is still deterministic, so the XLA and Pallas kernel
    backends produce bit-identical results by construction (an ordinary
    ``psum``'s accumulation order is the compiler's choice). Tensors
    whose size does not chunk-divide fall back to the plain dense
    ``psum``/``pmean`` (metered)."""
    from ..parallel.mesh import collective_axis_size

    world = collective_axis_size(axis_name)
    if world <= 1:
        return y
    n = y.size
    flat = y.reshape(-1).astype(jnp.float32)
    if qspec is not None and qspec.divides(n, world):
        q, s, _ = quantize_blockwise(flat, bits=qspec.bits, block=qspec.block,
                                     manual_sharding=True)
        if stats is not None:
            deq = dequantize_blockwise(q, s, block=qspec.block,
                                       manual_sharding=True)
            stats.append(_rel_err(flat, deq))
        payload = pack_int4(q) if qspec.bits == 4 else q
        out = quantized_chunk_exchange(
            payload, s, n=n, axis_name=axis_name, world=world, qspec=qspec,
            op_prefix=op, reduce=reduce, stats=stats)
        return out.reshape(y.shape).astype(y.dtype)
    if qspec is not None:
        _note_fallback(op)
    if n % world == 0:
        record_collective(f"{op}_reduce_scatter", n * 4, n * 4,
                          axis_name, world)
        recv = jax.lax.all_to_all(flat.reshape(world, -1), axis_name,
                                  0, 0, tiled=False)
        chunk = (jnp.mean(recv, axis=0) if reduce == "mean"
                 else jnp.sum(recv, axis=0))
        record_collective(f"{op}_all_gather", chunk.size * 4, chunk.size * 4,
                          axis_name, world)
        out = jax.lax.all_gather(chunk, axis_name, axis=0, tiled=True)
        return out.reshape(y.shape).astype(y.dtype)
    # not even chunkable (tiny/ragged): the plain dense collective
    record_collective(f"{op}_dense", n * 4, n * 4, axis_name, world)
    red = jax.lax.pmean if reduce == "mean" else jax.lax.psum
    return red(y, axis_name)


# ----------------------------------------------------------------------
# T3-style exposure model (shared by the NORTHSTAR projection, the
# MULTICHIP comm lane and the quant-comm smoke gate)

def modeled_exposure(*, param_bytes: float, grad_bytes: float,
                     n_blocks: int, compute_s: float, link_bps: float,
                     world: int,
                     weight_qspec: Optional[QuantSpec] = None,
                     grad_qspec: Optional[QuantSpec] = None,
                     weight_itemsize: int = 2,
                     grad_itemsize: int = 4,
                     tiles_per_block: int = 1) -> Dict[str, float]:
    """Analytic exposed-comm model for the staged ZeRO-3 schedule.

    Per step, ZeRO-3 moves the parameter set through TWO all-gathers
    (forward + backward re-gather) and the gradient set through ONE
    reduce-scatter, each split into ``n_blocks`` per-block collectives.
    The staged schedule (parallel/zero.py Zero3BlockSchedule) issues
    block i+1's gather before block i's compute and defers block i+1's
    reduce behind block i's backward, so only the pipeline fill/drain
    collectives plus any per-block excess (comm outrunning the block's
    compute window) stay exposed:

        serial_s     = (2*W + G) * (world-1)/world / bw
        overlapped_s = fill + drain + sum_i max(0, c_block_i - t_block_i)

    with the forward window ``compute_s/3 / n_blocks`` per block and the
    backward window ``2*compute_s/3 / n_blocks`` (fwd:bwd FLOP ratio
    1:2). Compression scales the wire volume by the quantized ratio
    before the division. All quantities are per-chip step time.

    ``tiles_per_block`` models the fused kernel backend
    (comm/backends.py) and applies to the FORWARD gather window only:
    the fused forward splits each block's all-gather into that many
    per-tile ring stages interleaved with slices of the same block's
    compute (dequant+matmul tile i while tile i+1's shard is in
    flight), so the forward fill shrinks from one block's collective to
    one tile's. The backward is deliberately NOT tiled — the shipped
    fused backward re-gathers the block through the plain facade (the
    cotangent contracts over the gathered dim, which cannot
    column-tile) and its reduce is one post-epilogue chunk exchange
    (only the quantization is in-kernel) — so its fill/drain stays at
    per-block granularity. At ``tiles_per_block=1`` this is exactly the
    PR-10 per-layer block-schedule model."""
    frac = (world - 1) / world if world > 1 else 0.0
    numel_w = param_bytes / weight_itemsize
    numel_g = grad_bytes / grad_itemsize
    w_wire = (weight_qspec.wire_nbytes(int(numel_w))
              if weight_qspec else param_bytes)
    g_wire = (grad_qspec.wire_nbytes(int(numel_g))
              if grad_qspec else grad_bytes)
    serial_dense = (2 * param_bytes + grad_bytes) * frac / link_bps
    serial_comp = (2 * w_wire + g_wire) * frac / link_bps
    tiles = max(int(tiles_per_block), 1)
    # per-block comm vs the compute window it hides behind; the forward
    # gather additionally splits into `tiles` per-tile stages
    c_gather = w_wire * frac / link_bps / n_blocks
    c_reduce = g_wire * frac / link_bps / n_blocks
    n_fwd_stages = n_blocks * tiles
    c_gather_tile = c_gather / tiles
    t_fwd = compute_s / 3.0 / n_fwd_stages
    t_bwd = 2.0 * compute_s / 3.0 / n_blocks
    fwd_exposed = (c_gather_tile
                   + (n_fwd_stages - 1) * max(0.0, c_gather_tile - t_fwd))
    bwd_exposed = (c_gather + c_reduce                       # fill + drain
                   + (n_blocks - 1) * max(0.0, c_gather + c_reduce - t_bwd))
    overlapped = fwd_exposed + bwd_exposed
    return {
        "serial_dense_s": serial_dense,
        "serial_compressed_s": serial_comp,
        "overlapped_compressed_s": overlapped,
        "exposure_reduction_vs_serial": (1.0 - overlapped / serial_dense
                                         if serial_dense > 0 else 0.0),
        "weight_wire_ratio": param_bytes / w_wire if w_wire else 1.0,
        "grad_wire_ratio": grad_bytes / g_wire if g_wire else 1.0,
        "n_blocks": float(n_blocks),
        "tiles_per_block": float(tiles),
    }


def modeled_decode_ab(*, d_model: int, d_ff: int, tp: int,
                      link_bps: float, peak_flops: float,
                      batch: int = 1, itemsize: int = 2,
                      qspec: Optional[QuantSpec] = None) -> Dict[str, float]:
    """Analytic decode-latency A/B for the TP MLP down-projection: with
    one token in flight the all-reduce of the [b, d_model] partial sums
    is pure exposed latency after the matmul. The fused backend
    (comm/backends.py matmul_all_reduce) splits the exchange into
    ``tp`` per-tile chunk hops produced by the matmul kernel's epilogue,
    so all but the pipeline fill hides behind the matmul itself:

        unfused = t_matmul + t_allreduce
        fused   = max(t_matmul, t_comm) + min(t_matmul, t_comm) / tp

    (two-stage pipeline over ``tp`` tiles). A ``qspec`` additionally
    scales the exchanged bytes by the quantized wire ratio — the
    serving-side compression lever."""
    flops = 2.0 * batch * d_ff * d_model / tp          # per-chip partial
    t_matmul = flops / peak_flops
    n = batch * d_model
    wire = qspec.wire_nbytes(n) if qspec else n * itemsize
    frac = 2.0 * (tp - 1) / tp if tp > 1 else 0.0      # rs + ag hops
    t_comm = wire * frac / link_bps
    unfused = t_matmul + t_comm
    tiles = max(tp, 1)
    fused = (max(t_matmul, t_comm)
             + min(t_matmul, t_comm) / tiles) if tp > 1 else t_matmul
    return {
        "t_matmul_s": t_matmul,
        "t_allreduce_s": t_comm,
        "decode_mlp_unfused_s": unfused,
        "decode_mlp_fused_s": fused,
        "fused_speedup": unfused / fused if fused > 0 else 1.0,
        "exposed_comm_unfused_s": t_comm,
        "exposed_comm_fused_s": max(0.0, fused - t_matmul),
        "tp": float(tp),
    }
