"""Pretrained-checkpoint ingestion: HuggingFace -> native stacked layout.

Parity with the reference's checkpoint-loading surface:
``module_inject/load_checkpoint.py`` (v1 sharded HF loading into injected
containers), ``inference/v2/model_implementations/flat_model_helpers.py``
(FastGen parses HF checkpoints into per-layer containers) and
``inference/engine.py:324`` (``load_model_with_checkpoint``). TPU-first
design: instead of per-module tensor surgery on a live torch model, HF
tensors are mapped once into the native stacked-layer pytree
([n_layers, ...] leading dim, see models/transformer.py init) and placed
with ``jax.device_put`` under the model's PartitionSpecs — GSPMD handles
TP/ZeRO sharding from there; no injection machinery.

Supported families: Llama/Mistral (RMSNorm+RoPE+SwiGLU+GQA; Mistral
sliding windows kept exact past the window), Qwen2 (qkv-only biases,
mixed full/sliding layers), GPT-2 (Conv1D fused qkv), OPT (learned
positions with the +2 offset, ReLU), Bloom (ALiBi + embed-norm), GPT-J
(interleaved partial rotary, parallel residual), GPT-NeoX/Pythia
(rotary_pct, dual-norm parallel residual), GPT-Neo (alternating
global/local attention, unscaled logits), Falcon-7B-style (multi-query,
parallel attention), Mixtral (routed experts over the MoE transformer),
BERT/DistilBERT (post-LN encoders, MLM head), CLIP (two-tower
contrastive), and InternLM (llama layout with biased attention
projections). Megatron-LM GPT checkpoints load via checkpoint/megatron.py;
diffusers UNet/VAE via checkpoint/diffusers.py.

Formats: ``*.safetensors`` (single or index-sharded) and
``pytorch_model.bin`` (torch pickle, single or index-sharded).
"""

from __future__ import annotations

import json
import os
from typing import Any, Callable, Dict, Optional, Tuple

import numpy as np

__all__ = ["read_hf_state", "hf_config", "map_hf_params", "from_pretrained"]


# ----------------------------------------------------------------------
# raw tensor reading
def _to_numpy(t) -> np.ndarray:
    """torch tensor -> numpy. bf16 is reinterpreted bit-exact through a
    uint16 view into an ml_dtypes.bfloat16 array (torch has no numpy bf16
    bridge) — NEVER upcast through fp32, which would transiently need 2x
    the checkpoint size in host RAM (28 GB for a 7B bf16 checkpoint)."""
    import torch

    if t.dtype == torch.bfloat16:
        import ml_dtypes

        return t.view(torch.uint16).numpy().view(ml_dtypes.bfloat16)
    return t.numpy()


def read_hf_state(model_dir: str) -> Dict[str, np.ndarray]:
    """Read every tensor of an HF checkpoint directory into numpy."""
    d = str(model_dir)
    state: Dict[str, np.ndarray] = {}

    st_index = os.path.join(d, "model.safetensors.index.json")
    pt_index = os.path.join(d, "pytorch_model.bin.index.json")
    if os.path.exists(st_index) or os.path.exists(pt_index):
        index = st_index if os.path.exists(st_index) else pt_index
        with open(index) as f:
            weight_map = json.load(f)["weight_map"]
        for shard in sorted(set(weight_map.values())):
            state.update(_read_one(os.path.join(d, shard)))
        return state

    for name in ("model.safetensors", "pytorch_model.bin"):
        path = os.path.join(d, name)
        if os.path.exists(path):
            return _read_one(path)
    raise FileNotFoundError(
        f"no model.safetensors / pytorch_model.bin (or index) under {d}")


def _read_one(path: str) -> Dict[str, np.ndarray]:
    if path.endswith(".safetensors"):
        from safetensors import safe_open

        out = {}
        with safe_open(path, framework="np") as f:
            for key in f.keys():
                try:
                    out[key] = f.get_tensor(key)
                except (TypeError, ValueError):
                    # bf16 et al. unsupported by the numpy framework bridge
                    out[key] = None
        if any(v is None for v in out.values()):
            with safe_open(path, framework="pt") as f:
                for key, v in list(out.items()):
                    if v is None:
                        out[key] = _to_numpy(f.get_tensor(key))
        return out
    import torch

    sd = torch.load(path, map_location="cpu", weights_only=True)
    return {k: _to_numpy(v) for k, v in sd.items()}


# ----------------------------------------------------------------------
# config translation
def _uniform_windows(window, max_seq: int, n_layers: int):
    """Per-layer attn_windows for a uniform sliding window (Mistral/
    Mixtral); None when no window is configured or it never binds."""
    if window is None or window >= max_seq:
        return None
    return tuple([int(window)] * n_layers)


def hf_config(model_dir: str):
    """Parse HF config.json -> (family, TransformerConfig)."""
    from ..models.transformer import TransformerConfig

    with open(os.path.join(str(model_dir), "config.json")) as f:
        hc = json.load(f)
    family = hc.get("model_type", "")
    if family in ("llama", "mistral"):
        # loud failure beats silently-wrong logits for unsupported variants
        if hc.get("rope_scaling"):
            raise NotImplementedError(
                f"rope_scaling={hc['rope_scaling']} not supported "
                "(plain RoPE only)")
        if hc.get("attention_bias"):
            raise NotImplementedError("llama attention_bias=true not supported")
        max_seq = hc.get("max_position_embeddings", 2048)
        window = hc.get("sliding_window")
        n_layers = hc["num_hidden_layers"]
        # Mistral sliding window: the full position table stays usable
        # (decode past the window is exact); every layer attends the
        # trailing `window` positions. The core elides the window math —
        # and keeps dense flash — whenever seq <= window; a BINDING
        # uniform window dispatches the banded flash kernel at
        # O(s*window); only per-layer-varying windows fall back to the
        # masked O(s^2) jnp path (see TransformerConfig.attn_windows)
        windows = _uniform_windows(window, max_seq, n_layers)
        cfg = TransformerConfig(
            vocab_size=hc["vocab_size"], d_model=hc["hidden_size"],
            n_layers=n_layers, n_heads=hc["num_attention_heads"],
            n_kv_heads=hc.get("num_key_value_heads", hc["num_attention_heads"]),
            d_ff=hc["intermediate_size"],
            max_seq_len=max_seq, attn_windows=windows,
            norm="rms", activation="silu_glu", position="rope",
            rope_theta=hc.get("rope_theta", 10000.0),
            tie_embeddings=hc.get("tie_word_embeddings", False),
            use_bias=False, norm_eps=hc.get("rms_norm_eps", 1e-6))
    elif family == "internlm":
        # reference module_inject/containers/internlm.py:20 — llama-shaped
        # (RMSNorm + RoPE + gated SiLU) with biases on ALL four attention
        # projections (config "bias": true) and a bias-free MLP
        if hc.get("rope_scaling"):
            raise NotImplementedError("internlm rope_scaling not supported")
        bias = bool(hc.get("bias", True))
        cfg = TransformerConfig(
            vocab_size=hc["vocab_size"], d_model=hc["hidden_size"],
            n_layers=hc["num_hidden_layers"],
            n_heads=hc["num_attention_heads"],
            n_kv_heads=hc.get("num_key_value_heads",
                              hc["num_attention_heads"]),
            d_ff=hc["intermediate_size"],
            max_seq_len=hc.get("max_position_embeddings", 2048),
            norm="rms", activation="silu_glu", position="rope",
            rope_theta=hc.get("rope_theta", 10000.0),
            tie_embeddings=hc.get("tie_word_embeddings", False),
            use_bias=False, qkv_bias=bias, attn_o_bias=bias,
            norm_eps=hc.get("rms_norm_eps", 1e-6))
    elif family == "qwen2":
        if hc.get("rope_scaling"):
            raise NotImplementedError("qwen2 rope_scaling not supported")
        n_layers = hc["num_hidden_layers"]
        max_seq = hc.get("max_position_embeddings", 32768)
        windows = None
        if hc.get("use_sliding_window", False) and hc.get("sliding_window") \
                and hc["sliding_window"] < max_seq:
            w = int(hc["sliding_window"])
            if "layer_types" in hc:
                # honor the explicit per-layer pattern (transformers >=4.51
                # serializes and masks by it; it may be hand-edited)
                if len(hc["layer_types"]) != n_layers:
                    raise ValueError(
                        f"qwen2 layer_types has {len(hc['layer_types'])} "
                        f"entries for {n_layers} layers")
                windows = tuple(w if t == "sliding_attention" else 0
                                for t in hc["layer_types"])
            else:
                # legacy derivation: layers below max_window_layers stay
                # full attention, the rest slide
                mwl = hc.get("max_window_layers", n_layers)
                windows = tuple(0 if i < mwl else w
                                for i in range(n_layers))
            if not any(windows):
                windows = None
        cfg = TransformerConfig(
            vocab_size=hc["vocab_size"], d_model=hc["hidden_size"],
            n_layers=n_layers, n_heads=hc["num_attention_heads"],
            n_kv_heads=hc.get("num_key_value_heads", hc["num_attention_heads"]),
            d_ff=hc["intermediate_size"], max_seq_len=max_seq,
            attn_windows=windows,
            norm="rms", activation="silu_glu", position="rope",
            rope_theta=hc.get("rope_theta", 10000.0),  # HF Qwen2Config default
            tie_embeddings=hc.get("tie_word_embeddings", False),
            use_bias=False, qkv_bias=True,  # Qwen2: bias on q/k/v only
            norm_eps=hc.get("rms_norm_eps", 1e-6))
    elif family == "gpt2":
        cfg = TransformerConfig(
            vocab_size=hc["vocab_size"], d_model=hc["n_embd"],
            n_layers=hc["n_layer"], n_heads=hc["n_head"],
            d_ff=hc.get("n_inner") or 4 * hc["n_embd"],
            max_seq_len=hc.get("n_positions", 1024),
            norm="layer", activation="gelu", position="learned",
            tie_embeddings=True, use_bias=True,
            norm_eps=hc.get("layer_norm_epsilon", 1e-5))
    elif family == "opt":
        if not hc.get("do_layer_norm_before", True):
            raise NotImplementedError(
                "post-norm OPT (do_layer_norm_before=false, the 350m variant) "
                "not supported")
        act = hc.get("activation_function", "relu")
        cfg = TransformerConfig(
            vocab_size=hc["vocab_size"], d_model=hc["hidden_size"],
            n_layers=hc["num_hidden_layers"], n_heads=hc["num_attention_heads"],
            d_ff=hc.get("ffn_dim", 4 * hc["hidden_size"]),
            max_seq_len=hc.get("max_position_embeddings", 2048),
            norm="layer", activation="relu" if act == "relu" else "gelu",
            position="learned",
            tie_embeddings=hc.get("tie_word_embeddings", True),
            use_bias=hc.get("enable_bias", True), norm_eps=1e-5)
        if hc["hidden_size"] != hc.get("word_embed_proj_dim", hc["hidden_size"]):
            raise NotImplementedError("OPT word_embed_proj_dim != hidden_size")
    elif family == "mixtral":
        from ..models.moe import MoETransformerConfig

        if hc.get("rope_scaling"):
            raise NotImplementedError("mixtral rope_scaling not supported")
        max_seq = hc.get("max_position_embeddings", 4096)
        window = hc.get("sliding_window")
        n_layers = hc["num_hidden_layers"]
        windows = _uniform_windows(window, max_seq, n_layers)
        cfg = MoETransformerConfig(
            vocab_size=hc["vocab_size"], d_model=hc["hidden_size"],
            n_layers=n_layers, n_heads=hc["num_attention_heads"],
            n_kv_heads=hc.get("num_key_value_heads", hc["num_attention_heads"]),
            d_ff=hc["intermediate_size"], max_seq_len=max_seq,
            attn_windows=windows,
            norm="rms", activation="silu_glu", position="rope",
            rope_theta=hc.get("rope_theta", 1e6),
            tie_embeddings=hc.get("tie_word_embeddings", False),
            use_bias=False, norm_eps=hc.get("rms_norm_eps", 1e-5),
            n_experts=hc["num_local_experts"],
            top_k=hc["num_experts_per_tok"])
    elif family == "bloom":
        nh = hc["n_head"]
        cfg = TransformerConfig(
            vocab_size=hc["vocab_size"], d_model=hc["hidden_size"],
            n_layers=hc["n_layer"], n_heads=nh,
            d_ff=4 * hc["hidden_size"],
            # ALiBi extrapolates — no position table exists and real Bloom
            # configs carry no seq_length key; the bound only sizes KV
            # asserts, so keep it generous
            max_seq_len=hc.get("seq_length", 131072),
            norm="layer", activation="gelu", position="alibi",
            embed_norm=True, tie_embeddings=True, use_bias=True,
            norm_eps=hc.get("layer_norm_epsilon", 1e-5))
    elif family == "gptj":
        hd = hc["n_embd"] // hc["n_head"]
        cfg = TransformerConfig(
            vocab_size=hc["vocab_size"], d_model=hc["n_embd"],
            n_layers=hc["n_layer"], n_heads=hc["n_head"],
            d_ff=hc.get("n_inner") or 4 * hc["n_embd"],
            max_seq_len=hc.get("n_positions", 2048),
            norm="layer", activation="gelu", position="rope",
            rope_pct=hc.get("rotary_dim", hd) / hd, rope_interleaved=True,
            parallel_residual=True, tie_embeddings=False, use_bias=True,
            norm_eps=hc.get("layer_norm_epsilon", 1e-5))
    elif family == "gpt_neox":
        act = hc.get("hidden_act", "gelu")
        act_map = {"gelu": "gelu_exact",  # HF NeoX "gelu" is the erf GELU
                   "gelu_new": "gelu", "gelu_fast": "gelu",
                   "gelu_pytorch_tanh": "gelu", "relu": "relu"}
        if act not in act_map:
            raise NotImplementedError(f"gpt_neox hidden_act '{act}' not supported")
        cfg = TransformerConfig(
            vocab_size=hc["vocab_size"], d_model=hc["hidden_size"],
            n_layers=hc["num_hidden_layers"],
            n_heads=hc["num_attention_heads"],
            d_ff=hc.get("intermediate_size", 4 * hc["hidden_size"]),
            max_seq_len=hc.get("max_position_embeddings", 2048),
            norm="layer", activation=act_map[act], position="rope",
            rope_pct=hc.get("rotary_pct", 1.0),
            rope_theta=hc.get("rotary_emb_base", 10000.0),
            parallel_residual=hc.get("use_parallel_residual", True),
            tie_embeddings=hc.get("tie_word_embeddings", False),
            use_bias=True, norm_eps=hc.get("layer_norm_eps", 1e-5))
        if not cfg.parallel_residual:
            raise NotImplementedError(
                "gpt_neox with use_parallel_residual=false not supported")
    elif family == "falcon":
        if hc.get("new_decoder_architecture", False):
            raise NotImplementedError(
                "falcon new_decoder_architecture (40B+) not supported yet")
        if hc.get("alibi", False):
            raise NotImplementedError("falcon alibi variant not supported")
        if not hc.get("parallel_attn", True):
            raise NotImplementedError("falcon parallel_attn=false not supported")
        nh = hc["num_attention_heads"]
        cfg = TransformerConfig(
            vocab_size=hc["vocab_size"], d_model=hc["hidden_size"],
            n_layers=hc["num_hidden_layers"], n_heads=nh,
            n_kv_heads=1 if hc.get("multi_query", True) else nh,
            d_ff=4 * hc["hidden_size"],
            max_seq_len=hc.get("max_position_embeddings", 2048),
            norm="layer", activation="gelu", position="rope",
            rope_theta=hc.get("rope_theta", 10000.0),
            parallel_residual=True,
            tie_embeddings=hc.get("tie_word_embeddings", True),
            use_bias=bool(hc.get("bias", False)),
            norm_eps=hc.get("layer_norm_epsilon", 1e-5))
    elif family == "gpt_neo":
        # attention_types: [[[pattern...], repeat], ...] expands to one
        # entry per layer; "local" layers use window_size, "global" full
        layer_types = []
        for pattern, rep in hc["attention_types"]:
            layer_types += list(pattern) * rep
        if len(layer_types) != hc["num_layers"]:
            raise ValueError(
                f"gpt_neo attention_types expand to {len(layer_types)} "
                f"layers, config has {hc['num_layers']}")
        window = hc.get("window_size", 256)
        cfg = TransformerConfig(
            vocab_size=hc["vocab_size"], d_model=hc["hidden_size"],
            n_layers=hc["num_layers"], n_heads=hc["num_heads"],
            d_ff=hc.get("intermediate_size") or 4 * hc["hidden_size"],
            max_seq_len=hc.get("max_position_embeddings", 2048),
            norm="layer", activation="gelu", position="learned",
            tie_embeddings=True, use_bias=True, qkv_bias=False,
            attn_scale=1.0,  # GPT-Neo attention is unscaled
            attn_windows=tuple(window if t == "local" else 0
                               for t in layer_types),
            use_flash=False,
            norm_eps=hc.get("layer_norm_epsilon", 1e-5))
    elif family == "bert":
        if hc.get("position_embedding_type", "absolute") != "absolute":
            raise NotImplementedError(
                f"bert position_embedding_type="
                f"'{hc['position_embedding_type']}' not supported "
                "(absolute only — relative-key biases would be dropped)")
        act = hc.get("hidden_act", "gelu")
        act_map = {"gelu": "gelu_exact",  # HF BERT "gelu" is the erf GELU
                   "gelu_new": "gelu", "gelu_pytorch_tanh": "gelu",
                   "relu": "relu"}
        if act not in act_map:
            raise NotImplementedError(f"bert hidden_act '{act}' not supported")
        cfg = TransformerConfig(
            vocab_size=hc["vocab_size"], d_model=hc["hidden_size"],
            n_layers=hc["num_hidden_layers"],
            n_heads=hc["num_attention_heads"],
            d_ff=hc.get("intermediate_size", 4 * hc["hidden_size"]),
            max_seq_len=hc.get("max_position_embeddings", 512),
            norm="layer", activation=act_map[act], position="learned",
            causal=False, prenorm=False, embed_norm=True,
            type_vocab_size=hc.get("type_vocab_size", 2),
            mlm_head=True, pooler=False,  # from_pretrained reconciles to ckpt
            tie_embeddings=True, use_bias=True,
            norm_eps=hc.get("layer_norm_eps", 1e-12))
    elif family == "distilbert":
        if hc.get("sinusoidal_pos_embds", False):
            raise NotImplementedError(
                "distilbert sinusoidal_pos_embds=true not supported")
        act = hc.get("activation", "gelu")
        act_map = {"gelu": "gelu_exact", "relu": "relu"}
        if act not in act_map:
            raise NotImplementedError(
                f"distilbert activation '{act}' not supported")
        cfg = TransformerConfig(
            vocab_size=hc["vocab_size"], d_model=hc["dim"],
            n_layers=hc["n_layers"], n_heads=hc["n_heads"],
            d_ff=hc.get("hidden_dim", 4 * hc["dim"]),
            max_seq_len=hc.get("max_position_embeddings", 512),
            norm="layer", activation=act_map[act], position="learned",
            causal=False, prenorm=False, embed_norm=True,
            mlm_head=True, tie_embeddings=True, use_bias=True, norm_eps=1e-12)
    elif family == "clip":
        from ..models.clip import CLIPConfig

        act_map = {"quick_gelu": "quick_gelu", "gelu": "gelu_exact"}

        def tower(tc, **kw):
            act = tc.get("hidden_act", "quick_gelu")
            if act not in act_map:
                raise NotImplementedError(f"clip hidden_act '{act}' not supported")
            return TransformerConfig(
                d_model=tc["hidden_size"], n_layers=tc["num_hidden_layers"],
                n_heads=tc["num_attention_heads"],
                d_ff=tc["intermediate_size"], norm="layer",
                activation=act_map[act], tie_embeddings=True, use_bias=True,
                norm_eps=tc.get("layer_norm_eps", 1e-5), **kw)

        tc, vc = hc["text_config"], hc["vision_config"]
        eos = tc.get("eos_token_id", 2)
        cfg = CLIPConfig(
            text=tower(tc, vocab_size=tc["vocab_size"],
                       max_seq_len=tc.get("max_position_embeddings", 77),
                       position="learned", causal=True),
            vision=tower(vc, vocab_size=1, max_seq_len=1, position="none",
                         causal=False, embed_norm=True),
            proj_dim=hc.get("projection_dim", 512),
            image_size=vc.get("image_size", 224),
            patch_size=vc.get("patch_size", 32),
            n_channels=vc.get("num_channels", 3),
            # HF CLIPTextTransformer: eos_token_id==2 is the legacy config
            # whose pooling is plain argmax (EOS = highest id)
            eos_token_id=None if eos == 2 else eos)
    else:
        raise ValueError(f"unsupported HF model_type '{family}' "
                         f"(supported: llama, mistral, gpt2, opt, bloom, "
                         f"gptj, gpt_neo, gpt_neox, falcon, mixtral, bert, "
                         f"distilbert, clip, qwen2)")
    return family, cfg


# ----------------------------------------------------------------------
# weight mapping (per family)
def _stack(state, fmt: str, n: int, transpose=False) -> np.ndarray:
    """Stack per-layer tensors into one [n, ...] array, POPPING the source
    entries so host peak memory decays as the stacked layout is built
    (one stacked copy + the not-yet-consumed remainder, instead of 2x)."""
    arrs = [state.pop(fmt.format(i)) for i in range(n)]
    if transpose:
        arrs = [a.T for a in arrs]
    return np.stack(arrs)


def _map_llama(state, c) -> Dict[str, Any]:
    n = c.n_layers
    pre = "model." if "model.embed_tokens.weight" in state else ""
    L = pre + "layers.{}."
    layers = {
        "attn_norm_w": _stack(state, L + "input_layernorm.weight", n),
        # torch Linear stores [out, in]; native layout is [in, out]
        "wq": _stack(state, L + "self_attn.q_proj.weight", n, transpose=True),
        "wk": _stack(state, L + "self_attn.k_proj.weight", n, transpose=True),
        "wv": _stack(state, L + "self_attn.v_proj.weight", n, transpose=True),
        "wo": _stack(state, L + "self_attn.o_proj.weight", n, transpose=True),
        "mlp_norm_w": _stack(state, L + "post_attention_layernorm.weight", n),
        "w_gate": _stack(state, L + "mlp.gate_proj.weight", n, transpose=True),
        "w_up": _stack(state, L + "mlp.up_proj.weight", n, transpose=True),
        "w_down": _stack(state, L + "mlp.down_proj.weight", n, transpose=True),
    }
    if c.qkv_bias:  # Qwen2-style q/k/v-only biases on the llama layout
        layers["bq"] = _stack(state, L + "self_attn.q_proj.bias", n)
        layers["bk"] = _stack(state, L + "self_attn.k_proj.bias", n)
        layers["bv"] = _stack(state, L + "self_attn.v_proj.bias", n)
    if getattr(c, "attn_o_bias", False):  # InternLM: o_proj bias too
        layers["bo"] = _stack(state, L + "self_attn.o_proj.bias", n)
    params = {
        "tok_embed": state[pre + "embed_tokens.weight"],
        "layers": layers,
        "final_norm_w": state[pre + "norm.weight"],
    }
    if not c.tie_embeddings:
        params["lm_head"] = (state["lm_head.weight"]
                             if "lm_head.weight" in state
                             else state[pre + "embed_tokens.weight"]).T
    return params


def _map_gpt2(state, c) -> Dict[str, Any]:
    n, d = c.n_layers, c.d_model
    pre = "transformer." if "transformer.wte.weight" in state else ""
    L = pre + "h.{}."
    # HF Conv1D stores [in, out] — native orientation already; fused c_attn
    # splits [d, 3d] -> q, k, v along the output dim
    qkv_w = [state.pop((L + "attn.c_attn.weight").format(i)) for i in range(n)]
    qkv_b = [state.pop((L + "attn.c_attn.bias").format(i)) for i in range(n)]
    layers = {
        "attn_norm_w": _stack(state, L + "ln_1.weight", n),
        "attn_norm_b": _stack(state, L + "ln_1.bias", n),
        "wq": np.stack([w[:, :d] for w in qkv_w]),
        "wk": np.stack([w[:, d:2 * d] for w in qkv_w]),
        "wv": np.stack([w[:, 2 * d:] for w in qkv_w]),
        "bq": np.stack([b[:d] for b in qkv_b]),
        "bk": np.stack([b[d:2 * d] for b in qkv_b]),
        "bv": np.stack([b[2 * d:] for b in qkv_b]),
        "wo": _stack(state, L + "attn.c_proj.weight", n),
        "bo": _stack(state, L + "attn.c_proj.bias", n),
        "mlp_norm_w": _stack(state, L + "ln_2.weight", n),
        "mlp_norm_b": _stack(state, L + "ln_2.bias", n),
        "w_up": _stack(state, L + "mlp.c_fc.weight", n),
        "b_up": _stack(state, L + "mlp.c_fc.bias", n),
        "w_down": _stack(state, L + "mlp.c_proj.weight", n),
        "b_down": _stack(state, L + "mlp.c_proj.bias", n),
    }
    return {
        "tok_embed": state[pre + "wte.weight"],
        "pos_embed": state[pre + "wpe.weight"],
        "layers": layers,
        "final_norm_w": state[pre + "ln_f.weight"],
        "final_norm_b": state[pre + "ln_f.bias"],
    }


def _map_opt(state, c) -> Dict[str, Any]:
    n = c.n_layers
    pre = "model." if "model.decoder.embed_tokens.weight" in state else ""
    D = pre + "decoder."
    L = D + "layers.{}."
    layers = {
        "attn_norm_w": _stack(state, L + "self_attn_layer_norm.weight", n),
        "attn_norm_b": _stack(state, L + "self_attn_layer_norm.bias", n),
        "wq": _stack(state, L + "self_attn.q_proj.weight", n, transpose=True),
        "wk": _stack(state, L + "self_attn.k_proj.weight", n, transpose=True),
        "wv": _stack(state, L + "self_attn.v_proj.weight", n, transpose=True),
        "bq": _stack(state, L + "self_attn.q_proj.bias", n),
        "bk": _stack(state, L + "self_attn.k_proj.bias", n),
        "bv": _stack(state, L + "self_attn.v_proj.bias", n),
        "wo": _stack(state, L + "self_attn.out_proj.weight", n, transpose=True),
        "bo": _stack(state, L + "self_attn.out_proj.bias", n),
        "mlp_norm_w": _stack(state, L + "final_layer_norm.weight", n),
        "mlp_norm_b": _stack(state, L + "final_layer_norm.bias", n),
        "w_up": _stack(state, L + "fc1.weight", n, transpose=True),
        "b_up": _stack(state, L + "fc1.bias", n),
        "w_down": _stack(state, L + "fc2.weight", n, transpose=True),
        "b_down": _stack(state, L + "fc2.bias", n),
    }
    params = {
        "tok_embed": state[D + "embed_tokens.weight"],
        # OPTLearnedPositionalEmbedding carries a +2 offset: rows 0-1 unused
        "pos_embed": state[D + "embed_positions.weight"][2:],
        "layers": layers,
        "final_norm_w": state[D + "final_layer_norm.weight"],
        "final_norm_b": state[D + "final_layer_norm.bias"],
    }
    if not c.tie_embeddings:
        params["lm_head"] = (state["lm_head.weight"] if "lm_head.weight" in state
                             else state[D + "embed_tokens.weight"]).T
    return params


def _defuse_qkv(w, n_heads: int, hd: int):
    """Bloom/NeoX fused query_key_value weight [3*d, d] with HEADS-MAJOR
    row layout [n_heads, 3, hd, d] -> (wq, wk, wv) in native [in, out]."""
    d_in = w.shape[1]
    w4 = w.reshape(n_heads, 3, hd, d_in)
    return tuple(np.ascontiguousarray(
        w4[:, j].reshape(n_heads * hd, d_in).T) for j in range(3))


def _defuse_qkv_bias(b, n_heads: int, hd: int):
    b3 = b.reshape(n_heads, 3, hd)
    return tuple(np.ascontiguousarray(b3[:, j].reshape(-1)) for j in range(3))


def _defused_qkv_stacks(state, fmt: str, n: int, nh: int, hd: int):
    """Pop n layers of fused query_key_value weight+bias and return the six
    stacked native tensors {wq,wk,wv,bq,bk,bv} (Bloom and NeoX share the
    heads-major fused layout)."""
    qs, ks, vs, bqs, bks, bvs = [], [], [], [], [], []
    for i in range(n):
        wq, wk, wv = _defuse_qkv(state.pop((fmt + ".weight").format(i)), nh, hd)
        bq, bk, bv = _defuse_qkv_bias(state.pop((fmt + ".bias").format(i)),
                                      nh, hd)
        qs.append(wq); ks.append(wk); vs.append(wv)
        bqs.append(bq); bks.append(bk); bvs.append(bv)
    return {"wq": np.stack(qs), "wk": np.stack(ks), "wv": np.stack(vs),
            "bq": np.stack(bqs), "bk": np.stack(bks), "bv": np.stack(bvs)}


def _map_mixtral(state, c) -> Dict[str, Any]:
    """Mixtral: Llama-style attention + routed expert FFNs
    (block_sparse_moe: gate + experts.{e}.w1/w3 up-projections, w2 down)."""
    n, E = c.n_layers, c.n_experts
    pre = "model." if "model.embed_tokens.weight" in state else ""
    L = pre + "layers.{}."
    layers = {
        "attn_norm_w": _stack(state, L + "input_layernorm.weight", n),
        "wq": _stack(state, L + "self_attn.q_proj.weight", n, transpose=True),
        "wk": _stack(state, L + "self_attn.k_proj.weight", n, transpose=True),
        "wv": _stack(state, L + "self_attn.v_proj.weight", n, transpose=True),
        "wo": _stack(state, L + "self_attn.o_proj.weight", n, transpose=True),
        "mlp_norm_w": _stack(state, L + "post_attention_layernorm.weight", n),
        # router: HF [E, d] -> native wg [d, E]
        "wg": _stack(state, L + "block_sparse_moe.gate.weight", n,
                     transpose=True),
        # experts: HF w1 (gate) / w3 (up) [f, d], w2 (down) [d, f] ->
        # native [n, E, d, f] / [n, E, f, d]
        "w_gate": np.stack([np.stack(
            [state.pop((L + "block_sparse_moe.experts.{}.w1.weight")
                       .format(i, e)).T for e in range(E)]) for i in range(n)]),
        "w_up": np.stack([np.stack(
            [state.pop((L + "block_sparse_moe.experts.{}.w3.weight")
                       .format(i, e)).T for e in range(E)]) for i in range(n)]),
        "w_down": np.stack([np.stack(
            [state.pop((L + "block_sparse_moe.experts.{}.w2.weight")
                       .format(i, e)).T for e in range(E)]) for i in range(n)]),
    }
    params = {
        "tok_embed": state[pre + "embed_tokens.weight"],
        "layers": layers,
        "final_norm_w": state[pre + "norm.weight"],
    }
    if not c.tie_embeddings:
        params["lm_head"] = (state["lm_head.weight"]
                             if "lm_head.weight" in state
                             else state[pre + "embed_tokens.weight"]).T
    return params


def _map_bloom(state, c) -> Dict[str, Any]:
    n, nh, hd = c.n_layers, c.n_heads, c.d_model // c.n_heads
    pre = "transformer." if "transformer.word_embeddings.weight" in state else ""
    L = pre + "h.{}."
    layers = {
        "attn_norm_w": _stack(state, L + "input_layernorm.weight", n),
        "attn_norm_b": _stack(state, L + "input_layernorm.bias", n),
        **_defused_qkv_stacks(state, L + "self_attention.query_key_value",
                              n, nh, hd),
        "wo": _stack(state, L + "self_attention.dense.weight", n, transpose=True),
        "bo": _stack(state, L + "self_attention.dense.bias", n),
        "mlp_norm_w": _stack(state, L + "post_attention_layernorm.weight", n),
        "mlp_norm_b": _stack(state, L + "post_attention_layernorm.bias", n),
        "w_up": _stack(state, L + "mlp.dense_h_to_4h.weight", n, transpose=True),
        "b_up": _stack(state, L + "mlp.dense_h_to_4h.bias", n),
        "w_down": _stack(state, L + "mlp.dense_4h_to_h.weight", n, transpose=True),
        "b_down": _stack(state, L + "mlp.dense_4h_to_h.bias", n),
    }
    return {
        "tok_embed": state[pre + "word_embeddings.weight"],
        "embed_norm_w": state[pre + "word_embeddings_layernorm.weight"],
        "embed_norm_b": state[pre + "word_embeddings_layernorm.bias"],
        "layers": layers,
        "final_norm_w": state[pre + "ln_f.weight"],
        "final_norm_b": state[pre + "ln_f.bias"],
    }


def _map_gptj(state, c) -> Dict[str, Any]:
    n = c.n_layers
    pre = "transformer." if "transformer.wte.weight" in state else ""
    L = pre + "h.{}."
    zeros_attn = np.zeros((n, c.d_model), np.float32)
    ln_w = _stack(state, L + "ln_1.weight", n)
    ln_b = _stack(state, L + "ln_1.bias", n)
    layers = {
        # single shared LN feeds both parallel branches: duplicate it
        "attn_norm_w": ln_w, "attn_norm_b": ln_b,
        "mlp_norm_w": ln_w.copy(), "mlp_norm_b": ln_b.copy(),
        "wq": _stack(state, L + "attn.q_proj.weight", n, transpose=True),
        "wk": _stack(state, L + "attn.k_proj.weight", n, transpose=True),
        "wv": _stack(state, L + "attn.v_proj.weight", n, transpose=True),
        "wo": _stack(state, L + "attn.out_proj.weight", n, transpose=True),
        # GPT-J attention has no biases; the global use_bias flag expects
        # them, so zeros (mathematically identical)
        "bq": zeros_attn.copy(), "bk": zeros_attn.copy(),
        "bv": zeros_attn.copy(), "bo": zeros_attn.copy(),
        "w_up": _stack(state, L + "mlp.fc_in.weight", n, transpose=True),
        "b_up": _stack(state, L + "mlp.fc_in.bias", n),
        "w_down": _stack(state, L + "mlp.fc_out.weight", n, transpose=True),
        "b_down": _stack(state, L + "mlp.fc_out.bias", n),
    }
    params = {
        "tok_embed": state[pre + "wte.weight"],
        "layers": layers,
        "final_norm_w": state[pre + "ln_f.weight"],
        "final_norm_b": state[pre + "ln_f.bias"],
        "lm_head": state["lm_head.weight"].T,
    }
    if "lm_head.bias" in state:
        params["lm_head_b"] = state["lm_head.bias"]
    return params


def _map_gpt_neox(state, c) -> Dict[str, Any]:
    n, nh, hd = c.n_layers, c.n_heads, c.d_model // c.n_heads
    pre = "gpt_neox." if "gpt_neox.embed_in.weight" in state else ""
    L = pre + "layers.{}."
    layers = {
        "attn_norm_w": _stack(state, L + "input_layernorm.weight", n),
        "attn_norm_b": _stack(state, L + "input_layernorm.bias", n),
        "mlp_norm_w": _stack(state, L + "post_attention_layernorm.weight", n),
        "mlp_norm_b": _stack(state, L + "post_attention_layernorm.bias", n),
        **_defused_qkv_stacks(state, L + "attention.query_key_value",
                              n, nh, hd),
        "wo": _stack(state, L + "attention.dense.weight", n, transpose=True),
        "bo": _stack(state, L + "attention.dense.bias", n),
        "w_up": _stack(state, L + "mlp.dense_h_to_4h.weight", n, transpose=True),
        "b_up": _stack(state, L + "mlp.dense_h_to_4h.bias", n),
        "w_down": _stack(state, L + "mlp.dense_4h_to_h.weight", n, transpose=True),
        "b_down": _stack(state, L + "mlp.dense_4h_to_h.bias", n),
    }
    params = {
        "tok_embed": state[pre + "embed_in.weight"],
        "layers": layers,
        "final_norm_w": state[pre + "final_layer_norm.weight"],
        "final_norm_b": state[pre + "final_layer_norm.bias"],
    }
    if not c.tie_embeddings:
        params["lm_head"] = state["embed_out.weight"].T
    return params


def _map_falcon(state, c) -> Dict[str, Any]:
    """Falcon-7B-style (old decoder architecture, multi-query, parallel
    attention): fused qkv rows are [n_heads*hd | hd (k) | hd (v)]."""
    n, nh, hd = c.n_layers, c.n_heads, c.d_model // c.n_heads
    nkv = c.n_kv_heads
    pre = "transformer." if "transformer.word_embeddings.weight" in state else ""
    L = pre + "h.{}."
    qs, ks, vs = [], [], []
    for i in range(n):
        w = state.pop((L + "self_attention.query_key_value.weight").format(i))
        q_rows = nh * hd
        qs.append(np.ascontiguousarray(w[:q_rows].T))
        ks.append(np.ascontiguousarray(w[q_rows:q_rows + nkv * hd].T))
        vs.append(np.ascontiguousarray(w[q_rows + nkv * hd:].T))
    ln_w = _stack(state, L + "input_layernorm.weight", n)
    ln_b = _stack(state, L + "input_layernorm.bias", n)
    layers = {
        # single shared LN feeds both parallel branches (like GPT-J)
        "attn_norm_w": ln_w, "attn_norm_b": ln_b,
        "mlp_norm_w": ln_w.copy(), "mlp_norm_b": ln_b.copy(),
        "wq": np.stack(qs), "wk": np.stack(ks), "wv": np.stack(vs),
        "wo": _stack(state, L + "self_attention.dense.weight", n, transpose=True),
        "w_up": _stack(state, L + "mlp.dense_h_to_4h.weight", n, transpose=True),
        "w_down": _stack(state, L + "mlp.dense_4h_to_h.weight", n, transpose=True),
    }
    params = {
        "tok_embed": state[pre + "word_embeddings.weight"],
        "layers": layers,
        "final_norm_w": state[pre + "ln_f.weight"],
        "final_norm_b": state[pre + "ln_f.bias"],
    }
    if not c.tie_embeddings:
        params["lm_head"] = (state["lm_head.weight"]
                             if "lm_head.weight" in state
                             else state[pre + "word_embeddings.weight"]).T
    return params


def _map_gpt_neo(state, c) -> Dict[str, Any]:
    n = c.n_layers
    pre = "transformer." if "transformer.wte.weight" in state else ""
    L = pre + "h.{}."
    # GPT-Neo uses torch Linear ([out, in] -> transpose), unlike GPT-2's
    # Conv1D; q/k/v carry no bias, out_proj does
    layers = {
        "attn_norm_w": _stack(state, L + "ln_1.weight", n),
        "attn_norm_b": _stack(state, L + "ln_1.bias", n),
        "wq": _stack(state, L + "attn.attention.q_proj.weight", n, transpose=True),
        "wk": _stack(state, L + "attn.attention.k_proj.weight", n, transpose=True),
        "wv": _stack(state, L + "attn.attention.v_proj.weight", n, transpose=True),
        "wo": _stack(state, L + "attn.attention.out_proj.weight", n, transpose=True),
        "bo": _stack(state, L + "attn.attention.out_proj.bias", n),
        "mlp_norm_w": _stack(state, L + "ln_2.weight", n),
        "mlp_norm_b": _stack(state, L + "ln_2.bias", n),
        "w_up": _stack(state, L + "mlp.c_fc.weight", n, transpose=True),
        "b_up": _stack(state, L + "mlp.c_fc.bias", n),
        "w_down": _stack(state, L + "mlp.c_proj.weight", n, transpose=True),
        "b_down": _stack(state, L + "mlp.c_proj.bias", n),
    }
    return {
        "tok_embed": state[pre + "wte.weight"],
        "pos_embed": state[pre + "wpe.weight"],
        "layers": layers,
        "final_norm_w": state[pre + "ln_f.weight"],
        "final_norm_b": state[pre + "ln_f.bias"],
    }


def _map_bert(state, c) -> Dict[str, Any]:
    n = c.n_layers
    pre = "bert." if "bert.embeddings.word_embeddings.weight" in state else ""
    L = pre + "encoder.layer.{}."
    layers = {
        # post-LN mapping: attention.output.LayerNorm runs AFTER the attn
        # residual -> attn_norm; output.LayerNorm after the FFN -> mlp_norm
        "wq": _stack(state, L + "attention.self.query.weight", n, transpose=True),
        "bq": _stack(state, L + "attention.self.query.bias", n),
        "wk": _stack(state, L + "attention.self.key.weight", n, transpose=True),
        "bk": _stack(state, L + "attention.self.key.bias", n),
        "wv": _stack(state, L + "attention.self.value.weight", n, transpose=True),
        "bv": _stack(state, L + "attention.self.value.bias", n),
        "wo": _stack(state, L + "attention.output.dense.weight", n, transpose=True),
        "bo": _stack(state, L + "attention.output.dense.bias", n),
        "attn_norm_w": _stack(state, L + "attention.output.LayerNorm.weight", n),
        "attn_norm_b": _stack(state, L + "attention.output.LayerNorm.bias", n),
        "w_up": _stack(state, L + "intermediate.dense.weight", n, transpose=True),
        "b_up": _stack(state, L + "intermediate.dense.bias", n),
        "w_down": _stack(state, L + "output.dense.weight", n, transpose=True),
        "b_down": _stack(state, L + "output.dense.bias", n),
        "mlp_norm_w": _stack(state, L + "output.LayerNorm.weight", n),
        "mlp_norm_b": _stack(state, L + "output.LayerNorm.bias", n),
    }
    params = {
        "tok_embed": state[pre + "embeddings.word_embeddings.weight"],
        "pos_embed": state[pre + "embeddings.position_embeddings.weight"],
        "type_embed": state[pre + "embeddings.token_type_embeddings.weight"],
        "embed_norm_w": state[pre + "embeddings.LayerNorm.weight"],
        "embed_norm_b": state[pre + "embeddings.LayerNorm.bias"],
        "layers": layers,
    }
    # head surface varies by checkpoint class (BertModel carries neither,
    # BertForMaskedLM the MLM head, BertForPreTraining both) — map whatever
    # the weights provide; from_pretrained reconciles the config flags to
    # the mapped tree BEFORE constructing the model (no cfg mutation here)
    if "cls.predictions.transform.dense.weight" in state:
        params["mlm_dense_w"] = state["cls.predictions.transform.dense.weight"].T
        params["mlm_dense_b"] = state["cls.predictions.transform.dense.bias"]
        params["mlm_norm_w"] = state["cls.predictions.transform.LayerNorm.weight"]
        params["mlm_norm_b"] = state["cls.predictions.transform.LayerNorm.bias"]
        params["mlm_bias"] = state["cls.predictions.bias"]
        # HF normally ties cls.predictions.decoder to the word embeddings,
        # but a tie_word_embeddings=false fine-tune unties it; silently
        # keeping the tie would load cleanly yet emit wrong MLM logits.
        dec = state.get("cls.predictions.decoder.weight")
        if dec is not None and (dec.shape != params["tok_embed"].shape
                                or not np.array_equal(dec, params["tok_embed"])):
            params["lm_head"] = dec.T  # untied decoder: [vocab, d] -> [d, vocab]
    if pre + "pooler.dense.weight" in state:
        params["pooler_w"] = state[pre + "pooler.dense.weight"].T
        params["pooler_b"] = state[pre + "pooler.dense.bias"]
    return params


def _map_distilbert(state, c) -> Dict[str, Any]:
    n = c.n_layers
    pre = "distilbert." if "distilbert.embeddings.word_embeddings.weight" in state else ""
    L = pre + "transformer.layer.{}."
    layers = {
        "wq": _stack(state, L + "attention.q_lin.weight", n, transpose=True),
        "bq": _stack(state, L + "attention.q_lin.bias", n),
        "wk": _stack(state, L + "attention.k_lin.weight", n, transpose=True),
        "bk": _stack(state, L + "attention.k_lin.bias", n),
        "wv": _stack(state, L + "attention.v_lin.weight", n, transpose=True),
        "bv": _stack(state, L + "attention.v_lin.bias", n),
        "wo": _stack(state, L + "attention.out_lin.weight", n, transpose=True),
        "bo": _stack(state, L + "attention.out_lin.bias", n),
        "attn_norm_w": _stack(state, L + "sa_layer_norm.weight", n),
        "attn_norm_b": _stack(state, L + "sa_layer_norm.bias", n),
        "w_up": _stack(state, L + "ffn.lin1.weight", n, transpose=True),
        "b_up": _stack(state, L + "ffn.lin1.bias", n),
        "w_down": _stack(state, L + "ffn.lin2.weight", n, transpose=True),
        "b_down": _stack(state, L + "ffn.lin2.bias", n),
        "mlp_norm_w": _stack(state, L + "output_layer_norm.weight", n),
        "mlp_norm_b": _stack(state, L + "output_layer_norm.bias", n),
    }
    params = {
        "tok_embed": state[pre + "embeddings.word_embeddings.weight"],
        "pos_embed": state[pre + "embeddings.position_embeddings.weight"],
        "embed_norm_w": state[pre + "embeddings.LayerNorm.weight"],
        "embed_norm_b": state[pre + "embeddings.LayerNorm.bias"],
        "layers": layers,
    }
    if "vocab_transform.weight" in state:
        params["mlm_dense_w"] = state["vocab_transform.weight"].T
        params["mlm_dense_b"] = state["vocab_transform.bias"]
        params["mlm_norm_w"] = state["vocab_layer_norm.weight"]
        params["mlm_norm_b"] = state["vocab_layer_norm.bias"]
        params["mlm_bias"] = state["vocab_projector.bias"]
        proj = state.get("vocab_projector.weight")  # untied fine-tunes only
        if proj is not None and (proj.shape != params["tok_embed"].shape
                                 or not np.array_equal(proj, params["tok_embed"])):
            params["lm_head"] = proj.T
    return params


def _clip_tower_layers(state, prefix: str, n: int) -> Dict[str, Any]:
    """Shared pre-LN CLIP encoder layer stack (text and vision towers use
    identical per-layer key names under different prefixes)."""
    L = prefix + "encoder.layers.{}."
    return {
        "attn_norm_w": _stack(state, L + "layer_norm1.weight", n),
        "attn_norm_b": _stack(state, L + "layer_norm1.bias", n),
        "wq": _stack(state, L + "self_attn.q_proj.weight", n, transpose=True),
        "bq": _stack(state, L + "self_attn.q_proj.bias", n),
        "wk": _stack(state, L + "self_attn.k_proj.weight", n, transpose=True),
        "bk": _stack(state, L + "self_attn.k_proj.bias", n),
        "wv": _stack(state, L + "self_attn.v_proj.weight", n, transpose=True),
        "bv": _stack(state, L + "self_attn.v_proj.bias", n),
        "wo": _stack(state, L + "self_attn.out_proj.weight", n, transpose=True),
        "bo": _stack(state, L + "self_attn.out_proj.bias", n),
        "mlp_norm_w": _stack(state, L + "layer_norm2.weight", n),
        "mlp_norm_b": _stack(state, L + "layer_norm2.bias", n),
        "w_up": _stack(state, L + "mlp.fc1.weight", n, transpose=True),
        "b_up": _stack(state, L + "mlp.fc1.bias", n),
        "w_down": _stack(state, L + "mlp.fc2.weight", n, transpose=True),
        "b_down": _stack(state, L + "mlp.fc2.bias", n),
    }


def _map_clip(state, c) -> Dict[str, Any]:
    text = {
        "tok_embed": state["text_model.embeddings.token_embedding.weight"],
        "pos_embed": state["text_model.embeddings.position_embedding.weight"],
        "layers": _clip_tower_layers(state, "text_model.", c.text.n_layers),
        "final_norm_w": state["text_model.final_layer_norm.weight"],
        "final_norm_b": state["text_model.final_layer_norm.bias"],
    }
    pw = state["vision_model.embeddings.patch_embedding.weight"]  # [d,3,p,p]
    d = pw.shape[0]
    vision = {
        # the 1-row token table is an unused core artifact on the pixel path
        "tok_embed": np.zeros((1, d), pw.dtype),
        "patch_w": pw.reshape(d, -1).T,  # (c, ph, pw)-ordered patch vectors
        "cls_embed": state["vision_model.embeddings.class_embedding"],
        "pos_embed": state["vision_model.embeddings.position_embedding.weight"],
        "embed_norm_w": state["vision_model.pre_layrnorm.weight"],
        "embed_norm_b": state["vision_model.pre_layrnorm.bias"],
        "layers": _clip_tower_layers(state, "vision_model.", c.vision.n_layers),
        "final_norm_w": state["vision_model.post_layernorm.weight"],
        "final_norm_b": state["vision_model.post_layernorm.bias"],
    }
    return {
        "text": text,
        "vision": vision,
        "text_proj": state["text_projection.weight"].T,
        "vision_proj": state["visual_projection.weight"].T,
        "logit_scale": state["logit_scale"],
    }


_MAPPERS: Dict[str, Callable] = {
    "llama": _map_llama, "mistral": _map_llama, "qwen2": _map_llama,
    "internlm": _map_llama,
    "gpt2": _map_gpt2, "opt": _map_opt,
    "bloom": _map_bloom, "gptj": _map_gptj, "gpt_neox": _map_gpt_neox,
    "gpt_neo": _map_gpt_neo,
    "falcon": _map_falcon, "mixtral": _map_mixtral,
    "bert": _map_bert, "distilbert": _map_distilbert,
    "clip": _map_clip,
}


def map_hf_params(state: Dict[str, np.ndarray], family: str, config) -> Dict[str, Any]:
    """HF state dict -> native stacked params pytree (numpy, source dtype —
    bf16 checkpoints stay ml_dtypes.bfloat16).

    CONSUMES ``state``: per-layer entries are popped as they are stacked so
    host peak memory decays during mapping. Pass a copy if you need the
    flat dict afterwards."""
    if family not in _MAPPERS:
        raise ValueError(f"unsupported family '{family}'")
    return _MAPPERS[family](state, config)


# ----------------------------------------------------------------------
def from_pretrained(model_dir: str, dtype=None, topology=None,
                    ) -> Tuple[Any, Dict[str, Any]]:
    """Load an HF checkpoint directory into (Transformer, params).

    ``dtype``: computation dtype for the params (default bfloat16).
    ``topology``: optional Topology — params are placed with the model's
    TP/pipe PartitionSpecs over its mesh (the auto-TP analog: sharded
    serving is data placement, not module surgery).
    """
    import jax
    import jax.numpy as jnp

    from ..models.transformer import Transformer

    import ml_dtypes

    dtype = dtype if dtype is not None else jnp.bfloat16
    family, cfg = hf_config(model_dir)
    state = read_hf_state(model_dir)
    host_params = map_hf_params(state, family, cfg)
    del state  # mappers pop what they stack; drop the embeds' extra refs too
    if family in ("bert", "distilbert"):
        # the head surface follows the checkpoint class (BertModel vs
        # ForMaskedLM vs ForPreTraining); align the config to the mapped
        # tree before the model is constructed
        cfg.mlm_head = "mlm_dense_w" in host_params
        cfg.pooler = "pooler_w" in host_params
        # an untied MLM decoder was mapped to lm_head (see _map_bert)
        cfg.tie_embeddings = "lm_head" not in host_params
    if family == "mixtral":
        from ..models.moe import MoETransformer

        model = MoETransformer(cfg)
    elif family == "clip":
        from ..models.clip import CLIP

        model = CLIP(cfg)
    else:
        model = Transformer(cfg)
    # cast on host (ml_dtypes covers bf16 numpy) so each leaf ships to the
    # devices already-sharded — never materializing a full unsharded param
    # in one chip's HBM; copy=False keeps bf16 checkpoints zero-copy here
    np_dtype = np.dtype(ml_dtypes.bfloat16) if dtype == jnp.bfloat16 \
        else np.dtype(dtype)
    host_params = jax.tree_util.tree_map(
        lambda a: np.ascontiguousarray(a.astype(np_dtype, copy=False)),
        host_params)
    if topology is not None:
        model.bind_topology(topology)
        from jax.sharding import NamedSharding

        specs = model.partition_specs(host_params, topology)
        shardings = jax.tree_util.tree_map(
            lambda s: NamedSharding(topology.mesh, s), specs,
            is_leaf=lambda x: not isinstance(x, dict))
        params = jax.tree_util.tree_map(jax.device_put, host_params, shardings)
    else:
        params = jax.tree_util.tree_map(jax.device_put, host_params)
    return model, params
