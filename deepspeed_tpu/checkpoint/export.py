"""Export native params back to HuggingFace format.

The reference's ``save_16bit_model`` emits an HF-loadable
``pytorch_model.bin`` because its module IS a torch HF model
(engine.py:3010 save path + utils/zero_to_fp32.py consolidation). The
native stacked layout needs the inverse of checkpoint/hf.py's ingestion
mapping: unstack the [n_layers, ...] leaves, transpose [in, out] back to
torch's [out, in], and write safetensors + config.json that
``transformers`` (and any HF-ecosystem tool) loads directly.

Supported: the llama-layout families (Llama/Mistral/InternLM/Qwen2 —
RMSNorm + RoPE + gated SiLU + GQA, with optional attention biases).
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict

import numpy as np

__all__ = ["export_hf_llama", "export_hf_gpt2", "export_hf_mixtral"]


def _t(x) -> np.ndarray:
    return np.ascontiguousarray(np.asarray(x))


def _tT(x) -> np.ndarray:
    """Transpose to torch's [out, in] and make it CONTIGUOUS: safetensors
    serializes the raw buffer, so a strided .T view would silently write
    the untransposed bytes under a transposed header."""
    return np.ascontiguousarray(np.asarray(x).T)


def _llama_trunk_state(c, params) -> Dict[str, np.ndarray]:
    """Embeddings + final norm + (untied) head + per-layer llama-style
    attention/norm keys — the state shared by every rms+rope exporter."""
    lay = params["layers"]
    state: Dict[str, np.ndarray] = {
        "model.embed_tokens.weight": _t(params["tok_embed"]),
        "model.norm.weight": _t(params["final_norm_w"]),
    }
    if not c.tie_embeddings:
        state["lm_head.weight"] = _tT(params["lm_head"])
    for i in range(c.n_layers):
        L = f"model.layers.{i}."
        state.update({
            L + "input_layernorm.weight": _t(lay["attn_norm_w"][i]),
            L + "post_attention_layernorm.weight": _t(lay["mlp_norm_w"][i]),
            L + "self_attn.q_proj.weight": _tT(lay["wq"][i]),
            L + "self_attn.k_proj.weight": _tT(lay["wk"][i]),
            L + "self_attn.v_proj.weight": _tT(lay["wv"][i]),
            L + "self_attn.o_proj.weight": _tT(lay["wo"][i]),
        })
    return state


def _save_safetensors(state: Dict[str, np.ndarray], out_dir: str) -> None:
    from safetensors.numpy import save_file

    # safetensors has no bf16 numpy dtype bridge everywhere — export fp32
    # unless the leaves already are a numpy-native dtype
    state = {k: (v.astype(np.float32)
                 if v.dtype not in (np.float32, np.float16) else v)
             for k, v in state.items()}
    save_file(state, os.path.join(out_dir, "model.safetensors"))


def _base_causal_config(c, model_type: str, arch: str) -> Dict[str, Any]:
    hf_config: Dict[str, Any] = {
        "architectures": [arch],
        "model_type": model_type,
        "vocab_size": c.vocab_size,
        "hidden_size": c.d_model,
        "intermediate_size": c.d_ff,
        "num_hidden_layers": c.n_layers,
        "num_attention_heads": c.n_heads,
        "num_key_value_heads": c.n_kv_heads,
        "max_position_embeddings": c.max_seq_len,
        "rms_norm_eps": c.norm_eps,
        "rope_theta": c.rope_theta,
        "tie_word_embeddings": bool(c.tie_embeddings),
        "hidden_act": "silu",
        "torch_dtype": "float32",
    }
    if getattr(c, "attn_windows", None):
        w = c.attn_windows[0]
        if w and all(x == w for x in c.attn_windows):
            hf_config["sliding_window"] = int(w)
    return hf_config


def export_hf_llama(model, params: Dict[str, Any], out_dir: str,
                    model_type: str = "llama") -> str:
    """Write ``out_dir/model.safetensors`` + ``config.json`` in HF llama
    naming from a native Transformer's params. Inverse of
    checkpoint/hf.py::_map_llama (transposes + per-layer unstacking)."""
    c = model.config
    if c.norm != "rms" or c.activation != "silu_glu" or c.position != "rope":
        raise NotImplementedError(
            "export_hf_llama handles the llama layout (rms + silu_glu + "
            f"rope); got norm={c.norm} activation={c.activation} "
            f"position={c.position}")
    # bias layouts must match what the TARGET class constructs, or
    # from_pretrained leaves unmatched bias params randomly initialized
    # (silently wrong logits): Llama/Mistral attention_bias covers all
    # four projections; Qwen2 has qkv-only biases.
    o_bias = bool(getattr(c, "attn_o_bias", False))
    if model_type in ("llama", "mistral", "internlm"):
        if bool(c.qkv_bias) != o_bias:
            raise NotImplementedError(
                f"{model_type} export needs qkv_bias == attn_o_bias "
                f"(attention_bias covers all four projections); got "
                f"qkv_bias={c.qkv_bias} attn_o_bias={o_bias} — export as "
                "model_type='qwen2' for qkv-only biases")
    elif model_type == "qwen2":
        if not c.qkv_bias or o_bias:
            raise NotImplementedError(
                "qwen2 export is the qkv-only-bias layout; got "
                f"qkv_bias={c.qkv_bias} attn_o_bias={o_bias}")
    else:
        raise ValueError(f"unknown export model_type '{model_type}'")
    os.makedirs(out_dir, exist_ok=True)
    lay = params["layers"]
    state = _llama_trunk_state(c, params)
    for i in range(c.n_layers):
        L = f"model.layers.{i}."
        state.update({
            L + "mlp.gate_proj.weight": _tT(lay["w_gate"][i]),
            L + "mlp.up_proj.weight": _tT(lay["w_up"][i]),
            L + "mlp.down_proj.weight": _tT(lay["w_down"][i]),
        })
        if "bq" in lay:
            state[L + "self_attn.q_proj.bias"] = _t(lay["bq"][i])
            state[L + "self_attn.k_proj.bias"] = _t(lay["bk"][i])
            state[L + "self_attn.v_proj.bias"] = _t(lay["bv"][i])
        if "bo" in lay:
            state[L + "self_attn.o_proj.bias"] = _t(lay["bo"][i])
    _save_safetensors(state, out_dir)

    arch = {"llama": "LlamaForCausalLM", "mistral": "MistralForCausalLM",
            "qwen2": "Qwen2ForCausalLM",
            "internlm": "InternLMForCausalLM"}[model_type]
    hf_config = _base_causal_config(c, model_type, arch)
    if model_type in ("llama", "mistral", "internlm"):
        hf_config["attention_bias"] = bool(c.qkv_bias)
    if model_type == "internlm":
        # InternLM's remote-code config reads the 'bias' key (default
        # True) — the same key hf.py ingestion reads (hc.get('bias', ...))
        hf_config["bias"] = bool(c.qkv_bias)
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(hf_config, f, indent=2)
    return out_dir


def export_hf_mixtral(model, params: Dict[str, Any], out_dir: str) -> str:
    """Write HF Mixtral format from a native MoETransformer: llama-style
    attention plus per-layer routed experts unstacked from the native
    [n_layers, n_experts, ...] banks into block_sparse_moe.experts.{e}.w1/
    w2/w3. Inverse of checkpoint/hf.py::_map_mixtral — the MoE leg of the
    reference's MoE save surface (runtime/engine.py _save_moe_checkpoint),
    closing the fine-tune-then-serve round trip for sparse models."""
    c = model.config
    if c.norm != "rms" or c.activation != "silu_glu" or c.position != "rope":
        raise NotImplementedError(
            "export_hf_mixtral handles the mixtral layout (rms + silu_glu "
            f"+ rope); got norm={c.norm} activation={c.activation} "
            f"position={c.position}")
    E = getattr(c, "n_experts", 0)
    if not E:
        raise ValueError("model has no experts — use export_hf_llama")
    if getattr(c, "n_shared_experts", 0):
        raise NotImplementedError(
            "MixtralForCausalLM has no shared-expert branch")
    if bool(c.qkv_bias) or bool(getattr(c, "attn_o_bias", False)):
        raise NotImplementedError(
            "MixtralForCausalLM constructs bias-free attention; got "
            f"qkv_bias={c.qkv_bias} attn_o_bias={c.attn_o_bias}")
    os.makedirs(out_dir, exist_ok=True)
    lay = params["layers"]
    state = _llama_trunk_state(c, params)
    for i in range(c.n_layers):
        L = f"model.layers.{i}."
        # router: native wg [d, E] -> HF gate [E, d]
        state[L + "block_sparse_moe.gate.weight"] = _tT(lay["wg"][i])
        for e in range(E):
            X = L + f"block_sparse_moe.experts.{e}."
            # native banks [n, E, d, f] (gate/up) and [n, E, f, d] (down)
            # -> HF w1/w3 [f, d], w2 [d, f]
            state[X + "w1.weight"] = _tT(lay["w_gate"][i, e])
            state[X + "w3.weight"] = _tT(lay["w_up"][i, e])
            state[X + "w2.weight"] = _tT(lay["w_down"][i, e])
    _save_safetensors(state, out_dir)

    hf_config = _base_causal_config(c, "mixtral", "MixtralForCausalLM")
    hf_config.update({
        "num_local_experts": int(E),
        "num_experts_per_tok": int(c.top_k),
        "output_router_logits": False,
    })
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump(hf_config, f, indent=2)
    return out_dir


def export_hf_gpt2(model, params: Dict[str, Any], out_dir: str) -> str:
    """Write HF GPT-2 format (Conv1D [in, out] — the native orientation,
    fused c_attn) from a native GPT-2-layout Transformer. Together with
    checkpoint/megatron.py this is a Megatron-LM -> HF conversion
    pipeline. Inverse of checkpoint/hf.py::_map_gpt2."""
    c = model.config
    if c.norm != "layer" or c.position != "learned" or not c.use_bias:
        raise NotImplementedError(
            "export_hf_gpt2 handles the GPT-2 layout (layer norm + learned "
            f"positions + biases); got norm={c.norm} position={c.position} "
            f"use_bias={c.use_bias}")
    if c.n_kv_heads != c.n_heads:
        raise NotImplementedError("GPT-2 layout has no GQA")
    if not c.tie_embeddings:
        # GPT2LMHeadModel always ties wte to the head — exporting an
        # untied model would silently drop lm_head
        raise NotImplementedError(
            "GPT-2 export requires tie_embeddings=True (GPT2LMHeadModel "
            "ties the head to wte)")
    os.makedirs(out_dir, exist_ok=True)
    lay = params["layers"]
    state: Dict[str, np.ndarray] = {
        "wte.weight": _t(params["tok_embed"]),
        "wpe.weight": _t(params["pos_embed"]),
        "ln_f.weight": _t(params["final_norm_w"]),
        "ln_f.bias": _t(params["final_norm_b"]),
    }
    for i in range(c.n_layers):
        L = f"h.{i}."
        state.update({
            L + "ln_1.weight": _t(lay["attn_norm_w"][i]),
            L + "ln_1.bias": _t(lay["attn_norm_b"][i]),
            L + "attn.c_attn.weight": np.concatenate(
                [_t(lay["wq"][i]), _t(lay["wk"][i]), _t(lay["wv"][i])],
                axis=1),
            L + "attn.c_attn.bias": np.concatenate(
                [_t(lay["bq"][i]), _t(lay["bk"][i]), _t(lay["bv"][i])]),
            L + "attn.c_proj.weight": _t(lay["wo"][i]),
            L + "attn.c_proj.bias": _t(lay["bo"][i]),
            L + "ln_2.weight": _t(lay["mlp_norm_w"][i]),
            L + "ln_2.bias": _t(lay["mlp_norm_b"][i]),
            L + "mlp.c_fc.weight": _t(lay["w_up"][i]),
            L + "mlp.c_fc.bias": _t(lay["b_up"][i]),
            L + "mlp.c_proj.weight": _t(lay["w_down"][i]),
            L + "mlp.c_proj.bias": _t(lay["b_down"][i]),
        })

    from safetensors.numpy import save_file

    state = {k: (v.astype(np.float32)
                 if v.dtype not in (np.float32, np.float16) else v)
             for k, v in state.items()}
    save_file(state, os.path.join(out_dir, "model.safetensors"))
    with open(os.path.join(out_dir, "config.json"), "w") as f:
        json.dump({
            "architectures": ["GPT2LMHeadModel"], "model_type": "gpt2",
            "vocab_size": c.vocab_size, "n_embd": c.d_model,
            "n_layer": c.n_layers, "n_head": c.n_heads,
            "n_positions": c.max_seq_len, "n_inner": c.d_ff,
            "layer_norm_epsilon": c.norm_eps,
            "activation_function": "gelu_new",
            "tie_word_embeddings": True, "torch_dtype": "float32",
        }, f, indent=2)
    return out_dir
