"""Megatron-LM GPT checkpoint ingestion.

Parity target: the reference's Megatron policy + container
(``module_inject/containers/megatron_gpt.py:1``,
``containers/features/megatron.py:27`` — the megatron_v2 fused-qkv
re-interleave) and its checkpoint loader surface
(``module_inject/load_checkpoint.py`` megatron branch). Megatron's GPT is
architecturally GPT-2 (pre-LN, learned positions, gelu, fused qkv, tied
head), so ingestion lands on the same native stacked layout the GPT-2
family uses — only the checkpoint format differs:

* file: ``<dir>/mp_rank_00/model_optim_rng.pt`` (or ``model_rng.pt``) —
  a torch pickle ``{"model": {"language_model": ...}, "args",
  "checkpoint_version"}``.
* fused qkv ordering: checkpoint_version >= 2 stores rows as
  [heads, (q|k|v), head_dim] ("megatron_v2"); v1 stores [(q|k|v), heads,
  head_dim]. The native layout wants the v1 (flat q|k|v) order — v2
  checkpoints are de-interleaved exactly like the reference's
  ``_align_qkv_transposed``.
"""

from __future__ import annotations

import os
from typing import Any, Dict, Optional, Tuple

import numpy as np

__all__ = ["read_megatron_state", "megatron_config", "map_megatron_gpt",
           "from_megatron", "map_megatron_gpt_moe", "from_megatron_moe"]


def _flatten(prefix: str, tree: Any, out: Dict[str, np.ndarray]) -> None:
    import torch

    if isinstance(tree, dict):
        for k, v in tree.items():
            _flatten(f"{prefix}.{k}" if prefix else str(k), v, out)
    elif isinstance(tree, torch.Tensor):
        from .hf import _to_numpy

        out[prefix] = _to_numpy(tree.detach().cpu())


def read_megatron_state(ckpt_dir: str) -> Tuple[Dict[str, np.ndarray], Dict[str, Any], float]:
    """Read a Megatron-LM checkpoint directory (single mp rank).

    Returns (flat state, args dict, checkpoint_version)."""
    import torch

    d = str(ckpt_dir)
    candidates = [d]
    for sub in ("mp_rank_00",):
        candidates.append(os.path.join(d, sub))
    path = None
    for c in candidates:
        for name in ("model_optim_rng.pt", "model_rng.pt", "model.pt"):
            p = os.path.join(c, name)
            if os.path.exists(p):
                path = p
                break
        if path:
            break
    if path is None:
        raise FileNotFoundError(f"no Megatron checkpoint under {d}")
    blob = torch.load(path, map_location="cpu", weights_only=False)
    model = blob.get("model", blob)
    lm = model.get("language_model", model)
    flat: Dict[str, np.ndarray] = {}
    _flatten("", lm, flat)
    args = blob.get("args")
    args = vars(args) if args is not None and not isinstance(args, dict) else (args or {})
    version = float(blob.get("checkpoint_version", 0))
    return flat, args, version


def megatron_config(args: Dict[str, Any]):
    """Megatron args -> native TransformerConfig (GPT-2 architecture)."""
    from ..models.transformer import TransformerConfig

    return TransformerConfig(
        vocab_size=args["padded_vocab_size"],
        d_model=args["hidden_size"],
        n_layers=args["num_layers"],
        n_heads=args["num_attention_heads"],
        n_kv_heads=args["num_attention_heads"],
        d_ff=args.get("ffn_hidden_size", 4 * args["hidden_size"]),
        max_seq_len=args["max_position_embeddings"],
        norm="layer", activation="gelu", position="learned",
        tie_embeddings=True, use_bias=True,
        norm_eps=args.get("layernorm_epsilon", 1e-5))


def _deinterleave_qkv(x: np.ndarray, n_heads: int) -> np.ndarray:
    """megatron_v2 fused qkv rows [heads, 3, hd] -> flat [3, heads, hd]
    (reference features/megatron.py:16 _align_qkv_transposed, numpy form).
    Works for [3h, ...] weights and [3h] biases."""
    three_h = x.shape[0]
    hd = three_h // n_heads // 3
    grouped = x.reshape(n_heads, 3, hd, *x.shape[1:])
    return np.concatenate([grouped[:, i] for i in range(3)],
                          axis=0).reshape(x.shape)


def map_megatron_gpt(state: Dict[str, np.ndarray], c,
                     checkpoint_version: float = 3.0,
                     skip_dense_mlp: bool = False) -> Dict[str, Any]:
    """Flat Megatron language_model state -> native stacked pytree.

    ``skip_dense_mlp``: MoE checkpoints have no per-layer dense FFN keys
    (map_megatron_gpt_moe fills the expert bank instead)."""
    n = c.n_layers
    # keys may carry the 'transformer.' (classic) or 'encoder.' prefix
    pre = "transformer."
    if not any(k.startswith(pre) for k in state):
        pre = "encoder."
    L = pre + "layers.{}."

    def qkv(fmt, is_bias):
        arrs = []
        for i in range(n):
            x = state.pop(fmt.format(i))
            if checkpoint_version >= 2.0:
                x = _deinterleave_qkv(x, c.n_heads)
            arrs.append(x if is_bias else x.T)  # Linear [out,in] -> [in,out]
        return np.stack(arrs)

    qkv_w = qkv(L + "attention.query_key_value.weight", False)
    qkv_b = qkv(L + "attention.query_key_value.bias", True)
    d = c.d_model
    wq, wk, wv = qkv_w[:, :, :d], qkv_w[:, :, d:2 * d], qkv_w[:, :, 2 * d:]
    bq, bk, bv = qkv_b[:, :d], qkv_b[:, d:2 * d], qkv_b[:, 2 * d:]

    def stack(fmt, transpose=False):
        arrs = [state.pop(fmt.format(i)) for i in range(n)]
        return np.stack([a.T for a in arrs] if transpose else arrs)

    layers = {
        "attn_norm_w": stack(L + "input_layernorm.weight"),
        "attn_norm_b": stack(L + "input_layernorm.bias"),
        "wq": wq, "wk": wk, "wv": wv, "bq": bq, "bk": bk, "bv": bv,
        "wo": stack(L + "attention.dense.weight", transpose=True),
        "bo": stack(L + "attention.dense.bias"),
        "mlp_norm_w": stack(L + "post_attention_layernorm.weight"),
        "mlp_norm_b": stack(L + "post_attention_layernorm.bias"),
    }
    if not skip_dense_mlp:
        layers.update({
            "w_up": stack(L + "mlp.dense_h_to_4h.weight", transpose=True),
            "b_up": stack(L + "mlp.dense_h_to_4h.bias"),
            "w_down": stack(L + "mlp.dense_4h_to_h.weight", transpose=True),
            "b_down": stack(L + "mlp.dense_4h_to_h.bias"),
        })
    return {
        "tok_embed": state["embedding.word_embeddings.weight"],
        "pos_embed": state["embedding.position_embeddings.weight"],
        "layers": layers,
        "final_norm_w": state[pre + "final_layernorm.weight"],
        "final_norm_b": state[pre + "final_layernorm.bias"],
    }


def from_megatron(ckpt_dir: str, dtype=None, topology=None):
    """(model, params) from a Megatron-LM GPT checkpoint directory —
    the Megatron analog of checkpoint.from_pretrained."""
    import jax
    import jax.numpy as jnp

    from ..models.transformer import Transformer

    state, args, version = read_megatron_state(ckpt_dir)
    cfg = megatron_config(args)
    model = Transformer(cfg)
    params = map_megatron_gpt(state, cfg, checkpoint_version=version)
    dtype = dtype or jnp.float32
    params = jax.tree_util.tree_map(
        lambda x: jnp.asarray(x, dtype), params)
    if topology is not None:
        model.bind_topology(topology)
    return model, params


# ----------------------------------------------------------------------
# Megatron-DeepSpeed MoE (reference module_inject/containers/
# megatron_gpt_moe.py — experts live at
# mlp.deepspeed_moe.experts.deepspeed_experts.<e>.dense_{h_to_4h,4h_to_h})

def _moe_layer_experts(state, L, i):
    pre = L.format(i) + "mlp.deepspeed_moe."
    es = []
    e = 0
    while f"{pre}experts.deepspeed_experts.{e}.dense_h_to_4h.weight" in state:
        es.append(e)
        e += 1
    return pre, es


def map_megatron_gpt_moe(state: Dict[str, np.ndarray], c,
                         checkpoint_version: float = 3.0) -> Dict[str, Any]:
    """Megatron-DeepSpeed MoE GPT -> native MoETransformer pytree.

    Requires every layer to carry a deepspeed_moe FFN (the uniform-MoE
    configuration); mixed dense/MoE stacks raise loudly rather than
    silently mis-mapping."""
    params = map_megatron_gpt(state, c, checkpoint_version,
                              skip_dense_mlp=True)
    n = c.n_layers
    pre = "transformer." if any(k.startswith("transformer.") for k in state) \
        else "encoder."
    L = pre + "layers.{}."

    wg, w_up, b_up, w_down, b_down = [], [], [], [], []
    for i in range(n):
        moe_pre, experts = _moe_layer_experts(state, L, i)
        if not experts:
            raise NotImplementedError(
                f"layer {i} has no deepspeed_moe experts — mixed dense/MoE "
                "Megatron stacks are not supported (uniform MoE only)")
        wg.append(state.pop(moe_pre + "gate.wg.weight").T)
        ups, bus, downs, bds = [], [], [], []
        for e in experts:
            ep = f"{moe_pre}experts.deepspeed_experts.{e}."
            ups.append(state.pop(ep + "dense_h_to_4h.weight").T)
            bus.append(state.pop(ep + "dense_h_to_4h.bias"))
            downs.append(state.pop(ep + "dense_4h_to_h.weight").T)
            bds.append(state.pop(ep + "dense_4h_to_h.bias"))
        w_up.append(np.stack(ups))
        b_up.append(np.stack(bus))
        w_down.append(np.stack(downs))
        b_down.append(np.stack(bds))
    layers = params["layers"]
    # the dense FFN slots are replaced by the expert bank
    for k in ("w_up", "b_up", "w_down", "b_down"):
        layers.pop(k, None)
    layers.update({"wg": np.stack(wg), "w_up": np.stack(w_up),
                   "b_up": np.stack(b_up), "w_down": np.stack(w_down),
                   "b_down": np.stack(b_down)})
    return params


def from_megatron_moe(ckpt_dir: str, dtype=None, topology=None):
    """(MoETransformer, params) from a Megatron-DeepSpeed MoE checkpoint."""
    import jax
    import jax.numpy as jnp

    from ..models.moe import MoETransformer, MoETransformerConfig

    state, args, version = read_megatron_state(ckpt_dir)
    base = megatron_config(args)
    pre = "transformer." if any(k.startswith("transformer.") for k in state) \
        else "encoder."
    _, experts = _moe_layer_experts(state, pre + "layers.{}.", 0)
    if not experts:
        raise ValueError(f"no deepspeed_moe experts found under {ckpt_dir}")
    cfg = MoETransformerConfig(
        vocab_size=base.vocab_size, d_model=base.d_model,
        n_layers=base.n_layers, n_heads=base.n_heads,
        n_kv_heads=base.n_kv_heads, d_ff=base.d_ff,
        max_seq_len=base.max_seq_len, norm="layer", activation="gelu",
        position="learned", tie_embeddings=True, use_bias=True,
        norm_eps=base.norm_eps,
        n_experts=int(args.get("num_experts", len(experts))
                      if not isinstance(args.get("num_experts"), list)
                      else args["num_experts"][0]),
        top_k=int(args.get("topk", 1)))
    model = MoETransformer(cfg)
    params = map_megatron_gpt_moe(state, cfg, checkpoint_version=version)
    dtype = dtype or jnp.float32
    params = jax.tree_util.tree_map(lambda x: jnp.asarray(x, dtype), params)
    if topology is not None:
        model.bind_topology(topology)
    return model, params
