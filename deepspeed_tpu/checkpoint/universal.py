"""Universal-checkpoint tooling (CLI).

Reference surface: ``deepspeed/checkpoint/ds_to_universal.py:286`` (convert
a sharded ZeRO checkpoint into topology-free per-param files) and
``deepspeed/utils/zero_to_fp32.py`` (offline consolidation of ZeRO shards
into a plain fp32 state dict).

The native checkpoint layout is ALREADY topology-independent — every leaf
is stored as a full logical array (runtime/checkpoint.py), so no shard
merging is needed. These tools exist for the same downstream uses as the
reference's:

* ``to-universal`` — explode a checkpoint into one ``.npy`` file per param
  plus ``universal_index.json`` (framework-free consumption, surgical
  editing, partial loads);
* ``zero-to-fp32`` — one ``.npz`` with every param consolidated to fp32
  (drop-in for the reference's ``zero_to_fp32.py`` output).

Usage:
    python -m deepspeed_tpu.checkpoint.universal to-universal CKPT_DIR OUT_DIR [--tag TAG]
    python -m deepspeed_tpu.checkpoint.universal zero-to-fp32 CKPT_DIR OUT_FILE [--tag TAG]
"""

from __future__ import annotations

import argparse
import json
import os
import re
from typing import Any, Dict, Optional

import numpy as np


def _load_state(ckpt_dir: str, tag: Optional[str] = None) -> Dict[str, Any]:
    import orbax.checkpoint as ocp

    if tag is None:
        latest = os.path.join(ckpt_dir, "latest")
        if not os.path.isfile(latest):
            raise FileNotFoundError(f"no 'latest' pointer in {ckpt_dir}; pass --tag")
        with open(latest) as f:
            tag = f.read().strip()
    state_path = os.path.join(ckpt_dir, str(tag), "state")
    if not os.path.isdir(state_path):
        raise FileNotFoundError(f"checkpoint state dir not found: {state_path}")
    restored = ocp.StandardCheckpointer().restore(os.path.abspath(state_path))
    return restored


def _flat_params(state: Dict[str, Any]) -> Dict[str, np.ndarray]:
    import jax

    params = state.get("params", state)
    leaves, _ = jax.tree_util.tree_flatten_with_path(params)
    out = {}
    for path, leaf in leaves:
        key = jax.tree_util.keystr(path)
        key = re.sub(r"[^A-Za-z0-9_.]+", ".", key).strip(".")
        out[key] = np.asarray(leaf)
    return out


def _write_universal(flat, out_dir: str, source: Optional[str] = None) -> str:
    """Shared explode-to-universal writer (per-param .npy + index)."""
    os.makedirs(out_dir, exist_ok=True)
    index = {}
    for key, arr in flat.items():
        fname = f"{key}.npy"
        np.save(os.path.join(out_dir, fname), arr)
        index[key] = {"file": fname, "shape": list(arr.shape),
                      "dtype": str(arr.dtype)}
    meta = {"version": 1, "params": index}
    if source:
        meta["source"] = source
    with open(os.path.join(out_dir, "universal_index.json"), "w") as f:
        json.dump(meta, f, indent=2)
    return out_dir


def to_universal(ckpt_dir: str, out_dir: str, tag: Optional[str] = None) -> str:
    """Explode a checkpoint into per-param .npy files + an index
    (reference ds_to_universal.py:286 main)."""
    return _write_universal(_flat_params(_load_state(ckpt_dir, tag)), out_dir)


def zero_to_fp32(ckpt_dir: str, out_file: str, tag: Optional[str] = None) -> str:
    """Consolidate every param to fp32 in one .npz (reference
    utils/zero_to_fp32.py convert_zero_checkpoint_to_fp32_state_dict)."""
    flat = _flat_params(_load_state(ckpt_dir, tag))
    fp32 = {k: np.asarray(v, np.float32) for k, v in flat.items()}
    os.makedirs(os.path.dirname(os.path.abspath(out_file)), exist_ok=True)
    np.savez(out_file, **fp32)
    return out_file


def megatron_to_universal(megatron_dir: str, out_dir: str) -> str:
    """Megatron-LM GPT checkpoint -> universal layout (the reference's
    ds_to_universal path also reshapes Megatron checkpoints). Dense and
    deepspeed_moe checkpoints both supported; the exploded params use the
    NATIVE stacked naming, so any mesh/stage can consume them. One
    checkpoint read: the blob is loaded once and mapped directly."""
    from .megatron import (map_megatron_gpt, map_megatron_gpt_moe,
                           megatron_config, read_megatron_state)

    state, args, version = read_megatron_state(megatron_dir)
    moe = any(".deepspeed_moe." in k for k in state)
    if moe:
        from ..models.moe import MoETransformerConfig

        base = megatron_config(args)
        n_exp = args.get("num_experts", 0)
        n_exp = n_exp[0] if isinstance(n_exp, list) else n_exp
        cfg = MoETransformerConfig(
            vocab_size=base.vocab_size, d_model=base.d_model,
            n_layers=base.n_layers, n_heads=base.n_heads,
            n_kv_heads=base.n_kv_heads, d_ff=base.d_ff,
            max_seq_len=base.max_seq_len, norm="layer", activation="gelu",
            position="learned", tie_embeddings=True, use_bias=True,
            norm_eps=base.norm_eps, n_experts=int(n_exp) or 1,
            top_k=int(args.get("topk", 1)))
        params = map_megatron_gpt_moe(state, cfg, checkpoint_version=version)
    else:
        params = map_megatron_gpt(state, megatron_config(args),
                                  checkpoint_version=version)
    flat = _flat_params({"params": params})
    flat = {k[len("params."):] if k.startswith("params.") else k: v
            for k, v in flat.items()}
    return _write_universal(flat, out_dir, source="megatron")


def load_universal(universal_dir: str) -> Dict[str, np.ndarray]:
    """Read a to-universal directory back into a flat {key: array} dict."""
    with open(os.path.join(universal_dir, "universal_index.json")) as f:
        index = json.load(f)["params"]
    return {k: np.load(os.path.join(universal_dir, meta["file"]))
            for k, meta in index.items()}


def main(argv=None) -> int:
    p = argparse.ArgumentParser(prog="deepspeed_tpu.checkpoint.universal",
                                description=__doc__)
    sub = p.add_subparsers(dest="cmd", required=True)
    pu = sub.add_parser("to-universal")
    pu.add_argument("ckpt_dir")
    pu.add_argument("out_dir")
    pu.add_argument("--tag", default=None)
    pf = sub.add_parser("zero-to-fp32")
    pf.add_argument("ckpt_dir")
    pf.add_argument("out_file")
    pf.add_argument("--tag", default=None)
    pm = sub.add_parser("from-megatron")
    pm.add_argument("megatron_dir")
    pm.add_argument("out_dir")
    args = p.parse_args(argv)
    if args.cmd == "to-universal":
        out = to_universal(args.ckpt_dir, args.out_dir, args.tag)
    elif args.cmd == "from-megatron":
        out = megatron_to_universal(args.megatron_dir, args.out_dir)
    else:
        out = zero_to_fp32(args.ckpt_dir, args.out_file, args.tag)
    print(out)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
