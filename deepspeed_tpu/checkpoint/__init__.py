"""Checkpoint tooling (reference ``deepspeed/checkpoint/``): HF pretrained
ingestion, universal-checkpoint conversion surface."""

from .hf import from_pretrained, hf_config, map_hf_params, read_hf_state  # noqa: F401
