"""Checkpoint tooling (reference ``deepspeed/checkpoint/``): HF pretrained
ingestion, Megatron-LM GPT ingestion, diffusers UNet/VAE ingestion,
universal-checkpoint conversion surface."""

from .hf import from_pretrained, hf_config, map_hf_params, read_hf_state  # noqa: F401
from .megatron import from_megatron  # noqa: F401
from .diffusers import load_unet, load_vae  # noqa: F401
from .export import (export_hf_gpt2, export_hf_llama,  # noqa: F401
                     export_hf_mixtral)
