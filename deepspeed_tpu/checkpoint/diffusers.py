"""Diffusers checkpoint ingestion: UNet2DConditionModel / AutoencoderKL.

Parity target: the reference's diffusers injection policies read weights
off live torch modules (``module_inject/containers/unet.py:34`` pulls
to_q/to_k/to_v/to_out per attention, ``vae.py``); here the diffusers
state-dict (``diffusion_pytorch_model.safetensors`` /``.bin``) is mapped
once into the native NHWC pytree of
:class:`deepspeed_tpu.models.diffusion.UNet2DCondition` /
:class:`~deepspeed_tpu.models.diffusion.AutoencoderKL`.

Layout rules (torch -> TPU-native):
  Conv2d   OIHW  -> HWIO   (transpose 2,3,1,0)
  Linear   [o,i] -> [i,o]  (transpose)
  Norm     weight -> scale
plus naming reconciliation: ``transformer_blocks``->``blocks``,
``to_out.0``->``to_out``, GEGLU ``ff.net.0.proj``/``ff.net.2``->
``ff.proj``/``ff.out``, and the pre-0.13 VAE attention names
(``query/key/value/proj_attn``)->(``to_q/to_k/to_v/to_out``). Linear
proj_in/proj_out (SD2 ``use_linear_projection``) are reshaped to 1x1
convs so one forward serves both variants.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Tuple

import numpy as np

from .hf import read_hf_state, _read_one  # shared tensor readers

__all__ = ["map_diffusers_unet", "map_diffusers_vae", "unet_config",
           "vae_config", "read_diffusers_state", "load_unet", "load_vae"]


def read_diffusers_state(model_dir: str) -> Dict[str, np.ndarray]:
    d = str(model_dir)
    for name in ("diffusion_pytorch_model.safetensors",
                 "diffusion_pytorch_model.bin"):
        path = os.path.join(d, name)
        if os.path.exists(path):
            return _read_one(path)
    return read_hf_state(d)


# -- name/layout normalization -----------------------------------------

_RENAME = {"transformer_blocks": "blocks", "query": "to_q", "key": "to_k",
           "value": "to_v", "proj_attn": "to_out"}


def _tokens(key: str):
    toks = key.split(".")
    out = []
    i = 0
    while i < len(toks):
        t = toks[i]
        if t == "to_out" and i + 1 < len(toks) and toks[i + 1] == "0":
            out.append("to_out")
            i += 2
            continue
        if t == "ff" and i + 2 < len(toks) and toks[i + 1] == "net":
            # ff.net.0.proj.* -> ff.proj.*   ff.net.2.* -> ff.out.*
            out.append("ff")
            if toks[i + 2] == "0":
                out.append("proj")
                i += 4
            else:
                out.append("out")
                i += 3
            continue
        out.append(_RENAME.get(t, t))
        i += 1
    return out


def _leaf(name: str, t: np.ndarray, conv_ctx: bool) -> Tuple[str, np.ndarray]:
    if name == "weight":
        if t.ndim == 4:                       # Conv2d OIHW -> HWIO
            return "kernel", np.transpose(t, (2, 3, 1, 0))
        if t.ndim == 2:
            if conv_ctx:                      # linear proj_in/out -> 1x1 conv
                return "kernel", np.transpose(t)[None, None, :, :]
            return "kernel", np.transpose(t)
        return "scale", t                     # norm weight
    return name, t


def _insert(tree: Dict[str, Any], toks, value):
    node = tree
    for i, t in enumerate(toks[:-1]):
        nxt_is_idx = toks[i + 1].isdigit() if i + 1 < len(toks) else False
        if t.isdigit():
            idx = int(t)
            while len(node) <= idx:
                node.append({})
            node = node[idx]
        else:
            if t not in node:
                node[t] = [] if nxt_is_idx else {}
            node = node[t]
    last = toks[-1]
    if last.isdigit():
        raise ValueError(f"unexpected trailing index in {toks}")
    node[last] = value


def _map_state(state: Dict[str, np.ndarray]) -> Dict[str, Any]:
    tree: Dict[str, Any] = {}
    for key, t in state.items():
        toks = _tokens(key)
        conv_ctx = any(x in ("proj_in", "proj_out") for x in toks)
        name, val = _leaf(toks[-1], np.asarray(t), conv_ctx)
        _insert(tree, toks[:-1] + [name], val)
    return tree


def _ensure_attn_lists(tree: Dict[str, Any]) -> None:
    """Blocks without attentions need the empty list the forward checks."""
    for blocks in ("down_blocks", "up_blocks"):
        for blk in tree.get(blocks, []):
            blk.setdefault("attentions", [])
            blk.setdefault("resnets", [])


def map_diffusers_unet(state: Dict[str, np.ndarray]) -> Dict[str, Any]:
    tree = _map_state(state)
    _ensure_attn_lists(tree)
    return tree


def map_diffusers_vae(state: Dict[str, np.ndarray]) -> Dict[str, Any]:
    tree = _map_state(state)
    for side in ("encoder", "decoder"):
        sub = tree.get(side, {})
        for blk in sub.get("down_blocks", []) + sub.get("up_blocks", []):
            blk.setdefault("resnets", [])
    return tree


# -- config --------------------------------------------------------------

def unet_config(model_dir: str):
    from ..models.diffusion import UNetConfig

    with open(os.path.join(str(model_dir), "config.json")) as f:
        hc = json.load(f)
    ahd = hc.get("attention_head_dim", 8)
    return UNetConfig(
        sample_size=hc.get("sample_size", 64),
        in_channels=hc.get("in_channels", 4),
        out_channels=hc.get("out_channels", 4),
        block_out_channels=tuple(hc.get("block_out_channels", (320, 640, 1280, 1280))),
        layers_per_block=hc.get("layers_per_block", 2),
        cross_attention_dim=hc.get("cross_attention_dim", 768),
        attention_head_dim=tuple(ahd) if isinstance(ahd, list) else ahd,
        down_block_types=tuple(hc.get("down_block_types", ())) or
            ("CrossAttnDownBlock2D",) * 3 + ("DownBlock2D",),
        up_block_types=tuple(hc.get("up_block_types", ())) or
            ("UpBlock2D",) + ("CrossAttnUpBlock2D",) * 3,
        norm_num_groups=hc.get("norm_num_groups", 32),
    )


def vae_config(model_dir: str):
    from ..models.diffusion import VAEConfig

    with open(os.path.join(str(model_dir), "config.json")) as f:
        hc = json.load(f)
    return VAEConfig(
        in_channels=hc.get("in_channels", 3),
        out_channels=hc.get("out_channels", 3),
        latent_channels=hc.get("latent_channels", 4),
        block_out_channels=tuple(hc.get("block_out_channels", (128, 256, 512, 512))),
        layers_per_block=hc.get("layers_per_block", 2),
        norm_num_groups=hc.get("norm_num_groups", 32),
        scaling_factor=hc.get("scaling_factor", 0.18215),
    )


def load_unet(model_dir: str):
    """(UNet2DCondition, params) from a diffusers unet/ directory."""
    from ..models.diffusion import UNet2DCondition

    cfg = unet_config(model_dir)
    params = map_diffusers_unet(read_diffusers_state(model_dir))
    return UNet2DCondition(cfg), params


def load_vae(model_dir: str):
    from ..models.diffusion import AutoencoderKL

    cfg = vae_config(model_dir)
    params = map_diffusers_vae(read_diffusers_state(model_dir))
    return AutoencoderKL(cfg), params
