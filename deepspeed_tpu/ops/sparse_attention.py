"""Block-sparse attention.

Reference surface: ``deepspeed/ops/sparse_attention/`` — the
``SparsityConfig`` family (``sparsity_config.py``: Dense, Fixed, Variable,
BigBird, BSLongformer, LocalSlidingWindow), the blocked Triton matmul /
softmax kernels (``matmul.py``, ``softmax.py``), and ``SparseSelfAttention``
(``sparse_self_attention.py``).

TPU-first redesign: the reference's hand-written Triton SDD/DSD kernels
become a *gather-then-dense* formulation that XLA maps straight onto the
MXU. A sparsity layout is a boolean ``[heads, nq_blocks, nk_blocks]``
matrix (same abstraction as the reference's ``make_layout``); each q-block
row is padded to the max active-block count A, the active K/V blocks are
gathered with ``take_along_axis`` (memory ∝ active blocks only), and one
dense blocked attention runs over ``[.., nq, block, A*block]`` scores.
FLOPs and HBM traffic scale with the layout's density — the same saving
the Triton kernels buy — with zero custom-kernel lowering risk, and the
blocked einsums are exactly the shapes the MXU wants.
"""

from __future__ import annotations

from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


# ----------------------------------------------------------------------
# sparsity configs (reference sparsity_config.py vocabulary)

class SparsityConfig:
    """Base: a layout is bool [num_heads, nq_blocks, nk_blocks]."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False):
        self.num_heads = num_heads
        self.block = block
        self.different_layout_per_head = different_layout_per_head

    def setup_layout(self, seq_len: int) -> np.ndarray:
        if seq_len % self.block != 0:
            raise ValueError(f"seq_len {seq_len} not divisible by block {self.block}")
        n = seq_len // self.block
        return np.zeros((self.num_heads, n, n), dtype=bool)

    def make_layout(self, seq_len: int) -> np.ndarray:
        raise NotImplementedError

    def _finalize(self, layout: np.ndarray, attention: str) -> np.ndarray:
        if attention == "unidirectional":
            n = layout.shape[1]
            layout &= np.tril(np.ones((n, n), dtype=bool))[None]
        return layout


class DenseSparsityConfig(SparsityConfig):
    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        layout[:] = True
        return self._finalize(layout, self.attention)


class FixedSparsityConfig(SparsityConfig):
    """Sparse-Transformers fixed pattern (arXiv:1904.10509): block-local
    windows of ``num_local_blocks``; the last ``num_global_blocks`` of each
    window are global columns (everyone attends to them), optionally
    global rows too (``horizontal_global_attention``)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_local_blocks: int = 4, num_global_blocks: int = 1,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False,
                 num_different_global_patterns: int = 1):
        super().__init__(num_heads, block, different_layout_per_head)
        if num_local_blocks % num_global_blocks:
            raise ValueError("num_global_blocks must divide num_local_blocks")
        self.num_local_blocks = num_local_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention
        self.num_different_global_patterns = (
            num_different_global_patterns if different_layout_per_head else 1)

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        L, G = self.num_local_blocks, self.num_global_blocks
        for start in range(0, n, L):
            end = min(start + L, n)
            layout[:, start:end, start:end] = True
        for h in range(self.num_heads):
            # head-dependent choice of which sub-block of each window is
            # the global representative (num_different_global_patterns)
            pat = h % max(1, self.num_different_global_patterns)
            first = max(0, L - (pat + 1) * G)
            cols = np.concatenate(
                [np.arange(s + first, min(s + first + G, n))
                 for s in range(0, n, L)])
            cols = cols[cols < n]
            layout[h, :, cols] = True
            if self.horizontal_global_attention and self.attention == "bidirectional":
                layout[h, cols, :] = True
        return self._finalize(layout, self.attention)


class VariableSparsityConfig(SparsityConfig):
    """Per-window variable local sizes + explicit global block indices
    (reference sparsity_config.py:239)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 0,
                 local_window_blocks: Optional[List[int]] = None,
                 global_block_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional",
                 horizontal_global_attention: bool = False):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.local_window_blocks = local_window_blocks or [4]
        self.global_block_indices = global_block_indices or [0]
        self.attention = attention
        self.horizontal_global_attention = horizontal_global_attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        start = 0
        i = 0
        while start < n:
            w = self.local_window_blocks[min(i, len(self.local_window_blocks) - 1)]
            end = min(start + w, n)
            layout[:, start:end, start:end] = True
            start, i = end, i + 1
        cols = [c for c in self.global_block_indices if c < n]
        layout[:, :, cols] = True
        if self.horizontal_global_attention and self.attention == "bidirectional":
            layout[:, cols, :] = True
        if self.num_random_blocks:
            rng = np.random.default_rng(0)
            for h in range(self.num_heads):
                hh = h if self.different_layout_per_head else 0
                r = np.random.default_rng(hh)
                for qb in range(n):
                    picks = r.choice(n, size=min(self.num_random_blocks, n),
                                     replace=False)
                    layout[h, qb, picks] = True
        return self._finalize(layout, self.attention)


class BigBirdSparsityConfig(SparsityConfig):
    """BigBird (arXiv:2007.14062): sliding window + global first/last
    blocks + per-row random blocks (reference sparsity_config.py:411)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_random_blocks: int = 1,
                 num_sliding_window_blocks: int = 3,
                 num_global_blocks: int = 1,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_random_blocks = num_random_blocks
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.num_global_blocks = num_global_blocks
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for qb in range(n):
            layout[:, qb, max(0, qb - w):min(n, qb + w + 1)] = True
        g = min(self.num_global_blocks, n)
        layout[:, :, :g] = True
        layout[:, :g, :] = True
        if self.attention == "bidirectional":
            layout[:, :, n - g:] = True
            layout[:, n - g:, :] = True
        for h in range(self.num_heads):
            hh = h if self.different_layout_per_head else 0
            r = np.random.default_rng(hh)
            for qb in range(n):
                picks = r.choice(n, size=min(self.num_random_blocks, n),
                                 replace=False)
                layout[h, qb, picks] = True
        return self._finalize(layout, self.attention)


class BSLongformerSparsityConfig(SparsityConfig):
    """Blocked Longformer: sliding window + listed global blocks
    (reference sparsity_config.py:546)."""

    def __init__(self, num_heads: int, block: int = 16,
                 different_layout_per_head: bool = False,
                 num_sliding_window_blocks: int = 3,
                 global_block_indices: Optional[List[int]] = None,
                 attention: str = "bidirectional"):
        super().__init__(num_heads, block, different_layout_per_head)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.global_block_indices = global_block_indices or [0]
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for qb in range(n):
            layout[:, qb, max(0, qb - w):min(n, qb + w + 1)] = True
        cols = [c for c in self.global_block_indices if c < n]
        layout[:, :, cols] = True
        layout[:, cols, :] = True
        return self._finalize(layout, self.attention)


class LocalSlidingWindowSparsityConfig(SparsityConfig):
    """Pure sliding window (reference sparsity_config.py:674)."""

    def __init__(self, num_heads: int, block: int = 16,
                 num_sliding_window_blocks: int = 3,
                 attention: str = "unidirectional"):
        super().__init__(num_heads, block, False)
        self.num_sliding_window_blocks = num_sliding_window_blocks
        self.attention = attention

    def make_layout(self, seq_len: int) -> np.ndarray:
        layout = self.setup_layout(seq_len)
        n = layout.shape[1]
        w = self.num_sliding_window_blocks // 2
        for qb in range(n):
            layout[:, qb, max(0, qb - w):min(n, qb + w + 1)] = True
        return self._finalize(layout, self.attention)


# ----------------------------------------------------------------------
# blocked sparse attention (reference matmul.py SDD/DSD + softmax.py fused)

def _layout_to_indices(layout: np.ndarray) -> Tuple[np.ndarray, np.ndarray]:
    """[h, nq, nk] bool -> (idx [h, nq, A] int32, valid [h, nq, A] bool)
    where A = max active k-blocks over all (h, q) rows."""
    h, nq, nk = layout.shape
    counts = layout.sum(-1)
    A = max(1, int(counts.max()))
    idx = np.zeros((h, nq, A), np.int32)
    valid = np.zeros((h, nq, A), bool)
    for i in range(h):
        for q in range(nq):
            cols = np.nonzero(layout[i, q])[0]
            idx[i, q, :len(cols)] = cols
            valid[i, q, :len(cols)] = True
    return idx, valid


def sparse_attention(q, k, v, layout: np.ndarray, block: int,
                     causal: bool = False,
                     scale: Optional[float] = None) -> jnp.ndarray:
    """q/k/v: [b, s, h, d]; layout: bool [h or 1, s//block, s//block].
    Returns [b, s, h, d]. Compute/memory scale with layout density."""
    b, s, h, d = q.shape
    nq = s // block
    if layout.shape[0] == 1:
        layout = np.broadcast_to(layout, (h, *layout.shape[1:]))
    idx_np, valid_np = _layout_to_indices(np.asarray(layout, bool))
    A = idx_np.shape[-1]
    idx = jnp.asarray(idx_np)            # [h, nq, A]
    valid = jnp.asarray(valid_np)
    scale = scale if scale is not None else 1.0 / np.sqrt(d)

    qb = q.transpose(0, 2, 1, 3).reshape(b, h, nq, block, d)
    kb = k.transpose(0, 2, 1, 3).reshape(b, h, nq, block, d)
    vb = v.transpose(0, 2, 1, 3).reshape(b, h, nq, block, d)

    # gather active K/V blocks per (h, q-block): [b, h, nq, A, block, d]
    kg = jnp.take_along_axis(kb[:, :, None], idx[None, :, :, :, None, None],
                             axis=3)
    vg = jnp.take_along_axis(vb[:, :, None], idx[None, :, :, :, None, None],
                             axis=3)

    scores = jnp.einsum("bhqid,bhqajd->bhqiaj", qb, kg,
                        preferred_element_type=jnp.float32) * scale
    # scores: [b, h, nq, i, A, j]; mask padding lanes (and causality) out
    if causal:
        q_pos = (jnp.arange(nq)[:, None] * block
                 + jnp.arange(block)[None, :])                 # [nq, i]
        k_pos = (idx[..., None] * block
                 + jnp.arange(block)[None, None, None, :])     # [h, nq, A, j]
        causal_m = (q_pos[None, :, :, None, None]              # [1,nq,i,1,1]
                    >= k_pos[:, :, None, :, :])                # [h,nq,1,A,j]
        full_m = valid[:, :, None, :, None] & causal_m         # [h,nq,i,A,j]
        scores = jnp.where(full_m[None], scores, NEG_INF)
    else:
        scores = jnp.where(valid[None, :, :, None, :, None], scores, NEG_INF)
    flat = scores.reshape(b, h, nq, block, A * block)
    probs = jax.nn.softmax(flat, axis=-1)
    # fully-masked rows (causal + sparse row with nothing visible): zero out
    all_masked = jnp.all(flat <= NEG_INF / 2, axis=-1, keepdims=True)
    probs = jnp.where(all_masked, 0.0, probs)
    probs = probs.reshape(b, h, nq, block, A, block).astype(q.dtype)
    out = jnp.einsum("bhqiaj,bhqajd->bhqid", probs, vg)
    return out.reshape(b, h, s, d).transpose(0, 2, 1, 3)


def dense_reference(q, k, v, layout: np.ndarray, block: int,
                    causal: bool = False,
                    scale: Optional[float] = None) -> jnp.ndarray:
    """Numerics oracle: dense attention with the layout expanded to an
    element mask."""
    b, s, h, d = q.shape
    if layout.shape[0] == 1:
        layout = np.broadcast_to(layout, (h, *layout.shape[1:]))
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    el = np.kron(np.asarray(layout, np.float32),
                 np.ones((block, block), np.float32)).astype(bool)  # [h,s,s]
    if causal:
        el = el & np.tril(np.ones((s, s), dtype=bool))[None]
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(jnp.float32) * scale
    logits = jnp.where(jnp.asarray(el)[None], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    probs = jnp.where(jnp.all(logits <= NEG_INF / 2, axis=-1, keepdims=True),
                      0.0, probs)
    return jnp.einsum("bhqk,bkhd->bqhd", probs.astype(q.dtype), v)


class SparseSelfAttention:
    """Reference ``SparseSelfAttention`` parity: holds a SparsityConfig and
    applies block-sparse attention to [b, s, h, d] tensors."""

    def __init__(self, sparsity_config: SparsityConfig,
                 causal: Optional[bool] = None):
        self.config = sparsity_config
        self.causal = (causal if causal is not None
                       else getattr(sparsity_config, "attention",
                                    "bidirectional") == "unidirectional")
        self._layouts = {}

    def layout(self, seq_len: int) -> np.ndarray:
        if seq_len not in self._layouts:
            self._layouts[seq_len] = self.config.make_layout(seq_len)
        return self._layouts[seq_len]

    def __call__(self, q, k, v):
        return sparse_attention(q, k, v, self.layout(q.shape[1]),
                                self.config.block, causal=self.causal)


def pad_to_block_size(x, block: int, axis: int = 1):
    """SparseAttentionUtils.pad_to_block_size parity: right-pad the seq axis
    to a block multiple; returns (padded, pad_len)."""
    s = x.shape[axis]
    pad = (-s) % block
    if pad == 0:
        return x, 0
    widths = [(0, 0)] * x.ndim
    widths[axis] = (0, pad)
    return jnp.pad(x, widths), pad
