"""Rotary position embeddings.

Replaces the reference's CUDA rotary kernels
(``csrc/transformer/inference/csrc/apply_rotary_pos_emb.cu`` and FastGen's
``linear_blocked_kv_rotary``). Pure jnp: XLA fuses the sin/cos modulation
into the QK projection epilogue.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_len: int, theta: float = 10000.0):
    """Precompute [max_len, head_dim/2] angle table."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    return jnp.outer(t, inv_freq)  # [max_len, head_dim//2]


def apply_rotary(x, angles, positions=None, rotary_dim=None,
                 interleaved=False):
    """Apply RoPE. x: [..., seq, n_heads, head_dim]; angles:
    [max_len, rotary_dim/2]; positions: optional [..., seq] int32 (for
    KV-cache decode offsets).

    ``rotary_dim`` < head_dim rotates only the leading dims (GPT-NeoX
    ``rotary_pct``); ``interleaved`` uses the GPT-J pairing — (x[2i],
    x[2i+1]) rotate together — instead of the Llama/NeoX half-split."""
    if rotary_dim is not None and rotary_dim < x.shape[-1]:
        xr, xp = x[..., :rotary_dim], x[..., rotary_dim:]
        xr = apply_rotary(xr, angles, positions, interleaved=interleaved)
        return jnp.concatenate([xr, xp], axis=-1)
    if positions is None:
        seq = x.shape[-3]
        ang = angles[:seq]  # [seq, rd/2]
        ang = ang[(None,) * (x.ndim - 3) + (slice(None), None, slice(None))]
    else:
        ang = angles[positions]  # [..., seq, rd/2]
        ang = ang[..., None, :]  # broadcast over heads
    sin, cos = jnp.sin(ang), jnp.cos(ang)
    xf = x.astype(jnp.float32)
    if interleaved:
        x1, x2 = xf[..., 0::2], xf[..., 1::2]
        r1, r2 = x1 * cos - x2 * sin, x1 * sin + x2 * cos
        out = jnp.stack([r1, r2], axis=-1).reshape(x.shape)
    else:
        x1, x2 = jnp.split(xf, 2, axis=-1)
        out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos],
                              axis=-1)
    return out.astype(x.dtype)


def alibi_slopes(n_heads: int) -> jnp.ndarray:
    """ALiBi per-head slopes (Press et al. 2022; Bloom's position scheme —
    reference module_inject/containers/bloom.py consumes torch's
    build_alibi_tensor). Standard geometric construction incl. the
    non-power-of-two fixup."""
    import math

    def pow2_slopes(n):
        start = 2.0 ** (-(2.0 ** -(math.log2(n) - 3)))
        return [start * (start ** i) for i in range(n)]

    if math.log2(n_heads).is_integer():
        slopes = pow2_slopes(n_heads)
    else:
        base = 2 ** math.floor(math.log2(n_heads))
        slopes = pow2_slopes(base)
        extra = pow2_slopes(2 * base)[0::2][: n_heads - base]
        slopes += extra
    return jnp.asarray(slopes, jnp.float32)
