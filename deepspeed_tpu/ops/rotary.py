"""Rotary position embeddings.

Replaces the reference's CUDA rotary kernels
(``csrc/transformer/inference/csrc/apply_rotary_pos_emb.cu`` and FastGen's
``linear_blocked_kv_rotary``). Pure jnp: XLA fuses the sin/cos modulation
into the QK projection epilogue.
"""

from __future__ import annotations

import jax.numpy as jnp


def rope_frequencies(head_dim: int, max_len: int, theta: float = 10000.0):
    """Precompute [max_len, head_dim/2] angle table."""
    inv_freq = 1.0 / (theta ** (jnp.arange(0, head_dim, 2, dtype=jnp.float32) / head_dim))
    t = jnp.arange(max_len, dtype=jnp.float32)
    return jnp.outer(t, inv_freq)  # [max_len, head_dim//2]


def apply_rotary(x, angles, positions=None):
    """Apply RoPE. x: [..., seq, n_heads, head_dim]; angles: [max_len, hd/2];
    positions: optional [..., seq] int32 (for KV-cache decode offsets)."""
    if positions is None:
        seq = x.shape[-3]
        ang = angles[:seq]  # [seq, hd/2]
        ang = ang[(None,) * (x.ndim - 3) + (slice(None), None, slice(None))]
    else:
        ang = angles[positions]  # [..., seq, hd/2]
        ang = ang[..., None, :]  # broadcast over heads
    sin, cos = jnp.sin(ang), jnp.cos(ang)
    x1, x2 = jnp.split(x.astype(jnp.float32), 2, axis=-1)
    out = jnp.concatenate([x1 * cos - x2 * sin, x1 * sin + x2 * cos], axis=-1)
    return out.astype(x.dtype)
