"""Normalization ops.

Replaces the reference's fused CUDA norm kernels
(``csrc/transformer/inference/csrc/layer_norm.cu`` / ``rms_norm.cu`` and the
FastGen v2 ``cuda_layer_norm`` / ``cuda_rms_norm`` modules). On TPU these are
bandwidth-bound elementwise+reduction patterns that XLA fuses into the
surrounding matmul epilogue/prologue, so the jnp forms below compile to the
same fused program the reference hand-writes; a Pallas variant exists in
``ops/pallas/fused_norm.py`` for cases XLA can't fuse (quantized epilogues).
"""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x, weight, eps: float = 1e-6):
    """RMSNorm (pre-norm Llama style). fp32 accumulation regardless of input
    dtype, matching the reference kernels' internal float accumulators."""
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jnp.reciprocal(jnp.sqrt(var + eps))
    return (y * weight.astype(jnp.float32)).astype(x.dtype)


def layer_norm(x, weight, bias, eps: float = 1e-5):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    y = (x32 - mean) * jnp.reciprocal(jnp.sqrt(var + eps))
    return (y * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)
