"""Normalization ops.

Replaces the reference's fused CUDA norm kernels
(``csrc/transformer/inference/csrc/layer_norm.cu`` / ``rms_norm.cu`` and the
FastGen v2 ``cuda_layer_norm`` / ``cuda_rms_norm`` modules). On TPU these are
bandwidth-bound elementwise+reduction patterns that XLA fuses into the
surrounding matmul epilogue/prologue, so the jnp forms below compile to the
same fused program the reference hand-writes; a Pallas variant exists in
``ops/pallas/fused_norm.py`` for cases XLA can't fuse (quantized epilogues).
"""

from __future__ import annotations

import jax.numpy as jnp


def rms_norm(x, weight, eps: float = 1e-6):
    """RMSNorm (pre-norm Llama style). fp32 accumulation regardless of input
    dtype, matching the reference kernels' internal float accumulators."""
    x32 = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(x32), axis=-1, keepdims=True)
    y = x32 * jnp.reciprocal(jnp.sqrt(var + eps))
    return (y * weight.astype(jnp.float32)).astype(x.dtype)


def layer_norm(x, weight, bias, eps: float = 1e-5):
    x32 = x.astype(jnp.float32)
    mean = jnp.mean(x32, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=-1, keepdims=True)
    y = (x32 - mean) * jnp.reciprocal(jnp.sqrt(var + eps))
    return (y * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)


def group_norm(x, weight, bias, groups: int = 32, eps: float = 1e-5):
    """GroupNorm over NHWC feature maps (diffusion UNet/VAE blocks — the
    layout TPU convs prefer; the reference's spatial kernels operate NCHW,
    csrc/spatial/csrc/opt_bias_add.cu). Normalizes each channel group over
    (H, W, C/g) with fp32 accumulation."""
    assert x.ndim == 4, (
        f"group_norm expects NHWC rank-4 input, got shape {x.shape} — a "
        "lower rank would silently mix statistics across the batch dim")
    *lead, c = x.shape
    assert c % groups == 0, (c, groups)
    x32 = x.astype(jnp.float32).reshape(*lead[:-2], -1, groups, c // groups)
    # reduce over all spatial positions and the within-group channels
    red = tuple(range(x32.ndim - 3, x32.ndim - 2)) + (x32.ndim - 1,)
    mean = jnp.mean(x32, axis=red, keepdims=True)
    var = jnp.mean(jnp.square(x32 - mean), axis=red, keepdims=True)
    y = (x32 - mean) * jnp.reciprocal(jnp.sqrt(var + eps))
    y = y.reshape(x.shape)
    return (y * weight.astype(jnp.float32) + bias.astype(jnp.float32)).astype(x.dtype)
