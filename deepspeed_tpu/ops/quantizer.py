"""Blockwise quantization ops (int8 / int4, symmetric & asymmetric).

Subsumes the reference's quantization kernel family: ``csrc/quantization/``
(quantize.cu, dequantize.cu, swizzled_quantize.cu, quant_reduce.cu,
fake_quantizer.cu, quantize_intX.cu) and the ``ops/quantizer`` python
bindings. Used by:
* ZeRO++-style quantized collectives (parallel/compressed.py),
* weight-only quantized inference (inference/quantization.py),
* the compression library's fake-quant training (compression/).

jnp formulation throughout — XLA fuses the scale/round/clamp chain into
single VPU loops, and on TPU the int8 tensors feed int8 MXU matmuls. The
reference's "swizzled" layouts served CUDA warp-shuffles; TPU lane layout
is the compiler's job, so there is no swizzle variant.
"""

from __future__ import annotations

from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np


def _reshape_blocks(x: jnp.ndarray, block: int) -> Tuple[jnp.ndarray, Tuple]:
    flat = x.reshape(-1)
    n = flat.shape[0]
    assert n % block == 0, f"size {n} not divisible by block {block}"
    return flat.reshape(n // block, block), x.shape


def quantize_blockwise(x: jnp.ndarray, bits: int = 8, block: int = 256,
                       symmetric: bool = True, manual_sharding: bool = False):
    """-> (q int8, scale f32[blocks], zero f32[blocks] | None).

    int4 values live in int8 storage in [-8, 7] / [0, 15] — packing two
    nibbles per byte is a serialization concern, not a compute one.
    """
    assert bits in (4, 8)
    if symmetric:
        from .pallas.quant import quantize_blockwise_pallas, use_pallas_quant

        if use_pallas_quant(int(np.prod(x.shape)), block,
                            manual_sharding=manual_sharding):
            return quantize_blockwise_pallas(x, bits=bits, block=block)
    blocks, shape = _reshape_blocks(x.astype(jnp.float32), block)
    if symmetric:
        qmax = 2.0 ** (bits - 1) - 1
        scale = jnp.max(jnp.abs(blocks), axis=1, keepdims=True) / qmax
        scale = jnp.where(scale == 0, 1.0, scale)
        q = jnp.clip(jnp.round(blocks / scale), -qmax - 1, qmax).astype(jnp.int8)
        return q.reshape(shape), scale[:, 0], None
    qmax = 2.0 ** bits - 1
    lo = jnp.min(blocks, axis=1, keepdims=True)
    hi = jnp.max(blocks, axis=1, keepdims=True)
    scale = (hi - lo) / qmax
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round((blocks - lo) / scale), 0, qmax).astype(jnp.uint8)
    return q.reshape(shape), scale[:, 0], lo[:, 0]


def dequantize_blockwise(q: jnp.ndarray, scale: jnp.ndarray,
                         zero: Optional[jnp.ndarray] = None,
                         block: int = 256, dtype=jnp.float32,
                         manual_sharding: bool = False) -> jnp.ndarray:
    if zero is None:
        from .pallas.quant import dequantize_blockwise_pallas, use_pallas_quant

        if use_pallas_quant(int(np.prod(q.shape)), block,
                            manual_sharding=manual_sharding):
            return dequantize_blockwise_pallas(q, scale, block=block,
                                               dtype=dtype)
    blocks, shape = _reshape_blocks(q.astype(jnp.float32), block)
    if zero is None:
        out = blocks * scale[:, None]
    else:
        out = blocks * scale[:, None] + zero[:, None]
    return out.reshape(shape).astype(dtype)


def fake_quantize(x: jnp.ndarray, bits: int = 8, block: int = 256,
                  symmetric: bool = True) -> jnp.ndarray:
    """Quantize-dequantize round trip in the input dtype (reference
    fake_quantizer.cu — used for quantization-aware training). Straight-
    through estimator: gradients flow as identity."""

    @jax.custom_vjp
    def _fq(x):
        q, s, z = quantize_blockwise(x, bits=bits, block=block, symmetric=symmetric)
        return dequantize_blockwise(q, s, z, block=block, dtype=x.dtype)

    _fq.defvjp(lambda x: (_fq(x), None), lambda _, g: (g,))
    return _fq(x)


def quantized_nbytes(numel: int, bits: int, block: int) -> int:
    """Wire size of a quantized tensor (payload + scales) — the comm-volume
    accounting behind ZeRO++'s 4x claim. Partial bytes round UP: an odd
    numel at int4 still occupies the trailing half-filled byte on the
    wire, and a ragged final block still carries a full fp32 scale —
    flooring both under-reported the wire by up to 4 bytes + a nibble
    (visible on the ste_quant_gather path, whose leaves need not
    block-divide)."""
    payload = (numel * bits + 7) // 8
    scales = -(-numel // block) * 4
    return payload + scales


def quantize_kv(x: jnp.ndarray, bits: int = 8):
    """Per-vector symmetric quantization for KV-cache rows: ``x``
    [..., hd] -> (payload, scale [...]) with one fp32 scale per trailing
    vector (block = head_dim — a K or V head-vector is the natural
    quantization block for paged KV storage: the scatter/gather unit).

    int8: payload int8 [..., hd]. int4: values clamp to [-8, 7] and PACK
    two adjacent channels per byte -> uint8 [..., hd//2] (channel 2c in
    the low nibble, 2c+1 in the high — the layout :func:`unpack_kv`
    inverts), so a quantized pool leaf really is a quarter the fp32
    bytes. Error bound (the contract the serving docs state): each
    dequantized element is within ``scale/2`` of the input, where
    ``scale = absmax(vector)/qmax``. Traced-code safe (pure jnp)."""
    assert bits in (4, 8)
    qmax = 2.0 ** (bits - 1) - 1
    xf = x.astype(jnp.float32)
    scale = jnp.max(jnp.abs(xf), axis=-1) / qmax
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(xf / scale[..., None]), -qmax - 1, qmax)
    if bits == 8:
        return q.astype(jnp.int8), scale
    qi = q.astype(jnp.int32)
    lo = qi[..., 0::2] & 0x0F
    hi = (qi[..., 1::2] & 0x0F) << 4
    return (lo | hi).astype(jnp.uint8), scale


def unpack_kv_int4(packed: jnp.ndarray) -> jnp.ndarray:
    """Inverse of the int4 packing in :func:`quantize_kv`: uint8
    [..., hd//2] -> int32 [..., hd] in [-8, 7] (int32 out: the consumer
    multiplies by an fp scale immediately)."""
    p = packed.astype(jnp.int32)
    lo = p & 0x0F
    hi = (p >> 4) & 0x0F
    both = jnp.stack([lo, hi], axis=-1).reshape(p.shape[:-1] + (-1,))
    return jnp.where(both >= 8, both - 16, both)


def dequantize_kv(q: jnp.ndarray, scale: jnp.ndarray, bits: int = 8,
                  dtype=jnp.float32) -> jnp.ndarray:
    """Dequantize a :func:`quantize_kv` payload back to ``dtype``:
    payload [..., hd or hd//2] * scale [...] -> [..., hd]."""
    assert bits in (4, 8)
    vals = unpack_kv_int4(q) if bits == 4 else q.astype(jnp.int32)
    return (vals.astype(jnp.float32) * scale[..., None]).astype(dtype)


def pack_int4(q: jnp.ndarray) -> jnp.ndarray:
    """Pack int4 values (int8 storage in [-8, 7], even length) two nibbles
    per byte, so an inter-host int4 collective really moves half the
    elements — the wire-volume claim is carried by the program, not just
    the ledger. Layout: element 2k in the low nibble, 2k+1 in the high.

    Requires an even total numel (nibbles pair) — checked explicitly,
    because a silent floor-divide here would DROP the last element.
    Non-contiguous inputs (transposes, strided views) are fine: the
    flatten below copies into row-major order, and unpack_int4 restores
    exactly that order."""
    if q.size % 2:
        raise ValueError(
            f"pack_int4 needs an even number of elements (nibbles pair "
            f"two-per-byte), got {q.size}; pad the tensor or use an even "
            f"quantization block")
    flat = q.reshape(-1).astype(jnp.int32)
    lo = flat[0::2] & 0x0F
    hi = (flat[1::2] & 0x0F) << 4
    return (lo | hi).astype(jnp.uint8)


def unpack_int4(packed: jnp.ndarray) -> jnp.ndarray:
    """Inverse of :func:`pack_int4`: uint8 [n] -> int8 [2n] in [-8, 7]."""
    p = packed.reshape(-1).astype(jnp.int32)
    lo = p & 0x0F
    hi = (p >> 4) & 0x0F
    both = jnp.stack([lo, hi], axis=-1).reshape(-1)
    return jnp.where(both >= 8, both - 16, both).astype(jnp.int8)
