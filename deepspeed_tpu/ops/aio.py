"""Python surface of the native async-IO engine.

Parity with the reference ``aio_handle`` API
(csrc/aio/py_lib/deepspeed_py_aio_handle.cpp pybind exports: async_pread /
async_pwrite / sync_pread / sync_pwrite / wait, plus the pinned-tensor
manager). Buffers are numpy arrays (host memory IS the staging tier on
TPU — device HBM transfers go through jax.device_put separately).
"""

from __future__ import annotations

import ctypes
import os
from typing import Dict, Optional

import numpy as np

from .op_builder import AsyncIOBuilder


class AsyncIOHandle:
    """Thread-pool async file IO (reference aio_handle)."""

    def __init__(self, n_threads: int = 4, queue_depth: int = 128):
        self._builder = AsyncIOBuilder()
        self._lib = self._builder.load()
        self._h = self._lib.ds_aio_create(n_threads, queue_depth)
        if not self._h:
            raise RuntimeError("ds_aio_create failed")
        self._buffers: Dict[int, np.ndarray] = {}  # keep alive while inflight

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.ds_aio_destroy(self._h)
        except Exception:
            pass

    # -- async ----------------------------------------------------------
    def async_pread(self, buffer: np.ndarray, path: str, offset: int = 0) -> int:
        assert buffer.flags["C_CONTIGUOUS"]
        req = self._lib.ds_aio_pread(
            self._h, path.encode(), buffer.ctypes.data_as(ctypes.c_void_p),
            buffer.nbytes, offset)
        if req < 0:
            raise RuntimeError("aio queue full")
        self._buffers[req] = buffer
        return req

    def async_pwrite(self, buffer: np.ndarray, path: str, offset: int = 0,
                     truncate: bool = False) -> int:
        """``truncate=True`` drops any stale file tail beyond this write —
        use for whole-file rewrites (explicit, so chunked writers at other
        offsets of the same file are never clobbered)."""
        assert buffer.flags["C_CONTIGUOUS"]
        fn = self._lib.ds_aio_pwrite_trunc if truncate else self._lib.ds_aio_pwrite
        req = fn(self._h, path.encode(), buffer.ctypes.data_as(ctypes.c_void_p),
                 buffer.nbytes, offset)
        if req < 0:
            raise RuntimeError("aio queue full")
        self._buffers[req] = buffer
        return req

    def wait(self, count: int = 1):
        """Block for ``count`` completions; returns [(req_id, nbytes)].

        All ``count`` completions are drained (and their buffers released)
        before any error is raised, so a failed request can't strand later
        completions or leave buffers pinned.
        """
        ids = (ctypes.c_int64 * count)()
        res = (ctypes.c_int64 * count)()
        got = self._lib.ds_aio_wait(self._h, count, ids, res)
        out, errors = [], []
        for i in range(got):
            rid, r = int(ids[i]), int(res[i])
            self._buffers.pop(rid, None)
            if r < 0:
                errors.append((rid, -r))
            else:
                out.append((rid, r))
        if errors:
            rid, err = errors[0]
            exc = OSError(err, f"aio request {rid} (+{len(errors) - 1} more): "
                          + os.strerror(err))
            exc.completed = out    # successful (req_id, nbytes) pairs
            exc.failed = errors    # (req_id, errno) pairs
            raise exc
        return out

    def poll(self) -> int:
        return int(self._lib.ds_aio_poll(self._h))

    def inflight(self) -> int:
        return int(self._lib.ds_aio_inflight(self._h))

    # -- sync convenience (reference sync_pread/sync_pwrite) -------------
    def sync_pwrite(self, buffer: np.ndarray, path: str, offset: int = 0) -> int:
        self.async_pwrite(buffer, path, offset)
        return self.wait(1)[0][1]

    def sync_pread(self, buffer: np.ndarray, path: str, offset: int = 0) -> int:
        self.async_pread(buffer, path, offset)
        return self.wait(1)[0][1]
