"""Host-side ragged batch building: native C++ with a numpy fallback.

The reference keeps this on the native side
(``inference/v2/ragged/csrc/fast_host_buffer.cpp`` builds the flattened
buffers its ragged kernels consume); here the same construction backs
``inference/ragged.py``'s SplitFuse step. The C++ path loads lazily via
the op_builder registry; environments without a toolchain fall back to
the equivalent numpy loops (bit-identical outputs — tested).
"""

from __future__ import annotations

import ctypes
from typing import List, Optional, Sequence, Tuple

import numpy as np

from ..utils.logging import logger

_LIB = None
_TRIED = False


def _lib():
    global _LIB, _TRIED
    if not _TRIED:
        _TRIED = True
        try:
            from .op_builder import get_op_builder

            _LIB = get_op_builder("ds_ragged_host").load()
        except Exception as e:  # no toolchain / build failure: numpy path
            logger.warning(f"ds_ragged_host native build unavailable ({e}); "
                           "using numpy fallback")
            _LIB = None
    return _LIB


def _i32p(a: np.ndarray):
    return a.ctypes.data_as(ctypes.POINTER(ctypes.c_int32))


def build_batch(chunks: Sequence[Sequence[int]], seens: Sequence[int],
                slots: Sequence[int], T: int, pad_slot: int = -1,
                ) -> Tuple[np.ndarray, np.ndarray, np.ndarray, np.ndarray]:
    """Flatten scheduled per-sequence token chunks into the step batch.

    Returns (flat_tokens [T], flat_slot [T] (= pad_slot on unused lanes),
    flat_pos [T], last_index [n] — flat index of each chunk's final token).
    """
    n = len(chunks)
    lens = np.fromiter((len(c) for c in chunks), np.int32, count=n)
    offsets = np.zeros((n + 1,), np.int32)
    np.cumsum(lens, out=offsets[1:])
    if n and int(offsets[-1]) > T:
        raise ValueError(
            f"scheduled tokens {int(offsets[-1])} exceed batch width {T}")
    # one C-level conversion per chunk (not per token), then one concat
    concat = np.concatenate(
        [np.asarray(c, np.int32) for c in chunks]) if n else \
        np.zeros((0,), np.int32)
    seens = np.asarray(seens, np.int32)
    slots_a = np.asarray(slots, np.int32)
    flat_tokens = np.zeros((T,), np.int32)
    flat_slot = np.full((T,), pad_slot, np.int32)
    flat_pos = np.zeros((T,), np.int32)
    last_index = np.zeros((n,), np.int32)

    lib = _lib()
    if lib is not None:
        lib.ds_ragged_build_batch(
            np.int32(n), _i32p(concat), _i32p(offsets), _i32p(seens),
            _i32p(slots_a), _i32p(flat_tokens), _i32p(flat_slot),
            _i32p(flat_pos), _i32p(last_index))
        return flat_tokens, flat_slot, flat_pos, last_index

    cursor = 0
    for i in range(n):
        take = int(offsets[i + 1] - offsets[i])
        flat_tokens[cursor:cursor + take] = concat[offsets[i]:offsets[i + 1]]
        flat_slot[cursor:cursor + take] = slots_a[i]
        flat_pos[cursor:cursor + take] = np.arange(
            seens[i], seens[i] + take, dtype=np.int32)
        cursor += take
        last_index[i] = cursor - 1
    return flat_tokens, flat_slot, flat_pos, last_index


def fill_tables(block_lists: Sequence[Sequence[int]], slots: Sequence[int],
                max_seqs: int, max_pages: int) -> np.ndarray:
    """Scatter per-sequence block lists into the dense [max_seqs,
    max_pages] table (zero-padded rows). A sequence owning more than
    max_pages blocks is an engine invariant violation — raise loudly
    rather than truncate into silent wrong attention reads."""
    n = len(block_lists)
    tables = np.zeros((max_seqs, max_pages), np.int32)
    lens = np.fromiter((len(b) for b in block_lists), np.int32, count=n)
    if n and int(lens.max()) > max_pages:
        raise ValueError(
            f"sequence owns {int(lens.max())} blocks > max_pages {max_pages}")
    offsets = np.zeros((n + 1,), np.int32)
    np.cumsum(lens, out=offsets[1:])
    concat = np.concatenate(
        [np.asarray(b, np.int32) for b in block_lists]) if n else \
        np.zeros((0,), np.int32)
    slots_a = np.asarray(slots, np.int32)

    lib = _lib()
    if lib is not None:
        overflowed = lib.ds_ragged_fill_tables(
            np.int32(n), _i32p(concat), _i32p(offsets), _i32p(slots_a),
            np.int32(max_pages), _i32p(tables))
        if overflowed:  # unreachable past the pre-check; belt and braces
            raise ValueError(f"{overflowed} block lists exceed max_pages")
        return tables

    for i in range(n):
        blks = concat[offsets[i]:offsets[i + 1]]
        tables[slots_a[i], : len(blks)] = blks
    return tables
