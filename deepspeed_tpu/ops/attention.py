"""Attention ops.

Replaces the reference's attention kernel zoo — fused softmax/attention CUDA
kernels (``csrc/transformer/*.cu``), inference ``softmax_context``
(``ops/transformer/inference/op_binding/softmax_context.py``), the Evoformer
CUTLASS fMHA (``csrc/deepspeed4science/evoformer_attn/``) — with one
TPU-first surface:

* :func:`dot_product_attention` — jnp reference path; XLA already produces a
  flash-style fused softmax on TPU for moderate sequence lengths.
* :func:`flash_attention` — Pallas blocked/online-softmax kernel
  (``ops/pallas/flash_attention.py``) for long sequences; falls back to the
  jnp path off-TPU or for tiny shapes.
* GQA/MQA handled by K/V head broadcasting (n_kv_heads <= n_heads).
"""

from __future__ import annotations

import functools
import os
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np


def _repeat_kv(k, n_rep: int):
    if n_rep == 1:
        return k
    b, s, h, d = k.shape
    return jnp.repeat(k, n_rep, axis=2)


def dot_product_attention(q, k, v, *, causal: bool = True,
                          mask: Optional[jnp.ndarray] = None,
                          bias: Optional[jnp.ndarray] = None,
                          scale: Optional[float] = None,
                          logits_dtype=jnp.float32,
                          window: int = 0):
    """Reference attention. q: [b, sq, hq, d]; k/v: [b, skv, hkv, d].

    Softmax in fp32 (the reference kernels do the same via float accumulators
    in attn_softmax_v2). Causal masking uses absolute positions aligned to
    the *end* of the KV sequence so decode (sq=1, skv=cache_len) works.
    ``bias``: optional additive logit bias broadcastable to [b, h, sq, skv]
    (ALiBi). ``window`` > 0 bands causal attention to the trailing
    ``window`` keys (k > q - window).
    """
    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    assert hq % hkv == 0, f"query heads {hq} not a multiple of kv heads {hkv}"
    k = _repeat_kv(k, hq // hkv)
    v = _repeat_kv(v, hq // hkv)
    scale = scale if scale is not None else 1.0 / np.sqrt(d)
    logits = jnp.einsum("bqhd,bkhd->bhqk", q, k).astype(logits_dtype) * scale
    if bias is not None:
        logits = logits + bias.astype(logits_dtype)
    if causal:
        q_pos = jnp.arange(sq)[:, None] + (skv - sq)
        k_pos = jnp.arange(skv)[None, :]
        causal_mask = q_pos >= k_pos  # [sq, skv]
        if window > 0:
            causal_mask = causal_mask & (k_pos > q_pos - window)
        logits = jnp.where(causal_mask[None, None], logits, jnp.finfo(logits_dtype).min)
    if mask is not None:
        logits = jnp.where(mask, logits, jnp.finfo(logits_dtype).min)
    probs = jax.nn.softmax(logits, axis=-1).astype(q.dtype)
    return jnp.einsum("bhqk,bkhd->bqhd", probs, v)


def flash_attention(q, k, v, *, causal: bool = True, scale: Optional[float] = None,
                    block_q: int = 1024, block_k: int = 1024, window: int = 0):
    """Blocked flash attention. Dispatches to the Pallas TPU kernel when
    running on TPU with compatible shapes (padding odd causal self-attention
    lengths up to a lane multiple); jnp reference otherwise. ``window`` > 0
    (static; requires causal) bands attention to the trailing ``window``
    keys — the kernel skips tiles fully below the band (Mistral sliding
    window at O(s*window) compute)."""
    if window > 0 and not causal:
        raise ValueError("window > 0 requires causal attention")
    # kernel-tuning lever for the on-chip sweeps: override the tile shape
    # without touching call sites (traced once per shape, zero step cost)
    block_q = int(os.environ.get("DST_FLASH_BLOCK_Q", block_q))
    block_k = int(os.environ.get("DST_FLASH_BLOCK_K", block_k))
    if _use_pallas(q, k, block_q, block_k):
        from .pallas.flash_attention import flash_attention as _pallas_flash

        return _pallas_flash(q, k, v, causal, scale, block_q, block_k,
                             window=window)
    if _use_pallas_padded(q, k, causal):
        from .pallas.flash_attention import flash_attention_padded

        return flash_attention_padded(q, k, v, causal, scale,
                                      block_q, block_k, window=window)
    return dot_product_attention(q, k, v, causal=causal, scale=scale,
                                 window=window)


def _on_tpu() -> bool:
    """Shared platform probe for Pallas kernel dispatch."""
    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def _use_pallas(q, k, block_q: int, block_k: int) -> bool:
    if not _on_tpu():
        return False
    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    bq, bk = min(block_q, sq), min(block_k, skv)
    # clamped blocks must stay lane-aligned (Mosaic (8,128) tiles): a seq
    # like 264 would otherwise clamp to an untested non-multiple-of-128 block
    return (sq % bq == 0 and skv % bk == 0 and bq % 128 == 0 and bk % 128 == 0
            and d in (64, 128, 256) and hq % hkv == 0 and skv >= sq)


def _use_pallas_padded(q, k, causal: bool) -> bool:
    """Odd causal self-attention lengths go through the pad-to-lane wrapper
    (kernel coverage for s not divisible by 128, e.g. 1000)."""
    if not (_on_tpu() and causal):
        return False
    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    return (sq == skv and sq > 128 and d in (64, 128, 256)
            and hq % hkv == 0)
