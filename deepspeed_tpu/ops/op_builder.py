"""Native op builder registry.

Parity with the reference's ``op_builder/`` infrastructure (OpBuilder
builder.py:108 with jit_load :460 via torch cpp_extension; per-op
``is_compatible``/DS_BUILD_* gating; ``all_ops`` enumeration). Here native
ops are plain shared libraries compiled with g++ on first use and bound via
ctypes — no torch, no pybind. Pallas kernels don't go through this path
(XLA compiles them); this registry exists for the genuinely host-native
components (async IO today).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import sysconfig
from pathlib import Path
from typing import Dict, List, Optional

from ..utils.logging import log_dist, logger

_REPO_ROOT = Path(__file__).resolve().parents[2]
_BUILD_DIR = Path(os.environ.get(
    "DS_BUILD_DIR", os.path.join(os.path.expanduser("~"), ".cache",
                                 "deepspeed_tpu", "ops")))


class OpBuilder:
    """Compile-and-load for one native extension."""

    NAME = "base"
    SOURCES: List[str] = []            # relative to repo csrc/
    EXTRA_FLAGS: List[str] = []

    def __init__(self):
        self._lib: Optional[ctypes.CDLL] = None

    def absolute_sources(self) -> List[Path]:
        return [_REPO_ROOT / "csrc" / s for s in self.SOURCES]

    def lib_path(self) -> Path:
        return _BUILD_DIR / f"lib{self.NAME}.so"

    def is_compatible(self) -> bool:
        """Whether this op can build here (reference is_compatible)."""
        return all(p.is_file() for p in self.absolute_sources())

    def _needs_build(self) -> bool:
        out = self.lib_path()
        if not out.is_file():
            return True
        mtime = out.stat().st_mtime
        return any(p.stat().st_mtime > mtime for p in self.absolute_sources())

    def build(self) -> Path:
        out = self.lib_path()
        out.parent.mkdir(parents=True, exist_ok=True)
        srcs = [str(p) for p in self.absolute_sources()]
        cmd = ["g++", "-O2", "-shared", "-fPIC", "-pthread", "-std=c++17",
               *self.EXTRA_FLAGS, *srcs, "-o", str(out)]
        log_dist(f"building native op {self.NAME}: {' '.join(cmd)}")
        proc = subprocess.run(cmd, capture_output=True, text=True)
        if proc.returncode != 0:
            raise RuntimeError(
                f"native build of {self.NAME} failed:\n{proc.stderr}")
        return out

    def load(self) -> ctypes.CDLL:
        """Prebuilt-or-jit load (reference OpBuilder.load :442)."""
        if self._lib is not None:
            return self._lib
        if not self.is_compatible():
            raise RuntimeError(f"op {self.NAME}: sources missing "
                               f"({self.SOURCES})")
        if self._needs_build():
            self.build()
        self._lib = ctypes.CDLL(str(self.lib_path()))
        self._configure(self._lib)
        return self._lib

    def _configure(self, lib: ctypes.CDLL) -> None:
        """Subclasses declare argtypes/restypes."""


class AsyncIOBuilder(OpBuilder):
    """The reference AsyncIOBuilder (op_builder/async_io.py) analog."""

    NAME = "ds_aio"
    SOURCES = ["aio/ds_aio.cpp"]

    def _configure(self, lib: ctypes.CDLL) -> None:
        i64, p = ctypes.c_int64, ctypes.c_void_p
        lib.ds_aio_create.restype = p
        lib.ds_aio_create.argtypes = [ctypes.c_int, ctypes.c_int]
        lib.ds_aio_destroy.argtypes = [p]
        for fn in (lib.ds_aio_pread, lib.ds_aio_pwrite, lib.ds_aio_pwrite_trunc):
            fn.restype = i64
            fn.argtypes = [p, ctypes.c_char_p, ctypes.c_void_p, i64, i64]
        lib.ds_aio_wait.restype = i64
        lib.ds_aio_wait.argtypes = [p, i64, ctypes.POINTER(i64),
                                    ctypes.POINTER(i64)]
        lib.ds_aio_poll.restype = i64
        lib.ds_aio_poll.argtypes = [p]
        lib.ds_aio_inflight.restype = i64
        lib.ds_aio_inflight.argtypes = [p]


class RaggedHostBuilder(OpBuilder):
    """Host-side ragged batch building (reference
    inference/v2/ragged/csrc/fast_host_buffer.cpp analog)."""

    NAME = "ds_ragged_host"
    SOURCES = ["ragged/ds_ragged_host.cpp"]

    def _configure(self, lib: ctypes.CDLL) -> None:
        i32p = ctypes.POINTER(ctypes.c_int32)
        lib.ds_ragged_build_batch.restype = None
        lib.ds_ragged_build_batch.argtypes = [ctypes.c_int32] + [i32p] * 8
        lib.ds_ragged_fill_tables.restype = ctypes.c_int32
        lib.ds_ragged_fill_tables.argtypes = \
            [ctypes.c_int32] + [i32p] * 3 + [ctypes.c_int32, i32p]


ALL_OPS: Dict[str, type] = {
    AsyncIOBuilder.NAME: AsyncIOBuilder,
    RaggedHostBuilder.NAME: RaggedHostBuilder,
}


def get_op_builder(name: str) -> OpBuilder:
    if name not in ALL_OPS:
        raise KeyError(f"unknown op {name!r}; have {sorted(ALL_OPS)}")
    return ALL_OPS[name]()


def op_report() -> List:
    """(name, compatible, built) rows for ds_report."""
    rows = []
    for name, cls in ALL_OPS.items():
        b = cls()
        rows.append((name, b.is_compatible(), b.lib_path().is_file()))
    return rows
