"""Fused compute–collective Pallas kernels (docs/communication.md,
"Kernel backends").

PR 10 made the ZeRO-3 collectives cheap on the wire, but quantize/pack/
dequantize still ran as their own XLA computations bracketing each
collective, and overlap relied on the block schedule's coarse per-layer
fill/drain windows. Following T3 (arxiv 2401.16677) and the fused
computation-collective line (arxiv 2305.06942), these kernels move the
compression bracket INTO the consuming/producing matmul:

* :func:`dequant_matmul` — the all-gather consumer side: one kernel
  dequantizes a quantized weight shard (nibble-unpack for int4, blockwise
  scale multiply) and immediately multiplies it, so a ring all-gather can
  run dequant+matmul on tile *i* while tile *i+1*'s shard is still in
  flight (per-tile overlap instead of per-layer; the ring driver lives in
  ``comm/backends.py`` so collectives stay behind the facade).
* :func:`matmul_quantize` — the reduce-scatter producer side: the
  grad-producing matmul's epilogue quantizes each output tile blockwise
  (and nibble-packs int4) in-kernel, emitting the WIRE payload directly —
  no separate quantize pass over the gradient in HBM.
* :func:`matmul_pallas` — the dense twin (compression off), so the fused
  path has a bit-exact dense A/B.

Bit-exactness contract (enforced by tests/test_fused_collectives.py in
interpret mode): the quantize/dequantize arithmetic is copied verbatim
from ``ops/quantizer.py`` (same fp32 formula, same int clamps, same
nibble layout as ``pack_int4``), and every matmul accumulates fp32 over
the FULL contraction per output tile — output tiles split only
non-contraction dimensions, which slices bit-exactly (splitting the
contraction would reorder the fp32 accumulation; callers that need that
fall back to the unfused facade instead).

Layouts follow ``ops/pallas/quant.py``: quantized payloads travel as
``[rows, block]`` int8 (``[rows, block//2]`` uint8 nibble-packed for
int4) — exactly the facade's wire layout — and scales ride
lane-replicated ``[rows, LANES]`` (the Mosaic tiling trick the flash
kernel's LSE uses). Off-TPU callers run these kernels in interpret mode,
like ``ops/pallas/flash_attention.py``.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128


def _m_tile(m: int) -> int:
    """Largest row tile from {512, 256, 128, 64, 32, 16, 8} dividing
    ``m``, else ``m`` whole (decode runs m == 1)."""
    for t in (512, 256, 128, 64, 32, 16, 8):
        if m % t == 0 and m >= t:
            return t
    return m


def _unpack_nibbles(packed: jnp.ndarray, rows: int, block: int) -> jnp.ndarray:
    """[rows, block//2] uint8 -> [rows, block] int32 in [-8, 7]; the
    in-kernel inverse of ops.quantizer.pack_int4 (element 2k low nibble,
    2k+1 high)."""
    p = packed.astype(jnp.int32)
    lo = p & 0x0F
    hi = (p >> 4) & 0x0F
    both = jnp.stack([lo, hi], axis=-1).reshape(rows, block)
    return jnp.where(both >= 8, both - 16, both)


def _pack_nibbles(q: jnp.ndarray, rows: int, block: int) -> jnp.ndarray:
    """[rows, block] int8 in [-8, 7] -> [rows, block//2] uint8; the
    in-kernel twin of ops.quantizer.pack_int4 (same pairing of
    consecutive row-major elements)."""
    pairs = q.astype(jnp.int32).reshape(rows, block // 2, 2)
    lo = pairs[..., 0] & 0x0F
    hi = (pairs[..., 1] & 0x0F) << 4
    return (lo | hi).astype(jnp.uint8)


# ----------------------------------------------------------------------
# consumer side: dequantize + matmul in one kernel


def _dequant_matmul_kernel(h_ref, q_ref, s_ref, o_ref, *, bits: int,
                           block: int, k: int, b: int, w_dtype):
    rows = k * b // block
    q = q_ref[...]
    if bits == 4:
        q = _unpack_nibbles(q, rows, block)
    # blockwise dequant — same fp32 arithmetic as dequantize_blockwise:
    # int -> f32 is exact, then one multiply by the block scale
    w = q.astype(jnp.float32) * s_ref[...][:, :1]
    w = w.reshape(k, b).astype(w_dtype)
    h = h_ref[...]
    o_ref[...] = jax.lax.dot_general(
        h, w, (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(o_ref.dtype)


def dequant_matmul(h: jnp.ndarray, payload: jnp.ndarray, scales: jnp.ndarray,
                   *, bits: int, block: int, b: int,
                   out_dtype=jnp.float32, w_dtype=jnp.float32,
                   interpret: bool = False) -> jnp.ndarray:
    """``h [m, k] @ dequant(payload, scales) [k, b] -> [m, b]`` with the
    dequantize (nibble-unpack + blockwise scale) fused into the matmul
    prologue. ``payload`` is the facade wire format: flat int8 values
    (uint8 nibble-packed for bits=4) whose row-major reshape is the
    weight tile; ``scales`` is the flat ``[k*b/block]`` fp32 vector."""
    m, k = h.shape
    rows = k * b // block
    assert rows * block == k * b, (k, b, block)
    q2 = payload.reshape(rows, block // 2 if bits == 4 else block)
    s2 = jnp.broadcast_to(scales.reshape(rows, 1), (rows, LANES))
    tile_m = _m_tile(m)
    kernel = functools.partial(_dequant_matmul_kernel, bits=bits, block=block,
                               k=k, b=b, w_dtype=w_dtype)
    return pl.pallas_call(
        kernel,
        grid=(m // tile_m,),
        in_specs=[
            pl.BlockSpec((tile_m, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec(q2.shape, lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((rows, LANES), lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile_m, b), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, b), out_dtype),
        interpret=interpret,
    )(h, q2, s2)


# ----------------------------------------------------------------------
# dense twin (compression off): plain tiled matmul


def _matmul_kernel(a_ref, b_ref, o_ref):
    o_ref[...] = jax.lax.dot_general(
        a_ref[...], b_ref[...], (((1,), (0,)), ((), ())),
        preferred_element_type=jnp.float32).astype(o_ref.dtype)


def matmul_pallas(a: jnp.ndarray, b: jnp.ndarray, *, out_dtype=jnp.float32,
                  interpret: bool = False) -> jnp.ndarray:
    """``a [m, k] @ b [k, n] -> [m, n]`` (fp32 accumulation), tiled over
    the m rows — the dense per-tile step of the fused ring all-gather."""
    m, k = a.shape
    n = b.shape[1]
    tile_m = _m_tile(m)
    return pl.pallas_call(
        _matmul_kernel,
        grid=(m // tile_m,),
        in_specs=[
            pl.BlockSpec((tile_m, k), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((k, n), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((tile_m, n), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((m, n), out_dtype),
        interpret=interpret,
    )(a, b)


# ----------------------------------------------------------------------
# producer side: matmul with blockwise-quantize epilogue


def _matmul_quantize_kernel(a_ref, b_ref, q_ref, s_ref, *, trans_a: bool,
                            qmax: float, block: int, pack: bool,
                            out_rows: int, n: int):
    a = a_ref[...]
    bb = b_ref[...]
    dims = (((0,), (0,)), ((), ())) if trans_a else (((1,), (0,)), ((), ()))
    t = jax.lax.dot_general(a, bb, dims, preferred_element_type=jnp.float32)
    # epilogue: symmetric blockwise quantization of the tile, verbatim
    # the quantize_blockwise formula (scale = absmax/qmax, 0 -> 1, clip
    # round) so the emitted payload is bit-identical to the facade's
    rows = out_rows * n // block
    blocks = t.reshape(rows, block)
    scale = jnp.max(jnp.abs(blocks), axis=1, keepdims=True) / qmax
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(blocks / scale), -qmax - 1, qmax).astype(jnp.int8)
    if pack:
        q_ref[...] = _pack_nibbles(q, rows, block)
    else:
        q_ref[...] = q
    s_ref[...] = jnp.broadcast_to(scale, (rows, LANES))


def matmul_quantize(a: jnp.ndarray, b: jnp.ndarray, *, bits: int, block: int,
                    trans_a: bool = False,
                    interpret: bool = False
                    ) -> Tuple[jnp.ndarray, jnp.ndarray]:
    """The grad-producing matmul with its reduce-scatter quantization
    fused into the epilogue: computes ``a.T @ b`` (``trans_a``, the
    weight-gradient shape ``[k, m].T? -> [K, N]``) or ``a @ b``, then
    blockwise-quantizes each output tile in-kernel and emits the WIRE
    payload — ``(payload, scales)`` ready for
    ``comm.compressed.quantized_chunk_exchange``. Payload is ``[rows,
    block]`` int8, nibble-packed to ``[rows, block//2]`` uint8 for
    bits=4; scales come back as the flat ``[rows]`` fp32 vector.

    Output tiles split the non-contraction row dimension only (each tile
    runs the full contraction in fp32), and a tile boundary never splits
    a quantization block — both conditions the backend's fusability
    predicate checks."""
    assert bits in (4, 8)
    qmax = 2.0 ** (bits - 1) - 1
    if trans_a:
        m, out_rows = a.shape  # a [m, K] contracted over m
        n = b.shape[1]
    else:
        out_rows, m = a.shape  # a [M, k] contracted over k
        n = b.shape[1]
    numel = out_rows * n
    assert numel % block == 0, (out_rows, n, block)
    # tile the output rows only where row boundaries align with quant
    # blocks (n a block multiple); otherwise run the tile whole
    tile_r = _m_tile(out_rows) if n % block == 0 else out_rows
    rows_tile = tile_r * n // block
    rows = numel // block
    pack = bits == 4
    kernel = functools.partial(_matmul_quantize_kernel, trans_a=trans_a,
                               qmax=qmax, block=block, pack=pack,
                               out_rows=tile_r, n=n)
    if trans_a:
        a_spec = pl.BlockSpec((m, tile_r), lambda i: (0, i),
                              memory_space=pltpu.VMEM)
    else:
        a_spec = pl.BlockSpec((tile_r, m), lambda i: (i, 0),
                              memory_space=pltpu.VMEM)
    payload, s = pl.pallas_call(
        kernel,
        grid=(out_rows // tile_r,),
        in_specs=[
            a_spec,
            pl.BlockSpec(b.shape, lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((rows_tile, block // 2 if pack else block),
                         lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((rows_tile, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, block // 2 if pack else block),
                                 jnp.uint8 if pack else jnp.int8),
            jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(a, b)
    return payload.reshape(-1), s[:, 0]
