"""Blocked flash attention (Pallas TPU kernel), forward + backward.

Subsumes the reference's attention kernel surface: the fused training
softmax kernels (``csrc/transformer/softmax.cu``,
``general_kernels.cu``), the Evoformer CUTLASS fMHA
(``csrc/deepspeed4science/evoformer_attn/``), and the inference
``softmax_context`` path's core attention math
(``csrc/transformer/inference/csrc/softmax.cu``) — one online-softmax
kernel family instead of a per-era zoo.

Design (standard flash attention 2 on the MXU):
* forward: grid ``(batch, q_heads, q_blocks, kv_blocks)`` with the kv axis
  innermost; running row-max / row-sum / output accumulator live in VMEM
  scratch across kv steps; logits and softmax in fp32, output in the input
  dtype. Emits LSE (``m + log l``) residuals for the backward.
* causal masking skips fully-masked kv blocks via ``pl.when`` (no MXU work
  in the upper triangle) and applies the per-element mask on the diagonal
  blocks only.
* GQA/MQA: kv-head index derived in the BlockSpec index maps
  (``q_head // group``) — K/V are never materialized per-q-head in the
  forward.
* backward: two kernels — dq over ``(b, h, nq, nk)`` and dk/dv over
  ``(b, h, nk, nq)`` — both recompute probabilities from the LSE residual
  (flash-2 style: no stored attention matrix, ``delta = rowsum(dout*out)``
  precomputed outside).

Off-TPU the caller (``ops/attention.py``) uses the jnp reference path;
tests run these kernels in Pallas interpret mode.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30  # large-negative instead of -inf: avoids NaN from (-inf)-(-inf)
LANES = 128


def _causal_mask(qi, ki, block_q: int, block_k: int, sq: int, skv: int,
                 window: int = 0):
    """[block_q, block_k] bool mask for the (qi, ki) tile; query positions are
    aligned to the END of the kv sequence (decode parity with
    ops/attention.py dot_product_attention). ``window`` > 0 additionally
    bands the mask to the trailing ``window`` keys (k > q - window)."""
    q_pos = qi * block_q + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 0) \
        + (skv - sq)
    k_pos = ki * block_k + jax.lax.broadcasted_iota(jnp.int32, (block_q, block_k), 1)
    m = q_pos >= k_pos
    if window > 0:
        m = jnp.logical_and(m, k_pos > q_pos - window)
    return m


def _tile_runs(qi, ki, block_q: int, block_k: int, diag_offset: int,
               causal: bool, window: int):
    """Whether the (qi, ki) tile intersects the (banded) causal region:
    skip above the diagonal (causal) AND fully below the band (window)."""
    run = (not causal) or (ki * block_k <= qi * block_q + (block_q - 1) + diag_offset)
    if window > 0:
        run = jnp.logical_and(
            run, ki * block_k + (block_k - 1) > qi * block_q + diag_offset - window)
    return run


def _fwd_kernel(q_ref, k_ref, v_ref, o_ref, lse_ref,
                m_scr, l_scr, acc_scr,
                *, scale: float, causal: bool, block_q: int, block_k: int,
                sq: int, skv: int, window: int):
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    # skip tiles above the causal diagonal / fully below the window band
    diag_offset = skv - sq
    run = _tile_runs(qi, ki, block_q, block_k, diag_offset, causal, window)

    @pl.when(run)
    def _step():
        # matmul inputs stay in the storage dtype (bf16 on the training
        # path): the MXU takes bf16 operands with fp32 accumulation natively;
        # upcasting first would force fp32 MXU passes (~8x slower)
        q = q_ref[0, 0]                              # [bq, d]
        k = k_ref[0, 0]                              # [bk, d]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal and window > 0:
            # banded tiles can be partial on both edges — mask every
            # running tile (windowed models only pay this)
            s = jnp.where(_causal_mask(qi, ki, block_q, block_k, sq, skv,
                                       window), s, NEG_INF)
        elif causal:
            # apply the element mask only on blocks crossing the diagonal
            partial = ki * block_k + (block_k - 1) > qi * block_q + diag_offset
            s = jnp.where(
                jnp.logical_and(partial,
                                jnp.logical_not(_causal_mask(qi, ki, block_q,
                                                             block_k, sq, skv))),
                NEG_INF, s)
        m_prev = m_scr[:, :1]                        # [bq, 1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        p = jnp.exp(s - m_new)                       # [bq, bk]
        corr = jnp.exp(m_prev - m_new)               # [bq, 1]
        l_new = l_scr[:, :1] * corr + jnp.sum(p, axis=-1, keepdims=True)
        v = v_ref[0, 0]                              # [bk, d]
        pv = jax.lax.dot_general(p.astype(v.dtype), v, (((1,), (0,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        acc_scr[:] = acc_scr[:] * corr + pv
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)
        l_scr[:] = jnp.broadcast_to(l_new, l_scr.shape)

    @pl.when(ki == nk - 1)
    def _final():
        # fully-masked rows (possible when causal and skv < sq): m stays at
        # NEG_INF but p = exp(NEG_INF - NEG_INF) = 1 polluted l/acc, so
        # detect via m, zero the output, and push lse to +inf so the
        # backward's exp(s - lse) is 0 for these rows.
        masked = m_scr[:, :1] <= NEG_INF / 2
        l = l_scr[:, :1]
        l_safe = jnp.where(jnp.logical_or(masked, l == 0.0), 1.0, l)
        o_ref[0, 0] = jnp.where(masked, 0.0, acc_scr[:] / l_safe).astype(o_ref.dtype)
        # LSE is emitted lane-replicated as [block_q, LANES]: Mosaic requires
        # the last two block dims to tile (8, 128), so a rank-3 (1, 1, bq)
        # block is not lowerable; callers slice [..., 0].
        lse = jnp.where(masked, -NEG_INF, m_scr[:, :1] + jnp.log(l_safe))
        lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:])


def _kv_tile_clamp(causal: bool, window: int, block_q: int, block_k: int,
                   diag_offset: int):
    """Clamp a skipped tile's kv-block index onto the nearest RUNNING
    tile's index. Pallas elides the DMA when an input's block index
    repeats across grid steps, so tiles whose compute is pl.when-skipped
    (above the causal diagonal, or fully below the window band) stop
    costing K/V traffic too — the same dedup the paged kernel uses. For
    banded attention this turns K/V traffic from O(s^2/bk) into
    O(s * window / bk), matching the compute bound."""
    def clamp(qi, ki):
        j = ki
        if causal:
            # fully-masked q tiles (possible when skv < sq) make last_run
            # negative — pin to block 0, never a negative DMA index
            last_run = (qi * block_q + block_q - 1 + diag_offset) // block_k
            j = jnp.maximum(0, jnp.minimum(j, last_run))
        if window > 0:
            first_run = jnp.maximum(
                0, (qi * block_q + diag_offset - window + 1) // block_k)
            j = jnp.maximum(j, first_run)
        return j
    return clamp


def _q_tile_clamp(causal: bool, window: int, block_q: int, block_k: int,
                  diag_offset: int, nq: int):
    """The dkv-side twin of :func:`_kv_tile_clamp`: clamp a skipped tile's
    q-block index (derived from the fused (group, q_block) grid dim) onto
    the nearest RUNNING tile — same band inequalities solved for qi."""
    def clamp(ki, gq):
        qi = jax.lax.rem(gq, nq)
        if causal:
            # first running q tile for this kv block: qi*bq+bq-1+diag >= ki*bk
            qi = jnp.maximum(qi, jnp.maximum(
                0, (ki * block_k - diag_offset) // block_q))
        if window > 0:
            # last running q tile: qi*bq+diag-window < ki*bk+bk-1
            t = ki * block_k + block_k - 1 + window - diag_offset
            qi = jnp.minimum(qi, jnp.maximum(0, (t - 1) // block_q))
        return qi
    return clamp


def _flash_forward(q, k, v, scale, causal, block_q, block_k, interpret,
                   window=0):
    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    group = hq // hkv
    block_q = min(block_q, sq)
    block_k = min(block_k, skv)
    nq, nk = sq // block_q, skv // block_k
    # [b, h, s, d] layout: heads as a grid axis, seq contiguous for tiling
    qt = q.transpose(0, 2, 1, 3)
    kt = k.transpose(0, 2, 1, 3)
    vt = v.transpose(0, 2, 1, 3)
    clamp = _kv_tile_clamp(causal, window, block_q, block_k, skv - sq)

    kernel = functools.partial(
        _fwd_kernel, scale=scale, causal=causal,
        block_q=block_q, block_k=block_k, sq=sq, skv=skv, window=window)
    out, lse = pl.pallas_call(
        kernel,
        grid=(b, hq, nq, nk),
        in_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki, g=group: (bi, hi // g, clamp(qi, ki), 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_k, d),
                         lambda bi, hi, qi, ki, g=group: (bi, hi // g, clamp(qi, ki), 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((1, 1, block_q, d), lambda bi, hi, qi, ki: (bi, hi, qi, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((1, 1, block_q, LANES),
                         lambda bi, hi, qi, ki: (bi, hi, qi, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
            jax.ShapeDtypeStruct((b, hq, sq, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, LANES), jnp.float32),
            pltpu.VMEM((block_q, d), jnp.float32),
        ],
        interpret=interpret,
    )(qt, kt, vt)
    return out.transpose(0, 2, 1, 3), lse[..., 0]


def _dq_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref, dq_ref,
               acc_scr, *, scale: float, causal: bool,
               block_q: int, block_k: int, sq: int, skv: int, window: int):
    qi, ki = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)

    @pl.when(ki == 0)
    def _init():
        acc_scr[:] = jnp.zeros_like(acc_scr)

    diag_offset = skv - sq
    run = _tile_runs(qi, ki, block_q, block_k, diag_offset, causal, window)

    @pl.when(run)
    def _step():
        q = q_ref[0, 0]                              # storage dtype (bf16)
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]                   # [bq, 1]
        delta = delta_ref[0, 0][:, :1]               # [bq, 1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = jnp.where(_causal_mask(qi, ki, block_q, block_k, sq, skv,
                                       window), s, NEG_INF)
        p = jnp.exp(s - lse)                         # [bq, bk] fp32
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(k.dtype)
        acc_scr[:] += jax.lax.dot_general(ds, k, (((1,), (0,)), ((), ())),
                                          preferred_element_type=jnp.float32)

    @pl.when(ki == nk - 1)
    def _final():
        dq_ref[0, 0] = acc_scr[:].astype(dq_ref.dtype)


def _dkv_kernel(q_ref, k_ref, v_ref, do_ref, lse_ref, delta_ref,
                dk_ref, dv_ref, dk_scr, dv_scr,
                *, scale: float, causal: bool,
                block_q: int, block_k: int, sq: int, skv: int, nq: int,
                window: int):
    # last grid dim fuses (q-head group, q block): dk/dv accumulate across
    # the whole group in scratch without materializing per-q-head K/V
    ki, gq = pl.program_id(2), pl.program_id(3)
    n_gq = pl.num_programs(3)
    qi = jax.lax.rem(gq, nq)

    @pl.when(gq == 0)
    def _init():
        dk_scr[:] = jnp.zeros_like(dk_scr)
        dv_scr[:] = jnp.zeros_like(dv_scr)

    diag_offset = skv - sq
    run = _tile_runs(qi, ki, block_q, block_k, diag_offset, causal, window)

    @pl.when(run)
    def _step():
        q = q_ref[0, 0]                              # storage dtype (bf16)
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0]
        lse = lse_ref[0, 0][:, :1]
        delta = delta_ref[0, 0][:, :1]
        s = jax.lax.dot_general(q, k, (((1,), (1,)), ((), ())),
                                preferred_element_type=jnp.float32) * scale
        if causal:
            s = jnp.where(_causal_mask(qi, ki, block_q, block_k, sq, skv,
                                       window), s, NEG_INF)
        p = jnp.exp(s - lse)                         # [bq, bk] fp32
        # dv += P^T @ dO
        dv_scr[:] += jax.lax.dot_general(p.astype(do.dtype), do,
                                         (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)
        dp = jax.lax.dot_general(do, v, (((1,), (1,)), ((), ())),
                                 preferred_element_type=jnp.float32)
        ds = (p * (dp - delta) * scale).astype(q.dtype)  # [bq, bk]
        # dk += dS^T @ Q
        dk_scr[:] += jax.lax.dot_general(ds, q, (((0,), (0,)), ((), ())),
                                         preferred_element_type=jnp.float32)

    @pl.when(gq == n_gq - 1)
    def _final():
        dk_ref[0, 0] = dk_scr[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_scr[:].astype(dv_ref.dtype)


def _seq_spec(block: int, d: int, index_map):
    return pl.BlockSpec((1, 1, block, d), index_map, memory_space=pltpu.VMEM)


def _row_spec(block: int, index_map):
    # Row statistics (LSE, delta) travel lane-replicated as
    # [..., block_q, LANES] — see _fwd_kernel._final for why.
    return pl.BlockSpec((1, 1, block, LANES), index_map,
                        memory_space=pltpu.VMEM)


def _flash_backward(q, k, v, out, lse, do, scale, causal, block_q, block_k,
                    interpret, window=0):
    b, sq, hq, d = q.shape
    _, skv, hkv, _ = k.shape
    group = hq // hkv
    block_q = min(block_q, sq)
    block_k = min(block_k, skv)
    nq, nk = sq // block_q, skv // block_k
    qt, kt, vt = (x.transpose(0, 2, 1, 3) for x in (q, k, v))
    dot = do.transpose(0, 2, 1, 3)
    ot = out.transpose(0, 2, 1, 3)
    delta = jnp.sum(dot.astype(jnp.float32) * ot.astype(jnp.float32), axis=-1)
    # lane-replicate row stats for Mosaic-tileable [bq, LANES] blocks
    lse = jnp.broadcast_to(lse[..., None], (*lse.shape, LANES))
    delta = jnp.broadcast_to(delta[..., None], (*delta.shape, LANES))

    # dq: grid (b, q_head, q_block, kv_block); K/V indexed per kv-head group
    # (same trick as the forward — never expanded to q-heads). Skipped
    # tiles clamp their K/V index onto a running tile so they cost no DMA
    # (see _kv_tile_clamp).
    clamp = _kv_tile_clamp(causal, window, block_q, block_k, skv - sq)
    dq = pl.pallas_call(
        functools.partial(_dq_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, sq=sq, skv=skv,
                          window=window),
        grid=(b, hq, nq, nk),
        in_specs=[
            _seq_spec(block_q, d, lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            _seq_spec(block_k, d,
                      lambda bi, hi, qi, ki, g=group: (bi, hi // g, clamp(qi, ki), 0)),
            _seq_spec(block_k, d,
                      lambda bi, hi, qi, ki, g=group: (bi, hi // g, clamp(qi, ki), 0)),
            _seq_spec(block_q, d, lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            _row_spec(block_q, lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
            _row_spec(block_q, lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        ],
        out_specs=_seq_spec(block_q, d, lambda bi, hi, qi, ki: (bi, hi, qi, 0)),
        out_shape=jax.ShapeDtypeStruct((b, hq, sq, d), q.dtype),
        scratch_shapes=[pltpu.VMEM((block_q, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    # dk/dv: grid (b, kv_head, kv_block, group*q_block) — the fused last dim
    # walks every q-head of the group then every q block, accumulating into
    # one [block_k, d] scratch per kv head (no hq-sized dk/dv intermediates).
    # Skipped q tiles (above the diagonal for this kv block, or fully past
    # the window band) clamp their q-side index onto a running tile so
    # they cost no q/do/lse/delta DMA.
    def qhead(hk, gq, g=group):
        return hk * g + gq // nq

    q_clamp = _q_tile_clamp(causal, window, block_q, block_k, skv - sq, nq)

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, scale=scale, causal=causal,
                          block_q=block_q, block_k=block_k, sq=sq, skv=skv,
                          nq=nq, window=window),
        grid=(b, hkv, nk, group * nq),
        in_specs=[
            _seq_spec(block_q, d,
                      lambda bi, hk, ki, gq: (bi, qhead(hk, gq), q_clamp(ki, gq), 0)),
            _seq_spec(block_k, d, lambda bi, hk, ki, gq: (bi, hk, ki, 0)),
            _seq_spec(block_k, d, lambda bi, hk, ki, gq: (bi, hk, ki, 0)),
            _seq_spec(block_q, d,
                      lambda bi, hk, ki, gq: (bi, qhead(hk, gq), q_clamp(ki, gq), 0)),
            _row_spec(block_q,
                      lambda bi, hk, ki, gq: (bi, qhead(hk, gq), q_clamp(ki, gq), 0)),
            _row_spec(block_q,
                      lambda bi, hk, ki, gq: (bi, qhead(hk, gq), q_clamp(ki, gq), 0)),
        ],
        out_specs=[
            _seq_spec(block_k, d, lambda bi, hk, ki, gq: (bi, hk, ki, 0)),
            _seq_spec(block_k, d, lambda bi, hk, ki, gq: (bi, hk, ki, 0)),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((b, hkv, skv, d), k.dtype),
            jax.ShapeDtypeStruct((b, hkv, skv, d), v.dtype),
        ],
        scratch_shapes=[pltpu.VMEM((block_k, d), jnp.float32),
                        pltpu.VMEM((block_k, d), jnp.float32)],
        interpret=interpret,
    )(qt, kt, vt, dot, lse, delta)

    return (dq.transpose(0, 2, 1, 3),
            dk.transpose(0, 2, 1, 3),
            dv.transpose(0, 2, 1, 3))


@functools.partial(jax.custom_vjp, nondiff_argnums=(3, 4, 5, 6, 7, 8))
def flash_attention(q, k, v, causal: bool = True, scale: Optional[float] = None,
                    block_q: int = 1024, block_k: int = 1024,
                    interpret: bool = False, window: int = 0):
    """q: [b, sq, hq, d]; k/v: [b, skv, hkv, d] -> [b, sq, hq, d].

    ``sq``/``skv`` must divide by the (clamped) block sizes; the dispatcher
    in ``ops/attention.py`` falls back to the jnp path otherwise.
    ``window`` > 0 (static, requires causal) bands attention to the
    trailing ``window`` keys: tiles fully below the band are skipped, so
    compute is O(s * window) instead of O(s^2 / 2) (Mistral sliding
    window).
    """
    assert window <= 0 or causal, "window requires causal attention"
    scale_v = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    out, _ = _flash_forward(q, k, v, scale_v, causal, block_q, block_k,
                            interpret, window)
    return out


def _fa_fwd(q, k, v, causal, scale, block_q, block_k, interpret, window):
    scale_v = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    out, lse = _flash_forward(q, k, v, scale_v, causal, block_q, block_k,
                              interpret, window)
    # Name the kernel residuals so remat policies can SAVE them:
    # checkpoint_dots ("selective") does not match a pallas_call, so under
    # plain selective remat the backward replays this whole forward kernel
    # per layer just to regenerate (out, lse). The "selective_flash" policy
    # (runtime/activation_checkpointing.py) saves these names instead —
    # one flash forward per layer per step, ~33 MB/layer at the bench
    # shape. q/k/v are projection dot outputs, already policy-saved.
    from jax.ad_checkpoint import checkpoint_name

    out = checkpoint_name(out, "flash_out")
    lse = checkpoint_name(lse, "flash_lse")
    return out, (q, k, v, out, lse)


def _fa_bwd(causal, scale, block_q, block_k, interpret, window, res, g):
    q, k, v, out, lse = res
    scale_v = scale if scale is not None else 1.0 / np.sqrt(q.shape[-1])
    dq, dk, dv = _flash_backward(q, k, v, out, lse, g, scale_v, causal,
                                 block_q, block_k, interpret, window)
    return dq, dk, dv


flash_attention.defvjp(_fa_fwd, _fa_bwd)


def flash_attention_padded(q, k, v, causal: bool = True,
                           scale: Optional[float] = None,
                           block_q: int = 1024, block_k: int = 1024,
                           interpret: bool = False, window: int = 0):
    """Arbitrary-length causal SELF-attention via symmetric zero-padding to
    a lane multiple. Exact: with sq == skv and causal masking, a real query
    i attends keys <= i, so padded keys (> real length) are always masked
    out; padded query rows produce garbage that the final slice drops, and
    their cotangent is zero so dk/dv stay exact through the backward.
    (Banding by ``window`` composes: the band only removes keys.)"""
    assert causal and q.shape[1] == k.shape[1], \
        "padding trick requires causal self-attention (sq == skv)"
    s = q.shape[1]
    pad = (-s) % LANES
    if pad == 0:
        return flash_attention(q, k, v, causal, scale, block_q, block_k,
                               interpret, window)
    widths = ((0, 0), (0, pad), (0, 0), (0, 0))
    out = flash_attention(jnp.pad(q, widths), jnp.pad(k, widths),
                          jnp.pad(v, widths), causal, scale,
                          block_q, block_k, interpret, window)
    return out[:, :s]
