"""Paged-attention decode kernel (Pallas TPU) with scalar-prefetched block
tables.

Reference surface: FastGen's ragged kernels
(``deepspeed/inference/v2/kernels/ragged_ops/`` — blocked flash over a
paged KV cache, with host-built "atoms" describing each sequence's pages).
TPU-first redesign: the block table is a scalar-prefetch operand
(``pltpu.PrefetchScalarGridSpec``), so each grid step's page is DMA'd
straight from the pool in HBM via the BlockSpec index map — no [T, ctx]
gather materialization (the jnp fallback in ``inference/ragged.py`` does
exactly that and is correctness-only).

Layout contract (chosen for TPU tiling):
  q:        [T, hq, hd]                 one token per ragged lane
  k_pool:   [n_pages, hkv, block, hd]   (block, hd) minor = native tiles
  v_pool:   [n_pages, hkv, block, hd]
  tables:   [T, max_pages] int32        per-token page list
  positions:[T] int32                   absolute position of each token
Output:     [T, hq, hd]

Grid: (T, max_pages) with pages innermost and ALL kv heads folded into
each step — one [hkv, block, hd] page DMA per step (hkv x bigger than a
per-head grid, which at block 16 moved 2 KB per step and was DMA-latency
bound). Online softmax in VMEM scratch (flash-2 style, as
ops/pallas/flash_attention.py) over [hkv*group, ...] row tiles. Pages past
a token's context are skipped compute-side via ``pl.when`` AND their index
map is clamped to the last visible page — Pallas elides the copy when the
block index repeats, so dead pages cost no DMA either.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128


def _kernel(tables_ref, pos_ref,          # scalar prefetch
            q_ref, k_ref, v_ref,          # blocks
            o_ref,                        # out
            m_scr, l_scr, acc_scr,
            *, scale: float, block: int, hkv: int, group: int):
    t, p = pl.program_id(0), pl.program_id(1)
    np_pages = pl.num_programs(1)

    @pl.when(p == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    pos = pos_ref[t]
    run = p * block <= pos  # page holds at least one visible row

    @pl.when(run)
    def _step():
        q = q_ref[0]                                 # [hkv, group, hd] bf16
        k = k_ref[0]                                 # [hkv, block, hd] bf16
        # batched-over-heads MXU matmul: [hkv, group, block]
        s = jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                                preferred_element_type=jnp.float32) * scale
        s = s.reshape(hkv * group, block)
        row_pos = p * block + jax.lax.broadcasted_iota(
            jnp.int32, s.shape, 1)                   # [hkv*group, block]
        s = jnp.where(row_pos <= pos, s, NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        pr = jnp.exp(s - m_new)                      # [hkv*group, block]
        corr = jnp.exp(m_prev - m_new)
        l_scr[:] = jnp.broadcast_to(l_scr[:, :1] * corr +
                                    jnp.sum(pr, axis=-1, keepdims=True),
                                    l_scr.shape)
        v = v_ref[0]                                 # [hkv, block, hd] bf16
        pv = jax.lax.dot_general(
            pr.reshape(hkv, group, block).astype(v.dtype), v,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)      # [hkv, group, hd]
        acc_scr[:] = acc_scr[:] * corr + pv.reshape(hkv * group, -1)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(p == np_pages - 1)
    def _final():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)         # fully-masked lane guard
        o_ref[0] = (acc_scr[:] / l_safe).reshape(o_ref.shape[1:]) \
            .astype(o_ref.dtype)


def paged_attention(q, k_pool, v_pool, tables, positions, *,
                    scale=None, interpret: bool = False):
    """Decode attention over a paged KV pool. See module docstring for the
    layout contract. Causal by construction: token t sees pool rows with
    position <= positions[t] along its own page list."""
    T, hq, hd = q.shape
    n_pages, hkv, block, _ = k_pool.shape
    max_pages = tables.shape[1]
    group = hq // hkv
    assert hq % hkv == 0
    scale = scale if scale is not None else 1.0 / np.sqrt(hd)

    qg = q.reshape(T, hkv, group, hd)
    tables = tables.astype(jnp.int32)
    positions = positions.astype(jnp.int32)

    def q_index(t, p, tbl, pos):
        return (t, 0, 0, 0)

    def kv_index(t, p, tbl, pos):
        # past-the-end pages re-use the last visible page's index: Pallas
        # skips the copy when the block index repeats, so they cost no DMA
        p_c = jnp.minimum(p, pos[t] // block)
        return (tbl[t, p_c], 0, 0, 0)

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(T, max_pages),
        in_specs=[
            pl.BlockSpec((1, hkv, group, hd), q_index),
            pl.BlockSpec((1, hkv, block, hd), kv_index),
            pl.BlockSpec((1, hkv, block, hd), kv_index),
        ],
        out_specs=pl.BlockSpec((1, hkv, group, hd), q_index),
        scratch_shapes=[
            pltpu.VMEM((hkv * group, LANES), jnp.float32),
            pltpu.VMEM((hkv * group, LANES), jnp.float32),
            pltpu.VMEM((hkv * group, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, scale=scale, block=block,
                          hkv=hkv, group=group),
        out_shape=jax.ShapeDtypeStruct((T, hkv, group, hd), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(tables, positions, qg, k_pool, v_pool)
    return out.reshape(T, hq, hd)


def paged_attention_reference(q, k_pool, v_pool, tables, positions, *,
                              scale=None):
    """jnp reference (gather-based) with identical semantics — the numerics
    oracle for the kernel and the off-TPU fallback formulation."""
    T, hq, hd = q.shape
    n_pages, hkv, block, _ = k_pool.shape
    scale = scale if scale is not None else 1.0 / np.sqrt(hd)
    group = hq // hkv
    # [T, max_pages, hkv, block, hd] -> [T, ctx, hkv, hd]
    keys = k_pool[tables].transpose(0, 2, 1, 3, 4).reshape(
        T, hkv, -1, hd).transpose(0, 2, 1, 3)
    vals = v_pool[tables].transpose(0, 2, 1, 3, 4).reshape(
        T, hkv, -1, hd).transpose(0, 2, 1, 3)
    keys = jnp.repeat(keys, group, axis=2)
    vals = jnp.repeat(vals, group, axis=2)
    logits = jnp.einsum("thd,tkhd->thk", q.astype(jnp.float32),
                        keys.astype(jnp.float32)) * scale
    kv_pos = jnp.arange(keys.shape[1])[None, :]
    visible = kv_pos <= positions[:, None]
    logits = jnp.where(visible[:, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("thk,tkhd->thd", probs,
                      vals.astype(jnp.float32)).astype(q.dtype)
