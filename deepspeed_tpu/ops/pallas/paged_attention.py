"""Paged-attention decode kernel (Pallas TPU) with scalar-prefetched block
tables and multi-page chunks.

Reference surface: FastGen's ragged kernels
(``deepspeed/inference/v2/kernels/ragged_ops/`` — blocked flash over a
paged KV cache, with host-built "atoms" describing each sequence's pages).
TPU-first redesign: the block table is a scalar-prefetch operand
(``pltpu.PrefetchScalarGridSpec``) and every grid step's pages are DMA'd
straight from the pool in HBM by the Pallas pipeline — no [T, ctx] gather
materialization (the jnp fallback in ``inference/ragged.py`` does exactly
that and is correctness-only).

Layout contract (chosen for TPU tiling):
  q:        [T, hq, hd]                 one token per ragged lane
  k_pool:   [n_pages, hkv, block, hd]   (block, hd) minor = native tiles
  v_pool:   [n_pages, hkv, block, hd]
  tables:   [T, max_pages] int32        per-token page list
  positions:[T] int32                   absolute position of each token
Output:     [T, hq, hd]

Grid: (T, n_chunks) where a chunk is ``pages_per_chunk`` pages. The KV
pools enter as 2*ppc separate BlockSpec inputs — one [hkv, block, hd]
page slot each, whose index maps pick that slot's page id out of the
prefetched table — so the standard Pallas pipeline double-buffers the
scattered page fetches (manual ``make_async_copy`` cannot: Mosaic rejects
any hand-rolled DMA whose lane dim is under 128, i.e. every hd=64 pool).
In-kernel the ppc page blocks concatenate along the row dim into one
[hkv, ppc*block, hd] tile per chunk, so each grid step runs one big
batched MXU matmul instead of ppc tiny ones. Online softmax in VMEM
scratch (flash-2 style, as ops/pallas/flash_attention.py) over
[hkv*group, ...] row tiles. Chunks past a token's context are skipped
compute-side via ``pl.when`` AND their page indices clamp to the last
live page — Pallas elides the copy when an input's block index repeats,
so dead chunks cost (almost) no DMA either. An earlier revision used a
(T, max_pages) grid with one page per step; at 64 seqs x 64 pages that is
4096 sequential grid steps of ~32 KB each and ran DMA-latency bound,
~0.8x the XLA gather path. This formulation replaces it.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30
LANES = 128


def _kernel(*refs,
            scale: float, block: int, hkv: int, group: int, ppc: int,
            num_scalars: int, window: int = 0, kv_bits: int = 0):
    # scalar-prefetch refs lead; positions is always the last of them.
    # kv_bits > 0 = quantized pool: 2*ppc extra per-page SCALE inputs
    # follow the payload pages, and the payload dequantizes in VMEM
    # right after the concat (the "dequant inside the kernel read path")
    pos_ref = refs[num_scalars - 1]
    q_ref, *rest = refs[num_scalars:]
    krefs, vrefs = rest[:ppc], rest[ppc:2 * ppc]
    n_in = 2 * ppc + (2 * ppc if kv_bits else 0)
    ksrefs = rest[2 * ppc:3 * ppc] if kv_bits else ()
    vsrefs = rest[3 * ppc:4 * ppc] if kv_bits else ()
    o_ref = rest[n_in]
    m_scr, l_scr, acc_scr = rest[n_in + 1:]
    t, c = pl.program_id(0), pl.program_id(1)
    nchunks = pl.num_programs(1)
    span = ppc * block

    @pl.when(c == 0)
    def _init():
        m_scr[:] = jnp.full_like(m_scr, NEG_INF)
        l_scr[:] = jnp.zeros_like(l_scr)
        acc_scr[:] = jnp.zeros_like(acc_scr)

    pos = pos_ref[t]
    run = c * span <= pos  # chunk holds at least one visible row
    if window > 0:
        # banded: rows <= pos - window are invisible; skip chunks whose
        # whole span lies below the band
        run = jnp.logical_and(run, (c + 1) * span - 1 > pos - window)

    @pl.when(run)
    def _step():
        q = q_ref[0]                                 # [hkv, group, hd] bf16
        k = jnp.concatenate([kr[0] for kr in krefs], axis=1)
        v = jnp.concatenate([vr[0] for vr in vrefs], axis=1)
        if kv_bits:
            # quantized pages: unpack (int4) + per-row scale in VMEM; the
            # matmuls below then run in fp32 (q is cast to match). The
            # nibble layout lives in ONE place (ops/quantizer) — pure
            # jnp, so it traces inside the kernel body too
            from ...ops.quantizer import unpack_kv_int4

            ks = jnp.concatenate([r[0] for r in ksrefs], axis=1)  # [hkv, span]
            vs = jnp.concatenate([r[0] for r in vsrefs], axis=1)
            if kv_bits == 4:
                k = unpack_kv_int4(k)
                v = unpack_kv_int4(v)
            k = k.astype(jnp.float32) * ks[..., None]
            v = v.astype(jnp.float32) * vs[..., None]
            q = q.astype(jnp.float32)
        # batched-over-heads MXU matmul: [hkv, group, span]
        s = jax.lax.dot_general(q, k, (((2,), (2,)), ((0,), (0,))),
                                preferred_element_type=jnp.float32) * scale
        s = s.reshape(hkv * group, span)
        row_pos = c * span + jax.lax.broadcasted_iota(jnp.int32, s.shape, 1)
        visible = row_pos <= pos
        if window > 0:
            visible = jnp.logical_and(visible, row_pos > pos - window)
        s = jnp.where(visible, s, NEG_INF)
        m_prev = m_scr[:, :1]
        m_new = jnp.maximum(m_prev, jnp.max(s, axis=-1, keepdims=True))
        pr = jnp.exp(s - m_new)                      # [hkv*group, span]
        corr = jnp.exp(m_prev - m_new)
        l_scr[:] = jnp.broadcast_to(l_scr[:, :1] * corr +
                                    jnp.sum(pr, axis=-1, keepdims=True),
                                    l_scr.shape)
        pv = jax.lax.dot_general(
            pr.reshape(hkv, group, span).astype(v.dtype), v,
            (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32)      # [hkv, group, hd]
        acc_scr[:] = acc_scr[:] * corr + pv.reshape(hkv * group, -1)
        m_scr[:] = jnp.broadcast_to(m_new, m_scr.shape)

    @pl.when(c == nchunks - 1)
    def _final():
        l = l_scr[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)         # fully-masked lane guard
        o_ref[0] = (acc_scr[:] / l_safe).reshape(o_ref.shape[1:]) \
            .astype(o_ref.dtype)


def _check_quant_geometry(k_pool, hd: int, kv_bits: int) -> None:
    """Fail loudly on a kv_bits/payload mismatch: an int4 nibble-packed
    pool read with the default ``kv_bits=8`` would dequantize to
    shape-valid garbage (hd//2 channels silently re-folded by the
    downstream reshape), not an error."""
    if kv_bits == 4:
        if k_pool.dtype != jnp.uint8 or k_pool.shape[-1] * 2 != hd:
            raise ValueError(
                f"kv_bits=4 expects a nibble-packed uint8 pool "
                f"[..., hd//2={hd // 2}], got {k_pool.dtype} "
                f"[..., {k_pool.shape[-1]}] — pass the kv_bits the pool "
                f"was quantized with")
    elif kv_bits == 8:
        if k_pool.dtype != jnp.int8 or k_pool.shape[-1] != hd:
            raise ValueError(
                f"kv_bits=8 expects an int8 pool [..., hd={hd}], got "
                f"{k_pool.dtype} [..., {k_pool.shape[-1]}] — pass the "
                f"kv_bits the pool was quantized with")
    else:
        raise ValueError(f"kv_bits must be 4 or 8 with scales, got {kv_bits}")


def paged_attention(q, k_pool, v_pool, tables, positions, *,
                    seq_slots=None, scale=None,
                    pages_per_chunk: int | None = None,
                    live_pages: int | None = None,
                    window: int = 0,
                    k_scale=None, v_scale=None, kv_bits: int = 8,
                    interpret: bool = False):
    """Decode attention over a paged KV pool. See module docstring for the
    layout contract. Causal by construction: token t sees pool rows with
    position <= positions[t] along its own page list.

    ``tables`` is per-token [T, max_pages] by default. For ragged batches
    where many tokens share a sequence (SplitFuse prefill chunks), pass
    per-sequence tables [n_seqs, max_pages] plus ``seq_slots`` [T] mapping
    each token to its table row — the prefetched scalars then stay
    O(n_seqs * max_pages) instead of O(T * max_pages), which must fit SMEM
    (a [4096, 128] per-token table is 2 MB and does not).

    ``live_pages`` (static) bounds the page walk: the grid only visits
    ceil(live_pages / ppc) chunks per token. Dead chunks are pl.when-skipped
    anyway, but their ~us of grid overhead dominates short-context decode
    over a long max_context table (caller guarantees every
    positions[t] < live_pages * block; rows beyond are silently ignored).

    ``window`` > 0 (static) bands attention to the trailing ``window``
    positions (Mistral/Qwen2 sliding-window serving): chunks wholly below
    the band are pl.when-skipped AND their page DMA indices clamp to the
    band's first live page, so repeated block indices dedup the copies —
    compute and traffic are O(window), not O(context).

    ``k_scale``/``v_scale`` [n_pages, hkv, block] switch the pools to
    quantized storage (``ops/quantizer.quantize_kv``; int8 payload, or
    nibble-packed uint8 [..., hd//2] at ``kv_bits=4``): scales ride the
    same per-page BlockSpec pipeline as the payloads (half/quarter the
    page DMA bytes vs an fp pool) and the payload dequantizes in VMEM
    right before the QK^T matmul. NB: the f32 scale tile's lane dim is
    ``block`` (< 128 for typical pools) — fine in interpret mode and on
    current Mosaic via padding, but on-TPU validation of the quantized
    kernel outside interpret mode is a follow-up (same status the fused
    collective kernels shipped with)."""
    T, hq, hd = q.shape
    n_pages, hkv, block, _ = k_pool.shape
    quant = k_scale is not None
    if quant:
        _check_quant_geometry(k_pool, hd, kv_bits)
    max_pages = tables.shape[1]
    group = hq // hkv
    assert hq % hkv == 0
    scale = scale if scale is not None else 1.0 / np.sqrt(hd)
    walk_pages = max_pages if live_pages is None \
        else max(1, min(live_pages, max_pages))
    if pages_per_chunk is None:
        pages_per_chunk = max(1, min(walk_pages, 256 // block))
    ppc = min(pages_per_chunk, walk_pages)
    nchunks = -(-walk_pages // ppc)

    qg = q.reshape(T, hkv, group, hd)
    tables = tables.astype(jnp.int32)
    positions = positions.astype(jnp.int32)
    if seq_slots is None:
        scalars = (tables, positions)
    else:
        scalars = (tables, seq_slots.astype(jnp.int32), positions)

    def row_of(t, s):
        return t if seq_slots is None else s[1][t]

    def q_index(t, c, *s):
        return (t, 0, 0, 0)

    def page_index(i):
        def index(t, c, *s):
            # past-the-end slots re-use the last live page's index: Pallas
            # skips the copy when the block index repeats, so dead chunks
            # cost no DMA — and the table read never strays off the row.
            # With a window, below-band slots clamp UP to the band's first
            # live page for the same dedup effect.
            tbl, pos = s[0], s[-1]
            j = jnp.minimum(c * ppc + i, max_pages - 1)
            j = jnp.minimum(j, pos[t] // block)
            if window > 0:
                lo = jnp.maximum(pos[t] - (window - 1), 0) // block
                j = jnp.maximum(j, lo)
            return (tbl[row_of(t, s), j], 0, 0, 0)
        return index

    def page_index3(i):
        # the scale leaves are [n_pages, hkv, block] (no channel dim):
        # same page pick as the payload, one fewer trailing zero
        idx4 = page_index(i)

        def index(t, c, *s):
            return idx4(t, c, *s)[:3]
        return index

    hd_p = k_pool.shape[-1]               # packed channel dim (= hd unless int4)
    page_spec = lambda i: pl.BlockSpec((1, hkv, block, hd_p), page_index(i))
    scale_spec = lambda i: pl.BlockSpec((1, hkv, block), page_index3(i))
    in_specs = [pl.BlockSpec((1, hkv, group, hd), q_index)] \
        + [page_spec(i) for i in range(ppc)] * 2
    operands = [qg, *([k_pool] * ppc), *([v_pool] * ppc)]
    if quant:
        in_specs += [scale_spec(i) for i in range(ppc)] * 2
        operands += [*([k_scale] * ppc), *([v_scale] * ppc)]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(scalars),
        grid=(T, nchunks),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, hkv, group, hd), q_index),
        scratch_shapes=[
            pltpu.VMEM((hkv * group, LANES), jnp.float32),
            pltpu.VMEM((hkv * group, LANES), jnp.float32),
            pltpu.VMEM((hkv * group, hd), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, scale=scale, block=block, hkv=hkv,
                          group=group, ppc=ppc, num_scalars=len(scalars),
                          window=int(window),  # dslint: disable=host-sync -- window is a static Python int kernel parameter, never a tracer
                          kv_bits=int(kv_bits) if quant else 0),  # dslint: disable=host-sync -- kv_bits is a static Python int kernel parameter, never a tracer
        out_shape=jax.ShapeDtypeStruct((T, hkv, group, hd), q.dtype),
        grid_spec=grid_spec,
        interpret=interpret,
    )(*scalars, *operands)
    return out.reshape(T, hq, hd)


def paged_attention_reference(q, k_pool, v_pool, tables, positions, *,
                              scale=None, window: int = 0,
                              k_scale=None, v_scale=None, kv_bits: int = 8):
    """jnp reference (gather-based) with identical semantics — the numerics
    oracle for the kernel and the off-TPU fallback formulation.
    ``window`` > 0 bands attention to the trailing ``window`` positions
    (sliding-window serving: k > pos - window).

    ``k_scale``/``v_scale`` [n_pages, hkv, block] switch the pools to
    quantized storage (``ops/quantizer.quantize_kv``): int8 payloads —
    or, at ``kv_bits=4``, nibble-packed uint8 [..., hd//2] — are
    dequantized AFTER the per-token page gather (only pages actually
    read pay the dequant, mirroring the kernel's in-VMEM dequant)."""
    from ..quantizer import dequantize_kv

    T, hq, hd = q.shape
    n_pages, hkv, block, _ = k_pool.shape
    scale = scale if scale is not None else 1.0 / np.sqrt(hd)
    group = hq // hkv
    if k_scale is not None:
        _check_quant_geometry(k_pool, hd, kv_bits)
        # gather first ([T, max_pages, hkv, block, hd_p]), then dequant
        # page payloads with their per-row scales ([T, max_pages, hkv,
        # block] broadcast over hd)
        k_pages = dequantize_kv(k_pool[tables], k_scale[tables],
                                bits=kv_bits)
        v_pages = dequantize_kv(v_pool[tables], v_scale[tables],
                                bits=kv_bits)
        keys = k_pages.transpose(0, 2, 1, 3, 4).reshape(
            T, hkv, -1, hd).transpose(0, 2, 1, 3)
        vals = v_pages.transpose(0, 2, 1, 3, 4).reshape(
            T, hkv, -1, hd).transpose(0, 2, 1, 3)
        keys = jnp.repeat(keys, group, axis=2)
        vals = jnp.repeat(vals, group, axis=2)
        logits = jnp.einsum("thd,tkhd->thk", q.astype(jnp.float32),
                            keys) * scale
        kv_pos = jnp.arange(keys.shape[1])[None, :]
        visible = kv_pos <= positions[:, None]
        if window > 0:
            visible = visible & (kv_pos > positions[:, None] - window)
        logits = jnp.where(visible[:, None, :], logits, NEG_INF)
        probs = jax.nn.softmax(logits, axis=-1)
        return jnp.einsum("thk,tkhd->thd", probs, vals).astype(q.dtype)
    # [T, max_pages, hkv, block, hd] -> [T, ctx, hkv, hd]
    keys = k_pool[tables].transpose(0, 2, 1, 3, 4).reshape(
        T, hkv, -1, hd).transpose(0, 2, 1, 3)
    vals = v_pool[tables].transpose(0, 2, 1, 3, 4).reshape(
        T, hkv, -1, hd).transpose(0, 2, 1, 3)
    keys = jnp.repeat(keys, group, axis=2)
    vals = jnp.repeat(vals, group, axis=2)
    logits = jnp.einsum("thd,tkhd->thk", q.astype(jnp.float32),
                        keys.astype(jnp.float32)) * scale
    kv_pos = jnp.arange(keys.shape[1])[None, :]
    visible = kv_pos <= positions[:, None]
    if window > 0:
        visible = visible & (kv_pos > positions[:, None] - window)
    logits = jnp.where(visible[:, None, :], logits, NEG_INF)
    probs = jax.nn.softmax(logits, axis=-1)
    return jnp.einsum("thk,tkhd->thd", probs,
                      vals.astype(jnp.float32)).astype(q.dtype)
