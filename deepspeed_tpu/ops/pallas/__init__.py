"""Pallas TPU kernels — the replacement for the reference's ``csrc/`` CUDA
kernel zoo (SURVEY.md §2.4). Each module documents which reference kernels
it subsumes."""
