"""Pallas blockwise int8 quantize/dequantize kernels.

SURVEY §2.4 parity target: the reference's CUDA quantizer suite
(``csrc/quantization/{quantize.cu,dequantize.cu,pt_binding.cpp}`` — fused
absmax + scale + pack at memory bandwidth). The XLA path in
``ops/quantizer.py`` stays the reference semantics (and the fallback);
these kernels fuse the scale reduction and the pack/unpack into single
VMEM passes so the qwZ/qgZ bracket cost is one HBM read + one write —
the quantity ``scripts/tpu_quant_comm_bench.py`` measures.

Layout: values as [rows, block] with ``block`` a lane multiple (256
default = 2 lanes); scales are emitted lane-replicated [rows, 128] (the
same Mosaic constraint trick as the flash kernel's LSE) and sliced to
[rows] by the wrapper. int8 tiles are (32, 128)-aligned, so ``rows`` is
processed in multiples of 32 per grid step.
"""

from __future__ import annotations

import functools
from typing import Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
ROW_TILE = 256          # rows per grid step (multiple of 32 for int8 tiles)


def _row_tile(rows: int) -> int:
    """Largest tile in {256,128,64,32} dividing ``rows`` (int8 tiles are
    (32,128)-aligned, so rows must be a multiple of 32 — the dispatch
    guard enforces that)."""
    for t in (ROW_TILE, 128, 64, 32):
        if rows % t == 0:
            return t
    raise AssertionError(f"rows {rows} not a multiple of 32")


def _quant_kernel(x_ref, q_ref, s_ref, *, qmax: float):
    x = x_ref[...].astype(jnp.float32)                    # [R, block]
    scale = jnp.max(jnp.abs(x), axis=1, keepdims=True) / qmax
    scale = jnp.where(scale == 0, 1.0, scale)
    q = jnp.clip(jnp.round(x / scale), -qmax - 1, qmax)
    q_ref[...] = q.astype(jnp.int8)
    s_ref[...] = jnp.broadcast_to(scale, (x.shape[0], LANES))


def _dequant_kernel(q_ref, s_ref, o_ref):
    q = q_ref[...].astype(jnp.float32)                    # [R, block]
    scale = s_ref[...][:, :1]                             # [R, 1]
    o_ref[...] = (q * scale).astype(o_ref.dtype)


def quantize_blockwise_pallas(x: jnp.ndarray, bits: int = 8,
                              block: int = 256, interpret: bool = False
                              ) -> Tuple[jnp.ndarray, jnp.ndarray, None]:
    """Fused symmetric blockwise quantization (signature-compatible with
    ops.quantizer.quantize_blockwise for the symmetric case)."""
    assert bits in (4, 8)
    qmax = 2.0 ** (bits - 1) - 1
    flat = x.reshape(-1)
    n = flat.shape[0]
    assert n % block == 0, f"size {n} not divisible by block {block}"
    rows = n // block
    row_tile = _row_tile(rows)
    xb = flat.reshape(rows, block)

    q, s = pl.pallas_call(
        functools.partial(_quant_kernel, qmax=qmax),
        grid=(rows // row_tile,),
        in_specs=[pl.BlockSpec((row_tile, block), lambda i: (i, 0),
                               memory_space=pltpu.VMEM)],
        out_specs=[
            pl.BlockSpec((row_tile, block), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_tile, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((rows, block), jnp.int8),
            jax.ShapeDtypeStruct((rows, LANES), jnp.float32),
        ],
        interpret=interpret,
    )(xb)
    return q.reshape(x.shape), s[:, 0], None


def dequantize_blockwise_pallas(q: jnp.ndarray, scale: jnp.ndarray,
                                zero=None, block: int = 256,
                                dtype=jnp.float32,
                                interpret: bool = False) -> jnp.ndarray:
    assert zero is None, "pallas path is symmetric-only"
    flat = q.reshape(-1)
    rows = flat.shape[0] // block
    row_tile = _row_tile(rows)
    qb = flat.reshape(rows, block)
    sb = jnp.broadcast_to(scale[:, None], (rows, LANES))

    out = pl.pallas_call(
        _dequant_kernel,
        grid=(rows // row_tile,),
        in_specs=[
            pl.BlockSpec((row_tile, block), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((row_tile, LANES), lambda i: (i, 0),
                         memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec((row_tile, block), lambda i: (i, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((rows, block), dtype),
        interpret=interpret,
    )(qb, sb)
    return out.reshape(q.shape).astype(dtype)


def use_pallas_quant(numel: int, block: int,
                     manual_sharding: bool = False) -> bool:
    """Dispatch guard: TPU + lane-aligned block + whole row tiles.
    DST_NO_PALLAS_QUANT=1 pins the XLA path (microbench A/B lever).

    On multi-device PROCESSES the auto path yields to jnp: GSPMD-auto
    call sites (engine ste_quant, inference weight loads) would bake a
    replicated pallas_call into the trace (the flash-attention hazard —
    transformer._local_flash). ``manual_sharding=True`` is the opt-in for
    callers already inside a shard_map manual region (compressed.py
    collectives), where the kernel is device-local and safe. The check
    uses jax.devices() (not the topology singleton) so it cannot be
    defeated by trace-before-initialize ordering."""
    import os

    from ..attention import _on_tpu

    if os.environ.get("DST_NO_PALLAS_QUANT") == "1":
        return False
    if not _on_tpu():
        return False
    if not manual_sharding:
        import jax

        if len(jax.devices()) > 1:
            return False
    if block % LANES or numel % block:
        return False
    rows = numel // block
    return rows % 32 == 0
