"""Memory introspection (reference ``runtime/utils.py`` see_memory_usage /
``memory_breakdown`` config).

The reference prints torch.cuda allocator stats at every engine phase
boundary. TPU-native form: per-device HBM stats from the PJRT allocator
(``Device.memory_stats()`` — bytes_in_use / peak_bytes_in_use /
bytes_limit) plus host RSS from /proc, logged through the shared
log_dist channel. ``TrainEngine`` calls :func:`see_memory_usage` at the
train-step boundary when ``memory_breakdown: true`` (config.py:548).
"""

from __future__ import annotations

import os
from typing import Dict, Optional

from .logging import log_dist


def device_memory_stats(device=None) -> Dict[str, float]:
    """HBM stats for one device in GB; empty when the backend has no
    allocator stats (CPU test meshes)."""
    import jax

    dev = device if device is not None else jax.devices()[0]
    stats = {}
    try:
        raw = dev.memory_stats() or {}
    except Exception:
        return stats
    for key, out in (("bytes_in_use", "hbm_in_use_gb"),
                     ("peak_bytes_in_use", "hbm_peak_gb"),
                     ("bytes_limit", "hbm_limit_gb"),
                     ("largest_free_block_bytes", "hbm_largest_free_gb")):
        if key in raw:
            stats[out] = round(raw[key] / 1e9, 3)
    return stats


def host_rss_gb() -> Optional[float]:
    try:
        with open(f"/proc/{os.getpid()}/status") as f:
            for line in f:
                if line.startswith("VmRSS:"):
                    return round(int(line.split()[1]) / 1e6, 3)  # kB -> GB
    except OSError:
        pass
    return None


def see_memory_usage(tag: str, force: bool = False, ranks=(0,)) -> Dict[str, float]:
    """Log (and return) current device + host memory. ``force`` mirrors the
    reference's signature: callers gate on config themselves or pass
    force=True for unconditional output."""
    stats = device_memory_stats()
    rss = host_rss_gb()
    if rss is not None:
        stats["host_rss_gb"] = rss
    pretty = ", ".join(f"{k}={v}" for k, v in stats.items()) or "no allocator stats"
    log_dist(f"MEM {tag}: {pretty}", ranks=list(ranks))
    return stats
