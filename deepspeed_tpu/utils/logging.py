"""Rank-aware logging utilities.

Capability parity with the reference's ``deepspeed/utils/logging.py``
(``logger`` + ``log_dist`` rank-filtered logging), rebuilt for a JAX
multi-process world where the process index comes from
``jax.process_index()`` instead of ``torch.distributed.get_rank()``.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Iterable, Optional

_LOG_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"


def _create_logger(name: str = "deepspeed_tpu", level: int = logging.INFO) -> logging.Logger:
    lg = logging.getLogger(name)
    if lg.handlers:
        return lg
    lg.setLevel(level)
    lg.propagate = False
    handler = logging.StreamHandler(stream=sys.stderr)
    handler.setFormatter(logging.Formatter(_LOG_FORMAT, datefmt="%Y-%m-%d %H:%M:%S"))
    lg.addHandler(handler)
    return lg


logger = _create_logger()


def _process_index() -> int:
    # Avoid importing jax at module import time (tests set env vars first);
    # also works before jax.distributed initialization.
    try:
        import jax

        return jax.process_index()
    except Exception:
        return int(os.environ.get("RANK", "0"))


def log_dist(message: str, ranks: Optional[Iterable[int]] = None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the given process ranks (default: rank 0 only).

    ``ranks=[-1]`` logs on every process.
    """
    my_rank = _process_index()
    ranks = list(ranks) if ranks is not None else [0]
    if -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def warning_once(message: str, _seen=set()) -> None:  # noqa: B006 - intentional cache
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)
