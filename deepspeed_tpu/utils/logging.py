"""Rank-aware logging utilities.

Capability parity with the reference's ``deepspeed/utils/logging.py``
(``logger`` + ``log_dist`` rank-filtered logging), rebuilt for a JAX
multi-process world where the process index comes from
``jax.process_index()`` instead of ``torch.distributed.get_rank()``.
"""

from __future__ import annotations

import logging
import os
import sys
from typing import Iterable, Optional

_LOG_FORMAT = "[%(asctime)s] [%(levelname)s] [%(name)s:%(lineno)d] %(message)s"


def _create_logger(name: str = "deepspeed_tpu", level: int = logging.INFO) -> logging.Logger:
    lg = logging.getLogger(name)
    if lg.handlers:
        return lg
    lg.setLevel(level)
    lg.propagate = False
    handler = logging.StreamHandler(stream=sys.stderr)
    handler.setFormatter(logging.Formatter(_LOG_FORMAT, datefmt="%Y-%m-%d %H:%M:%S"))
    lg.addHandler(handler)
    return lg


logger = _create_logger()


_cached_process_index: Optional[int] = None


def _process_index() -> int:
    # Avoid importing jax at module import time (tests set env vars first);
    # also works before jax.distributed initialization. The successful
    # jax.process_index() result is cached — the index never changes within
    # a process, and re-resolving it on every log_dist call costs an
    # attribute walk into jax per log line.
    env = os.environ.get("DST_LOG_RANK")  # test/tooling override
    if env is not None:
        try:
            return int(env)
        except ValueError:
            warning_once(f"DST_LOG_RANK={env!r} is not an integer; ignored")
    global _cached_process_index
    if _cached_process_index is not None:
        return _cached_process_index
    try:
        import jax

        _cached_process_index = jax.process_index()
        return _cached_process_index
    except Exception:
        # not cached: jax may simply not be initialized yet
        return int(os.environ.get("RANK", "0"))


def reset_process_index_cache() -> None:
    """Drop the cached process index (tests; re-init after jax.distributed)."""
    global _cached_process_index
    _cached_process_index = None


def log_dist(message: str, ranks: Optional[Iterable[int]] = None, level: int = logging.INFO) -> None:
    """Log ``message`` only on the given process ranks (default: rank 0 only).

    ``ranks=[-1]`` logs on every process.
    """
    my_rank = _process_index()
    ranks = list(ranks) if ranks is not None else [0]
    if -1 in ranks or my_rank in ranks:
        logger.log(level, f"[Rank {my_rank}] {message}")


def warning_once(message: str, _seen=set()) -> None:  # noqa: B006 - intentional cache
    if message not in _seen:
        _seen.add(message)
        logger.warning(message)
