"""Durable small-file IO shared by the checkpoint engine, heartbeat and
elastic agent: JSON written via temp + (optional fsync) + atomic rename, so
a crash at any byte leaves either the old file or the new one, never a
torn read for whoever polls it."""

from __future__ import annotations

import json
import os
from typing import Any, Optional


def fsync_dir(path: str) -> None:
    """Durably record a directory entry (a rename itself). Best-effort:
    some filesystems refuse O_RDONLY dir fds."""
    try:
        fd = os.open(path, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
    except OSError:  # pragma: no cover - fs-dependent
        pass


def write_json_atomic(path: str, obj: Any, fsync: bool = False,
                      indent: Optional[int] = None) -> None:
    """Write JSON via temp + rename. ``fsync=True`` for commit-protocol
    files that must survive power loss; False for liveness files where
    write latency matters more than durability."""
    tmp = path + ".tmp"
    with open(tmp, "w", encoding="utf-8") as f:
        json.dump(obj, f, indent=indent, default=str)
        if fsync:
            f.flush()
            os.fsync(f.fileno())
    os.replace(tmp, path)
