"""Wall-clock and throughput timers.

Capability parity with the reference's ``deepspeed/utils/timer.py``
(SynchronizedWallClockTimer + ThroughputTimer driven by EngineTimers,
engine.py:140). On TPU, synchronization means ``jax.block_until_ready`` on a
representative array instead of CUDA events.
"""

from __future__ import annotations

import time
from collections import defaultdict
from typing import Any, Dict, List, Optional

from .logging import log_dist


def _fence(obj: Any) -> None:
    """Host-side completion fence. ``jax.block_until_ready`` is NOT a fence
    through remote-dispatch relays (e.g. the axon TPU tunnel) — only a host
    fetch reliably waits for the device, so fetch the (scalar) sync object."""
    import jax

    jax.device_get(obj)


class _Timer:
    def __init__(self, name: str):
        self.name = name
        self._start: Optional[float] = None
        self.elapsed_total = 0.0
        self.count = 0

    def start(self) -> None:
        self._start = time.perf_counter()

    def stop(self, sync_obj: Any = None) -> float:
        if sync_obj is not None:
            _fence(sync_obj)
        assert self._start is not None, f"timer {self.name} stopped before start"
        dt = time.perf_counter() - self._start
        self.elapsed_total += dt  # dslint: disable=races -- legacy reference-compat shim: each named timer is started/stopped by one engine thread; the monitor role reaches mean_ms only through a diagnostic log path that tolerates a stale float
        self.count += 1  # dslint: disable=races -- same single-timing-thread contract as elapsed_total above
        self._start = None
        return dt

    def mean_ms(self) -> float:
        return (self.elapsed_total / self.count * 1e3) if self.count else 0.0

    def reset(self) -> None:
        self.elapsed_total = 0.0
        self.count = 0
        self._start = None


class SynchronizedWallClockTimer:
    """Named-timer registry (reference utils/timer.py same-named class)."""

    def __init__(self):
        self.timers: Dict[str, _Timer] = {}

    def __call__(self, name: str) -> _Timer:
        if name not in self.timers:
            self.timers[name] = _Timer(name)  # dslint: disable=races -- legacy reference-compat shim: timers are registered by the engine thread during setup; log() readers tolerate a momentarily missing name
        return self.timers[name]

    def log(self, names: Optional[List[str]] = None, reset: bool = True) -> str:
        names = names or list(self.timers)
        parts = [f"{n}: {self.timers[n].mean_ms():.2f}ms" for n in names if n in self.timers]
        msg = " | ".join(parts)
        if msg:
            log_dist(f"time (ms) | {msg}")
        if reset:
            for n in names:
                if n in self.timers:
                    self.timers[n].reset()
        return msg


class ThroughputTimer:
    """Samples/sec + TFLOPs tracking (reference utils/timer.py ThroughputTimer)."""

    def __init__(self, batch_size: int, steps_per_output: int = 50, monitor_memory: bool = False):
        self.batch_size = batch_size
        self.steps_per_output = steps_per_output
        self.monitor_memory = monitor_memory
        self.total_samples = 0
        self.total_time = 0.0
        self._start = None
        self.step_count = 0
        self._window_time = 0.0
        self._window_steps = 0
        self.last_step_s: Optional[float] = None
        # latest device-memory sample (report boundaries only, so the
        # steady-state step never pays the allocator-stats call)
        self.last_memory: Dict[str, float] = {}

    def start(self) -> None:
        self._start = time.perf_counter()

    def will_report_next(self) -> bool:
        """True if the NEXT stop() will emit the throughput line — the
        engine uses this to decide whether to pass a sync object, so the
        report-boundary predicate lives in exactly one place."""
        return (self.step_count + 1) % self.steps_per_output == 0

    def stop(self, sync_obj: Any = None, report_speed: bool = True) -> Optional[float]:
        if self._start is None:
            return None
        if sync_obj is not None:
            _fence(sync_obj)
        dt = time.perf_counter() - self._start
        self._start = None
        self.step_count += 1
        self.total_samples += self.batch_size
        self.total_time += dt
        self._window_time += dt
        self._window_steps += 1
        self.last_step_s = dt
        if report_speed and self.step_count % self.steps_per_output == 0:
            # window-averaged ms/step: under async dispatch the engine only
            # syncs at the report boundary, so the boundary step's own dt
            # covers the whole drained window — dt alone would read ~window x
            # the true step time (and ~0 on unsynced steps)
            ms = self._window_time / self._window_steps * 1e3
            mem = ""
            if self.monitor_memory:
                # report boundary == already host-synced (the engine passed
                # a sync object), so sampling allocator stats here adds no
                # extra device round trip to the steady-state step
                from .memory import device_memory_stats

                self.last_memory = device_memory_stats()
                if self.last_memory:
                    mem = ", " + ", ".join(
                        f"{k}={v}" for k, v in self.last_memory.items())
            log_dist(
                f"step {self.step_count}: {self.avg_samples_per_sec():.2f} samples/s, "
                f"{ms:.1f} ms/step (avg over {self._window_steps}){mem}"
            )
            self._window_time = 0.0
            self._window_steps = 0
        return dt

    def avg_samples_per_sec(self) -> float:
        return self.total_samples / self.total_time if self.total_time else 0.0
