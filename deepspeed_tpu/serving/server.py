"""The serving front-end: request lifecycle over the ragged engine.

``ServingEngine`` is the production surface the FastGen/MII blogs
describe — live request arrival, SLO-aware continuous batching,
streaming responses — promoted out of the benchmark script's throwaway
loop (scripts/tpu_serve_bench.py pre-PR5) into a real subsystem:

* ``submit()`` with bounded-queue backpressure: a full queue rejects
  explicitly (state REJECTED) instead of buffering unboundedly while
  TTFTs rot;
* a background driver thread runs one engine tick at a time — the
  policy (:mod:`.scheduler`) picks the request set, the engine's
  Dynamic-SplitFuse packing fits it into the one static step shape;
* ``stream()`` yields tokens as the driver emits them;
* ``cancel()`` at any lifecycle stage releases the engine state it
  holds (slot + KV pages) with zero leaked blocks;
* preempted requests resume bit-exactly: the driver re-prefills
  ``prompt + emitted`` (the prefix cache makes this cheap) and greedy
  decode continues the identical stream;
* a tick fault (device error, injected chaos) discards the touched
  engine state — never publishing suspect KV into the prefix cache —
  and re-queues each touched request until its retry budget is spent;
* ``drain()`` stops admission and serves out the backlog; a
  :class:`~deepspeed_tpu.resilience.preemption.PreemptionGuard` latch
  triggers the same graceful drain (finish live work, reject the queue)
  so a cloud preemption never tears down mid-request;
* a watchdog thread flags stuck ticks (``serving/stuck_ticks``) when a
  device call wedges past ``stuck_tick_timeout_s``.

Serving decodes greedily (argmax on the engine's returned logits):
bit-exact preempt-resume and fault-retry require the continuation to be
a pure function of the token stream. Sampling belongs in the engine's
own ``generate``/``stream`` paths.

Telemetry: per-request spans (queue_wait, TTFT, tokens/s — see
:class:`~deepspeed_tpu.telemetry.spans.RequestStats`) plus queue-depth /
KV-occupancy gauges and admitted/rejected/preempted counters, all
through the shared registry (docs/observability.md, docs/serving.md).
"""

from __future__ import annotations

import queue as queue_mod
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..inference.ragged import PoolExhausted
from ..resilience.clock import Clock, get_clock
from ..resilience.locksan import named_rlock
from ..telemetry.tracing import (begin_request_segment, end_request_segment,
                                 ensure_request_root, finish_request_trace,
                                 get_tracer, request_event)
from ..utils.logging import log_dist, logger
from .request import Request, RequestState
from .scheduler import CapacityView, SchedulerPolicy, make_policy


def emit_request_span(telemetry, req: Request, digest=None) -> None:
    """Emit one terminal request's span record — shared by the
    ServingEngine retire path and fleet-level rejections (a request shed
    before it ever reached a replica must still appear in
    requests.jsonl: one logical request, one record, no matter where it
    died). ``digest`` is the emitting tier's
    :class:`~deepspeed_tpu.telemetry.digest.DigestSource`: the same
    terminal observations also feed the replica→region rollup plane."""
    from ..telemetry.spans import RequestStats

    # terminal trace closure lives HERE because every terminal request
    # passes through exactly once (replica retire backlog, fleet shed,
    # failover-cancel) — the root span ends with the request, whatever
    # killed it, and the span/ledger join keys ride the record below
    finish_request_trace(req, state=req.state.value,
                         new_tokens=len(req.tokens),
                         preemptions=req.preemptions, retries=req.retries,
                         error=req.error)
    root = getattr(req, "_trace_root", None)
    n = len(req.tokens)
    decode_s = (req.t_finish - req.t_first_token
                if req.t_finish is not None
                and req.t_first_token is not None else None)
    # SLO verdict: judge completions against their deadlines; a
    # rejected or failed request that CARRIED an SLO is a miss (the
    # terminal timestamp is not a serve time — judging it would read
    # near-100% attainment exactly when the system sheds load); a
    # user cancel is the caller's choice, not judged
    had_slo = (req.deadline_s is not None
               or req.ttft_deadline_s is not None)
    if req.state is RequestState.FINISHED:
        in_slo = req.in_slo()
    elif req.state is RequestState.CANCELLED and req.error is None:
        in_slo = None
    else:
        in_slo = False if had_slo else None
    if digest is not None:
        # rollup-plane copy of the hot-path observations: sketch
        # observes are O(1) and the digest publishes deltas upward on
        # the monitor cadence (telemetry/digest.py)
        digest.count("requests")
        digest.observe("queue_wait_s", req.queue_wait_s)
        digest.observe("ttft_s", req.ttft_s)
        if req.state is RequestState.FINISHED:
            digest.observe("request_latency_s", req.latency_s)
        if decode_s and n > 1:
            digest.observe("tokens_per_s", (n - 1) / decode_s)
        if n:
            digest.count("generated_tokens", n)
    # the rollup plane above feeds regardless of the registry sink: the
    # region's SLO tracker and digest rollups must see every terminal
    # request even when telemetry output is disabled
    if not telemetry.enabled:
        return
    telemetry.record_request_span(RequestStats(
        uid=req.uid, state=req.state.value,
        client_request_id=req.client_request_id, priority=req.priority,
        prompt_tokens=len(req.prompt), new_tokens=n,
        queue_wait_s=req.queue_wait_s, ttft_s=req.ttft_s,
        # latency only for served requests: near-zero reject/cancel
        # "latencies" would drag the histogram DOWN exactly when the
        # system sheds load (same shedding guard as in_slo below)
        latency_s=(req.latency_s
                   if req.state is RequestState.FINISHED else None),
        # n tokens span n-1 decode intervals (the first token ends
        # prefill): n/decode_s would inflate the rate, infinitely so
        # for single-token requests
        tokens_per_s=((n - 1) / decode_s if decode_s and n > 1 else None),
        preemptions=req.preemptions, retries=req.retries,
        spec_proposed=(req.spec_proposed if req.spec_proposed else None),
        spec_accepted=(req.spec_accepted if req.spec_proposed else None),
        model_version=req.model_version,
        tenant=req.tenant,
        in_slo=in_slo, error=req.error,
        trace_id=(root.trace_id if root is not None and not root.is_noop
                  else None),
        span_id=(root.span_id if root is not None and not root.is_noop
                 else None)))


def stream_tokens(server, prompt: Sequence[int], **kwargs):
    """Streaming generator over any submit/cancel surface — shared by
    :meth:`ServingEngine.stream` and ``ServingFleet.stream``. Yields
    tokens as the driver emits them; breaking out (or ``close()``-ing
    the generator) cancels the request."""
    if "on_token" in kwargs:
        raise ValueError("stream() owns the on_token callback")
    q: "queue_mod.Queue[int]" = queue_mod.Queue()
    req = server.submit(prompt, on_token=q.put, **kwargs)
    if req.state is RequestState.REJECTED:
        raise RuntimeError(f"request rejected: {req.error}")
    try:
        emitted = 0
        while True:
            try:
                yield q.get(timeout=0.05)
                emitted += 1
            except queue_mod.Empty:
                if req.is_terminal:
                    break
        while emitted < len(req.tokens):   # tokens raced the sentinel
            yield q.get_nowait()
            emitted += 1
        if req.state is RequestState.REJECTED:
            # shed after admission to the queue (deadline expiry,
            # drain, preemption latch) — must not read as a
            # successful empty/partial generation
            raise RuntimeError(f"request rejected: {req.error}")
        if req.state is RequestState.CANCELLED and req.error:
            raise RuntimeError(f"request failed: {req.error}")
    finally:
        if not req.is_terminal:
            server.cancel(req)


class ServingEngine:
    """SLO-aware continuous-batching front-end over a
    :class:`~deepspeed_tpu.inference.ragged.RaggedInferenceEngine`."""

    def __init__(self, engine, config: Any = None,
                 policy: Optional[SchedulerPolicy] = None,
                 preemption_guard: Any = None,
                 start: bool = True,
                 replica_id: Optional[str] = None,
                 on_handoff=None,
                 on_retire=None,
                 clock: Optional[Clock] = None):
        from ..config import ServingConfig

        if config is None:
            config = ServingConfig()
        elif isinstance(config, dict):
            config = ServingConfig.from_dict(config)
        self.config = config
        self._engine = engine
        self.policy = policy if policy is not None else make_policy(
            config.policy, **(dict(kv_pressure=config.kv_pressure,
                                   reject_expired=config.reject_expired,
                                   preemption=config.preemption)
                              if config.policy == "slo" else {}))
        self._guard = preemption_guard
        # fleet wiring: a replica_id namespaces this engine's metrics
        # (serving/<replica_id>/...) so N replicas don't stomp one gauge;
        # on_handoff receives (request, KVExport) when a handoff-flagged
        # request finishes prefill; on_retire fires once per terminal
        # request (both called OUTSIDE the serving lock, driver thread)
        self.replica_id = replica_id
        self._metric_prefix = (f"serving/{replica_id}" if replica_id
                               else "serving")
        # replica-tier digest source (telemetry/digest.py): terminal
        # request observations + tick timings collected here, published
        # as deltas up the fleet→cell→region rollup on the monitor
        # cadence — region reads never scan replicas
        from ..telemetry.digest import DigestSource

        self.digest = DigestSource(replica_id or "serving")
        self._on_handoff = on_handoff
        self._on_retire = on_retire
        # every deadline, latency stamp and poll interval reads this
        # clock; a SimClock here makes the whole driver virtual-time
        # (docs/dst.md)
        self._clock = clock if clock is not None else get_clock()
        # speculative decoding (docs/serving.md "Speculative scheduling"):
        # drafting needs the engine's draft/verify surface; per-PRIORITY
        # acceptance EMAs drive the token credit that sizes chains.
        # Declared kv_quant must match the engine's own mode — a fleet
        # whose replicas disagree on pool storage would corrupt every
        # disaggregated hand-off at import time, so fail at construction.
        self._spec_on = bool(getattr(config, "speculative", False)) and \
            hasattr(engine, "put_spec") and hasattr(engine, "draft_tokens")
        self._spec_ema_by_class: Dict[int, float] = {}
        want_quant = str(getattr(config, "kv_quant", "none"))
        have_quant = str(getattr(engine.config, "kv_quant", "none"))
        if want_quant != "none" and want_quant != have_quant:
            raise ValueError(
                f"serving.kv_quant='{want_quant}' but the engine stores "
                f"KV as '{have_quant}' — configure both from one source")
        self._kv_quant = have_quant
        # model-version ledger (docs/serving.md "Rollout, canary, and
        # migration"): the version of the weights this engine serves.
        # Monotonic ints, bumped by hot_swap(); requests are stamped at
        # placement and continuations are version-affine — a stream
        # started on version N is never continued on N+1 (the DST
        # two-version-stream invariant).
        self.model_version = int(getattr(config, "model_version", 0) or 0)
        # AOT-warmup countdown after a hot swap: the new version is
        # compiled/warmed for this many ticks before the replica takes
        # traffic again (counts down in _tick even when idle)
        self._warmup_remaining = 0
        # built through the locksan seam: a plain RLock in production,
        # an order-recording wrapper under tests/DST (docs/dst.md)
        self._lock = named_rlock("ServingEngine._lock")
        self._queue: List[Request] = []
        self._live: Dict[int, Request] = {}
        self._requests: Dict[int, Request] = {}   # uid -> non-terminal req
        self._accepting = True
        self._span_backlog: List[Request] = []   # retired, span not yet emitted
        self._adoptions: List[tuple] = []        # (req, KVExport) to import
        self._handoff_backlog: List[tuple] = []  # (req, KVExport) to ship
        self._handoffs_in_flight = 0             # popped, export not done
        # global KV tier pens (docs/serving.md "Global KV tier"). Unlike
        # _adoptions these hold NO requests and no allocator refs —
        # adoption is best-effort prefetch, never owed work — so they are
        # excluded from pending_work/_idle_locked and dropping them at
        # kill/close is free. Processed on the driver thread only.
        self._prefix_export_requests: List[tuple] = []  # (tokens, on_ready)
        self._prefix_adoptions: List[Any] = []          # PrefixExport
        self._kv_tier = None                     # fleet's KVTier (or None)
        self._kv_member = ""                     # our name in the directory
        self._residency: Optional[tuple] = None  # (hashes, t_captured)
        self._last_residency_pub = float("-inf")
        self._cold_readmits_seen = 0
        self._last_gauges: Optional[tuple] = None
        self._stop_evt = threading.Event()
        self._tick_count = 0
        self._in_tick = False
        self._tick_started = 0.0
        self._stuck_reported = False
        # stuck-tick escalation (docs/fault_tolerance.md "Gray
        # failures"): consecutive wedged watchdog polls; past the
        # configured budget the replica marks ITSELF unhealthy and the
        # fleet monitor evacuates it instead of log-and-hope
        self._stuck_polls = 0
        self._watchdog_unhealthy = False
        # gray-failure evidence: busy engine ticks and the degraded
        # subset since the fleet monitor last drained them (the per-poll
        # distress-ratio sample feeding serving/health.py)
        self._busy_ticks = 0
        self._distress_ticks = 0
        self._driver: Optional[threading.Thread] = None
        self._watchdog: Optional[threading.Thread] = None
        if getattr(config, "speculative", False) and not self._spec_on:
            logger.warning(
                "ServingEngine: serving.speculative requested but the "
                "engine has no put_spec/draft_tokens surface — serving "
                "plain decode")
        log_dist(f"ServingEngine{f'[{replica_id}]' if replica_id else ''}: "
                 f"policy={self.policy.name} "
                 f"max_queue={config.max_queue} "
                 f"preemption={getattr(self.policy, 'preemption', False)}"
                 + (f" speculative=on(lookahead={config.spec_lookahead})"
                    if self._spec_on else "")
                 + (f" kv_quant={self._kv_quant}"
                    if self._kv_quant != "none" else ""))
        if start:
            self.start()

    # -- telemetry (resolved per call: pipeline may install later) -------
    @property
    def _telemetry(self):
        from ..telemetry import get_telemetry

        return get_telemetry()

    def _count(self, name: str, n: float = 1.0) -> None:
        self._telemetry.registry.counter(
            f"{self._metric_prefix}/{name}").inc(n)

    # -- lifecycle -------------------------------------------------------
    def start(self) -> None:
        if self._driver is not None:
            return
        # dslint: disable-next-line=races -- thread-handle lifecycle: start precedes any competing writer (the fleet spawns, then starts); kill()/close() join the threads before clearing, and a doubled join is harmless
        self._driver = threading.Thread(target=self._drive, daemon=True,
                                        name="serving-driver")
        self._driver.start()
        if self.config.stuck_tick_timeout_s > 0:
            # dslint: disable-next-line=races -- thread-handle lifecycle: same start/kill/close serialization as _driver above
            self._watchdog = threading.Thread(target=self._watch, daemon=True,
                                              name="serving-watchdog")
            self._watchdog.start()

    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               priority: int = 0,
               deadline_s: Optional[float] = None,
               ttft_deadline_s: Optional[float] = None,
               client_request_id: Optional[str] = None,
               on_token=None) -> Request:
        """Enqueue a request. Returns immediately; the request may come
        back already REJECTED (backpressure — full queue, serving closed,
        or a prompt the engine can never hold). Callers stream via
        ``on_token`` or block on ``request.result()``."""
        req = Request(prompt=list(prompt),
                      max_new_tokens=(max_new_tokens if max_new_tokens
                                      is not None
                                      else self.config.default_max_new_tokens),
                      eos_token_id=eos_token_id, priority=priority,
                      deadline_s=deadline_s, ttft_deadline_s=ttft_deadline_s,
                      client_request_id=client_request_id,
                      on_token=on_token)
        return self.submit_request(req)

    def submit_request(self, req: Request,
                       requeue: bool = False) -> Optional[Request]:
        """Enqueue an existing QUEUED :class:`Request` — the fleet-facing
        half of :meth:`submit`: the router builds (or re-routes) the
        request object and each replica only validates and queues it.
        ``t_submit`` is preserved when already set (a failed-over request
        keeps its ORIGINAL clock: its deadlines are promises to the
        caller, not to whichever replica ends up serving it).

        ``requeue`` marks the CONTINUATION of an already-admitted request
        (fail-over, hand-off fallback): like :meth:`adopt` it bypasses
        the admission gate and the ``max_queue`` bound — a draining
        replica must serve out admitted work, not shed it. Only a
        stopped driver refuses a requeue, and it does so NON-terminally
        (returns None with the request untouched) so the caller can
        place it on another replica."""
        if req.state is not RequestState.QUEUED:
            raise ValueError(
                f"submit_request needs a QUEUED request, got {req.state.name}")
        # the request's whole lifecycle is timed on ITS owner's clock: a
        # Request built under the global clock but submitted to an
        # engine with an injected one would otherwise mix timebases
        # (virtual t_submit vs wall t_finish corrupts every SLO verdict)
        req._clock = self._clock
        if req.t_submit is None:
            req.t_submit = self._clock.now()
        # tracing: single-engine submissions open the root here (the
        # fleet opens it earlier, around routing); every (re)queue is a
        # fresh "queue" segment on the owning replica's track
        ensure_request_root(req, prompt_tokens=len(req.prompt),
                            priority=req.priority)
        with self._lock:
            if requeue and self._stop_evt.is_set():
                return None
            if (requeue and req.tokens
                    and req.model_version is not None
                    and req.model_version != self.model_version):
                # version affinity: a continuation with tokens already
                # out must finish on the version that emitted them — a
                # mixed-version stream is exactly what the DST
                # two-version invariant forbids. NON-terminal refusal
                # (like the stopped-driver case): the caller re-places
                # it on a same-version replica or cancels it explicitly.
                return None
            if not requeue and not self._accepting:
                self._reject(req, "serving closed to new requests")
            elif (len(req.prompt) + req.max_new_tokens
                    > self._engine.config.max_context):
                # would deadlock FCFS at the head of the queue forever
                self._reject(req, "prompt + max_new_tokens exceeds "
                                  "engine max_context")
            elif (self._engine.blocks_needed(len(req.prompt)
                                             + req.max_new_tokens)
                    > self._engine.allocator.n_blocks):
                # same deadlock via the KV pool: a request that can never
                # hold all its pages at once can never finish — it would
                # head-of-line-block FCFS (and thrash mid-decode recovery
                # under any policy) forever
                self._reject(req, "prompt + max_new_tokens exceeds "
                                  "engine KV pool capacity")
            elif not requeue and len(self._queue) >= self.config.max_queue:
                # backpressure is for NEW work; a failed-over continuation
                # was already admitted once and queues past the bound
                # rather than being shed
                self._reject(req, "admission queue full")
            else:
                if not req.tokens:
                    # stamp (or re-stamp) the serving version: with no
                    # tokens out yet nothing binds the stream, so a
                    # failed-over prefill may legally restart on the new
                    # version — only emitted tokens create affinity
                    req.model_version = self.model_version
                self._requests[req.uid] = req
                self._enqueue_locked(req, requeue=bool(requeue))
        self._flush_spans()
        return req

    def _enqueue_locked(self, req: Request, *, requeue: bool = False,
                        **attrs) -> None:
        """Append to the admission queue (serving lock held) and open
        the request's "queue" trace segment — the append + segment pair
        lives HERE only, so every (re-)queue edge — fresh submit,
        preemption, tick-fault retry, adopt fallback, handoff-callback
        recovery — lands on the request's tree."""
        self._queue.append(req)
        begin_request_segment(req, "queue", track=self.replica_id,
                              requeue=requeue, **attrs)

    def adopt(self, req: Request, kv_export) -> bool:
        """Hand-off arrival (disaggregated decode replica): take over a
        request whose KV a prefill replica already computed. The import
        happens on the DRIVER thread at the next tick boundary — engine
        state is only ever touched from there — so this just queues the
        (request, export) pair. If the import cannot land (pool pressure,
        geometry), the request falls back to the normal resume path:
        re-queued here and re-prefilled from ``prompt + tokens``.

        Unlike :meth:`submit_request` this does NOT check ``_accepting``:
        a hand-off is the continuation of an already-admitted request,
        and a draining fleet must serve out exactly these (admission
        closed, backlog finishes). A stopped driver (killed / closed
        replica) REFUSES — returns False with the request untouched, so
        the fleet can place it elsewhere (nothing here would ever
        process the pen)."""
        with self._lock:
            if self._stop_evt.is_set():
                return False
            if (req.tokens and req.model_version is not None
                    and req.model_version != self.model_version):
                # version affinity (same contract as submit_request): a
                # hand-off with tokens out must land on ITS version
                return False
            if not req.tokens:
                req.model_version = self.model_version
            self._requests[req.uid] = req
            self._adoptions.append((req, kv_export))
        return True

    # -- global KV tier surface (docs/serving.md "Global KV tier") -------
    def enable_kv_tier(self, tier, member: str) -> None:
        """Attach this replica to the fleet's :class:`KVTier`: engine
        hooks (cold-tier spill + synchronous directory invalidation on
        eviction) plus the residency-publish cadence state. Called by
        the fleet at spawn, before traffic routes here; the directory
        invalidate closure takes only the directory's LEAF lock, so it
        is legal from the eviction path under the engine's own locks."""
        eng = self._engine
        if not hasattr(eng, "enable_kv_tier"):
            return
        with self._lock:
            self._kv_tier = tier
            self._kv_member = member
        eng.enable_kv_tier(
            member=member,
            cold_tier=tier.cold,
            on_invalidate=self._kvtier_invalidate)

    def _kvtier_invalidate(self, h: int) -> None:
        """Eviction hook: remove the hash from the directory AND from
        the pending residency snapshot. The second half closes a
        publish race — the fleet's poll republishes the snapshot
        captured at the last publish tick, and without the scrub an
        eviction landing between capture and publish would resurrect
        the entry after its pages were freed (the exact
        entry-outlives-pages shape invariant #17 hunts)."""
        with self._lock:
            tier, member = self._kv_tier, self._kv_member
            if self._residency is not None and h in self._residency[0]:
                hashes, t = self._residency
                self._residency = ([x for x in hashes if x != h], t)
        if tier is not None:
            tier.directory.invalidate(member, h)

    def request_prefix_export(self, tokens, on_ready) -> bool:
        """Donor-side adoption pen: the DRIVER pops this at its next
        tick and runs the engine's prefix gather OUTSIDE the serving
        lock, then calls ``on_ready(export_or_None)`` (also outside the
        lock, donor driver thread). Best-effort: a killed/closed driver
        refuses (False) and a dropped pen simply never fires on_ready —
        the importer side prefills locally, degraded but never lost."""
        with self._lock:
            if self._stop_evt.is_set() or self._kv_tier is None:
                return False
            self._prefix_export_requests.append((list(tokens), on_ready))
        return True

    def adopt_prefix(self, export) -> bool:
        """Importer-side adoption pen: the driver verifies the export's
        checksum and imports it into the prefix cache at its next tick
        (engine state is driver-thread-confined, same rule as
        :meth:`adopt`). Holds no request and no pool references."""
        with self._lock:
            if self._stop_evt.is_set() or self._kv_tier is None:
                return False
            self._prefix_adoptions.append(export)
        return True

    def residency_snapshot(self) -> Optional[tuple]:
        """(prefix hashes, t_captured) from the driver's last publish
        tick, or None before the first one. The fleet's poll stamps the
        directory with t_captured — NOT poll time — so a wedged driver's
        entries age past the staleness bound instead of being kept
        artificially fresh."""
        with self._lock:
            return self._residency

    def stop_admission(self) -> None:
        """Close the front door (submissions reject) without touching the
        backlog — the graceful scale-down shape: the fleet stops routing
        here, live work serves out, then ``close()`` is safe."""
        with self._lock:
            self._accepting = False

    def resume_admission(self) -> None:
        """Re-open the front door after a drain that did NOT end in
        close/kill — the rollout controller's flip-abort and rollback
        paths (docs/serving.md "Rollout, canary, and migration")."""
        with self._lock:
            if not self._stop_evt.is_set() and self._warmup_remaining == 0:
                self._accepting = True

    def hot_swap(self, version: int, load_fn=None,
                 warmup_ticks: Optional[int] = None) -> bool:
        """Swap the serving weights to ``version`` in place — the
        zero-downtime deploy primitive (docs/serving.md "Rollout,
        canary, and migration"). Contract: admission must already be
        stopped and the backlog drained (the rollout controller's
        drain-and-flip seam) — swapping under live work would serve one
        stream from two versions.

        ``load_fn`` performs the actual weight load (checkpoint-streamed
        on the real path, a no-op in the DST sim); a load failure —
        including an injected corrupt new-version checkpoint — FALLS
        BACK: the old weights are untouched, admission resumes on the
        old version, and False is returned so the controller can retry
        or roll back. A failed swap never strands the replica.

        On success the version is bumped and the replica stays
        non-accepting for ``warmup_ticks`` engine ticks — the AOT-warmup
        window where the new version compiles before taking traffic
        (the countdown runs even on idle ticks)."""
        with self._lock:
            if self._stop_evt.is_set():
                return False
            if self._accepting or not self._idle_locked():
                raise RuntimeError(
                    f"hot_swap needs a drained, admission-stopped engine "
                    f"(accepting={self._accepting}, "
                    f"pending={not self._idle_locked()})")
            old = self.model_version
        from ..resilience.chaos import get_fault_injector

        failure: Optional[str] = None
        inj = get_fault_injector()
        if inj is not None and inj.should_corrupt_swap():
            failure = "injected corrupt checkpoint"
        if failure is None and load_fn is not None:
            try:
                load_fn()
            except Exception as e:
                # swap fallback IS the handler: the old weights are
                # intact, so the loss-free response to ANY load failure
                # is resume-on-old-version; InjectedFault (BaseException)
                # still propagates
                failure = f"{type(e).__name__}: {e}"
        if failure is not None:
            self._count("swap_failed")
            logger.warning(
                f"ServingEngine"
                f"{f'[{self.replica_id}]' if self.replica_id else ''}: "
                f"hot swap to version {version} failed ({failure}); "
                f"serving stays on version {old}")
            with self._lock:
                if not self._stop_evt.is_set():
                    self._accepting = True
            return False
        if warmup_ticks is None:
            warmup_ticks = getattr(
                getattr(self.config, "rollout", None), "warmup_ticks", 2)
        with self._lock:
            self.model_version = int(version)
            self._warmup_remaining = max(0, int(warmup_ticks))
            if self._warmup_remaining == 0:
                self._accepting = True
        self._count("swaps")
        log_dist(f"ServingEngine"
                 f"{f'[{self.replica_id}]' if self.replica_id else ''}: "
                 f"hot-swapped {old} -> {version} "
                 f"(warmup {warmup_ticks} ticks)")
        return True

    def migrate_out(self) -> Tuple[List[Request], List[tuple]]:
        """Live-migration harvest — the first-class sibling of
        :meth:`evacuate` (docs/serving.md "Rollout, canary, and
        migration"). Call after ``kill()``: unlike the failure path, the
        engine state here is TRUSTED, so decodes with a complete KV
        footprint are exported over the quantized ``export_kv`` wire for
        adoption elsewhere instead of being recomputed.

        Returns ``(queued, exports)``: ``queued`` holds every request
        with nothing worth shipping (queue, pens, mid-prefill live work
        — these re-route and re-prefill normally), ``exports`` the
        ``(request, KVExport)`` pairs to :meth:`adopt` on the
        destination. Zero blocks stay behind either way."""
        with self._lock:
            queued: List[Request] = list(self._queue)
            exports: List[tuple] = []
            for uid, req in list(self._live.items()):
                seq = self._engine.seqs.get(uid)
                if (req.state is RequestState.DECODE and req.tokens
                        and seq is not None and seq.pending == 0):
                    # complete, trusted KV: ship it (the driver is
                    # joined, so the export copy under our lock cannot
                    # stall a tick — nothing else runs here)
                    export = self._engine.export_kv(uid)
                    self._engine.preempt(uid)
                    req.transition(RequestState.QUEUED)
                    req._pending_token = None
                    exports.append((req, export))
                else:
                    # mid-prefill (or no tokens out): nothing a KV
                    # import could resume — release and re-prefill
                    self._release_engine_state(uid, publish=True)
                    req.transition(RequestState.QUEUED)
                    req._pending_token = None
                    queued.append(req)
            for req, _ in self._adoptions:        # never imported
                queued.append(req)
            for req, export in self._handoff_backlog:  # already exported
                exports.append((req, export))
            for req in queued:
                request_event(req, "migrate", replica=self.replica_id)
                end_request_segment(req, outcome="migrated")
            for req, _ in exports:
                request_event(req, "migrate", replica=self.replica_id,
                              kv_shipped=True)
                end_request_segment(req, outcome="migrated")
            self._queue.clear()
            self._live.clear()
            self._adoptions.clear()
            self._handoff_backlog.clear()
            # kv-tier pens hold no requests/refs: drop, never migrate
            self._prefix_export_requests.clear()
            self._prefix_adoptions.clear()
            self._requests.clear()
            for req in queued:
                self._engine.clear_resume(req.uid)
            for req, _ in exports:
                self._engine.clear_resume(req.uid)
            self._accepting = False
        return queued, exports

    def kill(self) -> None:
        """Abrupt stop — the injected-replica-death shape. Joins the
        driver/watchdog threads (the in-flight tick completes; a real
        crash would tear mid-tick, which is exactly the suspect-KV case
        ``evacuate`` assumes) but does NOT drain, retire or release
        anything: the fleet harvests survivors via :meth:`evacuate`."""
        self._stop_evt.set()
        for t in (self._driver, self._watchdog):
            if t is not None:
                t.join(timeout=5.0)
        self._driver = self._watchdog = None

    def evacuate(self) -> List[Request]:
        """Post-``kill`` harvest: every non-terminal request, re-queued
        for another replica. Engine state of live requests is DISCARDED
        (suspect KV is never published into the prefix cache — the
        replica died, nothing it computed since its last publish can be
        trusted), so the allocator balances and the requests resume
        bit-exactly elsewhere from their token streams."""
        with self._lock:
            orphans: List[Request] = []
            for req in list(self._queue):
                orphans.append(req)
            for uid, req in list(self._live.items()):
                self._release_engine_state(uid, publish=False)
                req.transition(RequestState.QUEUED)
                req._pending_token = None
                orphans.append(req)
            for req, _ in self._adoptions:       # never imported: no state
                orphans.append(req)
            for req, _ in self._handoff_backlog:  # exported + released
                orphans.append(req)
            for req in orphans:
                request_event(req, "evacuate", replica=self.replica_id)
                end_request_segment(req, outcome="evacuated")
            self._queue.clear()
            self._live.clear()
            self._adoptions.clear()
            self._handoff_backlog.clear()
            self._prefix_export_requests.clear()
            self._prefix_adoptions.clear()
            self._requests.clear()
            for req in orphans:
                # these uids never come back to THIS engine
                self._engine.clear_resume(req.uid)
            self._accepting = False
        return orphans

    def stream(self, prompt: Sequence[int], **kwargs):
        """Generator yielding tokens as the driver emits them. Breaking
        out (or ``close()``-ing the generator) cancels the request."""
        return stream_tokens(self, prompt, **kwargs)

    def cancel(self, req) -> bool:
        """Cancel by Request or uid. QUEUED requests die immediately;
        live ones are released by the driver at the next tick boundary.
        Returns False for unknown/already-terminal requests."""
        with self._lock:
            if not isinstance(req, Request):
                req = self._requests.get(int(req))
            if req is None or req.is_terminal:
                return False
            req._cancel_requested = True
            # only requests actually sitting in OUR queue die here; ones
            # parked in the adoption/handoff pens (state QUEUED too) are
            # retired by the driver at their next boundary, where their
            # pen entry is dropped with them
            if req.state is RequestState.QUEUED and req in self._queue:
                self._queue.remove(req)
                self._retire(req, RequestState.CANCELLED)
        self._flush_spans()
        return True

    def drain(self, timeout: Optional[float] = None,
              reject_queued: bool = False) -> bool:
        """Stop accepting new requests and serve out the backlog. With
        ``reject_queued`` the queue is rejected instead of served (the
        preemption-latch shutdown shape). Returns True when every request
        reached a terminal state within ``timeout``."""
        with self._lock:
            self._accepting = False
            if reject_queued:
                for req in list(self._queue):
                    self._queue.remove(req)
                    self._reject(req, "rejected at drain")
        self._flush_spans()
        deadline = self._clock.deadline(
            timeout if timeout is not None else self.config.drain_timeout_s)
        while self._clock.now() < deadline:
            with self._lock:
                if self._idle_locked():
                    return True
            self._clock.sleep(self.config.poll_interval_s)
        with self._lock:
            return self._idle_locked()

    def _idle_locked(self) -> bool:
        """No request in any pre-terminal holding pen (lock held):
        queue, live set, deferred adoptions, un-shipped handoffs —
        including ones mid-export on the driver thread."""
        return (not self._queue and not self._live
                and not self._adoptions and not self._handoff_backlog
                and not self._handoffs_in_flight)

    def close(self, timeout: Optional[float] = None) -> None:
        """Graceful shutdown: drain, cancel whatever would not finish,
        stop the driver + watchdog threads."""
        drained = self.drain(timeout=timeout)
        if not drained:
            with self._lock:
                stuck = (list(self._queue) + list(self._live.values())
                         + [req for req, _ in self._adoptions])
            for req in stuck:
                self.cancel(req)
            t0 = self._clock.now()
            while self._clock.now() - t0 < 5.0:
                with self._lock:
                    if self._idle_locked():
                        break
                self._clock.sleep(self.config.poll_interval_s)
        self._stop_evt.set()
        for t in (self._driver, self._watchdog):
            if t is not None:
                t.join(timeout=5.0)
        self._driver = self._watchdog = None
        self._flush_handoffs()
        self._flush_spans()
        self._update_gauges()

    def __enter__(self) -> "ServingEngine":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- introspection ---------------------------------------------------
    @property
    def queue_depth(self) -> int:
        with self._lock:
            return len(self._queue)

    @property
    def warmup_remaining(self) -> int:
        """Ticks left in the post-hot-swap AOT-warmup window (0 = warm)."""
        with self._lock:
            return self._warmup_remaining

    @property
    def live_requests(self) -> int:
        with self._lock:
            return len(self._live)

    @property
    def pending_work(self) -> int:
        """Every request this replica still owes an outcome: queued,
        live, AND the adoption/handoff pens — the count the fleet's load
        view and scale-down reaping must use (the pens are invisible to
        ``queue_depth``/``live_requests``, and closing a replica with a
        parked adoption would cancel admitted work)."""
        with self._lock:
            return (len(self._queue) + len(self._live)
                    + len(self._adoptions) + len(self._handoff_backlog)
                    + self._handoffs_in_flight)

    def snapshot(self) -> Tuple[int, int, int]:
        """(queue_depth, live, pending_work) under ONE lock acquisition —
        the cell-digest publisher reads every replica each poll, and
        three separate locked property reads per replica would triple
        the digest's lock traffic for values that must be mutually
        consistent anyway."""
        with self._lock:
            pens = (len(self._adoptions) + len(self._handoff_backlog)
                    + self._handoffs_in_flight)
            return (len(self._queue), len(self._live),
                    len(self._queue) + len(self._live) + pens)

    def gray_drain(self) -> Tuple[int, int]:
        """(busy_ticks, distress_ticks) since the previous drain, in one
        lock acquisition — the fleet monitor folds the ratio into this
        replica's :class:`~deepspeed_tpu.serving.health.ReplicaHealth`
        score each poll. Draining (rather than cumulative counters)
        keeps every poll's sample independent, so one bad burst ages out
        of the EWMA instead of haunting the lifetime average."""
        with self._lock:
            out = (self._busy_ticks, self._distress_ticks)
            self._busy_ticks = 0
            self._distress_ticks = 0
            return out

    @property
    def watchdog_unhealthy(self) -> bool:
        """True once the stuck-tick watchdog escalated — the fleet
        monitor's health sweep evacuates this replica. Lock-free read of
        a watchdog-thread-owned bool (same sampling contract as
        ``_in_tick``): a stale read delays evacuation one poll."""
        return self._watchdog_unhealthy

    def _gray_note(self, distress: bool) -> None:
        """Book one busy engine tick (and whether it was degraded) for
        the fleet monitor's distress-ratio sample."""
        with self._lock:
            self._busy_ticks += 1
            if distress:
                self._distress_ticks += 1

    def steal_queued(self, max_n: int) -> List[Request]:
        """Remove up to ``max_n`` requests from the TAIL of the admission
        queue for placement elsewhere (the region's heal-time rebalance
        seam). Only QUEUED, cancel-free requests are taken — they hold
        no engine state, so moving them is pure bookkeeping; the head of
        the queue stays (it is closest to admission HERE, moving it
        would add latency, not shed it). The stolen requests stay QUEUED
        and MUST be re-routed by the caller: a steal without a matching
        re-route is a lost request, exactly what the DST conservation
        invariant exists to catch."""
        out: List[Request] = []
        with self._lock:
            for req in reversed(list(self._queue)):
                if len(out) >= max_n:
                    break
                if req._cancel_requested:
                    continue      # must die here, where cancel() saw it
                self._queue.remove(req)
                self._requests.pop(req.uid, None)
                end_request_segment(req, outcome="rebalanced")
                out.append(req)
            for req in out:
                # a previously preempted uid's resume marker must not
                # suppress telemetry when the uid re-prefills elsewhere
                self._engine.clear_resume(req.uid)
        return out

    def block_leaks(self) -> List[str]:
        """Allocator block-balance problems (empty = zero leak). Valid
        when idle (post-drain); mid-tick reads race the driver."""
        from ..inference.ragged import block_balance_report

        return block_balance_report(self._engine)["problems"]

    # -- driver ----------------------------------------------------------
    def step(self) -> bool:
        """One deterministic driver iteration — the manual-driving seam
        (``start=False``) the fleet's :meth:`~.fleet.ServingFleet.step`
        and the DST harness (docs/dst.md) use instead of the background
        thread. Returns False when idle."""
        return self._tick()

    def _drive(self) -> None:
        poll = self.config.poll_interval_s
        while not self._stop_evt.is_set():
            try:
                # start-time/flag writes must precede _in_tick: the
                # watchdog samples these fields without the lock, and the
                # reverse order lets it judge a fresh tick against the
                # previous tick's stale clock after an idle stretch
                self._tick_started = self._clock.now()  # dslint: disable=races -- deliberate lock-free watchdog sampling (comment above): the watchdog tolerates stale reads, and taking the serving lock in its poll would make the health check hang exactly when a tick wedges under that lock
                self._stuck_reported = False  # dslint: disable=races -- deliberate lock-free watchdog sampling: worst case is one duplicate/missed stuck-tick log line, never corrupted serving state
                self._in_tick = True  # dslint: disable=races -- deliberate lock-free watchdog sampling: a torn read flips one watchdog poll's verdict, which the next poll corrects
                did_work = self._tick()
            except Exception:  # dslint: disable=exception-discipline -- driver-loop bug guard: tick faults are handled INSIDE _tick; InjectedFault (BaseException) still crashes through
                # a driver-loop bug must not silently wedge every caller
                logger.exception("ServingEngine: driver tick crashed")
                did_work = False
            finally:
                self._in_tick = False
            if not did_work:
                self._clock.wait_event(self._stop_evt, poll)

    def _watch(self) -> None:
        timeout = self.config.stuck_tick_timeout_s
        while not self._clock.wait_event(self._stop_evt,
                                         min(1.0, timeout / 4)):
            self._watchdog_check()

    def _watchdog_check(self) -> None:
        """One watchdog poll, factored out of the thread loop so the
        SimClock regression test can drive it deterministically. A tick
        wedged past the timeout logs once per tick (as before); after
        ``stuck_tick_escalate_polls`` CONSECUTIVE wedged polls the
        replica marks itself watchdog-unhealthy so the fleet monitor
        evacuates it — a permanently wedged device call is a gray
        failure no amount of logging fixes."""
        timeout = self.config.stuck_tick_timeout_s
        if not (self._in_tick
                and self._clock.now() - self._tick_started > timeout):
            # tick finished (or a fresh one started): the escalation
            # budget demands CONSECUTIVE wedged polls
            self._stuck_polls = 0
            return
        self._stuck_polls += 1
        if not self._stuck_reported:
            self._stuck_reported = True
            self._count("stuck_ticks")
            logger.warning(
                f"ServingEngine: tick {self._tick_count} stuck for "
                f"> {timeout:.0f}s (device call wedged?)")
            tracer = get_tracer()
            if tracer.enabled:
                # black box of the ticks leading into the wedge
                # (watchdog thread; no serving lock held here)
                tracer.flight.note("stuck_tick",
                                   replica=self.replica_id,
                                   tick=self._tick_count)
                tracer.flight.dump("watchdog-stuck-tick")
        escalate = self.config.stuck_tick_escalate_polls
        if (escalate > 0 and not self._watchdog_unhealthy
                and self._stuck_polls >= escalate):
            self._watchdog_unhealthy = True
            self._count("watchdog_escalations")
            logger.error(
                f"ServingEngine: tick {self._tick_count} still wedged "
                f"after {self._stuck_polls} watchdog polls — marking "
                f"replica unhealthy for fleet evacuation")

    def _check_latch(self) -> None:
        """Preemption-latch poll, at the top of every tick (driver thread
        OR manual stepping — it used to live in the thread loop only,
        which made the latch invisible to deterministically-driven
        tests/simulations)."""
        if self._guard is None or not self._guard.should_stop:
            return
        with self._lock:
            accepting = self._accepting
        if not accepting:
            return
        logger.warning("ServingEngine: preemption latched — draining "
                       "(finishing live requests, rejecting the queue)")
        tracer = get_tracer()
        if tracer.enabled:
            tracer.flight.note("preemption_latch", replica=self.replica_id)
        with self._lock:
            self._accepting = False
            for req in list(self._queue):
                self._queue.remove(req)
                self._reject(req, "preemption drain")
        self._flush_spans()
        if tracer.enabled:
            # auto-dump the black box at the latch (outside the lock:
            # the dump is file I/O when a dump dir is configured)
            tracer.flight.dump("preemption-latch")

    def _tick_warmup(self) -> None:
        """Post-hot-swap AOT-warmup countdown, at the top of every tick
        — INCLUDING idle ones (an idle replica must still finish warming
        up and re-open, so this cannot ride ``_tick_count``, which only
        advances on busy ticks). Admission re-opens when it reaches
        zero."""
        reopened = False
        with self._lock:
            if self._warmup_remaining > 0:
                self._warmup_remaining -= 1
                if (self._warmup_remaining == 0
                        and not self._stop_evt.is_set()):
                    self._accepting = True
                    reopened = True
        if reopened:
            self._count("warmup_done")

    def _maybe_degrade_tick(self) -> bool:
        """Injected canary SLO regression (chaos ``degrade_version``):
        stall this tick — no admission, no engine put, virtual time still
        advances — when the injector degrades THIS replica's model
        version. Only busy ticks stall: an idle degraded replica must
        still report idle, or the fleet would never quiesce."""
        with self._lock:
            busy = bool(self._queue or self._requests)
            version = self.model_version
        if not busy:
            return False
        from ..resilience.chaos import get_fault_injector

        inj = get_fault_injector()
        if inj is None:
            return False
        if not (inj.should_degrade_replica(self.replica_id)
                or inj.should_degrade_tick(version)):
            return False
        self._count("degraded_ticks")
        # a degraded busy tick is the canonical distress sample: the
        # fleet monitor's next gray_drain() sees busy=1, distress=1
        self._gray_note(distress=True)
        self._flush_spans()
        self._update_gauges()
        return True

    def _tick(self) -> bool:
        """One driver iteration; times the productive ticks into the
        hot-path tick sketch (zero-width under a SimClock — the sketch
        stays deterministic; on a wall clock it is the real tick time)."""
        t0 = self._clock.now()
        did = self._tick_inner()
        if did:
            dt = self._clock.now() - t0
            self.digest.observe("tick_s", dt)
            t = self._telemetry
            if t.enabled:
                t.registry.sketch(
                    f"{self._metric_prefix}/tick_s").observe(dt)
        return did

    def _tick_inner(self) -> bool:
        """One driver iteration: latch poll, adoptions, cancellations,
        admission (+ preemption), one engine ``put()`` — a verify step
        when speculative chains are drafted — and token dispatch.
        Returns False when idle."""
        self._check_latch()
        self._tick_warmup()
        if self._maybe_degrade_tick():
            return True
        self._import_adoptions()
        self._service_kv_tier()
        with self._lock:
            self._process_cancellations()
            capacity = self._admit()
            uids, toks, drafts = self._build_feed(capacity)
        if not uids:
            self._flush_spans()
            self._update_gauges()
            return False
        self._tick_count += 1  # dslint: disable=races -- driver-thread-owned counter: only the ticking thread (driver or manual step, never both) increments; the watchdog and fleet chaos poll read it lock-free for diagnostics and tolerate staleness
        self._count("ticks")
        # a productive tick is a clean distress sample; the fault path
        # below flips it to distressed inside _on_tick_fault
        self._gray_note(distress=False)
        try:
            from ..resilience.chaos import get_fault_injector

            inj = get_fault_injector()
            if inj is not None:
                inj.on_serving_tick(self._tick_count)
            uids, logits, verified = self._put_with_recovery(uids, toks,
                                                             drafts)
        except Exception as e:   # InjectedFault crashes (BaseException) pass
            self._on_tick_fault(uids, e)
            self._flush_spans()
            return True
        accepted = self._verify_drafts(verified)
        with self._lock:
            handoffs, emissions, finished = self._dispatch(uids, logits,
                                                           accepted)
        # user callbacks run OUTSIDE the serving lock (dslint
        # lock-discipline): caller code under our lock could re-enter
        # submit()/cancel() or stall every client of this replica.
        # Ordering contract for stream(): tokens are delivered BEFORE
        # the request turns terminal below, so the post-sentinel drain
        # in stream_tokens() still sees every token.
        for req, tok in emissions:
            try:
                req.on_token(tok)
            except Exception:  # dslint: disable=exception-discipline -- user-callback isolation: a caller bug cancels only its own stream, never the tick
                logger.exception(
                    f"ServingEngine: on_token callback failed "
                    f"(request {req.uid}); cancelling its stream")
                req._cancel_requested = True
        with self._lock:
            self._finish(finished)
        self._export_handoffs(handoffs)
        self._flush_handoffs()
        self._flush_spans()
        self._update_gauges()
        return True

    # -- tick phases (driver thread; engine work OUTSIDE the lock) -------
    def _import_adoptions(self) -> None:
        """Import handed-off KV for adopted requests (driver thread only:
        the engine's pool is single-writer). The import itself — a full
        KV page copy — runs OUTSIDE the serving lock, which guards only
        the request structures; holding it across a multi-MB copy would
        stall every submit()/cancel() on this replica. An import that
        cannot land falls back to the normal resume path — the request
        re-queues HERE and re-prefills ``prompt + tokens`` — so a tight
        decode pool degrades to recompute, never to a lost request."""
        with self._lock:
            if not self._adoptions:
                return
            adoptions, self._adoptions = self._adoptions, []
        from ..resilience.chaos import get_fault_injector

        inj = get_fault_injector()
        deferred = []
        now = self._clock.now()
        for req, export in adoptions:
            if req._cancel_requested:
                with self._lock:
                    self._retire(req, RequestState.CANCELLED)
                continue
            if not req.tokens:
                # no emitted token to continue from — nothing a KV import
                # can resume; take the ordinary prefill path instead
                with self._lock:
                    self._enqueue_locked(req, requeue=True)
                continue
            if not self._engine._free_slots:
                # slot exhaustion is TRANSIENT (a live decode finishing
                # frees one, and adoptions run before admission each
                # tick): defer rather than burn the export on a
                # re-prefill that would queue behind the same slots
                deferred.append((req, export))
                continue
            try:
                if inj is not None:
                    # flaky-import chaos (docs/dst.md `flaky_import`):
                    # raises a RECOVERABLE fault every Nth import, which
                    # the fallback below absorbs into a re-prefill
                    inj.on_import_kv()
                self._engine.import_kv(req.uid, export)
            except Exception as e:
                logger.warning(
                    f"ServingEngine: KV import for request {req.uid} "
                    f"failed ({type(e).__name__}: {e}); falling back to "
                    f"re-prefill")
                self._count("adopt_fallbacks")
                request_event(req, "adopt_fallback",
                              replica=self.replica_id,
                              reason=type(e).__name__)
                with self._lock:
                    self._enqueue_locked(req, requeue=True)
                    # a failed import costs a re-prefill: distress
                    # evidence for the gray health score
                    self._distress_ticks += 1
                continue
            with self._lock:
                req.transition(RequestState.PREFILL)
                req.transition(RequestState.DECODE)
                req.t_admit = now
                if req.t_first_admit is None:
                    req.t_first_admit = now
                # the prefill replica emitted at least one token; feeding
                # the last one continues the greedy stream bit-exactly
                req._pending_token = req.tokens[-1]
                self._live[req.uid] = req
                begin_request_segment(req, "decode",
                                      track=self.replica_id,
                                      imported_pages=export.n_pages)
            self._count("adopted")
        if deferred:
            with self._lock:
                self._adoptions.extend(deferred)

    def _service_kv_tier(self) -> None:
        """Drain the global-KV-tier pens and refresh the residency
        snapshot (driver thread only — the engine's pool and prefix
        cache are single-writer). All engine work runs OUTSIDE the
        serving lock: a prefix gather/scatter is a multi-page copy and
        the lock guards only request structures. Failures here never
        touch a request — adoption is prefetch; the worst outcome is
        the local prefill that would have happened anyway."""
        with self._lock:
            tier = self._kv_tier
            if tier is None:
                return
            exports, self._prefix_export_requests = \
                self._prefix_export_requests, []
            adoptions, self._prefix_adoptions = self._prefix_adoptions, []
        from .kvtier import CorruptExport

        for tokens, on_ready in exports:
            export = None
            try:
                export = self._engine.export_prefix(tokens)
            except (ValueError, RuntimeError) as e:
                # donor isolation: a gather fault costs only this
                # prefetch, never the donor's tick
                logger.warning(
                    f"ServingEngine: prefix export failed "
                    f"({type(e).__name__}: {e}); adoption skipped")
            if export is not None:
                self._count("prefix_donated")
                self.digest.count("kvtier/donated")
            try:
                on_ready(export)
            except Exception:  # dslint: disable=exception-discipline -- fleet-callback isolation: same contract as on_token above
                logger.exception(
                    "ServingEngine: prefix-export on_ready callback "
                    "failed")
        for export in adoptions:
            try:
                if self._engine.import_prefix(export):
                    self._count("prefix_adopted")
                    self.digest.count("kvtier/adopted")
            except CorruptExport:
                # the checksum gate fired: the wire lied. Counted apart
                # from plain fallbacks — corruption detected-and-refused
                # is the invariant (#19); landing silently would not be
                self._count("prefix_adopt_corrupt")
                self.digest.count("kvtier/adopt_corrupt")
            except (ValueError, RuntimeError) as e:
                # geometry mismatch / pool exhaustion: degrade to local
                # prefill (the request was never parked on this pen)
                self._count("prefix_adopt_fallbacks")
                self.digest.count("kvtier/adopt_fallback")
                logger.warning(
                    f"ServingEngine: prefix adoption failed "
                    f"({type(e).__name__}: {e}); serving by local "
                    f"prefill")
        self._snapshot_residency(tier)

    def _snapshot_residency(self, tier) -> None:
        """Refresh the residency snapshot on the publish cadence (driver
        thread). Reads the engine's prefix-cache keys without any
        serving lock — the cache is driver-owned — then swaps the
        published tuple under the lock for the fleet's poll to read.
        Cold-readmit deltas ride the same cadence into the routing
        counters (serving/route/cold_readmit, satellite of the
        residency/affinity outcome set)."""
        now = self._clock.now()
        with self._lock:
            if (now - self._last_residency_pub
                    < tier.config.publish_interval_s):
                return
            self._last_residency_pub = now
        eng = self._engine
        hashes = (eng.prefix_residency_hashes()
                  if hasattr(eng, "prefix_residency_hashes") else [])
        readmits = int(getattr(eng, "kvtier_cold_readmits", 0))
        with self._lock:
            delta = readmits - self._cold_readmits_seen
            self._cold_readmits_seen = readmits
            self._residency = (hashes, now)
        if delta > 0:
            t = self._telemetry
            if t.enabled:
                t.registry.counter("serving/route/cold_readmit").inc(delta)
            self.digest.count("route/cold_readmit", delta)

    def _export_handoffs(self, reqs: List[Request]) -> None:
        """Export + release engine state for requests leaving through the
        hand-off seam (driver thread, OUTSIDE the serving lock — same
        stall argument as the import side). The prompt pages are
        published into OUR prefix cache on the way out (repeat prefixes
        still hit this prefill replica). ``_handoffs_in_flight`` keeps
        drain honest across the window where the request is in no pen."""
        for req in reqs:
            export = self._engine.export_kv(req.uid)
            self._engine.preempt(req.uid)
            self._engine.clear_resume(req.uid)   # leaves this engine for good
            req.transition(RequestState.QUEUED)
            req._pending_token = None
            begin_request_segment(req, "handoff", track=self.replica_id,
                                  pages=export.n_pages)
            with self._lock:
                self._handoff_backlog.append((req, export))
                self._handoffs_in_flight -= 1
            self._count("handoffs_out")

    def _process_cancellations(self) -> None:
        for uid, req in list(self._live.items()):
            if req._cancel_requested:
                # a hedge loser's KV is SUSPECT (the replica lost the
                # race for a reason): discard it un-published instead of
                # offering it to the prefix cache
                self._release_engine_state(
                    uid, publish=not getattr(req, "_discard_kv", False))
                del self._live[uid]
                self._retire(req, RequestState.CANCELLED)

    def _admit(self) -> CapacityView:
        """Policy-ordered admission pass (lock held). Returns the tick's
        :class:`CapacityView` — the feed builder reuses it for the
        speculative token-credit arithmetic, so admission and drafting
        judge the same capacity."""
        now = self._clock.now()
        capacity = CapacityView(self._engine,
                                reserve_output=self.config.reserve_output_blocks,
                                live=list(self._live.values()))
        for req in self.policy.admission_order(list(self._queue), now):
            if req._cancel_requested:
                # requeued (fault retry / mid-tick eviction) with a
                # cancel pending: die here, not after another prefill
                self._queue.remove(req)
                self._retire(req, RequestState.CANCELLED)
                continue
            reason = self.policy.should_reject(req, now)
            if reason is not None:
                self._queue.remove(req)
                self._reject(req, reason)
                continue
            if not capacity.fits(req):
                victims = self.policy.preemption_victims(
                    req, list(self._live.values()), capacity, now)
                for victim in victims:
                    self._preempt(victim)
                    capacity.uncharge_live(victim)
                if not victims or not capacity.fits(req):
                    if self.policy.head_of_line_blocking:
                        break
                    continue
            self._queue.remove(req)
            req.transition(RequestState.PREFILL)
            req.t_admit = now
            if req.t_first_admit is None:
                req.t_first_admit = now
            req._pending_token = None
            self._live[req.uid] = req
            capacity.charge(req)
            begin_request_segment(req, "prefill", track=self.replica_id,
                                  policy=self.policy.name,
                                  resume_tokens=len(req.tokens))
            self._count("admitted")
        return capacity

    def _preempt(self, victim: Request) -> None:
        self._release_engine_state(victim.uid, publish=True)
        self._live.pop(victim.uid, None)
        victim.transition(RequestState.QUEUED)
        victim.preemptions += 1
        victim._pending_token = None
        request_event(victim, "preempt", replica=self.replica_id,
                      tokens_in=len(victim.tokens))
        self._enqueue_locked(victim, requeue=True)
        self._count("preempted")
        logger.info(f"ServingEngine: preempted request {victim.uid} "
                    f"(priority {victim.priority}, "
                    f"{len(victim.tokens)} tokens in)")

    def _build_feed(self, capacity: Optional[CapacityView] = None
                    ) -> Tuple[List[int], List[List[int]], List[List[int]]]:
        """Assemble this tick's ``put()`` arguments: full resume context
        for freshly admitted requests, empty continuation chunks for
        mid-prefill ones, one pending decode token each for the rest.

        With speculative serving on, eligible decodes additionally get a
        draft chain — sized by the class acceptance credit
        (``CapacityView.chain_len_for``) and spent strictly out of the
        tick's token-budget SLACK (``CapacityView.draft_budget``): the
        prefill backlog's claim comes off the top, so drafting can slow
        only itself, never prompt progress or another decode's feed."""
        uids: List[int] = []
        toks: List[List[int]] = []
        drafts: List[List[int]] = []
        decode_rows: List[Tuple[int, Request]] = []
        prefill_tokens = 0
        for uid, req in self._live.items():
            seq = self._engine.seqs.get(uid)
            if seq is None:
                uids.append(uid)
                toks.append(req.prompt + req.tokens)
                drafts.append([])
                prefill_tokens += len(req.prompt) + len(req.tokens)
            elif seq.pending > 0:
                uids.append(uid)
                toks.append([])
                drafts.append([])
                prefill_tokens += seq.pending
            elif req._pending_token is not None:
                uids.append(uid)
                toks.append([req._pending_token])
                drafts.append([])
                decode_rows.append((len(uids) - 1, req))
        if self._spec_on and capacity is not None and decode_rows:
            slack = capacity.draft_budget(len(decode_rows), prefill_tokens)
            cfg = self.config
            for i, req in decode_rows:
                if slack <= 0:
                    break
                if req._spec_disabled:
                    continue
                ema = self._spec_ema_by_class.get(req.priority, 1.0)
                k = CapacityView.chain_len_for(ema, cfg.spec_lookahead)
                seq = self._engine.seqs[req.uid]
                k = min(k, slack,
                        self._engine.config.max_context - seq.seen - 1,
                        req.max_new_tokens - len(req.tokens) - 1)
                if k <= 0:
                    continue
                guesses = self._engine.draft_tokens(
                    req.uid, req._pending_token, cfg.spec_ngram, k)
                if guesses:
                    drafts[i] = guesses
                    slack -= len(guesses)
        return uids, toks, drafts

    # -- tick phases (lock NOT held) ------------------------------------
    def _put_with_recovery(self, uids, toks, drafts=None):
        """One engine tick; on KV-pool exhaustion, preempt the cheapest
        decode and retry. Tokens are admitted to the engine's descriptors
        before its pool check, so retries feed empty chunks — and an
        evicted victim must leave the feed entirely, or put() would mint
        a fresh empty descriptor for it and leak its slot.

        With draft chains the first attempt runs the verify step
        (``put_spec``); a PoolExhausted there strips every draft token
        before raising, so the retry degrades to a PLAIN put of the
        already-admitted feed — speculation is never worth an eviction."""
        uids, toks = list(uids), list(toks)
        use_spec = drafts is not None and any(drafts)
        drafts = list(drafts) if use_spec else None
        attempts = 0
        while True:
            try:
                if use_spec:
                    out, verified = self._engine.put_spec(uids, toks, drafts)
                    return uids, out, verified
                return uids, self._engine.put(uids, toks), {}
            except PoolExhausted:
                # the typed catch matters: a generic device RuntimeError
                # (e.g. XLA 'Resource exhausted' OOM) must take the
                # tick-fault path once, not preempt healthy decodes and
                # re-run the failing program live-count times
                use_spec = False       # drafts were stripped on the raise
                with self._lock:
                    # the attempt bound reads _live under the lock: an
                    # unlocked len() raced concurrent submit/cancel
                    # mutations (dsrace finding, PR 15)
                    if attempts >= len(self._live):
                        raise
                    attempts += 1
                    victim = self._pool_pressure_victim(set(uids))
                    if victim is None:
                        raise
                    self._preempt(victim)
                    if victim.uid in uids:
                        i = uids.index(victim.uid)
                        uids.pop(i)
                        toks.pop(i)
                    if not uids:
                        raise
                toks = [[] for _ in uids]   # already admitted: continue only

    def _pool_pressure_victim(self, feed_uids) -> Optional[Request]:
        """Mid-tick eviction pick when the pool runs dry despite admission
        control: the lowest-priority, latest-deadline decode — preferring
        one outside this tick's feed (cheaper: nothing to rebuild)."""
        pool = [r for r in self._live.values()
                if r.state is RequestState.DECODE]
        if not pool:
            return None
        dl = getattr(self.policy, "_deadline_key", lambda r: float("inf"))
        pool.sort(key=lambda r: (r.priority, -dl(r)))
        for r in pool:
            if r.uid not in feed_uids:
                return r
        return pool[0]

    def _on_tick_fault(self, uids, exc: Exception) -> None:
        """A tick died (device error / injected chaos). Engine state for
        every touched uid is suspect — ``seen`` may have advanced without
        its KV being written — so it is DISCARDED (never published into
        the prefix cache) and each request retries from its token stream,
        or fails once its budget is spent. No block leaks either way."""
        self._count("tick_faults")
        logger.warning(f"ServingEngine: tick {self._tick_count} fault: "
                       f"{type(exc).__name__}: {exc}")
        budget_spent = False
        with self._lock:
            # the busy tick was booked clean in _tick_inner; a faulted
            # tick is distress evidence for the gray health score
            self._distress_ticks += 1
            for uid in uids:
                self._release_engine_state(uid, publish=False)
                req = self._live.pop(uid, None)
                if req is None:
                    continue
                req._pending_token = None
                request_event(req, "tick_fault", replica=self.replica_id,
                              error=type(exc).__name__, retry=req.retries)
                if req._cancel_requested:
                    # no point retrying a request the caller already
                    # abandoned (cancel landed while put() was in flight)
                    self._retire(req, RequestState.CANCELLED)
                    continue
                req.retries += 1
                if req.retries <= self.config.tick_retry_limit:
                    req.transition(RequestState.QUEUED)
                    self._enqueue_locked(req, requeue=True,
                                         retry=req.retries)
                else:
                    req.error = (f"tick fault after {req.retries - 1} "
                                 f"retries: {exc}")
                    budget_spent = True
                    self._retire(req, RequestState.CANCELLED)
        if budget_spent:
            tracer = get_tracer()
            if tracer.enabled:
                # retry budget exhausted: dump the black box (outside
                # the serving lock — the dump may write a file)
                tracer.flight.note("tick_fault_retry_exhausted",
                                   replica=self.replica_id,
                                   tick=self._tick_count)
                tracer.flight.dump("tick-fault-exhausted")

    def _verify_drafts(self, verified) -> Dict[int, List[int]]:
        """Greedy accept/trim pass over the tick's verified draft chains
        (driver thread, OUTSIDE the serving lock — the rejected-tail
        trim may touch the device for a copy-on-write page). For each
        chain the longest argmax-matching prefix is accepted — row 0 is
        exactly the plain tick's logits, so the emitted stream is
        TOKEN-IDENTICAL to non-speculative serving by induction — then
        the engine rewinds to the validated context. Returns uid -> the
        emitted tokens ``_dispatch`` applies under the lock; acceptance
        feeds the per-request rolling EMA (fallback floor) and the
        per-class credit EMA (chain sizing).

        A trim that FAILS (its copy-on-write boundary page can allocate,
        so PoolExhausted is reachable here) is contained per uid: that
        request takes the tick-fault path — engine state discarded, this
        round's accepted tokens withheld (they re-generate bit-equal on
        the resume re-prefill), requeue under the retry budget — and
        every other uid's acceptance proceeds. Letting it escape would
        skip ``_on_tick_fault`` entirely and leave already-trimmed and
        not-yet-trimmed streams silently diverged from their requests."""
        if not verified:
            return {}
        with self._lock:
            reqs = {uid: self._live.get(uid) for uid in verified}
        eng = self._engine
        cfg = self.config
        accepted: Dict[int, List[int]] = {}
        failed: Dict[int, Exception] = {}
        tick_prop = tick_acc = 0
        for uid, (chain, rows) in verified.items():
            req = reqs.get(uid)
            seq = eng.seqs.get(uid)
            a = np.argmax(np.asarray(rows), axis=-1)
            matched = 0
            while (matched < len(chain) - 1
                   and int(a[matched]) == chain[matched + 1]):
                matched += 1
            proposed = len(chain) - 1
            tick_prop += proposed
            tick_acc += matched
            if req is None or seq is None:      # evicted mid-tick
                continue
            emitted = [int(x) for x in a[:matched + 1]]
            emitted = emitted[:max(0, req.max_new_tokens - len(req.tokens))]
            if req.eos_token_id is not None and req.eos_token_id in emitted:
                emitted = emitted[:emitted.index(req.eos_token_id) + 1]
            # rewind to the validated context: fed = chain, validated =
            # the pending token + accepted (and emitted) proposals
            keep = seq.seen - len(chain) + len(emitted)
            try:
                if keep < seq.seen:
                    eng.trim(uid, keep)
            except Exception as e:  # dslint: disable=exception-discipline -- every caught exception is handed to _on_tick_fault (the recovery path) via the deferred `failed` dict after the loop; InjectedFault is BaseException and still propagates
                failed[uid] = e
                continue
            accepted[uid] = emitted
            if proposed:
                req.spec_proposed += proposed
                req.spec_accepted += matched
                rate = matched / proposed
                alpha = cfg.spec_ema
                req._spec_ema = (1 - alpha) * req._spec_ema + alpha * rate
                with self._lock:
                    # the class credit is read by _build_feed under the
                    # serving lock; folding into it unlocked from the
                    # driver raced that read (dsrace finding, PR 15)
                    cur = self._spec_ema_by_class.get(req.priority, 1.0)
                    self._spec_ema_by_class[req.priority] = \
                        (1 - alpha) * cur + alpha * rate
                request_event(req, "spec_verify", replica=self.replica_id,
                              proposed=proposed, accepted=matched)
                if (not req._spec_disabled
                        and req.spec_proposed
                        >= cfg.spec_floor_min_proposed
                        and req._spec_ema < cfg.spec_accept_floor):
                    # rolling acceptance under the floor: this request's
                    # context is unpredictable — stop paying for drafts
                    # (plain decode; the stream is identical either way)
                    req._spec_disabled = True
                    self._count("spec_fallbacks")
                    request_event(req, "spec_fallback",
                                  replica=self.replica_id,
                                  ema=round(req._spec_ema, 4))
        if tick_prop:
            self._count("spec_proposed", tick_prop)
            self._count("spec_accepted", tick_acc)
            if hasattr(eng, "record_spec"):
                eng.record_spec(proposed=tick_prop, accepted=tick_acc,
                                rounds=1)
        if failed:
            # per-uid tick-fault recovery: discard the suspect engine
            # state (the chain residue is still on the stream), requeue
            # under the retry budget — resumed bit-exactly from the
            # tokens delivered BEFORE this tick
            self._on_tick_fault(list(failed),
                                next(iter(failed.values())))
        return accepted

    def _dispatch(self, uids, logits: np.ndarray,
                  accepted: Optional[Dict[int, List[int]]] = None
                  ) -> Tuple[List[Request], List[Tuple[Request, int]],
                             List[int]]:
        """Turn the tick's logits into emitted tokens, completions and
        telemetry. Returns (handoff requests, (request, token) pairs for
        ``on_token`` delivery, finished uids) — the KV exports, the user
        callbacks and the FINISHED retirements all happen back in
        ``_tick`` AFTER this lock-held pass: callbacks must not run
        under the serving lock, and retirement must come after delivery
        so ``stream()`` never sees a terminal request with undelivered
        tokens."""
        now = self._clock.now()
        finished: List[int] = []
        handoffs: List[Request] = []
        emissions: List[Tuple[Request, int]] = []
        for row, uid in zip(logits, uids):
            req = self._live.get(uid)
            if req is None or np.isnan(row[0]):
                continue                      # evicted mid-tick / prefilling
            if accepted and uid in accepted:
                # speculative chain: apply the whole accepted run (tokens
                # delivered in order, before any terminal transition —
                # the stream() drain contract holds per token)
                emitted = accepted[uid]
                self._note_served_version(req)
                for tok in emitted:
                    req.tokens.append(tok)
                    if req.on_token is not None:
                        emissions.append((req, tok))
                req._pending_token = emitted[-1]
                if (len(req.tokens) >= req.max_new_tokens
                        or (req.eos_token_id is not None
                            and emitted[-1] == req.eos_token_id)):
                    finished.append(uid)
                continue
            tok = int(np.argmax(row))
            if req.state is RequestState.PREFILL:
                req.transition(RequestState.DECODE)
                if req.t_first_token is None:
                    req.t_first_token = now
                begin_request_segment(req, "decode",
                                      track=self.replica_id)
            self._note_served_version(req)
            req.tokens.append(tok)
            req._pending_token = tok
            if req.on_token is not None:
                emissions.append((req, tok))
            if (len(req.tokens) >= req.max_new_tokens
                    or (req.eos_token_id is not None
                        and tok == req.eos_token_id)):
                finished.append(uid)
            elif (req._handoff_requested and self._on_handoff is not None
                    and self._engine.seqs.get(uid) is not None
                    and self._engine.seqs[uid].pending == 0):
                # disaggregated hand-off: prefill is done and the first
                # token(s) resolved — hand the request to
                # ``_export_handoffs`` (KV export + release outside the
                # lock), which ships it to a decode replica via the
                # fleet callback
                self._live.pop(uid)
                self._requests.pop(uid, None)
                self._handoffs_in_flight += 1
                handoffs.append(req)
        return handoffs, emissions, finished

    def _finish(self, finished: List[int]) -> None:
        """Retire this tick's completed requests (lock held; runs after
        token delivery). Only the driver thread pops ``_live``, so the
        uids are still present — the guard covers nothing but a
        mid-close evacuate()."""
        for uid in finished:
            req = self._live.pop(uid, None)
            if req is None:
                continue
            self._engine.flush([uid])         # publishes into prefix cache
            self._retire(req, RequestState.FINISHED)

    # -- shared helpers --------------------------------------------------
    def _note_served_version(self, req: Request) -> None:
        """Record that THIS engine's version is emitting tokens for
        ``req`` (lock held, just before the append). Consecutive
        duplicates collapse, so the list stays the ordered set of
        distinct serving versions — the DST two-version-stream auditor
        reads it directly."""
        v = self.model_version
        if not req.served_versions or req.served_versions[-1] != v:
            req.served_versions.append(v)

    def _release_engine_state(self, uid: int, publish: bool) -> None:
        """Release whatever the engine holds for ``uid``. ``publish``
        offers full KV blocks to the prefix cache (cancel / preempt);
        tick faults must not (the KV may be torn)."""
        if uid not in self._engine.seqs:
            return
        if publish:
            self._engine.preempt(uid)
        else:
            self._engine.discard(uid)

    def _reject(self, req: Request, reason: str) -> None:
        req.error = reason
        self._retire(req, RequestState.REJECTED)

    def _retire(self, req: Request, state: RequestState) -> None:
        req.transition(state)
        self._requests.pop(req.uid, None)
        # a preempted/faulted request that dies without re-admission must
        # not leave a stale resume marker behind (uid-reuse telemetry)
        self._engine.clear_resume(req.uid)
        self._count({RequestState.FINISHED: "completed",
                     RequestState.CANCELLED: "cancelled",
                     RequestState.REJECTED: "rejected"}[state])
        # span emission does disk I/O (JSONL write + flush): defer it out
        # of the serving lock — every _retire caller holds it, and a slow
        # sink must not stall submit()/cancel()/the next tick
        self._span_backlog.append(req)

    def _flush_handoffs(self) -> None:
        """Deliver exported requests to the fleet OUTSIDE the serving
        lock: the callback routes to (and locks) another replica, and
        holding our lock across that is a lock-order inversion waiting
        to happen."""
        if not self._handoff_backlog:  # dslint: disable=races -- deliberate unlocked peek (the idle driver must not take the lock every poll): worst case one deferred flush; the swap below is locked
            return
        with self._lock:
            backlog, self._handoff_backlog = self._handoff_backlog, []
        for req, export in backlog:
            try:
                self._on_handoff(req, export)
            except Exception:  # dslint: disable=exception-discipline -- hand-off recovery IS the handler: the loss-free response to any callback failure is local re-queue
                # the request's engine state is already released; the one
                # recovery that loses nothing is re-queueing it here
                logger.exception(
                    f"ServingEngine: handoff callback failed for request "
                    f"{req.uid}; re-queueing locally")
                with self._lock:
                    self._requests[req.uid] = req
                    self._enqueue_locked(req, requeue=True)

    def _flush_spans(self) -> None:
        """Emit deferred request spans OUTSIDE the serving lock (the
        request objects are terminal and immutable by now)."""
        if not self._span_backlog:   # unlocked peek: the idle driver loop  # dslint: disable=races -- deliberate unlocked peek (documented here since PR 5): worst case one deferred span flush; the swap below is locked
            return                   # must not take the lock every poll
        with self._lock:
            backlog, self._span_backlog = self._span_backlog, []
        for req in backlog:
            self._emit_span(req)
            if self._on_retire is not None:
                try:
                    self._on_retire(req)
                except Exception:  # dslint: disable=exception-discipline -- callback isolation: fleet bookkeeping crash must not stop span emission for later requests
                    logger.exception(
                        f"ServingEngine: on_retire callback failed "
                        f"(request {req.uid})")

    def _emit_span(self, req: Request) -> None:
        gate = getattr(req, "_hedge", None)
        if gate is not None:
            # a terminal leg decides a still-undecided hedge race
            # (primary wins by default — its outcome is what the client
            # sees; a shadow that dies first just failed to help)
            gate.settle(req.uid)
            if gate.is_suppressed(req.uid):
                # decided loser: the ledger judges the client request
                # ONCE, on the winning leg — no span, no SLO verdict.
                # The trace TREE still closes (observability is not the
                # ledger; an open root would read as a leaked request)
                finish_request_trace(req, state=req.state.value,
                                     new_tokens=len(req.tokens),
                                     error=req.error,
                                     hedge_suppressed=True)
                self._count("hedge_suppressed_spans")
                return
        emit_request_span(self._telemetry, req, digest=self.digest)

    def _update_gauges(self) -> None:
        t = self._telemetry
        if not t.enabled:
            return
        with self._lock:
            depth, live = len(self._queue), len(self._live)
            # the last-published compare-and-set runs under the lock:
            # driver ticks and a main-thread close() both publish, and
            # the unlocked check-then-write raced them (dsrace finding,
            # PR 15). kv_occupancy is host-side allocator arithmetic —
            # same class of locked engine read as _admit's CapacityView.
            snap = (depth, live, self._engine.kv_occupancy())
            if snap == self._last_gauges:   # idle loop: don't re-publish
                return                      # unchanged values every poll
            self._last_gauges = snap
            spec_credit = (min(self._spec_ema_by_class.values())
                           if self._spec_on and self._spec_ema_by_class
                           else None)
        r = t.registry
        r.gauge(f"{self._metric_prefix}/queue_depth").set(depth)
        r.gauge(f"{self._metric_prefix}/live_requests").set(live)
        r.gauge(f"{self._metric_prefix}/kv_occupancy").set(snap[2])
        if spec_credit is not None:
            # the serving-level acceptance credit (worst class is the
            # honest headline — one cold class means drafts are being
            # throttled somewhere)
            r.gauge(f"{self._metric_prefix}/spec_credit").set(spec_credit)
        if self._kv_quant != "none":
            # pool headroom under quantized storage: the capacity win
            # shows up as this gauge staying high at fixed byte budget
            r.gauge(f"{self._metric_prefix}/kv_quant_headroom").set(
                1.0 - snap[2])
