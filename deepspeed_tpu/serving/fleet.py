"""Multi-replica serving: one front-end, N engine replicas.

``ServingFleet`` exposes the same ``submit / stream / cancel / drain /
close`` surface as a single :class:`~.server.ServingEngine`, but
load-balances across N replicas — the MII deployment surface (one
front-end, many model replicas) reproduced TPU-natively. Three pillars:

* **routing** (:mod:`.router`) — least-loaded baseline, or
  prefix-cache-affinity consistent hashing so repeat traffic lands on
  the replica already holding its KV pages. Replicas are health-checked;
  a dead replica's in-flight requests are harvested and re-queued on the
  survivors through the SAME bit-exact resume path preemption uses (the
  dead replica's KV is suspect and is never published; the request
  re-prefills ``prompt + emitted`` elsewhere and the greedy stream
  continues identically).
* **disaggregated prefill/decode** — dedicated prefill replicas compute
  prompt KV, then hand the pages to decode replicas through the
  engine-level :meth:`~deepspeed_tpu.inference.ragged.RaggedInferenceEngine.export_kv`
  / ``import_kv`` seam (a CPU page copy today; the refcount discipline
  is identical to locally-computed pages, so ``assert_block_balance``
  holds on both sides). Prefill replicas keep publishing prompt pages
  into their own prefix caches, so affinity routing and disaggregation
  compose.
* **autoscaling** — a telemetry-driven controller (queue depth, in-SLA
  ratio, KV pressure) sized by
  :func:`deepspeed_tpu.elasticity.compute_serving_replicas` — the policy
  lives in ``elasticity/``, not here — growing the replica set through
  the replica factory and shrinking it with graceful drain (stop
  admission, serve out, close). Dead replicas are respawned with the
  same jittered exponential backoff contract as
  :class:`~deepspeed_tpu.launcher.agent.ElasticAgent`; multi-process
  deployments put each replica process under that agent and point the
  factory at its rendezvous.

Threading: the fleet owns one monitor thread (health + chaos + respawn +
autoscale). Each replica's ServingEngine keeps its own driver. Lock
order is strictly fleet -> replica: fleet callbacks invoked by replica
drivers (``on_handoff`` / ``on_retire``) run OUTSIDE the replica's
serving lock, so taking the fleet lock there cannot invert.

Telemetry: per-replica gauges ride the replica's namespaced metrics
(``serving/<replica>/...``); the fleet adds router counters
(``serving/fleet/affinity_{hits,misses}``, ``handoffs``, ``failovers``,
``respawns``, ``scale_{ups,downs}``) and fleet-wide gauges
(``serving/fleet/replicas``, ``queue_depth``). See docs/serving.md.
"""

from __future__ import annotations

import collections
import itertools
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..resilience.clock import Clock, get_clock
from ..telemetry.tracing import get_tracer, request_event
from ..utils.logging import log_dist, logger
from .request import Request, RequestState
from .router import (NoHealthyReplica, PrefixAffinityRouter, RouterPolicy,
                     least_loaded_pick, make_router)
from .server import ServingEngine, stream_tokens


class ReplicaState:
    HEALTHY = "healthy"
    DRAINING = "draining"
    DEAD = "dead"


class Replica:
    """One engine + its serving front-end, plus fleet-side bookkeeping."""

    def __init__(self, name: str, engine, serving: ServingEngine,
                 role: str = "unified"):
        self.name = name
        self.engine = engine
        self.serving = serving
        self.role = role                  # "unified" | "prefill" | "decode"
        self.state = ReplicaState.HEALTHY
        self.index = int(name.rsplit("-", 1)[-1]) if "-" in name else 0

    @property
    def accepting(self) -> bool:
        return self.state == ReplicaState.HEALTHY and self.serving._accepting

    @property
    def load(self) -> int:
        # pending_work, not queue+live: the adoption/handoff pens hold
        # admitted requests too, and both routing and scale-down reaping
        # must see them
        return self.serving.pending_work

    @property
    def driver_alive(self) -> bool:
        d = self.serving._driver
        return d is not None and d.is_alive()


class ServingFleet:
    """Replicated serving front-end; same call surface as ServingEngine.

    ``engine_factory()`` must return a FRESH
    :class:`~deepspeed_tpu.inference.ragged.RaggedInferenceEngine` (own
    KV pool, same model weights) per call — replicas share nothing but
    parameters. ``serving_config`` is the per-replica ServingConfig (dict
    or object); ``config`` the :class:`~deepspeed_tpu.config.FleetConfig`
    (dict or object). With ``start=False`` nothing ticks on its own:
    tests drive determinstically via :meth:`step` (one poll + one tick
    per replica).
    """

    def __init__(self, engine_factory, config: Any = None,
                 serving_config: Any = None,
                 router: Optional[RouterPolicy] = None,
                 preemption_guard: Any = None,
                 start: bool = True,
                 clock: Optional[Clock] = None):
        from ..config import FleetConfig, ServingConfig

        if config is None:
            config = FleetConfig()
        elif isinstance(config, dict):
            config = FleetConfig.from_dict(config)
        self.config = config
        if serving_config is None:
            serving_config = ServingConfig()
        elif isinstance(serving_config, dict):
            serving_config = ServingConfig.from_dict(serving_config)
        self._serving_config = serving_config
        self._factory = engine_factory
        self._guard = preemption_guard
        self._start_drivers = start
        # the fleet's timebase: health/autoscale intervals, respawn
        # backoff, drain budgets, request submit stamps — and every
        # replica it spawns inherits it (docs/dst.md)
        self._clock = clock if clock is not None else get_clock()
        self._lock = threading.RLock()
        self._replicas: Dict[str, Replica] = {}
        self._requests: Dict[int, Tuple[Request, str]] = {}  # uid -> (req, replica)
        self._name_counter = itertools.count()
        self._accepting = True
        self._stop_evt = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._last_autoscale = 0.0
        self._chaos_fired = False
        # sliding in-SLA window feeding the autoscaler (True/False per
        # SLO-carrying terminal request; cancels and SLO-less skipped)
        self._sla_window = collections.deque(maxlen=config.sla_window)
        self._shed_backlog: List[Request] = []   # fleet-rejected, span due
        # respawn backoff (ElasticAgent contract: exponential + healthy
        # reset; here per-fleet since replicas are interchangeable)
        self._respawn_after = 0.0
        self._respawn_delay = 0.5
        if router is not None:
            self.router = router
        else:
            self.router = make_router(
                config.router, block_size=self._probe_block_size(),
                vnodes=config.affinity_vnodes,
                spill_load=config.affinity_spill_load)
        if config.disaggregated:
            for _ in range(config.prefill_replicas):
                self._spawn(role="prefill")
            for _ in range(config.replicas):
                self._spawn(role="decode")
        else:
            for _ in range(config.replicas):
                self._spawn(role="unified")
        log_dist(f"ServingFleet: {len(self._replicas)} replicas "
                 f"router={self.router.name} "
                 f"disaggregated={config.disaggregated} "
                 f"autoscale={config.autoscale}")
        if start:
            self._monitor = threading.Thread(target=self._monitor_loop,
                                             daemon=True,
                                             name="fleet-monitor")
            self._monitor.start()

    def _probe_block_size(self) -> int:
        # the affinity key must match the engines' prefix-cache unit; all
        # replicas share one config, so any instance answers. No replica
        # exists yet at router-construction time, so build one eagerly
        # only when the router actually needs the block size.
        if self.config.router != "prefix_affinity":
            return 16
        eng = self._factory()
        self._pending_engine = eng
        return eng.config.kv_block_size

    # -- telemetry -------------------------------------------------------
    @property
    def _telemetry(self):
        from ..telemetry import get_telemetry

        return get_telemetry()

    def _count(self, name: str, n: float = 1.0) -> None:
        self._telemetry.registry.counter(f"serving/fleet/{name}").inc(n)

    def _update_gauges(self) -> None:
        t = self._telemetry
        if not t.enabled:
            return
        with self._lock:
            healthy = [r for r in self._replicas.values()
                       if r.state == ReplicaState.HEALTHY]
            depth = sum(r.serving.queue_depth for r in healthy)
        t.registry.gauge("serving/fleet/replicas").set(len(healthy))
        t.registry.gauge("serving/fleet/queue_depth").set(depth)

    # -- replica lifecycle ----------------------------------------------
    def _spawn(self, role: str = "unified") -> Replica:
        """Build one replica (engine via the factory + a namespaced
        ServingEngine) and register it with the router."""
        engine = getattr(self, "_pending_engine", None)
        if engine is not None:
            self._pending_engine = None
        else:
            engine = self._factory()
        name = f"replica-{next(self._name_counter)}"
        serving = ServingEngine(
            engine, self._serving_config,
            preemption_guard=self._guard,
            start=self._start_drivers,
            replica_id=name,
            on_handoff=(self._on_handoff if role == "prefill" else None),
            on_retire=self._on_retire,
            clock=self._clock)
        rep = Replica(name, engine, serving, role=role)
        with self._lock:
            self._replicas[name] = rep
            # the routing ring hashes over the replicas that PREFILL —
            # that's where prompt KV is computed and where the prefix
            # cache pays off. Disaggregated: the prefill pool; unified:
            # everyone. Decode replicas never own a ring segment (their
            # placement is least-loaded at hand-off time: the pages are
            # new to all of them).
            prefills = (role == "prefill" if self.config.disaggregated
                        else role == "unified")
            if prefills:
                self.router.on_join(name)
        self._update_gauges()
        return rep

    def _view(self, role: Optional[str] = None, live: bool = False,
              refused=()) -> Dict[str, int]:
        """name -> load routing view. ``live=False``: replicas accepting
        NEW work (health-checked admission view). ``live=True``: anything
        not DEAD — the continuation view (draining replicas finish
        admitted work, they just take no new admissions). ``role``
        filters; None = any serving (non-prefill) role. ``refused`` names
        are excluded (stop-race retry loops)."""
        out = {}
        for r in self._replicas.values():
            if r.name in refused:
                continue
            if (r.state == ReplicaState.DEAD) if live else not r.accepting:
                continue
            if role is not None and r.role != role:
                continue
            if role is None and r.role == "prefill":
                continue
            out[r.name] = r.load
        return out

    # -- submission ------------------------------------------------------
    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               priority: int = 0,
               deadline_s: Optional[float] = None,
               ttft_deadline_s: Optional[float] = None,
               client_request_id: Optional[str] = None,
               on_token=None) -> Request:
        """Route a request to a replica. Same contract as
        ``ServingEngine.submit``: returns immediately, possibly already
        REJECTED (no healthy replica, or the target's backpressure)."""
        req = Request(
            prompt=list(prompt),
            max_new_tokens=(max_new_tokens if max_new_tokens is not None
                            else self._serving_config.default_max_new_tokens),
            eos_token_id=eos_token_id, priority=priority,
            deadline_s=deadline_s, ttft_deadline_s=ttft_deadline_s,
            client_request_id=client_request_id, on_token=on_token)
        # adopt the fleet's clock before stamping (same timebase rule as
        # ServingEngine.submit_request: injected clock != global clock
        # must not split a request's lifecycle across two timebases)
        req._clock = self._clock
        req.t_submit = self._clock.now()
        # tracing: the root opens HERE, before routing, so the router
        # decision is the tree's first child even for fleet-level sheds
        tracer = get_tracer()
        if tracer.enabled:
            req._trace_root = tracer.new_trace(
                "request", prompt_tokens=len(req.prompt),
                priority=req.priority)
        self._route(req)
        self._flush_shed()
        return req

    def _route(self, req: Request, requeue: bool = False) -> None:
        """Pick a replica and enqueue. ``requeue`` marks the continuation
        of an already-admitted request (fail-over, hand-off fallback): it
        bypasses the fleet and replica admission gates — a draining fleet
        must serve out admitted work — and may land on DRAINING (never
        DEAD) replicas. A pick whose driver stopped between the view
        snapshot and the enqueue refuses non-terminally; the loop places
        the request elsewhere."""
        tracer = get_tracer()
        if requeue:
            request_event(req, "reroute")
        refused: set = set()
        while True:
            # the router decision is a span of its own on the request's
            # tree: replica pick + (for the affinity ring) hit/miss/spill
            # verdict, one span per routing attempt
            route_span = tracer.begin_span(
                "route", getattr(req, "_trace_root", None),
                requeue=bool(requeue), attempt=len(refused))
            with self._lock:
                if not self._accepting and not requeue:
                    tracer.finish_span(route_span, error="fleet closed")
                    self._reject(req, "fleet closed to new requests")
                    return
                if self.config.disaggregated:
                    # prefill pool first — routed by the CONFIGURED
                    # router below (affinity composes with
                    # disaggregation: the ring hashes the prefill
                    # replicas, where repeat prefixes find their cached
                    # KV); the handoff hook ships the result onward
                    view = self._view("prefill", live=requeue,
                                      refused=refused)
                    if not view:
                        # degrade: unified path on whatever can serve
                        view = self._view(live=requeue, refused=refused)
                        req._handoff_requested = False
                    else:
                        req._handoff_requested = True
                else:
                    view = self._view(live=requeue, refused=refused)
                if not view:
                    tracer.finish_span(route_span, error="no replica")
                    self._reject(req, "no healthy replica")
                    return
                try:
                    name = self.router.route(view, req.prompt)
                except NoHealthyReplica:
                    tracer.finish_span(route_span, error="no replica")
                    self._reject(req, "no healthy replica")
                    return
                if isinstance(self.router, PrefixAffinityRouter):
                    self._count("affinity_hits"
                                if self.router.last_was_primary
                                else "affinity_misses")
                # router verdict captured under the lock (router state
                # mutates per route()); the span finishes only after the
                # enqueue, so a refused pick is marked as such and the
                # trace shows which replica actually ACCEPTED
                route_info = self.router.route_info()
                self._requests[req.uid] = (req, name)
                replica = self._replicas[name]
            accepted = replica.serving.submit_request(
                req, requeue=requeue) is not None
            tracer.finish_span(route_span, replica=name,
                               accepted=accepted, **route_info)
            if accepted:
                self._count("routed")
                return
            refused.add(name)      # stopped mid-race: try the next one


    def stream(self, prompt: Sequence[int], **kwargs):
        """Generator yielding tokens as they are emitted (see
        ``ServingEngine.stream``)."""
        return stream_tokens(self, prompt, **kwargs)

    def cancel(self, req) -> bool:
        """Cancel by Request or uid, wherever the request currently
        lives. A request in flight between replicas (handoff/failover)
        carries the flag with it and dies at its next boundary."""
        with self._lock:
            if not isinstance(req, Request):
                ent = self._requests.get(int(req))
                if ent is None:
                    return False
                req = ent[0]
            if req.is_terminal:
                return False
            req._cancel_requested = True
            ent = self._requests.get(req.uid)
            replica = self._replicas.get(ent[1]) if ent is not None else None
        if replica is not None:
            replica.serving.cancel(req)
        return True

    # -- shutdown --------------------------------------------------------
    def drain(self, timeout: Optional[float] = None,
              reject_queued: bool = False) -> bool:
        """Stop admission fleet-wide and serve out every backlog. Prefill
        replicas drain first so their handoffs land before the decode
        replicas are judged empty."""
        with self._lock:
            self._accepting = False
            replicas = list(self._replicas.values())
        for r in replicas:
            if r.state == ReplicaState.HEALTHY:
                r.serving.stop_admission()
        budget = (timeout if timeout is not None
                  else self._serving_config.drain_timeout_s)
        deadline = self._clock.deadline(budget)
        ordered = ([r for r in replicas if r.role == "prefill"]
                   + [r for r in replicas if r.role != "prefill"])
        ok = True
        for r in ordered:
            if r.state == ReplicaState.DEAD:
                continue
            left = max(0.0, deadline - self._clock.now())
            ok = r.serving.drain(timeout=left, reject_queued=reject_queued) \
                and ok
        return ok

    def close(self, timeout: Optional[float] = None) -> None:
        """Graceful shutdown: drain, then close every replica and stop
        the monitor."""
        self.drain(timeout=timeout)
        self._stop_evt.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
            self._monitor = None
        with self._lock:
            replicas = list(self._replicas.values())
        for r in replicas:
            if r.state != ReplicaState.DEAD:
                r.serving.close(timeout=timeout)
        self._flush_shed()
        self._update_gauges()

    def __enter__(self) -> "ServingFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- introspection ---------------------------------------------------
    @property
    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas.values())

    @property
    def healthy_replicas(self) -> List[Replica]:
        with self._lock:
            return [r for r in self._replicas.values()
                    if r.state == ReplicaState.HEALTHY]

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return sum(r.serving.queue_depth for r in self._replicas.values()
                       if r.state != ReplicaState.DEAD)

    @property
    def live_requests(self) -> int:
        with self._lock:
            return sum(r.serving.live_requests
                       for r in self._replicas.values()
                       if r.state != ReplicaState.DEAD)

    def block_leaks(self) -> List[str]:
        """Fleet-wide KV leak audit: the union of every replica's
        block-balance problems, each prefixed with its replica name
        (empty list = zero leaks everywhere, dead replicas included —
        evacuation discards their sequences, so their allocators must
        balance too). Valid when idle; mid-tick reads race drivers."""
        from ..inference.ragged import block_balance_report

        problems: List[str] = []
        for r in self.replicas:
            for p in block_balance_report(r.engine)["problems"]:
                problems.append(f"{r.name}: {p}")
        return problems

    def in_sla_ratio(self) -> Optional[float]:
        """Fraction of recent SLO-carrying requests that met their SLO
        (None until one lands) — the autoscaler's quality signal."""
        with self._lock:
            if not self._sla_window:
                return None
            return sum(self._sla_window) / len(self._sla_window)

    # -- replica-driver callbacks (OUTSIDE the replica's serving lock) ---
    def _on_retire(self, req: Request) -> None:
        # same verdict discipline as the request span: completions judged
        # against their deadlines, sheds with an SLO count as misses,
        # user cancels not judged
        had_slo = (req.deadline_s is not None
                   or req.ttft_deadline_s is not None)
        with self._lock:
            self._requests.pop(req.uid, None)
            if req.state is RequestState.FINISHED:
                verdict = req.in_slo()
                if verdict is not None:
                    self._sla_window.append(bool(verdict))
            elif had_slo and not (req.state is RequestState.CANCELLED
                                  and req.error is None):
                self._sla_window.append(False)

    def _on_handoff(self, req: Request, export) -> None:
        """A prefill replica finished a flagged request's prompt: ship
        the KV to a decode replica (least-loaded — the pages are new to
        every decode replica, affinity buys nothing here). A hand-off is
        the CONTINUATION of an admitted request, so draining replicas
        (admission closed, serving out) still take it — only dead ones
        are excluded. No live decode replica means the request re-queues
        wherever possible and re-prefills (degraded, never lost)."""
        refused: set = set()
        while True:
            with self._lock:
                view = self._view("decode", live=True, refused=refused)
                if not view:
                    # last resort: decode ON a prefill replica (same
                    # engine, same weights) rather than shed admitted
                    # work — clear the flag or its next first-token
                    # would hand off again in an endless loop
                    view = self._view("prefill", live=True,
                                      refused=refused)
                    req._handoff_requested = False
                if not view:
                    self._reject(req, "no live replica for decode handoff")
                    break
                name = least_loaded_pick(view)
                self._requests[req.uid] = (req, name)
                replica = self._replicas[name]
            if replica.serving.adopt(req, export):
                self._count("handoffs")
                return
            # the pick stopped between the view snapshot and adopt()
            # (scale-down reap / kill race): place it elsewhere
            refused.add(name)
        self._flush_shed()

    def _reject(self, req: Request, reason: str) -> None:
        """Fleet-level shed (no replica ever owned the request). Same
        observable contract as a replica-level reject: span emitted into
        requests.jsonl and — when the request carried an SLO — a miss in
        the autoscaler's in-SLA window (shedding is exactly the signal
        that must drive scale-up). The span write is DEFERRED to
        :meth:`_flush_shed` — most callers hold the fleet lock, and sink
        I/O under it would stall every submit/cancel/poll exactly when
        the system sheds load (same discipline as the replica span
        backlog)."""
        req.error = reason
        req.transition(RequestState.REJECTED)
        self._count("rejected")
        with self._lock:    # reentrant: most (not all) callers hold it
            self._shed_backlog.append(req)

    def _flush_shed(self) -> None:
        """Emit deferred fleet-shed spans OUTSIDE the fleet lock (the
        requests are terminal and immutable by now)."""
        from .server import emit_request_span

        if not self._shed_backlog:
            return
        with self._lock:
            backlog, self._shed_backlog = self._shed_backlog, []
        for req in backlog:
            emit_request_span(self._telemetry, req)
            self._on_retire(req)

    # -- health / chaos / failover --------------------------------------
    def kill_replica(self, name: str, reason: str = "killed") -> bool:
        """Abrupt replica death (tests, chaos, ops). In-flight work fails
        over to the survivors when ``config.failover`` is on."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None or rep.state == ReplicaState.DEAD:
                return False
            rep.state = ReplicaState.DEAD
            self.router.on_leave(name)
        logger.warning(f"ServingFleet: replica {name} died ({reason})")
        rep.serving.kill()
        orphans = rep.serving.evacuate()
        self._failover_orphans(orphans, source=name)
        self._update_gauges()
        return True

    def _failover_orphans(self, orphans: List[Request],
                          source: str) -> None:
        """Re-place (or shed, per config) requests harvested from a dead
        or force-closed replica. Runs WITHOUT the fleet lock."""
        if self.config.failover:
            if orphans:
                self._count("failovers", len(orphans))
            for req in orphans:
                request_event(req, "failover", source=source)
                if req._cancel_requested:
                    # honor the pending cancel here (its replica is gone)
                    # with the full terminal contract: span + counter,
                    # same as a replica-level retire
                    from .server import emit_request_span

                    req.transition(RequestState.CANCELLED)
                    self._count("cancelled")
                    emit_request_span(self._telemetry, req)
                    self._on_retire(req)
                    continue
                self._route(req, requeue=True)
        else:
            for req in orphans:
                self._reject(req, f"replica {source} died")
        self._flush_shed()

    def poll(self) -> None:
        """One monitor pass: driver health, injected chaos, respawn,
        autoscale-interval check. The monitor thread loops this; tests
        call it directly for determinism."""
        self._check_chaos()
        self._check_health()
        self._check_respawn()
        if self.config.autoscale:
            now = self._clock.now()
            if now - self._last_autoscale >= self.config.autoscale_interval_s:
                self._last_autoscale = now
                self.autoscale_once()
        self._flush_shed()
        self._update_gauges()

    def _monitor_loop(self) -> None:
        while not self._clock.wait_event(self._stop_evt,
                                         self.config.health_interval_s):
            try:
                self.poll()
            except Exception:  # dslint: disable=exception-discipline -- monitor-loop bug guard: a respawn/autoscale crash must not kill the fleet thread; typed faults are handled inside poll()
                logger.exception("ServingFleet: monitor pass crashed")

    def _check_chaos(self) -> None:
        if self._chaos_fired:
            return
        from ..resilience.chaos import get_fault_injector

        inj = get_fault_injector()
        if inj is None:
            return
        with self._lock:
            candidates = [(r.name, r.index, r.serving._tick_count)
                          for r in self._replicas.values()
                          if r.state == ReplicaState.HEALTHY]
        for name, index, ticks in candidates:
            if inj.should_kill_replica(index, ticks):
                self._chaos_fired = True
                self.kill_replica(name, reason="chaos: injected death")
                return

    def _check_health(self) -> None:
        """A replica whose driver thread died (unhandled crash, real
        process trouble) is treated exactly like injected death —
        DRAINING replicas included: their backlog still needs a driver,
        and an unnoticed death would strand it forever."""
        if not self._start_drivers:
            return              # manual-step mode: no threads to check
        with self._lock:
            sick = [r.name for r in self._replicas.values()
                    if r.state != ReplicaState.DEAD and not r.driver_alive]
        for name in sick:
            self.kill_replica(name, reason="driver thread dead")

    def _check_respawn(self) -> None:
        """Replace dead capacity while the healthy count sits below
        ``min_replicas`` — the fleet-local analog of ElasticAgent's
        restart loop, with the same jittered exponential backoff shape
        (deterministic here: replicas are stateless to replace)."""
        if not self.config.respawn:
            return
        with self._lock:
            # each pool is audited against its own floor: the serving
            # (non-prefill) pool against min_replicas — same denominator
            # as scale_to/autoscale, else healthy prefill replicas mask
            # dead decode capacity — and, in disaggregated mode, the
            # prefill pool against prefill_replicas (losing it silently
            # degrades every request to unified re-prefill serving)
            healthy = sum(1 for r in self._replicas.values()
                          if r.state == ReplicaState.HEALTHY
                          and r.role != "prefill")
            prefill = sum(1 for r in self._replicas.values()
                          if r.state == ReplicaState.HEALTHY
                          and r.role == "prefill")
            want_prefill = (self.config.prefill_replicas
                            if self.config.disaggregated else 0)
            if self.config.disaggregated and prefill < want_prefill:
                role, have, floor = "prefill", prefill, want_prefill
            elif healthy < self.config.min_replicas:
                role = "decode" if self.config.disaggregated else "unified"
                have, floor = healthy, self.config.min_replicas
            else:
                self._respawn_delay = 0.5
                return
            if not self._accepting:
                return
            if self._clock.now() < self._respawn_after:
                return
            self._respawn_after = self._clock.now() + self._respawn_delay
            self._respawn_delay = min(self._respawn_delay * 2.0, 30.0)
        rep = self._spawn(role=role)
        self._count("respawns")
        from ..resilience import record_restart

        record_restart()
        logger.warning(f"ServingFleet: respawned {role} capacity as "
                       f"{rep.name} ({have}/{floor} healthy)")

    # -- autoscaling -----------------------------------------------------
    def _elastic_config(self):
        from ..elasticity import ServingElasticityConfig

        c = self.config
        return ServingElasticityConfig(
            min_replicas=c.min_replicas, max_replicas=c.max_replicas,
            scale_up_queue_per_replica=c.scale_up_queue_per_replica,
            scale_down_queue_per_replica=c.scale_down_queue_per_replica,
            kv_high=c.kv_high, sla_low=c.sla_low)

    def autoscale_once(self) -> int:
        """One controller decision: measure, size via the shared
        elasticity policy, apply. Returns the target count."""
        from ..elasticity import compute_serving_replicas

        with self._lock:
            scalable = [r for r in self._replicas.values()
                        if r.state != ReplicaState.DEAD
                        and r.role != "prefill"]
            healthy = [r for r in scalable
                       if r.state == ReplicaState.HEALTHY]
            queue_depth = sum(r.serving.queue_depth for r in scalable)
            # demand, not raw occupancy: cache-reclaimable pages are
            # capacity, and counting them would ratchet the fleet to
            # max_replicas after any warm-cache burst
            kv = (max(r.engine.kv_demand() for r in healthy)
                  if healthy else 0.0)
        target = compute_serving_replicas(
            max(1, len(healthy)), queue_depth=queue_depth, kv_occupancy=kv,
            in_sla_ratio=self.in_sla_ratio(), config=self._elastic_config())
        self.scale_to(target)
        return target

    def scale_to(self, n: int) -> None:
        """Grow to / shrink toward ``n`` serving (non-prefill) replicas.
        Scale-down is graceful: the least-loaded replica stops admission,
        serves out, and only then closes (finished by later polls)."""
        with self._lock:
            if not self._accepting:
                # draining/closing fleet: spawning replicas that can
                # never receive work just burns engines moments before
                # close() tears them down (the backlog reads as load
                # until it serves out)
                return
            # selection and state flip under ONE lock acquisition: a
            # stale snapshot could resurrect a replica kill_replica()
            # just flipped to DEAD
            healthy = [r for r in self._replicas.values()
                       if r.state == ReplicaState.HEALTHY
                       and r.role != "prefill"]
            delta = n - len(healthy)
            victims: List[Replica] = []
            if delta < 0:
                victims = sorted(healthy, key=lambda r: (r.load, r.name))
                victims = victims[:min(-delta, max(0, len(healthy) - 1))]
                for r in victims:
                    r.state = ReplicaState.DRAINING
                    self.router.on_leave(r.name)
        if delta > 0:
            role = "decode" if self.config.disaggregated else "unified"
            for _ in range(delta):
                self._spawn(role=role)
                self._count("scale_ups")
        for r in victims:
            r.serving.stop_admission()
            self._count("scale_downs")
        # reap drained replicas (from this call or earlier ones). DEAD is
        # flipped BEFORE close(): once close sets the replica's stop
        # event it refuses continuations, so it must already be out of
        # every requeue/handoff view (adopt()'s refusal return covers
        # the one in-flight call that raced the flip)
        with self._lock:
            drained = [r for r in self._replicas.values()
                       if r.state == ReplicaState.DRAINING and r.load == 0]
            for r in drained:
                r.state = ReplicaState.DEAD
        for r in drained:
            r.serving.close(timeout=5.0)
            # a continuation enqueued in the window between the DEAD flip
            # and close() stopping the driver would otherwise be stranded
            # in a joined-dead replica — harvest and re-place it
            stragglers = r.serving.evacuate()
            if stragglers:
                self._failover_orphans(stragglers, source=r.name)
            logger.info(f"ServingFleet: scale-down of {r.name} complete")
        self._update_gauges()

    # -- deterministic driving (tests / smoke) ---------------------------
    def step(self) -> bool:
        """Manual-mode driver: one monitor poll plus one tick per live
        replica. Returns True when any replica did work. Only meaningful
        with ``start=False`` (no competing threads)."""
        self.poll()
        did = False
        for r in self.replicas:
            if r.state == ReplicaState.DEAD:
                continue
            did = r.serving._tick() or did
        return did
