"""Multi-replica serving: one front-end, N engine replicas.

``ServingFleet`` exposes the same ``submit / stream / cancel / drain /
close`` surface as a single :class:`~.server.ServingEngine`, but
load-balances across N replicas — the MII deployment surface (one
front-end, many model replicas) reproduced TPU-natively. Three pillars:

* **routing** (:mod:`.router`) — least-loaded baseline, or
  prefix-cache-affinity consistent hashing so repeat traffic lands on
  the replica already holding its KV pages. Replicas are health-checked;
  a dead replica's in-flight requests are harvested and re-queued on the
  survivors through the SAME bit-exact resume path preemption uses (the
  dead replica's KV is suspect and is never published; the request
  re-prefills ``prompt + emitted`` elsewhere and the greedy stream
  continues identically).
* **disaggregated prefill/decode** — dedicated prefill replicas compute
  prompt KV, then hand the pages to decode replicas through the
  engine-level :meth:`~deepspeed_tpu.inference.ragged.RaggedInferenceEngine.export_kv`
  / ``import_kv`` seam (a CPU page copy today; the refcount discipline
  is identical to locally-computed pages, so ``assert_block_balance``
  holds on both sides). Prefill replicas keep publishing prompt pages
  into their own prefix caches, so affinity routing and disaggregation
  compose.
* **autoscaling** — a telemetry-driven controller (queue depth, in-SLA
  ratio, KV pressure) sized by
  :func:`deepspeed_tpu.elasticity.compute_serving_replicas` — the policy
  lives in ``elasticity/``, not here — growing the replica set through
  the replica factory and shrinking it with graceful drain (stop
  admission, serve out, close). Dead replicas are respawned with the
  same jittered exponential backoff contract as
  :class:`~deepspeed_tpu.launcher.agent.ElasticAgent`; multi-process
  deployments put each replica process under that agent and point the
  factory at its rendezvous.

Threading: the fleet owns one monitor thread (health + chaos + respawn +
autoscale). Each replica's ServingEngine keeps its own driver. Lock
order is strictly fleet -> replica: fleet callbacks invoked by replica
drivers (``on_handoff`` / ``on_retire``) run OUTSIDE the replica's
serving lock, so taking the fleet lock there cannot invert.

Telemetry: per-replica gauges ride the replica's namespaced metrics
(``serving/<replica>/...``); the fleet adds router counters
(``serving/fleet/affinity_{hits,misses}``, ``handoffs``, ``failovers``,
``respawns``, ``scale_{ups,downs}``) and fleet-wide gauges
(``serving/fleet/replicas``, ``queue_depth``). See docs/serving.md.
"""

from __future__ import annotations

import collections
import itertools
import random
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..resilience.clock import Clock, get_clock
from ..resilience.locksan import named_rlock
from ..resilience.retry import RetryBudget
from ..telemetry.tracing import get_tracer, request_event
from ..utils.logging import log_dist, logger
from .health import (BreakerState, CircuitBreaker, HealthState, HedgePair,
                     ReplicaHealth)
from .request import Request, RequestState
from .router import (NoHealthyReplica, PrefixAffinityRouter,
                     ResidencyAwareRouter, RouterPolicy, _hash64,
                     least_loaded_pick, make_router, prefix_key)
from .server import ServingEngine, stream_tokens


def route_budget_for(req: Request, size: int) -> RetryBudget:
    """The request's route-retry budget, created at first need and
    carried on the request itself. ONE budget per request LIFECYCLE,
    drawn from by every tier that re-routes it — this fleet's replica
    loop, a region's cell loop, failover and hand-off continuations —
    so a refusing or partitioned target is given up on explicitly
    rather than hammered forever. Scoping the pool to the request (not
    the fleet/region) matters: a process-lifetime pool would let past
    refusals accumulated across OTHER requests permanently starve
    future, healthy work of its retries."""
    budget = getattr(req, "_route_budget", None)
    if budget is None:
        budget = RetryBudget(size)
        req._route_budget = budget
    return budget


class ReplicaState:
    HEALTHY = "healthy"
    DRAINING = "draining"
    DEAD = "dead"


class Replica:
    """One engine + its serving front-end, plus fleet-side bookkeeping."""

    def __init__(self, name: str, engine, serving: ServingEngine,
                 role: str = "unified"):
        self.name = name
        self.engine = engine
        self.serving = serving
        self.role = role                  # "unified" | "prefill" | "decode"
        self.state = ReplicaState.HEALTHY
        self.index = int(name.rsplit("-", 1)[-1]) if "-" in name else 0

    @property
    def accepting(self) -> bool:
        return self.state == ReplicaState.HEALTHY and self.serving._accepting

    @property
    def version(self) -> int:
        """The model version this replica serves (hot_swap bumps it)."""
        return self.serving.model_version

    @property
    def load(self) -> int:
        # pending_work, not queue+live: the adoption/handoff pens hold
        # admitted requests too, and both routing and scale-down reaping
        # must see them
        return self.serving.pending_work

    @property
    def driver_alive(self) -> bool:
        d = self.serving._driver
        return d is not None and d.is_alive()


class ServingFleet:
    """Replicated serving front-end; same call surface as ServingEngine.

    ``engine_factory()`` must return a FRESH
    :class:`~deepspeed_tpu.inference.ragged.RaggedInferenceEngine` (own
    KV pool, same model weights) per call — replicas share nothing but
    parameters. ``serving_config`` is the per-replica ServingConfig (dict
    or object); ``config`` the :class:`~deepspeed_tpu.config.FleetConfig`
    (dict or object). With ``start=False`` nothing ticks on its own:
    tests drive determinstically via :meth:`step` (one poll + one tick
    per replica).
    """

    def __init__(self, engine_factory, config: Any = None,
                 serving_config: Any = None,
                 router: Optional[RouterPolicy] = None,
                 preemption_guard: Any = None,
                 start: bool = True,
                 clock: Optional[Clock] = None,
                 name: Optional[str] = None,
                 on_retire=None,
                 on_handoff_escalation=None,
                 on_route_escalation=None):
        from ..config import FleetConfig, ServingConfig

        if config is None:
            config = FleetConfig()
        elif isinstance(config, dict):
            config = FleetConfig.from_dict(config)
        self.config = config
        if serving_config is None:
            serving_config = ServingConfig()
        elif isinstance(serving_config, dict):
            serving_config = ServingConfig.from_dict(serving_config)
        self._serving_config = serving_config
        self._factory = engine_factory
        self._guard = preemption_guard
        self._start_drivers = start
        # cell identity (docs/serving.md "Region & cells"): a named
        # fleet IS one cell of a region — its replica names and every
        # metric it emits are namespaced serving/<name>/... so N cells
        # never stomp one gauge, and the trace tracks read cell/replica
        self.name = name
        self._metric_root = (f"serving/{name}/fleet" if name
                             else "serving/fleet")
        # route-retry discipline: refusals past the first draw from the
        # request's OWN budget (route_budget_for) — shared by every tier
        # that re-routes it, never by other requests — with jittered
        # exponential backoff. Deterministic jitter: the rng is seeded
        # by the fleet's name so a DST replay draws the identical
        # backoff sequence.
        self._route_rng = random.Random(name or "fleet")
        # region wiring: _retire_hook fires once per terminal request
        # AFTER the fleet's own bookkeeping (outside the fleet lock);
        # _handoff_escalation is offered (req, export) when no replica
        # in THIS fleet can take a disaggregated hand-off — the region
        # places it on another cell (True = taken)
        self._retire_hook = on_retire
        self._handoff_escalation = on_handoff_escalation
        self._route_escalation = on_route_escalation
        # the fleet's timebase: health/autoscale intervals, respawn
        # backoff, drain budgets, request submit stamps — and every
        # replica it spawns inherits it (docs/dst.md)
        self._clock = clock if clock is not None else get_clock()
        # locksan seam: plain RLock in production, order-recording
        # wrapper under tests/DST (docs/dst.md)
        self._lock = named_rlock("ServingFleet._lock")
        self._replicas: Dict[str, Replica] = {}
        self._requests: Dict[int, Tuple[Request, str]] = {}  # uid -> (req, replica)
        self._name_counter = itertools.count()
        self._accepting = True
        self._stop_evt = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        self._last_autoscale = 0.0
        self._chaos_fired = False
        # sliding in-SLA window feeding the autoscaler (True/False per
        # SLO-carrying terminal request; cancels and SLO-less skipped)
        self._sla_window = collections.deque(maxlen=config.sla_window)
        # fleet-tier digest source (telemetry/digest.py): per-tenant /
        # per-version SLO verdicts recorded at retire time, published as
        # deltas up the cell→region rollup alongside the replica sketches
        from ..telemetry.digest import DigestSource

        self.telemetry_source = DigestSource(
            f"{name}/fleet" if name else "fleet")
        # versioned serving (docs/serving.md "Rollout, canary, and
        # migration"): _fleet_version is what NEW replicas (spawn,
        # respawn, migration replacement) serve; _canary is the active
        # (version, traffic_fraction) canary split or None; per-version
        # in-SLA windows feed the rollout controller's canary-vs-stable
        # regression check
        self._fleet_version = int(
            getattr(serving_config, "model_version", 0) or 0)
        self._canary: Optional[Tuple[int, float]] = None
        self._version_sla: Dict[int, collections.deque] = {}
        self._shed_backlog: List[Request] = []   # fleet-rejected, span due
        # gray-failure resilience plane (serving/health.py;
        # docs/fault_tolerance.md "Gray failures"): per-replica
        # continuous health scores with quarantine/probation, routing
        # circuit breakers, and the hedged-dispatch ledger (BOTH legs'
        # uids map to their shared HedgePair gate). All three are
        # monitor-driven and fleet-lock-protected; dead replicas keep
        # their entries so transition history survives for the DST
        # no-flap / convergence auditors.
        self._health: Dict[str, ReplicaHealth] = {}
        self._breakers: Dict[str, CircuitBreaker] = {}
        self._hedges: Dict[int, HedgePair] = {}
        self._hedge_done: List[HedgePair] = []
        self._hedged_total = 0
        # respawn backoff (ElasticAgent contract: exponential + healthy
        # reset; here per-fleet since replicas are interchangeable)
        self._respawn_after = 0.0
        self._respawn_delay = 0.5
        if router is not None:
            self.router = router
        else:
            self.router = make_router(
                config.router, block_size=self._probe_block_size(),
                vnodes=config.affinity_vnodes,
                spill_load=config.affinity_spill_load)
        # global KV tier (docs/serving.md "Global KV tier"): one prefix
        # directory (+ optional fleet-wide host cold tier) shared by
        # every replica; built BEFORE the spawn loop so replicas wire
        # their eviction/spill hooks at construction. With the tier on,
        # an affinity router is upgraded in place to the residency-aware
        # subclass — same ring, same spill valve, directory consulted
        # first — and an explicitly "residency"-configured (or injected
        # residency-aware) router just gets the directory attached.
        self.kv_tier = None
        kv_cfg = getattr(serving_config, "kv_tier", None)
        if kv_cfg is not None and kv_cfg.enabled:
            from .kvtier import KVTier

            self.kv_tier = KVTier(kv_cfg)
            if isinstance(self.router, ResidencyAwareRouter):
                self.router.set_directory(self.kv_tier.directory,
                                          self._clock.now)
            elif isinstance(self.router, PrefixAffinityRouter):
                self.router = ResidencyAwareRouter(
                    block_size=self.router.block_size,
                    vnodes=self.router.vnodes,
                    spill_load=self.router.spill_load,
                    directory=self.kv_tier.directory,
                    now_fn=self._clock.now)
        if config.disaggregated:
            for _ in range(config.prefill_replicas):
                self._spawn(role="prefill")
            for _ in range(config.replicas):
                self._spawn(role="decode")
        else:
            for _ in range(config.replicas):
                self._spawn(role="unified")
        log_dist(f"ServingFleet: {len(self._replicas)} replicas "
                 f"router={self.router.name} "
                 f"disaggregated={config.disaggregated} "
                 f"autoscale={config.autoscale}")
        if start:
            self._monitor = threading.Thread(target=self._monitor_loop,
                                             daemon=True,
                                             name="fleet-monitor")
            self._monitor.start()

    def _probe_block_size(self) -> int:
        # the affinity key must match the engines' prefix-cache unit; all
        # replicas share one config, so any instance answers. No replica
        # exists yet at router-construction time, so build one eagerly
        # only when the router actually needs the block size.
        if self.config.router not in ("prefix_affinity", "residency"):
            return 16
        eng = self._factory()
        with self._lock:
            self._pending_engine = eng
        return eng.config.kv_block_size

    # -- telemetry -------------------------------------------------------
    @property
    def _telemetry(self):
        from ..telemetry import get_telemetry

        return get_telemetry()

    def _count(self, name: str, n: float = 1.0) -> None:
        self._telemetry.registry.counter(f"{self._metric_root}/{name}").inc(n)

    def _update_gauges(self) -> None:
        t = self._telemetry
        if not t.enabled:
            return
        with self._lock:
            healthy = [r for r in self._replicas.values()
                       if r.state == ReplicaState.HEALTHY]
            depth = sum(r.serving.queue_depth for r in healthy)
        t.registry.gauge(f"{self._metric_root}/replicas").set(len(healthy))
        t.registry.gauge(f"{self._metric_root}/queue_depth").set(depth)

    # -- replica lifecycle ----------------------------------------------
    def _spawn(self, role: str = "unified") -> Replica:
        """Build one replica (engine via the factory + a namespaced
        ServingEngine) and register it with the router."""
        with self._lock:
            # the probe engine hand-off is shared between __init__ and
            # the monitor thread's respawn path — take-and-clear must be
            # atomic (dsrace finding, PR 15); the factory call itself
            # stays outside the lock (it builds a whole engine)
            engine = getattr(self, "_pending_engine", None)
            self._pending_engine = None
            fleet_version = self._fleet_version
        if engine is None:
            engine = self._factory()
        name = f"replica-{next(self._name_counter)}"
        if self.name:
            # cell-namespaced replica id: metrics land under
            # serving/<cell>/replica-N/... and trace tracks read the
            # same path, so a region's timeline groups by failure domain
            name = f"{self.name}/{name}"
        serving = ServingEngine(
            engine, self._serving_config,
            preemption_guard=self._guard,
            start=self._start_drivers,
            replica_id=name,
            on_handoff=(self._on_handoff if role == "prefill" else None),
            on_retire=self._on_retire,
            clock=self._clock)
        # new capacity serves the fleet's CURRENT version: a mid-rollout
        # respawn or migration replacement must not resurrect the config
        # default and silently widen (or shrink) the canary
        serving.model_version = fleet_version
        if self.kv_tier is not None:
            serving.enable_kv_tier(self.kv_tier, name)
        rep = Replica(name, engine, serving, role=role)
        with self._lock:
            self._replicas[name] = rep
            # the routing ring hashes over the replicas that PREFILL —
            # that's where prompt KV is computed and where the prefix
            # cache pays off. Disaggregated: the prefill pool; unified:
            # everyone. Decode replicas never own a ring segment (their
            # placement is least-loaded at hand-off time: the pages are
            # new to all of them).
            prefills = (role == "prefill" if self.config.disaggregated
                        else role == "unified")
            if prefills:
                self.router.on_join(name)
        self._update_gauges()
        return rep

    def _view(self, role: Optional[str] = None, live: bool = False,
              refused=(), version: Optional[int] = None) -> Dict[str, int]:
        """name -> load routing view. ``live=False``: replicas accepting
        NEW work (health-checked admission view). ``live=True``: anything
        not DEAD — the continuation view (draining replicas finish
        admitted work, they just take no new admissions). ``role``
        filters; None = any serving (non-prefill) role. ``refused`` names
        are excluded (stop-race retry loops). ``version`` restricts to
        replicas serving exactly that model version — the canary split
        and the version-affine continuation path (docs/serving.md
        "Rollout, canary, and migration").

        The gray-failure plane filters HERE, on the NEW-work view only,
        which is what both routers walk — so quarantine and open
        breakers are consulted ahead of the ring walk without the
        router ever knowing they exist. Continuations (``live=True``)
        still reach a quarantined replica: it is degraded, not dead,
        and moving admitted streams would turn a p99 problem into
        recompute load."""
        gray = not live and (self.config.quarantine or self.config.breakers)
        now = self._clock.now() if gray else 0.0
        out = {}
        for r in self._replicas.values():
            if r.name in refused:
                continue
            if (r.state == ReplicaState.DEAD) if live else not r.accepting:
                continue
            if role is not None and r.role != role:
                continue
            if role is None and r.role == "prefill":
                continue
            if version is not None and r.version != version:
                continue
            if gray and not self._gray_admits_locked(r.name, now):
                continue
            out[r.name] = r.load
        return out

    def _gray_admits_locked(self, name: str, now: float) -> bool:
        """NEW-work eligibility per the gray plane (fleet lock held):
        quarantined replicas are drained out of the view; an open
        breaker excludes until its cooldown elapses, then admits the
        single deterministic half-open probe."""
        if self.config.quarantine:
            h = self._health.get(name)
            if h is not None and not h.routable:
                return False
        if self.config.breakers:
            b = self._breakers.get(name)
            if b is not None and not b.admits(now):
                return False
        return True

    # -- versioned serving (docs/serving.md "Rollout, canary, migration") -
    def set_canary(self, version: int, fraction: float) -> None:
        """Open a canary split: ``fraction`` of NEW traffic routes to
        replicas serving ``version``, the rest to the stable version.
        The slice is tenant-sticky (hash of the tenant key, not a coin
        flip per request), so one tenant sees ONE version for the whole
        rollout."""
        with self._lock:
            self._canary = (int(version), max(0.0, min(1.0, fraction)))

    def clear_canary(self) -> None:
        with self._lock:
            self._canary = None

    def set_fleet_version(self, version: int) -> None:
        """Move the version NEW capacity serves (promotion / rollback).
        Existing replicas are untouched — the rollout controller flips
        them one by one through drain + ``hot_swap``."""
        with self._lock:
            self._fleet_version = int(version)

    @property
    def fleet_version(self) -> int:
        with self._lock:
            return self._fleet_version

    def version_counts(self) -> Dict[int, int]:
        """model version -> live (non-DEAD) replica count — the rollout
        controller's progress view."""
        with self._lock:
            out: Dict[int, int] = {}
            for r in self._replicas.values():
                if r.state != ReplicaState.DEAD:
                    out[r.version] = out.get(r.version, 0) + 1
            return out

    def version_sla(self, version: int) -> Tuple[int, Optional[float]]:
        """(samples, in-SLA ratio) for SLO-carrying requests served by
        ``version`` — the canary regression check compares this between
        canary and stable."""
        with self._lock:
            win = self._version_sla.get(int(version))
            if not win:
                return 0, None
            return len(win), sum(win) / len(win)

    def _canary_slice(self, req: Request) -> bool:
        """Whether ``req`` falls in the canary traffic slice.
        Tenant-sticky: keyed on ``req.tenant`` (falling back to the
        stable ``client_request_id``) through the same process-stable
        hash the affinity ring uses, so the split is deterministic
        across replays and restarts."""
        canary = self._canary
        if canary is None:
            return False
        key = req.tenant if req.tenant is not None else req.client_request_id
        return (_hash64(f"canary:{key}") % 1000) < canary[1] * 1000.0

    def _versioned_view(self, role, live, refused, hard, soft,
                        req: Optional[Request] = None) -> Dict[str, int]:
        """Version-constrained routing view (fleet lock held). A HARD
        version (continuation affinity) never falls back — serving the
        stream from another version is the one thing routing must never
        do; a SOFT one (canary preference) degrades to the
        unconstrained view when the preferred version has no accepting
        capacity (canary still warming, stable side mid-flip). A spill
        is stamped on the request: the DST per-tenant monotonicity
        auditor exempts availability-over-affinity placements."""
        want = hard if hard is not None else soft
        view = self._view(role, live=live, refused=refused, version=want)
        if not view and want is not None and hard is None:
            view = self._view(role, live=live, refused=refused)
            if view:
                self._count("canary_spills")
                if req is not None:
                    req._canary_spilled = True
        return view

    # -- submission ------------------------------------------------------
    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               priority: int = 0,
               deadline_s: Optional[float] = None,
               ttft_deadline_s: Optional[float] = None,
               client_request_id: Optional[str] = None,
               tenant: Optional[str] = None,
               on_token=None) -> Request:
        """Route a request to a replica. Same contract as
        ``ServingEngine.submit``: returns immediately, possibly already
        REJECTED (no healthy replica, or the target's backpressure)."""
        req = Request(
            prompt=list(prompt),
            max_new_tokens=(max_new_tokens if max_new_tokens is not None
                            else self._serving_config.default_max_new_tokens),
            eos_token_id=eos_token_id, priority=priority,
            deadline_s=deadline_s, ttft_deadline_s=ttft_deadline_s,
            client_request_id=client_request_id, tenant=tenant,
            on_token=on_token)
        # adopt the fleet's clock before stamping (same timebase rule as
        # ServingEngine.submit_request: injected clock != global clock
        # must not split a request's lifecycle across two timebases)
        req._clock = self._clock
        req.t_submit = self._clock.now()
        # tracing: the root opens HERE, before routing, so the router
        # decision is the tree's first child even for fleet-level sheds
        tracer = get_tracer()
        if tracer.enabled:
            req._trace_root = tracer.new_trace(
                "request", prompt_tokens=len(req.prompt),
                priority=req.priority)
        self._route(req)
        self._flush_shed()
        return req

    def route_request(self, req: Request, requeue: bool = False,
                      shed: bool = True) -> bool:
        """Public routing entry for an EXISTING request — the region's
        cell tier hands pre-built requests here after its own cell pick
        (two-tier routing: cell ring, then this fleet's router). With
        ``shed=False`` a placement failure returns False with the
        request untouched (still QUEUED) so the caller can try another
        cell, instead of terminally rejecting it here."""
        return self._route(req, requeue=requeue, shed=shed)

    def _route(self, req: Request, requeue: bool = False,
               shed: bool = True, refused=()) -> bool:
        """Pick a replica and enqueue. ``requeue`` marks the continuation
        of an already-admitted request (fail-over, hand-off fallback): it
        bypasses the fleet and replica admission gates — a draining fleet
        must serve out admitted work — and may land on DRAINING (never
        DEAD) replicas. A pick whose driver stopped between the view
        snapshot and the enqueue refuses non-terminally; the loop places
        the request elsewhere — but NOT for free: every retry past the
        first pick draws from the request's own :class:`RetryBudget`
        (:func:`route_budget_for` — shared with the region tier's cell
        loop) with jittered exponential backoff between attempts, so a
        refusing (stopping, partitioned) target is given up on
        explicitly instead of hammered in a tight loop. ``shed=False``:
        failures return False with the request untouched (region
        multi-cell retry)."""
        tracer = get_tracer()
        if requeue:
            request_event(req, "reroute")
        refused = set(refused)   # hedge shadows pre-refuse the primary's
        backoff = self.config.route_backoff_s   # replica (failure domain)
        while True:
            # the router decision is a span of its own on the request's
            # tree: replica pick + (for the affinity ring) hit/miss/spill
            # verdict, one span per routing attempt
            route_span = tracer.begin_span(
                "route", getattr(req, "_trace_root", None),
                requeue=bool(requeue), attempt=len(refused))
            fail: Optional[str] = None
            name = ""
            with self._lock:
                if not self._accepting and not requeue:
                    fail = "fleet closed to new requests"
                else:
                    # version constraints (docs/serving.md "Rollout,
                    # canary, and migration"): a continuation with
                    # tokens out is HARD-bound to the version that
                    # emitted them (a mixed-version stream is the DST
                    # two-version violation); fresh work gets a SOFT
                    # canary-vs-stable preference that degrades to any
                    # capacity rather than shedding
                    hard = (req.model_version
                            if requeue and req.tokens
                            and req.model_version is not None else None)
                    soft = None
                    if hard is None and self._canary is not None:
                        soft = (self._canary[0] if self._canary_slice(req)
                                else self._fleet_version)
                        if soft == self._canary[0]:
                            self._count("canary_assigned")
                    if self.config.disaggregated:
                        # prefill pool first — routed by the CONFIGURED
                        # router below (affinity composes with
                        # disaggregation: the ring hashes the prefill
                        # replicas, where repeat prefixes find their
                        # cached KV); the handoff hook ships the result
                        # onward
                        view = self._versioned_view(
                            "prefill", requeue, refused, hard, soft, req)
                        if not view:
                            # degrade: unified path on whatever can serve
                            view = self._versioned_view(
                                None, requeue, refused, hard, soft, req)
                            req._handoff_requested = False
                        else:
                            req._handoff_requested = True
                    else:
                        view = self._versioned_view(
                            None, requeue, refused, hard, soft, req)
                    if not view:
                        fail = ("no healthy replica" if hard is None else
                                f"no live replica serving version {hard}")
                    else:
                        try:
                            name = self.router.route(view, req.prompt)
                        except NoHealthyReplica:
                            fail = "no healthy replica"
                if fail is None:
                    if isinstance(self.router, PrefixAffinityRouter):
                        self._count("affinity_hits"
                                    if self.router.last_was_primary
                                    else "affinity_misses")
                    if isinstance(self.router, ResidencyAwareRouter) \
                            and self.router.last_outcome is not None:
                        # per-outcome routing ledger (docs/serving.md
                        # "Global KV tier" fallback matrix): registry
                        # counters are the operator surface, the digest
                        # copy rides the fleet→cell→region rollup so the
                        # region can report global-vs-local hit rates
                        outcome = {"residency": "residency_hit",
                                   "affinity": "affinity_hit",
                                   "directory_stale": "directory_stale"}[
                                       self.router.last_outcome]
                        t = self._telemetry
                        if t.enabled:
                            t.registry.counter(
                                f"serving/route/{outcome}").inc()
                        self.telemetry_source.count(f"route/{outcome}")
                    # router verdict captured under the lock (router
                    # state mutates per route()); the span finishes only
                    # after the enqueue, so a refused pick is marked as
                    # such and the trace shows which replica ACCEPTED
                    route_info = self.router.route_info()
                    self._requests[req.uid] = (req, name)
                    replica = self._replicas[name]
                    if self.config.breakers:
                        b = self._breakers.get(name)
                        if b is not None:
                            # no-op unless half-open: this request IS
                            # the breaker's single deterministic probe
                            b.claim_probe()
            if fail is not None:
                # failure handling OUTSIDE the fleet lock: the requeue
                # escalation hook re-routes through the REGION (its lock
                # sits ABOVE ours in the documented order)
                tracer.finish_span(route_span, error=fail)
                return self._shed_or_escalate(req, requeue, shed, fail)
            accepted = replica.serving.submit_request(
                req, requeue=requeue) is not None
            tracer.finish_span(route_span, replica=name,
                               accepted=accepted, **route_info)
            if accepted:
                self._count("routed")
                if self.kv_tier is not None:
                    self._maybe_adopt_prefix(req, name)
                return True
            refused.add(name)      # stopped mid-race: try the next one
            self._breaker_event(name, ok=False)
            with self._lock:
                ent = self._requests.get(req.uid)
                if ent is not None and ent[1] == name:
                    del self._requests[req.uid]
            if not route_budget_for(
                    req, self.config.route_retry_budget).take("fleet_route"):
                request_event(req, "route_budget_exhausted")
                logger.warning(
                    f"ServingFleet{f'[{self.name}]' if self.name else ''}: "
                    f"route retry budget exhausted for request {req.uid}")
                if shed:
                    self._reject(req, "route retry budget exhausted")
                return False
            self._count("route_retries")
            d = backoff
            if d > 0:
                d *= 1.0 + self._route_rng.uniform(
                    0.0, self.config.route_backoff_jitter)
                self._clock.sleep(d)
            backoff = min(backoff * 2.0, 1.0)

    def _shed_or_escalate(self, req: Request, requeue: bool, shed: bool,
                          reason: str) -> bool:
        """A placement failure's endgame. ``shed=False``: hand the
        untouched request back to the caller (the region's multi-cell
        loop). Continuations (``requeue``) of a region-managed fleet
        first get offered one tier up — a cell with no replica left must
        not shed work another cell could finish — and only then retire
        with a REJECTED span (explicit, never silent). Runs WITHOUT the
        fleet lock: the escalation re-enters routing through the region,
        whose lock sits above ours."""
        if not shed:
            return False
        if requeue and self._route_escalation is not None:
            # ownership leaves this fleet: drop our table row BEFORE the
            # hand-over. The region may place the request on another
            # cell, whose retire hook never reaches this table — a row
            # left behind would leak for the fleet's lifetime and
            # resolve cancels to a replica that no longer owns the work.
            # (If the region routes it back here, placement writes a
            # fresh row.)
            with self._lock:
                self._requests.pop(req.uid, None)
            try:
                if self._route_escalation(req):
                    self._count("route_escalations")
                    return True
            except Exception:  # dslint: disable=exception-discipline -- escalation isolation: a region-layer bug must fall back to the local shed path, not strand an admitted request
                logger.exception(
                    f"ServingFleet: route escalation failed for request "
                    f"{req.uid}")
        self._reject(req, reason)
        return False

    # -- global KV tier (docs/serving.md "Global KV tier") ---------------
    def _maybe_adopt_prefix(self, req: Request, target: str) -> None:
        """Best-effort cross-replica prefix prefetch, fired AFTER the
        request was accepted (never on its critical path): when the
        directory says a DIFFERENT healthy replica holds the prompt's
        full-block prefix, pen a prefix export on that donor; its driver
        gathers the quantized pages outside its lock and the on_ready
        callback pens the import on the target's driver. Every leg is
        droppable — a dead donor, refused pen, failed gather, corrupt
        wire or full pool all end in the target prefilling locally.
        Runs OUTSIDE the fleet lock (takes it briefly for the replica
        lookup); the donor's driver later runs on_ready, which only
        touches the target's own pen lock."""
        tier = self.kv_tier
        if tier is None or not tier.config.adoption:
            return
        router = self.router
        if not isinstance(router, ResidencyAwareRouter):
            return
        if router.last_outcome == "residency":
            return                 # the target already holds the prefix
        key = prefix_key(req.prompt, router.block_size)
        if len(key) < router.block_size:
            return                 # nothing a prefix cache could hold
        fresh, _ = tier.directory.holders(_hash64(",".join(map(str, key))),
                                          self._clock.now())
        donor_serving = target_serving = None
        with self._lock:
            tgt = self._replicas.get(target)
            if tgt is not None and tgt.state != ReplicaState.DEAD:
                target_serving = tgt.serving
            for m in fresh:
                if m == target:
                    continue
                rep = self._replicas.get(m)
                if rep is not None and rep.state == ReplicaState.HEALTHY:
                    donor_serving = rep.serving
                    break
        if donor_serving is None or target_serving is None:
            return

        def _on_ready(export, _t=target_serving):
            if export is None:
                return             # donor evicted it meanwhile: plain miss
            _t.adopt_prefix(export)

        if donor_serving.request_prefix_export(list(key), _on_ready):
            self._count("adopt_prefetches")
            self.telemetry_source.count("kvtier/adopt_requested")

    def _kvtier_drop(self, name: str) -> None:
        """Directory scrub at the replica-death/retire boundary: the
        member's entries must never outlive its pages (DST invariant
        #17). Idempotent; the directory lock is a leaf, so this is legal
        under the fleet lock."""
        if self.kv_tier is not None:
            # call through .directory (not KVTier.drop_member): the
            # static race/lock analyzer resolves this receiver chain,
            # so the fleet->directory leaf edge lands in the lock graph
            # the runtime sanitizer cross-validates against
            self.kv_tier.directory.drop_member(name)

    def stream(self, prompt: Sequence[int], **kwargs):
        """Generator yielding tokens as they are emitted (see
        ``ServingEngine.stream``)."""
        return stream_tokens(self, prompt, **kwargs)

    def cancel(self, req) -> bool:
        """Cancel by Request or uid, wherever the request currently
        lives. A request in flight between replicas (handoff/failover)
        carries the flag with it and dies at its next boundary."""
        with self._lock:
            if not isinstance(req, Request):
                ent = self._requests.get(int(req))
                if ent is None:
                    return False
                req = ent[0]
            if req.is_terminal:
                return False
            req._cancel_requested = True
            ent = self._requests.get(req.uid)
            replica = self._replicas.get(ent[1]) if ent is not None else None
        if replica is not None:
            replica.serving.cancel(req)
        return True

    # -- shutdown --------------------------------------------------------
    def drain(self, timeout: Optional[float] = None,
              reject_queued: bool = False) -> bool:
        """Stop admission fleet-wide and serve out every backlog. Prefill
        replicas drain first so their handoffs land before the decode
        replicas are judged empty."""
        with self._lock:
            self._accepting = False
            replicas = list(self._replicas.values())
        for r in replicas:
            if r.state == ReplicaState.HEALTHY:
                r.serving.stop_admission()
        budget = (timeout if timeout is not None
                  else self._serving_config.drain_timeout_s)
        deadline = self._clock.deadline(budget)
        ordered = ([r for r in replicas if r.role == "prefill"]
                   + [r for r in replicas if r.role != "prefill"])
        ok = True
        for r in ordered:
            if r.state == ReplicaState.DEAD:
                continue
            left = max(0.0, deadline - self._clock.now())
            ok = r.serving.drain(timeout=left, reject_queued=reject_queued) \
                and ok
        return ok

    def close(self, timeout: Optional[float] = None) -> None:
        """Graceful shutdown: drain, then close every replica and stop
        the monitor."""
        self.drain(timeout=timeout)
        self._stop_evt.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
            self._monitor = None
        with self._lock:
            replicas = list(self._replicas.values())
        for r in replicas:
            if r.state != ReplicaState.DEAD:
                r.serving.close(timeout=timeout)
        self._flush_shed()
        self._update_gauges()

    def __enter__(self) -> "ServingFleet":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- introspection ---------------------------------------------------
    @property
    def replicas(self) -> List[Replica]:
        with self._lock:
            return list(self._replicas.values())

    @property
    def healthy_replicas(self) -> List[Replica]:
        with self._lock:
            return [r for r in self._replicas.values()
                    if r.state == ReplicaState.HEALTHY]

    @property
    def queue_depth(self) -> int:
        with self._lock:
            return sum(r.serving.queue_depth for r in self._replicas.values()
                       if r.state != ReplicaState.DEAD)

    @property
    def live_requests(self) -> int:
        with self._lock:
            return sum(r.serving.live_requests
                       for r in self._replicas.values()
                       if r.state != ReplicaState.DEAD)

    def block_leaks(self) -> List[str]:
        """Fleet-wide KV leak audit: the union of every replica's
        block-balance problems, each prefixed with its replica name
        (empty list = zero leaks everywhere, dead replicas included —
        evacuation discards their sequences, so their allocators must
        balance too). Valid when idle; mid-tick reads race drivers."""
        from ..inference.ragged import block_balance_report

        problems: List[str] = []
        for r in self.replicas:
            for p in block_balance_report(r.engine)["problems"]:
                problems.append(f"{r.name}: {p}")
        return problems

    def digest_fields(self) -> Dict[str, Any]:
        """One summarizing pass over this fleet for the cell digest
        (docs/serving.md "Region & cells"): every replica is visited
        ONCE, here, on the publish cadence — the region's per-route path
        reads the published digest and never scans replicas."""
        with self._lock:
            replicas = list(self._replicas.values())
            accepting = self._accepting
            quarantined = sum(
                1 for r in replicas
                if r.state == ReplicaState.HEALTHY
                and (h := self._health.get(r.name)) is not None
                and h.state == HealthState.QUARANTINED)
        queue = live = pending = healthy = 0
        kv = 0.0
        for r in replicas:
            if r.state == ReplicaState.DEAD:
                continue
            q, lv, pw = r.serving.snapshot()
            queue += q
            live += lv
            pending += pw
            if r.state == ReplicaState.HEALTHY:
                healthy += 1
                kv = max(kv, float(r.engine.kv_demand()))
        return {"queue_depth": queue, "live": live, "pending_work": pending,
                "healthy_replicas": healthy, "kv_demand": kv,
                "in_sla": self.in_sla_ratio(),
                "accepting": accepting and healthy > 0,
                "quarantined": quarantined}

    def in_sla_ratio(self) -> Optional[float]:
        """Fraction of recent SLO-carrying requests that met their SLO
        (None until one lands) — the autoscaler's quality signal."""
        with self._lock:
            if not self._sla_window:
                return None
            return sum(self._sla_window) / len(self._sla_window)

    def collect_telemetry_digest(self, t: float):
        """One rollup pass over this fleet (cell tier calls it on the
        monitor cadence): publish-and-merge every live replica's digest
        delta plus the fleet's own verdict source into ONE fixed-size
        digest for the region. The per-replica walk happens HERE, never
        on a region read."""
        with self._lock:
            replicas = list(self._replicas.values())
        out = self.telemetry_source.publish(t)
        for r in replicas:
            # DEAD replicas included: a replica that died after emitting
            # spans still holds unpublished deltas, and deltas already
            # observed are valid history — skipping them would undercount
            # the pooled stream
            out.merge(r.serving.digest.publish(t))
        return out

    # -- replica-driver callbacks (OUTSIDE the replica's serving lock) ---
    def _on_retire(self, req: Request) -> None:
        # hedge conservation (serving/health.py HedgePair): a terminal
        # leg decides a still-undecided race; a DECIDED loser's verdict
        # is suppressed — the SLO ledger judges the client request once,
        # on the winning leg (the loser's span was already gated at the
        # replica). Table cleanup below still runs for both legs.
        gate = getattr(req, "_hedge", None)
        if gate is not None:
            gate.settle(req.uid)
        suppressed = gate is not None and gate.is_suppressed(req.uid)
        # same verdict discipline as the request span: completions judged
        # against their deadlines, sheds with an SLO count as misses,
        # user cancels not judged
        had_slo = (req.deadline_s is not None
                   or req.ttft_deadline_s is not None)
        if suppressed:
            verdict = None
        elif req.state is RequestState.FINISHED:
            verdict = req.in_slo()
        elif had_slo and not (req.state is RequestState.CANCELLED
                              and req.error is None):
            verdict = False
        else:
            verdict = None
        with self._lock:
            ent = self._requests.pop(req.uid, None)
            if verdict is not None:
                self._sla_window.append(bool(verdict))
                self._note_version_sla(req, bool(verdict))
        if verdict is not None:
            # rollup-plane verdict (outside the fleet lock — the source
            # has its own leaf lock): per-tenant attainment and the
            # canary judge both read this via the region's SLO tracker
            self.telemetry_source.slo_verdict(req.tenant,
                                              req.model_version,
                                              bool(verdict))
            self.telemetry_source.count("slo_judged")
            if verdict:
                self.telemetry_source.count("slo_met")
        if self.config.breakers and ent is not None and not suppressed:
            # breaker evidence from real outcomes: a clean finish closes
            # (or keeps closed) the serving replica's breaker, an
            # errored death (tick-fault budget spent, injected fault)
            # counts against it. Sheds and user cancels are not the
            # replica's fault and stay neutral.
            if req.state is RequestState.FINISHED:
                self._breaker_event(ent[1], ok=True)
            elif req.state is RequestState.CANCELLED and req.error:
                self._breaker_event(ent[1], ok=False)
        if self._retire_hook is not None:
            # region bookkeeping, chained OUTSIDE the fleet lock (the
            # hook takes the Region lock; region -> cell -> fleet is the
            # documented order, so fleet-under-region would invert it)
            try:
                self._retire_hook(req)
            except Exception:  # dslint: disable=exception-discipline -- callback isolation: a region bookkeeping crash must not stop later retires on this fleet
                logger.exception(
                    f"ServingFleet: retire hook failed (request {req.uid})")

    def _note_version_sla(self, req: Request, ok: bool) -> None:
        """Fold one SLO verdict into the request's version window (fleet
        lock held) — the rollout controller's canary-vs-stable signal."""
        v = req.model_version
        if v is None:
            return
        win = self._version_sla.get(v)
        if win is None:
            win = self._version_sla[v] = collections.deque(
                maxlen=self.config.sla_window)
        win.append(bool(ok))

    def place_handoff(self, req: Request, export,
                      allow_prefill_fallback: bool = True) -> bool:
        """Place a prefilled (request, KV export) pair on a live replica
        of THIS fleet for decode — least-loaded (the pages are new to
        every decode replica, affinity buys nothing here).
        ``allow_prefill_fallback`` lets a prefill replica decode it
        itself as the last resort (clearing the flag, or its next
        first-token would hand off again in an endless loop); the
        region's escalation path disables the fallback on the FIRST
        local attempt so healthy decode capacity on another cell is
        preferred over cannibalizing the local prefill pool. Returns
        False with the request untouched when nothing qualifies — the
        cross-cell adoption path calls this on another cell's fleet, so
        refusal must stay non-terminal here."""
        # a hand-off with tokens out is HARD version-affine (same
        # contract as routing): the adopting replica must serve the
        # version that emitted them, or adopt() refuses anyway
        hard = (req.model_version if req.tokens
                and req.model_version is not None else None)
        refused: set = set()
        while True:
            with self._lock:
                view = self._view("decode", live=True, refused=refused,
                                  version=hard)
                if not view and allow_prefill_fallback:
                    view = self._view("prefill", live=True,
                                      refused=refused, version=hard)
                    req._handoff_requested = False
                if not view:
                    return False
                name = least_loaded_pick(view)
                self._requests[req.uid] = (req, name)
                replica = self._replicas[name]
            if replica.serving.adopt(req, export):
                self._count("handoffs")
                return True
            # the pick stopped between the view snapshot and adopt()
            # (scale-down reap / kill race): place it elsewhere
            refused.add(name)
            with self._lock:
                ent = self._requests.get(req.uid)
                if ent is not None and ent[1] == name:
                    del self._requests[req.uid]

    def _on_handoff(self, req: Request, export) -> None:
        """A prefill replica finished a flagged request's prompt: ship
        the KV to a decode replica. A hand-off is the CONTINUATION of an
        admitted request, so draining replicas (admission closed,
        serving out) still take it — only dead ones are excluded.
        Placement preference: the local decode pool, then (region mode)
        ESCALATION to another cell's decode pool — cross-cell KV
        adoption, partition-checked by the region — then a local
        prefill replica decoding it itself (the KV is already here),
        then a route escalation for a full re-prefill on another cell;
        only when nobody anywhere can take it is the request shed, with
        a span, never silently (degraded, never lost)."""
        if self.place_handoff(req, export,
                              allow_prefill_fallback=(
                                  self._handoff_escalation is None)):
            return
        if self._handoff_escalation is not None:
            # same table discipline as _shed_or_escalate: the region may
            # place the pair on another cell, so this fleet's row (still
            # naming the prefill replica) must go before the hand-over —
            # any placement back here writes a fresh row
            with self._lock:
                self._requests.pop(req.uid, None)
            try:
                if self._handoff_escalation(req, export):
                    return
            except Exception:  # dslint: disable=exception-discipline -- escalation isolation: a region-layer bug must degrade to the local shed path, not strand an admitted request
                logger.exception(
                    f"ServingFleet: handoff escalation failed for "
                    f"request {req.uid}")
            # the region had nowhere better either: local prefill-pool
            # decode is now the preferred fallback — the KV is already
            # here (a cross-cell re-prefill would recompute it on the
            # slow path, or ping-pong back to this very pool)
            if self.place_handoff(req, export,
                                  allow_prefill_fallback=True):
                return
        # nothing HERE can decode it: drop the export and escalate the
        # route for a full re-prefill continuation elsewhere (region
        # mode), else shed with a span — never silently
        req._handoff_requested = False
        self._shed_or_escalate(req, requeue=True, shed=True,
                               reason="no live replica for decode handoff")
        self._flush_shed()

    def _reject(self, req: Request, reason: str) -> None:
        """Fleet-level shed (no replica ever owned the request). Same
        observable contract as a replica-level reject: span emitted into
        requests.jsonl and — when the request carried an SLO — a miss in
        the autoscaler's in-SLA window (shedding is exactly the signal
        that must drive scale-up). The span write is DEFERRED to
        :meth:`_flush_shed` — most callers hold the fleet lock, and sink
        I/O under it would stall every submit/cancel/poll exactly when
        the system sheds load (same discipline as the replica span
        backlog)."""
        req.error = reason
        req.transition(RequestState.REJECTED)
        self._count("rejected")
        with self._lock:    # reentrant: most (not all) callers hold it
            self._shed_backlog.append(req)

    def _flush_shed(self) -> None:
        """Emit deferred fleet-shed spans OUTSIDE the fleet lock (the
        requests are terminal and immutable by now)."""
        from .server import emit_request_span

        if not self._shed_backlog:  # dslint: disable=races -- deliberate unlocked peek (the monitor must not take the fleet lock every poll): worst case one deferred shed span; the swap below is locked
            return
        with self._lock:
            backlog, self._shed_backlog = self._shed_backlog, []
        for req in backlog:
            emit_request_span(self._telemetry, req)
            self._on_retire(req)

    # -- health / chaos / failover --------------------------------------
    def shutdown_abrupt(self, reason: str = "cell outage") -> List[Request]:
        """Whole-fleet death — the CELL-outage shape (correlated replica
        death: the entire failure domain went dark at once). Every
        replica is flipped DEAD and killed, every non-terminal request
        harvested and returned UNROUTED (state QUEUED, engine state
        discarded — the whole cell's KV is suspect): there are no
        survivors here to fail over to, so placement is the REGION's
        job, one tier up. The monitor stops; the fleet is done."""
        with self._lock:
            self._accepting = False
            replicas = list(self._replicas.values())
            for rep in replicas:
                if rep.state != ReplicaState.DEAD:
                    rep.state = ReplicaState.DEAD
                    self.router.on_leave(rep.name)
                    self._kvtier_drop(rep.name)
        self._stop_evt.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
            self._monitor = None
        orphans: List[Request] = []
        for rep in replicas:
            rep.serving.kill()
            orphans.extend(rep.serving.evacuate())
        with self._lock:
            self._requests.clear()
        logger.warning(f"ServingFleet{f'[{self.name}]' if self.name else ''}"
                       f": abrupt shutdown ({reason}); "
                       f"{len(orphans)} requests harvested")
        self._update_gauges()
        return orphans

    def steal_queued(self, max_n: int) -> List[Request]:
        """Harvest up to ``max_n`` QUEUED requests off this fleet's most
        loaded replicas (the region's heal-time rebalance seam — see
        ``ServingEngine.steal_queued`` for the per-replica contract).
        The stolen requests stay QUEUED and must be re-routed by the
        caller."""
        out: List[Request] = []
        with self._lock:
            replicas = sorted(
                (r for r in self._replicas.values()
                 if r.state == ReplicaState.HEALTHY),
                key=lambda r: (-r.load, r.name))
        for rep in replicas:
            if len(out) >= max_n:
                break
            got = rep.serving.steal_queued(max_n - len(out))
            with self._lock:
                for req in got:
                    self._requests.pop(req.uid, None)
            out.extend(got)
        return out

    def kill_replica(self, name: str, reason: str = "killed") -> bool:
        """Abrupt replica death (tests, chaos, ops). In-flight work fails
        over to the survivors when ``config.failover`` is on."""
        with self._lock:
            rep = self._replicas.get(name)
            if rep is None or rep.state == ReplicaState.DEAD:
                return False
            rep.state = ReplicaState.DEAD
            self.router.on_leave(name)
            self._kvtier_drop(name)
        logger.warning(f"ServingFleet: replica {name} died ({reason})")
        rep.serving.kill()
        orphans = rep.serving.evacuate()
        self._failover_orphans(orphans, source=name)
        self._update_gauges()
        return True

    def migrate_replica(self, name: str,
                        reason: str = "migration") -> bool:
        """Live replica migration — evacuate + re-place UNDER traffic,
        promoted from the failure path to a first-class operation
        (docs/serving.md "Rollout, canary, and migration"). The order is
        spawn-first: a same-role, same-version replacement joins the
        router, THEN the victim stops admission, its driver is joined,
        and its work moves — decodes with complete KV over the quantized
        ``export_kv``/``adopt`` wire (no recompute), everything else
        through the normal re-route path. Unlike :meth:`kill_replica`
        the victim's engine state is trusted, so nothing re-prefills
        that doesn't have to.

        Returns False (untouched) when ``name`` is unknown or not
        HEALTHY — a migration raced by death/drain falls back to the
        failover path that is already running."""
        with self._lock:
            victim = self._replicas.get(name)
            if victim is None or victim.state != ReplicaState.HEALTHY:
                return False
            victim.state = ReplicaState.DRAINING
            self.router.on_leave(name)
            version = victim.version
            role = victim.role
        victim.serving.stop_admission()
        logger.info(f"ServingFleet{f'[{self.name}]' if self.name else ''}: "
                    f"migrating {name} ({reason})")
        # replacement first: capacity never dips below the pre-migration
        # count, and the victim's work has somewhere version-compatible
        # to land. _spawn stamps _fleet_version, so pin the victim's
        # ACTUAL version after (a canary replica migrates as a canary).
        replacement = self._spawn(role=role)
        replacement.serving.model_version = version
        with self._lock:
            victim.state = ReplicaState.DEAD
            self._kvtier_drop(name)
        victim.serving.kill()
        queued, exports = victim.serving.migrate_out()
        self._count("migrations")
        moved_kv = 0
        for req, export in exports:
            if req._cancel_requested:
                # honor the pending cancel at the boundary (same terminal
                # contract as the failover path)
                from .server import emit_request_span

                req.transition(RequestState.CANCELLED)
                self._count("cancelled")
                emit_request_span(self._telemetry, req)
                self._on_retire(req)
                continue
            request_event(req, "migrate_adopt", source=name,
                          target=replacement.name)
            with self._lock:
                self._requests[req.uid] = (req, replacement.name)
            if replacement.serving.adopt(req, export):
                moved_kv += 1
                continue
            # adopt refused (replacement raced a kill/version flip):
            # degrade to the ordinary re-route continuation — the KV is
            # recomputed, the request is never lost
            with self._lock:
                ent = self._requests.get(req.uid)
                if ent is not None and ent[1] == replacement.name:
                    del self._requests[req.uid]
            self._route(req, requeue=True)
        if moved_kv:
            self._count("migrated_kv", moved_kv)
        # queued / mid-prefill work re-routes unconditionally — a
        # migration is an OPERATION, not a death, so it must not shed
        # under failover=False the way _failover_orphans would
        for req in queued:
            if req._cancel_requested:
                from .server import emit_request_span

                req.transition(RequestState.CANCELLED)
                self._count("cancelled")
                emit_request_span(self._telemetry, req)
                self._on_retire(req)
                continue
            request_event(req, "migrate_reroute", source=name)
            self._route(req, requeue=True)
        self._flush_shed()
        self._update_gauges()
        return True

    def _failover_orphans(self, orphans: List[Request],
                          source: str) -> None:
        """Re-place (or shed, per config) requests harvested from a dead
        or force-closed replica. Runs WITHOUT the fleet lock."""
        if self.config.failover:
            if orphans:
                self._count("failovers", len(orphans))
            for req in orphans:
                request_event(req, "failover", source=source)
                if req._cancel_requested:
                    # honor the pending cancel here (its replica is gone)
                    # with the full terminal contract: span + counter,
                    # same as a replica-level retire
                    from .server import emit_request_span

                    req.transition(RequestState.CANCELLED)
                    self._count("cancelled")
                    emit_request_span(self._telemetry, req)
                    self._on_retire(req)
                    continue
                self._route(req, requeue=True)
        else:
            for req in orphans:
                self._reject(req, f"replica {source} died")
        self._flush_shed()

    def poll(self) -> None:
        """One monitor pass: driver health, injected chaos, respawn,
        autoscale-interval check. The monitor thread loops this; tests
        call it directly for determinism."""
        self._check_chaos()
        self._check_health()
        self._check_respawn()
        self._check_gray()
        self._check_hedges()
        self._resolve_hedges()
        self._publish_residency()
        if self.config.autoscale:
            from ..resilience.chaos import get_fault_injector

            now = self._clock.now()
            interval = self.config.autoscale_interval_s
            inj = get_fault_injector()
            if inj is not None:
                # injected controller lag: the decision cadence slows,
                # so demand runs ahead of capacity like it does behind a
                # real autoscaler's observe/decide/boot loop
                interval += getattr(inj, "autoscaler_lag_s", 0.0)
            with self._lock:
                # interval check-then-stamp under the lock: poll() runs
                # on the monitor thread AND via manual step() — unlocked
                # it could double-fire one interval's autoscale decision
                # (dsrace finding, PR 15)
                due = now - self._last_autoscale >= interval
                if due:
                    self._last_autoscale = now
            if due:
                self.autoscale_once()
        self._flush_shed()
        self._update_gauges()

    def _publish_residency(self) -> None:
        """Push every live replica's last residency snapshot into the
        prefix directory (docs/serving.md "Global KV tier"). Rides the
        existing monitor cadence — no extra thread, no extra wakeups —
        and stamps entries with the snapshot's CAPTURE time, so a
        replica whose driver stopped snapshotting ages past the
        staleness bound instead of looking perpetually fresh. The
        ``stale_directory`` chaos knob injects a deterministic bogus
        hash here (recorded in the injector's ground-truth ledger, so
        the DST auditor can tell an injected lie from a real leak)."""
        tier = self.kv_tier
        if tier is None:
            return
        from ..resilience.chaos import get_fault_injector

        inj = get_fault_injector()
        with self._lock:
            live = [(r.name, r.serving)
                    for r in self._replicas.values()
                    if r.state != ReplicaState.DEAD]
        for name, serving in live:
            snap = serving.residency_snapshot()
            if snap is None:
                continue
            hashes, t = snap
            if inj is not None:
                bogus = inj.on_directory_publish(name)
                if bogus is not None:
                    hashes = list(hashes) + [bogus]
            tier.directory.publish(name, hashes, t)

    def _monitor_loop(self) -> None:
        while not self._clock.wait_event(self._stop_evt,
                                         self.config.health_interval_s):
            try:
                self.poll()
            except Exception:  # dslint: disable=exception-discipline -- monitor-loop bug guard: a respawn/autoscale crash must not kill the fleet thread; typed faults are handled inside poll()
                logger.exception("ServingFleet: monitor pass crashed")

    def _check_chaos(self) -> None:
        if self._chaos_fired:
            return
        from ..resilience.chaos import get_fault_injector

        inj = get_fault_injector()
        if inj is None:
            return
        with self._lock:
            candidates = [(r.name, r.index, r.serving._tick_count)
                          for r in self._replicas.values()
                          if r.state == ReplicaState.HEALTHY]
        for name, index, ticks in candidates:
            if inj.should_kill_replica(index, ticks):
                self._chaos_fired = True
                self.kill_replica(name, reason="chaos: injected death")
                return

    def _check_health(self) -> None:
        """A replica whose driver thread died (unhandled crash, real
        process trouble) is treated exactly like injected death —
        DRAINING replicas included: their backlog still needs a driver,
        and an unnoticed death would strand it forever. A replica whose
        stuck-tick watchdog ESCALATED (N consecutive wedged polls —
        ``serving.stuck_tick_escalate_polls``) is evacuated the same
        way: its driver is alive but wedged inside a device call, which
        is worse — it still looks routable. The escalation check runs
        in manual-step mode too (the watchdog check itself is driven by
        tests there); only the thread-liveness check needs threads."""
        with self._lock:
            wedged = [r.name for r in self._replicas.values()
                      if r.state != ReplicaState.DEAD
                      and r.serving.watchdog_unhealthy]
        for name in wedged:
            self._count("watchdog_evacuations")
            self.kill_replica(name, reason="stuck-tick watchdog escalation")
        if not self._start_drivers:
            return              # manual-step mode: no threads to check
        with self._lock:
            sick = [r.name for r in self._replicas.values()
                    if r.state != ReplicaState.DEAD and not r.driver_alive]
        for name in sick:
            self.kill_replica(name, reason="driver thread dead")

    def _check_respawn(self) -> None:
        """Replace dead capacity while the healthy count sits below
        ``min_replicas`` — the fleet-local analog of ElasticAgent's
        restart loop, with the same jittered exponential backoff shape
        (deterministic here: replicas are stateless to replace)."""
        if not self.config.respawn:
            return
        with self._lock:
            # each pool is audited against its own floor: the serving
            # (non-prefill) pool against min_replicas — same denominator
            # as scale_to/autoscale, else healthy prefill replicas mask
            # dead decode capacity — and, in disaggregated mode, the
            # prefill pool against prefill_replicas (losing it silently
            # degrades every request to unified re-prefill serving)
            healthy = sum(1 for r in self._replicas.values()
                          if r.state == ReplicaState.HEALTHY
                          and r.role != "prefill")
            prefill = sum(1 for r in self._replicas.values()
                          if r.state == ReplicaState.HEALTHY
                          and r.role == "prefill")
            want_prefill = (self.config.prefill_replicas
                            if self.config.disaggregated else 0)
            if self.config.disaggregated and prefill < want_prefill:
                role, have, floor = "prefill", prefill, want_prefill
            elif healthy < self.config.min_replicas:
                role = "decode" if self.config.disaggregated else "unified"
                have, floor = healthy, self.config.min_replicas
            else:
                self._respawn_delay = 0.5
                return
            if not self._accepting:
                return
            if self._clock.now() < self._respawn_after:
                return
            self._respawn_after = self._clock.now() + self._respawn_delay
            self._respawn_delay = min(self._respawn_delay * 2.0, 30.0)
        rep = self._spawn(role=role)
        self._count("respawns")
        from ..resilience import record_restart

        record_restart()
        logger.warning(f"ServingFleet: respawned {role} capacity as "
                       f"{rep.name} ({have}/{floor} healthy)")

    # -- gray-failure plane (docs/fault_tolerance.md "Gray failures") ----
    def _gray_routable_locked(self, prefill: bool) -> int:
        """HEALTHY replicas of the given pool still in the NEW-work
        routing view per the quarantine machine (fleet lock held) — the
        capacity-floor denominator."""
        n = 0
        for r in self._replicas.values():
            if r.state != ReplicaState.HEALTHY:
                continue
            if (r.role == "prefill") != prefill:
                continue
            h = self._health.get(r.name)
            if h is None or h.routable:
                n += 1
        return n

    def _check_gray(self) -> None:
        """One gray-health monitor pass: drain each HEALTHY replica's
        distress counters into its continuous health score, advance the
        quarantine/probation machines, and enforce the capacity floor
        in BOTH directions — a quarantine that would hold the routable
        pool below ``min_replicas`` is deferred (the breach counter
        keeps accumulating; the next poll with headroom acts on it),
        and deaths that strand the pool below the floor release the
        longest-quarantined survivor back to probation."""
        cfg = self.config
        if not cfg.quarantine:
            return
        now = self._clock.now()
        entered: List[str] = []
        released: List[str] = []
        with self._lock:
            for r in list(self._replicas.values()):
                if r.state != ReplicaState.HEALTHY:
                    continue
                h = self._health.get(r.name)
                if h is None:
                    h = self._health[r.name] = ReplicaHealth(
                        r.name,
                        threshold=cfg.quarantine_threshold,
                        breach_polls=cfg.quarantine_after,
                        dwell_s=cfg.quarantine_dwell_s,
                        readmit_polls=cfg.quarantine_readmit_polls)
                floor = (cfg.prefill_replicas if r.role == "prefill"
                         else cfg.min_replicas)
                headroom = (self._gray_routable_locked(r.role == "prefill")
                            - (1 if h.routable else 0) >= floor)
                busy, distress = r.serving.gray_drain()
                if busy:
                    h.observe(distress / busy, now, can_quarantine=headroom)
                elif h.state == HealthState.ACTIVE:
                    h.idle_decay()
                else:
                    # a drained replica serves no NEW work, so idle IS
                    # its steady state: a zero-distress sample keeps
                    # the dwell clock and probation re-admission moving
                    h.observe(0.0, now, can_quarantine=headroom)
                if h.should_quarantine() and headroom:
                    h.quarantine(now)
                    entered.append(r.name)
            # the floor can break AFTER a quarantine (deaths, drains):
            # release the longest-quarantined survivors until it holds
            while self._gray_routable_locked(False) < cfg.min_replicas:
                q = [h for h in (self._health.get(r.name)
                                 for r in self._replicas.values()
                                 if r.state == ReplicaState.HEALTHY
                                 and r.role != "prefill")
                     if h is not None
                     and h.state == HealthState.QUARANTINED]
                if not q:
                    break
                q.sort(key=lambda h: (h.since, h.name))
                q[0].release(now)
                released.append(q[0].name)
        tag = f"ServingFleet{f'[{self.name}]' if self.name else ''}"
        for name in entered:
            self._count("quarantines")
            logger.warning(f"{tag}: quarantined {name} "
                           f"(gray-failure score breach)")
        for name in released:
            self._count("quarantine_floor_releases")
            logger.warning(f"{tag}: released {name} from quarantine "
                           f"(capacity floor)")

    def _breaker_event(self, name: str, ok: bool) -> None:
        """Fold one route/serve outcome into ``name``'s circuit breaker
        (no-op with breakers off, or for a replica already reaped)."""
        if not self.config.breakers:
            return
        now = self._clock.now()
        with self._lock:
            if name not in self._replicas:
                return
            b = self._breakers.get(name)
            if b is None:
                b = self._breakers[name] = CircuitBreaker(
                    name, failure_limit=self.config.breaker_failures,
                    cooldown_s=self.config.breaker_cooldown_s)
            before = b.state
            if ok:
                b.record_success(now)
            else:
                b.record_failure(now)
            opened = (b.state == BreakerState.OPEN
                      and before != BreakerState.OPEN)
        if opened:
            self._count("breaker_opens")
            logger.warning(
                f"ServingFleet{f'[{self.name}]' if self.name else ''}: "
                f"circuit breaker OPEN for {name}")

    def _check_hedges(self) -> None:
        """Hedged dispatch (docs/serving.md "Gray-failure resilience
        plane"): an interactive request (TTFT deadline) with no first
        token by ``hedge_ttft_fraction`` of its TTFT budget gets ONE
        backup leg dispatched to a second replica through the normal
        route path. The gate in serving/health.py guarantees
        conservation: first token wins, the loser's tokens never reach
        the client, its span/SLO verdict are suppressed and its KV dies
        un-published."""
        if not self.config.hedge:
            return
        now = self._clock.now()
        to_hedge: List[Tuple[Request, str]] = []
        with self._lock:
            for req, rname in list(self._requests.values()):
                if (req.ttft_deadline_s is None or req.t_submit is None
                        or req.is_terminal or req.tokens
                        or req.t_first_token is not None
                        or getattr(req, "_hedge", None) is not None):
                    continue
                if (now - req.t_submit >= req.ttft_deadline_s
                        * self.config.hedge_ttft_fraction):
                    to_hedge.append((req, rname))
        for req, rname in to_hedge:
            self._dispatch_hedge(req, rname)

    def _dispatch_hedge(self, primary: Request,
                        primary_replica: str) -> None:
        """Build and route the backup leg for ``primary``. The shadow
        is a fresh Request (own uid) sharing the client_request_id,
        prompt and deadlines; the primary's replica is pre-refused so
        the two legs never share a failure domain. Runs WITHOUT the
        fleet lock — routing takes it per attempt."""
        shadow = Request(
            prompt=list(primary.prompt),
            max_new_tokens=primary.max_new_tokens,
            eos_token_id=primary.eos_token_id,
            priority=primary.priority,
            deadline_s=primary.deadline_s,
            ttft_deadline_s=primary.ttft_deadline_s,
            client_request_id=primary.client_request_id,
            tenant=primary.tenant)
        shadow._clock = self._clock
        shadow.t_submit = primary.t_submit   # the client's clock started then
        pair = HedgePair(primary, shadow)
        inner = primary.on_token
        primary.on_token = (lambda tok, _p=pair, _u=primary.uid, _i=inner:
                            _p.deliver(_u, _i, tok))
        shadow.on_token = (lambda tok, _p=pair, _u=shadow.uid, _i=inner:
                           _p.deliver(_u, _i, tok))
        primary._hedge = pair
        shadow._hedge = pair
        if primary.tokens or primary.t_first_token is not None:
            # the primary raced the gate wiring to its first token: it
            # won outright — the gate stays (transparent to a winner),
            # no shadow is dispatched
            pair.settle(primary.uid)
            pair.resolved = True
            return
        request_event(primary, "hedge", shadow_uid=shadow.uid)
        with self._lock:
            self._hedges[primary.uid] = pair
            self._hedges[shadow.uid] = pair
            self._hedged_total += 1
        if self._route(shadow, shed=False, refused=(primary_replica,)):
            self._count("hedges")
        else:
            # nowhere to place the backup: the hedge quietly failed and
            # the primary continues as the sole (default-winning) leg;
            # no span, no verdict — the loser is suppressed by contract
            pair.settle(shadow.uid)
            pair.resolved = True
            shadow.error = "hedge shadow unplaceable"
            shadow.transition(RequestState.REJECTED)
            self._count("hedge_unplaced")

    def _resolve_hedges(self) -> None:
        """Cancel decided losers and GC both-terminal pairs. The loser
        dies with ``_discard_kv`` set: its engine state is SUSPECT (it
        lost the race for a reason) and is discarded un-published at
        the replica's cancel boundary."""
        if not self.config.hedge:
            return
        losers: List[Request] = []
        with self._lock:
            seen = set()
            for pair in self._hedges.values():
                if id(pair) in seen:
                    continue
                seen.add(id(pair))
                if pair.resolved or pair.winner_uid is None:
                    continue
                pair.resolved = True
                loser = pair.loser
                if loser is not None and not loser.is_terminal:
                    losers.append(loser)
        for req in losers:
            req._discard_kv = True
            self.cancel(req)
            self._count("hedge_losses")
        with self._lock:
            # GC the uid rows once both legs are terminal; the pair
            # object survives in _hedge_done — the DST hedge-
            # conservation auditor replays the whole ledger
            done = [uid for uid, p in self._hedges.items()
                    if p.primary.is_terminal and p.shadow.is_terminal]
            dropped = set()
            for uid in done:
                p = self._hedges.pop(uid)
                if id(p) not in dropped:
                    dropped.add(id(p))
                    self._hedge_done.append(p)

    def gray_snapshot(self) -> Dict[str, Any]:
        """Read-only view of the gray plane (health scores, breakers,
        hedge ledger) — the DST auditors' and gray-lane gates' window."""
        with self._lock:
            pairs = []
            seen = set()
            for p in list(self._hedges.values()) + self._hedge_done:
                if id(p) in seen:
                    continue
                seen.add(id(p))
                pairs.append(p.snapshot())
            return {
                "health": {n: h.snapshot()
                           for n, h in self._health.items()},
                "breakers": {n: b.snapshot()
                             for n, b in self._breakers.items()},
                "hedges": pairs,
                "hedged_total": self._hedged_total,
            }

    # -- autoscaling -----------------------------------------------------
    def _elastic_config(self):
        from ..elasticity import ServingElasticityConfig

        c = self.config
        return ServingElasticityConfig(
            min_replicas=c.min_replicas, max_replicas=c.max_replicas,
            scale_up_queue_per_replica=c.scale_up_queue_per_replica,
            scale_down_queue_per_replica=c.scale_down_queue_per_replica,
            kv_high=c.kv_high, sla_low=c.sla_low)

    def autoscale_once(self) -> int:
        """One controller decision: measure, size via the shared
        elasticity policy, apply. Returns the target count."""
        from ..elasticity import compute_serving_replicas

        with self._lock:
            scalable = [r for r in self._replicas.values()
                        if r.state != ReplicaState.DEAD
                        and r.role != "prefill"]
            healthy = [r for r in scalable
                       if r.state == ReplicaState.HEALTHY]
            queue_depth = sum(r.serving.queue_depth for r in scalable)
            # demand, not raw occupancy: cache-reclaimable pages are
            # capacity, and counting them would ratchet the fleet to
            # max_replicas after any warm-cache burst
            kv = (max(r.engine.kv_demand() for r in healthy)
                  if healthy else 0.0)
        target = compute_serving_replicas(
            max(1, len(healthy)), queue_depth=queue_depth, kv_occupancy=kv,
            in_sla_ratio=self.in_sla_ratio(), config=self._elastic_config())
        self.scale_to(target)
        return target

    def scale_to(self, n: int) -> None:
        """Grow to / shrink toward ``n`` serving (non-prefill) replicas.
        Scale-down is graceful: the least-loaded replica stops admission,
        serves out, and only then closes (finished by later polls)."""
        with self._lock:
            if not self._accepting:
                # draining/closing fleet: spawning replicas that can
                # never receive work just burns engines moments before
                # close() tears them down (the backlog reads as load
                # until it serves out)
                return
            # selection and state flip under ONE lock acquisition: a
            # stale snapshot could resurrect a replica kill_replica()
            # just flipped to DEAD
            healthy = [r for r in self._replicas.values()
                       if r.state == ReplicaState.HEALTHY
                       and r.role != "prefill"]
            delta = n - len(healthy)
            victims: List[Replica] = []
            if delta < 0:
                victims = sorted(healthy, key=lambda r: (r.load, r.name))
                victims = victims[:min(-delta, max(0, len(healthy) - 1))]
                for r in victims:
                    r.state = ReplicaState.DRAINING
                    self.router.on_leave(r.name)
        if delta > 0:
            role = "decode" if self.config.disaggregated else "unified"
            for _ in range(delta):
                self._spawn(role=role)
                self._count("scale_ups")
        for r in victims:
            r.serving.stop_admission()
            self._count("scale_downs")
        # reap drained replicas (from this call or earlier ones). DEAD is
        # flipped BEFORE close(): once close sets the replica's stop
        # event it refuses continuations, so it must already be out of
        # every requeue/handoff view (adopt()'s refusal return covers
        # the one in-flight call that raced the flip)
        with self._lock:
            drained = [r for r in self._replicas.values()
                       if r.state == ReplicaState.DRAINING and r.load == 0]
            for r in drained:
                r.state = ReplicaState.DEAD
                self._kvtier_drop(r.name)
        for r in drained:
            r.serving.close(timeout=5.0)
            # a continuation enqueued in the window between the DEAD flip
            # and close() stopping the driver would otherwise be stranded
            # in a joined-dead replica — harvest and re-place it
            stragglers = r.serving.evacuate()
            if stragglers:
                self._failover_orphans(stragglers, source=r.name)
            logger.info(f"ServingFleet: scale-down of {r.name} complete")
        self._update_gauges()

    # -- deterministic driving (tests / smoke) ---------------------------
    def step(self) -> bool:
        """Manual-mode driver: one monitor poll plus one tick per live
        replica. Returns True when any replica did work. Only meaningful
        with ``start=False`` (no competing threads)."""
        self.poll()
        did = False
        for r in self.replicas:
            if r.state == ReplicaState.DEAD:
                continue
            did = r.serving._tick() or did
        return did
