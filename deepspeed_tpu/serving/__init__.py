"""Serving layer: request lifecycle, SLO-aware continuous-batching
scheduling, and a streaming front-end over the ragged engine.

This is the FastGen/MII serving surface the reference exposes
(``mii/batching/ragged_batching.py``, the DeepSpeed-FastGen blog's
throughput-under-SLA methodology) promoted into a first-class subsystem:
:class:`Request` descriptors with a validated state machine, pluggable
admission/preemption policies (FCFS baseline + SLO-aware
earliest-deadline-first), and a :class:`ServingEngine` that owns the
background tick loop, backpressure, cancellation, graceful drain and
fault recovery. See docs/serving.md.
"""

from .request import (  # noqa: F401
    InvalidTransition,
    Request,
    RequestState,
    TERMINAL_STATES,
)
from .scheduler import (  # noqa: F401
    CapacityView,
    FCFSPolicy,
    SLOPolicy,
    SchedulerPolicy,
    make_policy,
)
from .server import ServingEngine  # noqa: F401
