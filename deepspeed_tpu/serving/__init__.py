"""Serving layer: request lifecycle, SLO-aware continuous-batching
scheduling, a streaming front-end over the ragged engine, and a
multi-replica fleet router on top.

This is the FastGen/MII serving surface the reference exposes
(``mii/batching/ragged_batching.py``, the DeepSpeed-FastGen blog's
throughput-under-SLA methodology) promoted into a first-class subsystem:
:class:`Request` descriptors with a validated state machine, pluggable
admission/preemption policies (FCFS baseline + SLO-aware
earliest-deadline-first), a :class:`ServingEngine` that owns the
background tick loop, backpressure, cancellation, graceful drain and
fault recovery — and a :class:`ServingFleet` that load-balances N engine
replicas behind the same call surface (least-loaded or
prefix-cache-affinity routing, failover via bit-exact resume,
disaggregated prefill/decode KV hand-off, telemetry-driven
autoscaling). The KV leak audit (:func:`block_balance_report` /
:func:`assert_block_balance`, re-exported from the ragged engine) is
part of the public serving contract: zero leaked pages after drain on
every replica. See docs/serving.md.
"""

from ..inference.ragged import (  # noqa: F401
    assert_block_balance,
    block_balance_report,
)
from .cell import (  # noqa: F401
    CellDigest,
    CellState,
    CellUnreachable,
    ServingCell,
    check_reachable,
)
from .fleet import Replica, ReplicaState, ServingFleet  # noqa: F401
from .kvtier import (  # noqa: F401
    ColdTier,
    CorruptExport,
    KVTier,
    PrefixDirectory,
    PrefixExport,
    prefix_hash,
)
from .region import Region  # noqa: F401
from .rollout import (  # noqa: F401
    RolloutController,
    RolloutPhase,
    TERMINAL_PHASES,
)
from .request import (  # noqa: F401
    InvalidTransition,
    Request,
    RequestState,
    TERMINAL_STATES,
)
from .router import (  # noqa: F401
    LeastLoadedRouter,
    NoHealthyReplica,
    PrefixAffinityRouter,
    ResidencyAwareRouter,
    RouterPolicy,
    make_router,
    prefix_key,
)
from .scheduler import (  # noqa: F401
    CapacityView,
    FCFSPolicy,
    SLOPolicy,
    SchedulerPolicy,
    make_policy,
)
from .server import ServingEngine, stream_tokens  # noqa: F401
