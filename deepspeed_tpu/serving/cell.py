"""A serving cell: one fleet wrapped as a region-level failure domain.

At pod scale the failure modes that matter are CORRELATED — a rack
power event or a ToR switch takes out every replica of a
:class:`~.fleet.ServingFleet` at once, and a fabric fault partitions
whole groups of cells from each other while each keeps serving locally.
The :class:`ServingCell` is the unit those failures act on: it wraps
one fleet, owns its place on the region's consistent-hash cell ring,
and summarizes its load/health into a :class:`CellDigest` the region
routes by.

The digest is **published, not scanned**: the cell walks its replicas
once per monitor poll (``publish_digest``) and stores an immutable
snapshot; the region's per-request route path does a dictionary read —
O(1) in replica count — so one process can simulate thousands of
replicas without O(N) per-route scans (ROADMAP item 3b).

Cross-cell flows (request hand-off, KV adoption, evacuation targets)
must consult the partition oracle
(:func:`~deepspeed_tpu.resilience.chaos.is_reachable`) and fail with
the typed :class:`CellUnreachable` across a severed pair — in one
process every object is trivially "reachable", so the type system is
what keeps the simulation honest about the network.

Lock order (enforced by dslint, docs/serving.md): Region -> ServingCell
-> ServingFleet -> ServingEngine. Cell state reads by the region's
route path touch only the published digest reference, never a fleet or
replica lock.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any, Dict, List, Optional

from ..resilience.chaos import is_reachable
from ..resilience.locksan import named_rlock
from .fleet import ServingFleet
from .request import Request


class CellUnreachable(RuntimeError):
    """A cross-cell operation (route, hand-off, KV adoption) crossed an
    active network partition. TYPED so recovery code can distinguish
    "the network said no" (degrade to a reachable cell, re-prefill)
    from a programming error — and so a broad ``except Exception``
    recovery block can never paper over a severed link silently."""

    def __init__(self, src: str, dst: str, op: str = "reach"):
        super().__init__(
            f"cell {dst} unreachable from {src} during {op} "
            f"(network partition)")
        self.src = src
        self.dst = dst
        self.op = op


def check_reachable(src: str, dst: str, op: str = "reach") -> None:
    """Raise :class:`CellUnreachable` when an active partition severs
    ``src`` from ``dst`` (no injector installed = network whole)."""
    if not is_reachable(src, dst):
        raise CellUnreachable(src, dst, op=op)


class CellState:
    UP = "up"
    DEAD = "dead"


@dataclass(frozen=True)
class CellDigest:
    """Immutable load/health summary of one cell, published on the
    monitor cadence. Everything the region's routing, spill, brownout
    and dead-cell detection need — and NOTHING that requires touching a
    replica at route time."""

    t: float                      # publish instant (region clock)
    queue_depth: int
    live: int
    pending_work: int
    healthy_replicas: int
    kv_demand: float
    in_sla: Optional[float]
    accepting: bool
    # gray-failure plane (docs/fault_tolerance.md "Gray failures"):
    # replicas drained out of the NEW-work view by quarantine — still
    # counted in healthy_replicas (they are alive and serving admitted
    # work), but region-level detection reads this to spot a graying
    # cell in O(cells)
    quarantined: int = 0

    @property
    def load_per_replica(self) -> float:
        """Queued work per healthy replica — the spill/brownout pressure
        unit (inf when nothing healthy: an empty cell is infinitely
        loaded for placement purposes)."""
        if self.healthy_replicas <= 0:
            return float("inf")
        return self.queue_depth / self.healthy_replicas


class ServingCell:
    """One fleet as a failure domain: digest publisher + life-cycle
    holder. The region owns construction (it wires the shared retry
    budget, retire hook and hand-off escalation into the fleet) and
    calls :meth:`publish_digest` from its monitor; everything else is a
    thin, lock-ordered pass-through to the fleet."""

    def __init__(self, name: str, fleet: ServingFleet, clock) -> None:
        self.name = name
        self.fleet = fleet
        self.index = int(name.rsplit("-", 1)[-1]) if "-" in name else 0
        self._clock = clock
        # locksan seam: plain RLock in production, order-recording
        # wrapper under tests/DST (docs/dst.md)
        self._lock = named_rlock("ServingCell._lock")
        self._state = CellState.UP
        self._digest: Optional[CellDigest] = None

    # -- state -----------------------------------------------------------
    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    @property
    def alive(self) -> bool:
        return self.state == CellState.UP

    def mark_dead(self) -> bool:
        """Flip to DEAD (idempotent). Returns True on the transition."""
        with self._lock:
            if self._state == CellState.DEAD:
                return False
            self._state = CellState.DEAD
            # a dead cell's last digest must not keep attracting routes
            # in the window before the region's ring drops it
            self._digest = None
        return True

    # -- digest ----------------------------------------------------------
    @property
    def digest(self) -> Optional[CellDigest]:
        """The last published digest (None before the first publish or
        after death). A bare attribute read under the cell lock — the
        route path's ONLY per-cell cost."""
        with self._lock:
            return self._digest

    def publish_digest(self) -> Optional[CellDigest]:
        """Walk the fleet once and publish a fresh digest (monitor
        cadence — the one place replica scans happen)."""
        with self._lock:
            if self._state == CellState.DEAD:
                return None
        fields = self.fleet.digest_fields()
        d = CellDigest(t=self._clock.now(), **fields)
        with self._lock:
            if self._state == CellState.DEAD:   # died mid-scan
                return None
            self._digest = d
        return d

    def publish_telemetry(self, t: float):
        """Collect this cell's telemetry digest delta (replica sketches
        + fleet verdict source merged into one fixed-size
        :class:`~deepspeed_tpu.telemetry.digest.TelemetryDigest`) for
        the region's rollup — same publish-not-scan cadence as
        :meth:`publish_digest`, a separate channel so the routing digest
        stays a flat frozen row."""
        with self._lock:
            if self._state == CellState.DEAD:
                return None
        return self.fleet.collect_telemetry_digest(t)

    # -- failure / shutdown ---------------------------------------------
    def kill(self, reason: str = "cell outage") -> List[Request]:
        """Whole-cell death: every replica dies at once, every
        non-terminal request is harvested (QUEUED, engine state
        discarded — the cell's KV is suspect in toto) and returned for
        the REGION to place on reachable cells."""
        self.mark_dead()
        return self.fleet.shutdown_abrupt(reason=reason)

    def ticks(self) -> int:
        """Max engine tick count across replicas — the chaos injector's
        cell-age signal (:meth:`FaultInjector.should_kill_cell`)."""
        counts = [r.serving._tick_count for r in self.fleet.replicas]
        return max(counts) if counts else 0

    def step(self) -> bool:
        """Manual-mode drive: one fleet step (monitor poll + one tick
        per live replica)."""
        return self.fleet.step()

    # -- introspection ---------------------------------------------------
    def block_leaks(self) -> List[str]:
        return [f"{self.name}: {p}" for p in self.fleet.block_leaks()]

    def to_dict(self) -> Dict[str, Any]:
        d = self.digest
        return {"name": self.name, "state": self.state,
                "digest": None if d is None else dict(d.__dict__)}
