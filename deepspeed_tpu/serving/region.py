"""The region front-end: a fleet of fleets behind two-tier routing.

``Region`` scales the serving plane one failure domain up: N
:class:`~.cell.ServingCell` cells (each one :class:`~.fleet.ServingFleet`
— the unit a rack/pod outage kills at once) behind a single
submit/stream/cancel/drain/close surface. The design goals, in order:

* **O(1)-in-replicas routing** — a request costs one brownout check,
  one cell-ring walk over PUBLISHED :class:`~.cell.CellDigest` reads
  (never a replica scan), then the chosen cell's own router (its ring
  walk over a bounded replica set). Per-route work is independent of
  the total replica count, pinned by a test — the property that lets
  one process simulate thousands of replicas (ROADMAP item 3b).
* **Provable chaos tolerance** — the failure modes that dominate at
  region scale are first-class, typed, and DST-auditable
  (docs/dst.md): a whole-cell outage harvests every admitted request
  and re-places it on reachable cells through the bit-exact re-prefill
  resume path (the PR-6 evacuation discipline lifted one tier — the
  dead cell's KV is suspect in toto); an inter-cell partition makes
  cross-cell hand-off/KV adoption fail with the typed
  :class:`~.cell.CellUnreachable` and degrade to re-prefill on a
  reachable cell (degraded, never lost); a partitioned-but-alive cell
  keeps serving its admitted work locally and is NOT failed over — the
  region has no cross-partition fencing, so re-routing a live cell's
  requests would mint the double-ownership the DST heal-convergence
  invariant exists to catch.
* **Explicit brownout, never silent drops** — when demand exceeds
  reachable capacity the region sheds NEW work below a priority floor
  that climbs one tier per multiple of
  ``region.brownout_queue_per_replica`` (the brownout ladder), each
  shed retiring with a REJECTED span; entry/exit and every cell
  death/partition land in the flight recorder so the post-mortem
  timeline shows the trigger next to the fallout.

Route retries at BOTH tiers draw from the request's own
:class:`~deepspeed_tpu.resilience.retry.RetryBudget`
(:func:`~.fleet.route_budget_for` — one budget per request lifecycle,
shared by the fleet's replica loop and the region's cell loop) with
jittered exponential backoff, so a flapping or partitioned cell is
given up on explicitly instead of hammered forever — while a fresh
request always starts with a full budget.

Lock order (dslint-enforced, docs/serving.md): ``Region._lock`` ->
``ServingCell._lock`` -> ``ServingFleet._lock`` ->
``ServingEngine._lock``. Fleet->region callbacks (retire hook, route /
hand-off escalation) are invoked by the fleet OUTSIDE its own lock, so
taking the region lock there cannot invert the order.

Telemetry: counters/gauges under ``serving/region/...``; each cell's
fleet under ``serving/<cell>/fleet/...`` and its replicas under
``serving/<cell>/replica-N/...`` — per-cell namespacing end to end.
"""

from __future__ import annotations

import collections
import hashlib
import json
import random
import threading
from typing import Any, Dict, List, Optional, Sequence, Tuple

from ..resilience.chaos import get_fault_injector, is_reachable
from ..resilience.clock import Clock, get_clock
from ..resilience.locksan import named_rlock
from ..telemetry.digest import DigestAccumulator, DigestSource
from ..telemetry.slo import SLOObjective, TenantSLOTracker
from ..telemetry.tracing import get_tracer, request_event
from ..utils.logging import log_dist, logger
from .cell import CellDigest, CellUnreachable, ServingCell, check_reachable
from .fleet import ServingFleet, route_budget_for
from .request import Request, RequestState
from .rollout import RolloutController
from .router import ConsistentHashRing, _hash64, prefix_key
from .server import emit_request_span, stream_tokens

#: the brownout ladder's top rung: an effectively-infinite priority
#: floor (shed ALL new work) without overflowing int arithmetic when
#: reachable capacity is zero and pressure divides to infinity
FLOOR_MAX = 1 << 30


class Region:
    """Cell-based fleet-of-fleets serving front-end (docs/serving.md
    "Region & cells"). Same call surface as :class:`ServingFleet` /
    :class:`ServingEngine`, one tier up.

    ``engine_factory`` must return a fresh engine per call — it is
    handed to every cell's fleet. ``config`` is the
    :class:`~deepspeed_tpu.config.RegionConfig`; ``fleet_config`` /
    ``serving_config`` apply to every cell identically (cells are
    interchangeable failure domains). With ``start=False`` nothing
    ticks on its own: drive deterministically via :meth:`step`.
    """

    def __init__(self, engine_factory, config: Any = None,
                 fleet_config: Any = None,
                 serving_config: Any = None,
                 preemption_guard: Any = None,
                 start: bool = True,
                 clock: Optional[Clock] = None,
                 name: str = "region"):
        from ..config import FleetConfig, RegionConfig, ServingConfig

        if config is None:
            config = RegionConfig()
        elif isinstance(config, dict):
            config = RegionConfig.from_dict(config)
        self.config = config
        if fleet_config is None:
            fleet_config = FleetConfig()
        elif isinstance(fleet_config, dict):
            fleet_config = FleetConfig.from_dict(fleet_config)
        self._fleet_config = fleet_config
        if serving_config is None:
            serving_config = ServingConfig()
        elif isinstance(serving_config, dict):
            serving_config = ServingConfig.from_dict(serving_config)
        self._serving_config = serving_config
        self.name = name
        self._factory = engine_factory
        self._guard = preemption_guard
        self._start_drivers = start
        self._clock = clock if clock is not None else get_clock()
        # locksan seam: plain RLock in production, order-recording
        # wrapper under tests/DST (docs/dst.md)
        self._lock = named_rlock("Region._lock")
        self._cells: Dict[str, ServingCell] = {}
        self._ring = ConsistentHashRing(vnodes=config.cell_ring_vnodes)
        self._requests: Dict[int, Tuple[Request, str]] = {}
        self._accepting = True
        self._shed_backlog: List[Request] = []
        # region telemetry plane (telemetry/digest.py, telemetry/slo.py):
        # per-cell digest deltas are absorbed on the rollup cadence into
        # ONE accumulator + SLO tracker — the flat per-request SLA deque
        # this replaces was a region-wide scan magnet and carried no
        # tenant attribution. All rollup state is touched only by the
        # monitor/poll thread (the digest-refresh discipline).
        self._slo_objective = SLOObjective(
            target=config.slo_target,
            window_s=config.slo_window_s,
            fast_window_s=config.slo_fast_window_s,
            slow_window_s=config.slo_slow_window_s,
            fast_burn_threshold=config.slo_fast_burn,
            slow_burn_threshold=config.slo_slow_burn,
            min_samples=config.slo_min_samples)
        self._slo = TenantSLOTracker(self._slo_objective)
        self._tel_rollup = DigestAccumulator()
        self._region_tel = DigestSource("region")
        # final deltas pulled from cells at death (kill_cell), absorbed
        # by the next rollup pass on the poll thread
        self._salvaged_digests: List[Any] = []
        self._rollup_tick = 0
        self._rollup_hasher = hashlib.sha256()
        #: per-rollup work accounting, pinned by the SLO lane: digest
        #: rows absorbed in the LAST rollup pass and cumulatively —
        #: O(cells), independent of replica count
        self.rollup_work_last = 0
        self.rollup_work_total = 0
        self.rollup_count = 0
        self._stop_evt = threading.Event()
        self._monitor: Optional[threading.Thread] = None
        # route retries draw from the REQUEST's own budget
        # (route_budget_for): fleet-internal replica retries and
        # region-level cell retries share the request's pool, so a
        # partitioned cell cannot be hammered forever by EITHER tier's
        # re-route loop (satellite: resilience/retry.py wiring).
        # Deterministic jitter, same rule as the cell tier: name-seeded
        # rng so a DST replay draws the identical backoff sequence.
        self._route_rng = random.Random(f"{name}/route")
        # brownout ladder state (docs/serving.md): floor 0 = off; floor
        # f sheds NEW requests with priority < f
        self._brownout_floor = 0
        #: (t, kind, priority, floor) rows while a brownout is active —
        #: the soak's strictly-priority-ordered shedding gate reads
        #: this. Bounded: a production region under sustained overload
        #: appends one row per admit/shed for as long as a floor is
        #: up, and the audit only ever needs a recent window
        self.brownout_log: collections.deque = collections.deque(
            maxlen=4096)
        self._partition_epoch_seen = 0
        self._partition_active = False
        self._cell_chaos_fired = False
        # per-route work accounting, pinned by tests: digest lookups +
        # ring steps for the LAST route and cumulatively — must be
        # independent of replica count per request
        self.route_work_last = 0
        self.route_work_total = 0
        for i in range(config.cells):
            self._spawn_cell(f"cell-{i}")
        # the region's prefix key must match the cells' prefix-cache
        # unit (same rule as the fleet's affinity ring one tier down)
        self._block_size = 16
        first = next(iter(self._cells.values()), None)
        if first is not None and first.fleet.replicas:
            eng = first.fleet.replicas[0].engine
            self._block_size = int(getattr(eng.config, "kv_block_size", 16))
        # zero-downtime rollout controller (serving/rollout.py): owns
        # the canary/promote/rollback state machine, stepped from poll()
        self._rollout = RolloutController(self, serving_config.rollout,
                                          self._clock)
        log_dist(f"Region[{name}]: {len(self._cells)} cells x "
                 f"{fleet_config.replicas} replicas "
                 f"router={fleet_config.router} "
                 f"brownout_step={config.brownout_queue_per_replica}")
        self._refresh_digests()
        if start:
            self._monitor = threading.Thread(target=self._monitor_loop,
                                             daemon=True,
                                             name="region-monitor")
            self._monitor.start()

    def _spawn_cell(self, name: str) -> ServingCell:
        fleet = ServingFleet(
            self._factory_for(name), self._fleet_config,
            self._serving_config,
            preemption_guard=self._guard,
            start=self._start_drivers,
            clock=self._clock,
            name=name,
            on_retire=self._on_fleet_retire,
            on_handoff_escalation=(
                lambda req, export, _src=name:
                self._escalate_handoff(_src, req, export)),
            on_route_escalation=(
                lambda req, _src=name:
                self._escalate_route(_src, req)))
        cell = ServingCell(name, fleet, self._clock)
        # ring membership changes outside the lock: cells join only at
        # construction (single-threaded), and the vnode insertion loop
        # has no business running under the routing lock
        self._ring.join(name)
        with self._lock:
            self._cells[name] = cell
        return cell

    def _factory_for(self, cell_name: str):
        # indirection point: multi-host deployments bind each cell's
        # factory to its own host group; in-process every cell shares
        # one factory
        return self._factory

    # -- telemetry -------------------------------------------------------
    @property
    def _telemetry(self):
        from ..telemetry import get_telemetry

        return get_telemetry()

    def _count(self, name: str, n: float = 1.0) -> None:
        self._telemetry.registry.counter(f"serving/region/{name}").inc(n)

    def _update_gauges(self) -> None:
        t = self._telemetry
        if not t.enabled:
            return
        with self._lock:
            cells = list(self._cells.values())
            floor = self._brownout_floor
        alive = [c for c in cells if c.alive]
        reachable = [c for c in alive
                     if is_reachable(self.name, c.name)]
        depth = 0
        quarantined = 0
        for c in reachable:
            # bind once: a concurrent mark_dead() nulls c.digest
            d = c.digest
            if d is not None:
                depth += d.queue_depth
                # gray-failure detection stays O(cells): the per-cell
                # quarantine count rides the published digest, so the
                # region-wide graying signal never scans a replica
                quarantined += d.quarantined
        r = t.registry
        r.gauge("serving/region/cells").set(len(alive))
        r.gauge("serving/region/reachable_cells").set(len(reachable))
        r.gauge("serving/region/queue_depth").set(depth)
        r.gauge("serving/region/brownout_floor").set(floor)
        r.gauge("serving/region/quarantined_replicas").set(quarantined)

    # -- submission ------------------------------------------------------
    def submit(self, prompt: Sequence[int], *,
               max_new_tokens: Optional[int] = None,
               eos_token_id: Optional[int] = None,
               priority: int = 0,
               deadline_s: Optional[float] = None,
               ttft_deadline_s: Optional[float] = None,
               client_request_id: Optional[str] = None,
               tenant: Optional[str] = None,
               on_token=None) -> Request:
        """Route a request through the cell ring. Same contract as
        ``ServingFleet.submit``: returns immediately, possibly already
        REJECTED (brownout shed, no reachable cell, backpressure)."""
        req = Request(
            prompt=list(prompt),
            max_new_tokens=(max_new_tokens if max_new_tokens is not None
                            else self._serving_config.default_max_new_tokens),
            eos_token_id=eos_token_id, priority=priority,
            deadline_s=deadline_s, ttft_deadline_s=ttft_deadline_s,
            client_request_id=client_request_id, tenant=tenant,
            on_token=on_token)
        # one timebase per lifecycle (the fleet/engine rule, one tier up)
        req._clock = self._clock
        req.t_submit = self._clock.now()
        tracer = get_tracer()
        if tracer.enabled:
            req._trace_root = tracer.new_trace(
                "request", prompt_tokens=len(req.prompt),
                priority=req.priority)
        self._route_request(req)
        self._flush_shed()
        return req

    def _cell_eligible(self, name: str, refused: set,
                       counter: List[int]) -> Optional[CellDigest]:
        """Digest-only eligibility read (NO fleet/replica access): the
        entire per-cell routing cost. ``counter`` meters the work."""
        counter[0] += 1
        if name in refused:
            return None
        cell = self._cells.get(name)
        if cell is None or not cell.alive:
            return None
        if not is_reachable(self.name, name):
            return None
        d = cell.digest
        if d is None or not d.accepting or d.healthy_replicas <= 0:
            return None
        return d

    def _pick_cell(self, prompt: Sequence[int],
                   refused: set) -> Optional[str]:
        """Two-tier hash tier one: walk the cell ring from the prompt's
        prefix-key hash, judging each candidate by its PUBLISHED digest;
        then the optional spill valve (an overloaded primary cell spills
        to the least-loaded reachable one — affinity is a throughput
        optimisation, not a hostage situation, at this tier too)."""
        work = [0]
        digests: Dict[str, CellDigest] = {}

        def eligible(name: str) -> bool:
            d = self._cell_eligible(name, refused, work)
            if d is None:
                return False
            digests[name] = d
            return True

        h = _hash64(",".join(map(str, prefix_key(prompt,
                                                 self._block_size))))
        chosen = self._ring.walk(h, eligible)
        spill = self.config.cell_spill_load
        if (chosen is not None and spill > 0
                and digests[chosen].load_per_replica >= spill):
            # the spill scan reads every cell's DIGEST (O(cells),
            # replica-independent — the same accounting unit as the walk)
            for name in self._cells:
                if name not in digests:
                    d = self._cell_eligible(name, refused, work)
                    if d is not None:
                        digests[name] = d
            alt = min(digests,
                      key=lambda n: (digests[n].load_per_replica, n))
            if digests[alt].load_per_replica \
                    < digests[chosen].load_per_replica:
                chosen = alt
        # global KV tier, cell tier (docs/serving.md "Global KV tier"):
        # when the walk's choice holds no fresh residency for this
        # prefix but another eligible cell's fleet directory does,
        # prefer that cell. An O(cells) leaf-lock peek — the same
        # accounting unit as the walk and the spill scan — and purely
        # advisory: a lying directory just lands the request on a cell
        # that prefills locally.
        tiered = any(getattr(c.fleet, "kv_tier", None) is not None
                     for c in self._cells.values())
        if (tiered and chosen is not None
                and not self._cell_has_residency(chosen, h)):
            for name in sorted(self._cells):
                if name == chosen:
                    continue
                d = digests.get(name)
                if d is None:
                    d = self._cell_eligible(name, refused, work)
                    if d is None:
                        continue
                    digests[name] = d
                if self._cell_has_residency(name, h):
                    chosen = name
                    self._count("cell_residency_hits")
                    break
        self.route_work_last = work[0]
        self.route_work_total += work[0]
        return chosen

    def _cell_has_residency(self, name: str, h: int) -> bool:
        """True when ``name``'s fleet runs the global KV tier AND its
        prefix directory holds a bounded-staleness-fresh entry for the
        prompt's prefix hash (cells publish replica residency in the
        same hash space the rings walk). The directory lock is a LEAF,
        so this peek is legal under the region lock."""
        cell = self._cells.get(name)
        if cell is None:
            return False
        tier = getattr(cell.fleet, "kv_tier", None)
        if tier is None:
            return False
        return tier.directory.has_fresh(h, self._clock.now())

    def _route_request(self, req: Request, requeue: bool = False) -> bool:
        """Tier-one placement loop. New work passes the brownout gate;
        continuations (cell failover, cross-cell degrade) bypass it —
        they were already admitted. Failures ALWAYS end in a terminal
        REJECTED span (never silent); refusals retry other cells under
        the request's own budget, shared with the fleet tier's loop."""
        tracer = get_tracer()
        if requeue:
            request_event(req, "region_reroute")
        refused: set = set()
        backoff = self._fleet_config.route_backoff_s
        while True:
            span = tracer.begin_span(
                "region_route", getattr(req, "_trace_root", None),
                requeue=bool(requeue), attempt=len(refused))
            with self._lock:
                if not self._accepting and not requeue:
                    tracer.finish_span(span, error="region closed")
                    self._reject(req, "region closed to new requests")
                    return False
                floor = self._brownout_floor
                if not requeue and floor > 0 and req.priority < floor:
                    tracer.finish_span(span, error="brownout",
                                       floor=floor)
                    self._shed_brownout(req, floor)
                    return False
                name = self._pick_cell(req.prompt, refused)
                # bind the route-work meter while the lock is still
                # held: _pick_cell writes it under this lock, and the
                # unlocked read below the release raced a concurrent
                # route's write (dsrace finding, PR 15)
                work = self.route_work_last
                if name is None:
                    tracer.finish_span(span, error="no reachable cell")
                    # a transiently empty health view (every digest
                    # stale, browned out mid-heal, a spill racing a
                    # quarantine) must not reject outright while live
                    # cells exist: retry the siblings under the
                    # request's own budget with the existing jittered
                    # backoff — the sleep runs OUTSIDE the lock below.
                    # A region with no live reachable cell at all is a
                    # different animal: nothing a retry can find.
                    retryable = any(
                        c.alive and is_reachable(self.name, c.name)
                        for c in self._cells.values())
                    if not retryable:
                        self._reject(req, "no reachable cell with capacity")
                        return False
                else:
                    self._requests[req.uid] = (req, name)
                    cell = self._cells[name]
            if name is None:
                if not route_budget_for(
                        req, self._fleet_config.route_retry_budget).take(
                            "region_route"):
                    request_event(req, "route_budget_exhausted")
                    self._reject(req, "no reachable cell with capacity")
                    self._flush_shed()
                    return False
                self._count("route_retries")
                refused.clear()   # a refused cell may have healed by now
                d = backoff
                if d > 0:
                    d *= 1.0 + self._route_rng.uniform(
                        0.0, self._fleet_config.route_backoff_jitter)
                    self._clock.sleep(d)
                backoff = min(backoff * 2.0, 1.0)
                continue
            accepted = cell.fleet.route_request(req, requeue=requeue,
                                                shed=False)
            tracer.finish_span(span, cell=name, accepted=accepted,
                               work=work)
            if accepted:
                self._count("routed")
                if floor > 0 and not requeue:
                    with self._lock:
                        self.brownout_log.append(
                            {"t": self._clock.now(), "kind": "admit",
                             "priority": req.priority, "floor": floor})
                return True
            refused.add(name)
            with self._lock:
                ent = self._requests.get(req.uid)
                if ent is not None and ent[1] == name:
                    del self._requests[req.uid]
            if not route_budget_for(
                    req, self._fleet_config.route_retry_budget).take(
                        "region_route"):
                request_event(req, "route_budget_exhausted")
                logger.warning(f"Region[{self.name}]: route retry budget "
                               f"exhausted for request {req.uid}")
                self._reject(req, "route retry budget exhausted")
                return False
            self._count("route_retries")
            d = backoff
            if d > 0:
                d *= 1.0 + self._route_rng.uniform(
                    0.0, self._fleet_config.route_backoff_jitter)
                self._clock.sleep(d)
            backoff = min(backoff * 2.0, 1.0)

    # -- shedding --------------------------------------------------------
    def _shed_brownout(self, req: Request, floor: int) -> None:
        """Priority-tiered load shed (region lock held, reentrant). The
        span (emitted at the next flush, outside the lock) carries the
        brownout reason — sheds are EXPLICIT: a terminal REJECTED span
        per shed request, audited by the DST shed-span invariant."""
        self.brownout_log.append(
            {"t": self._clock.now(), "kind": "shed",
             "priority": req.priority, "floor": floor})
        self._count("brownout_sheds")
        request_event(req, "brownout_shed", floor=floor)
        self._reject(req, f"brownout: shed at priority {req.priority} "
                          f"< floor {floor}")

    def _reject(self, req: Request, reason: str) -> None:
        """Region-level shed. Same observable contract as fleet/replica
        rejects: terminal REJECTED + span in requests.jsonl + an SLA
        miss when the request carried an SLO. Span I/O deferred out of
        the lock (the fleet's backlog discipline, one tier up)."""
        req.error = reason
        req.transition(RequestState.REJECTED)
        self._count("rejected")
        with self._lock:
            self._shed_backlog.append(req)

    def _flush_shed(self) -> None:
        if not self._shed_backlog:  # dslint: disable=races -- deliberate unlocked peek (the fleet tier's backlog discipline, one tier up): worst case one deferred shed span; the swap below is locked
            return
        with self._lock:
            backlog, self._shed_backlog = self._shed_backlog, []
        for req in backlog:
            emit_request_span(self._telemetry, req, digest=self._region_tel)
            # a region-tier shed never reached a fleet, so its SLO
            # verdict enters the plane HERE (fleet-retired requests are
            # recorded by their fleet's source — never twice)
            had_slo = (req.deadline_s is not None
                       or req.ttft_deadline_s is not None)
            if had_slo and not (req.state is RequestState.CANCELLED
                                and req.error is None):
                self._region_tel.slo_verdict(req.tenant, req.model_version,
                                             False)
                self._region_tel.count("slo_judged")
            self._on_fleet_retire(req)

    # -- fleet callbacks (invoked OUTSIDE fleet locks) -------------------
    def _on_fleet_retire(self, req: Request) -> None:
        # SLO verdicts live in the rollup plane (the fleet's digest
        # source records them); the region only clears its routing entry
        with self._lock:
            self._requests.pop(req.uid, None)

    def _escalate_route(self, src_cell: str, req: Request) -> bool:
        """A cell found no replica for a CONTINUATION: place it on
        another cell (re-prefill resume — the request's engine state is
        already gone). True = the region took responsibility (placed or
        terminally shed); False = untouched."""
        self._count("route_escalations")
        request_event(req, "cross_cell_reroute", source=src_cell)
        with self._lock:
            ent = self._requests.get(req.uid)
            if ent is not None and ent[1] == src_cell:
                del self._requests[req.uid]
        self._route_request(req, requeue=True)
        self._flush_shed()
        return True     # placed or region-shed — either way, handled

    def _escalate_handoff(self, src_cell: str, req: Request,
                          export) -> bool:
        """Cross-cell KV adoption: the source cell has nobody to decode
        a prefilled hand-off. Offer the (request, KV export) pair to
        reachable cells in digest-load order; an active partition makes
        the pair's transfer fail TYPED (:class:`CellUnreachable`); when
        nobody reachable can adopt, the pair is handed BACK to the
        source fleet (False return), whose prefill replica decodes it
        itself — the KV is already there, and a region-side re-prefill
        would land back on that same live prefill pool with the
        hand-off flag re-armed, ping-ponging forever. Only when local
        decode is impossible too does the fleet escalate the route for
        a full re-prefill on a reachable cell — degraded, never
        lost."""
        with self._lock:
            cells = [c for c in self._cells.values()
                     if c.alive and c.name != src_cell]
        candidates = []
        for c in cells:
            # bind once: a concurrent mark_dead() nulls c.digest
            d = c.digest
            if d is not None and d.accepting and d.healthy_replicas > 0:
                candidates.append((c.name, d))
        candidates.sort(key=lambda nd: (nd[1].load_per_replica, nd[0]))
        for name, _d in candidates:
            try:
                # the KV pages travel cell-to-cell: BOTH the inter-cell
                # link and the region's control link must be up
                check_reachable(src_cell, name, op="kv_adoption")
                check_reachable(self.name, name, op="kv_adoption")
            except CellUnreachable as e:
                self._count("partition_blocked_handoffs")
                request_event(req, "partition_degrade", target=name,
                              op=e.op)
                continue
            # table entry BEFORE the placement: a fast replica could
            # adopt, decode and retire the request while we are still
            # here, and the retire hook must find the entry to pop —
            # registering after the fact would resurrect it as a stale
            # row (the convergence invariant's terminal-in-table case)
            with self._lock:
                self._requests[req.uid] = (req, name)
            if self._cells[name].fleet.place_handoff(req, export):
                self._count("handoff_escalations")
                request_event(req, "cross_cell_handoff",
                              source=src_cell, target=name)
                return True
            # refusal: point the row BACK at the source cell, do not
            # delete it — the pair is handed back to the source fleet on
            # the False return below, and a deleted row would strand the
            # request ownerless in the region table (version-affine
            # hand-offs made cross-cell refusal a common outcome, not a
            # scale-down race). The ent guard keeps a concurrent retire
            # from being resurrected as a stale row.
            with self._lock:
                ent = self._requests.get(req.uid)
                if ent is not None and ent[1] == name:
                    self._requests[req.uid] = (req, src_cell)
        # nobody reachable can adopt the KV: hand the pair back to the
        # source fleet (False), whose prefill replica decodes it itself
        # as the last resort — the KV is already THERE, and a re-prefill
        # from here would just land back on that same prefill pool with
        # the hand-off flag re-armed (an endless prefill->hand-off->
        # degrade cycle). The fleet escalates the route back up only
        # when local decode is impossible too.
        self._count("handoff_degrades")
        request_event(req, "handoff_degraded", source=src_cell)
        return False

    # -- streaming / cancel ----------------------------------------------
    def stream(self, prompt: Sequence[int], **kwargs):
        """Generator yielding tokens as they are emitted (see
        ``ServingEngine.stream``)."""
        return stream_tokens(self, prompt, **kwargs)

    def cancel(self, req) -> bool:
        """Cancel by Request or uid, wherever in the region it lives."""
        with self._lock:
            if not isinstance(req, Request):
                ent = self._requests.get(int(req))
                if ent is None:
                    return False
                req = ent[0]
            if req.is_terminal:
                return False
            req._cancel_requested = True
            ent = self._requests.get(req.uid)
            cell = self._cells.get(ent[1]) if ent is not None else None
        if cell is not None:
            cell.fleet.cancel(req)
        return True

    # -- monitor ---------------------------------------------------------
    def poll(self) -> None:
        """One monitor pass: injected chaos, partition-state tracking,
        digest refresh (the ONE place replicas are scanned), dead-cell
        detection, the brownout ladder. Tests call it directly; the
        monitor thread loops it."""
        self._check_chaos()
        self._check_partitions()
        self._refresh_digests()
        self._check_dead_cells()
        self._update_brownout()
        self._rollout.step()
        self._flush_shed()
        self._update_gauges()

    def _monitor_loop(self) -> None:
        while not self._clock.wait_event(self._stop_evt,
                                         self.config.health_interval_s):
            try:
                self.poll()
            except Exception:  # dslint: disable=exception-discipline -- monitor-loop bug guard: a digest/brownout crash must not kill the region thread; typed faults are handled inside poll()
                logger.exception("Region: monitor pass crashed")

    def _check_chaos(self) -> None:
        if self._cell_chaos_fired:
            return
        inj = get_fault_injector()
        if inj is None:
            return
        with self._lock:
            cells = [c for c in self._cells.values() if c.alive]
        for cell in cells:
            if inj.should_kill_cell(cell.index, cell.ticks()):
                self._cell_chaos_fired = True
                self.kill_cell(cell.name, reason="chaos: injected cell "
                                                 "outage")
                return

    def _check_partitions(self) -> None:
        """Track the injector's partition epoch; on a change, record the
        new connectivity in the flight recorder (a partition is exactly
        the event whose trigger/fallout adjacency a post-mortem needs)
        and — on heal — rebalance queued work onto rejoined capacity."""
        inj = get_fault_injector()
        epoch = 0 if inj is None else inj.partition_epoch
        with self._lock:
            # epoch compare-then-stamp under the region lock: poll()
            # runs on the monitor thread AND via manual step(), and the
            # unlocked check could double-run (or skip) one epoch's
            # heal rebalance (dsrace finding, PR 15)
            if epoch == self._partition_epoch_seen:
                return
            self._partition_epoch_seen = epoch
            active = inj is not None and inj.partitioned
            was_active = self._partition_active
            self._partition_active = active
        tracer = get_tracer()
        if active:
            unreachable = sorted(
                name for name in self._cells  # dslint: disable=races -- cells are spawned only during __init__, before the monitor thread exists; the map is append-only and never mutated after construction
                if not is_reachable(self.name, name))
            self._count("partitions_detected")
            logger.warning(f"Region: partition detected; unreachable "
                           f"cells: {unreachable or 'none (inter-cell only)'}")
            if tracer.enabled:
                tracer.flight.note("partition_detected",
                                   unreachable=",".join(unreachable))
                tracer.flight.dump("partition-detected")
        elif was_active:
            self._count("partitions_healed")
            logger.warning("Region: partition healed; rebalancing")
            if tracer.enabled:
                tracer.flight.note("partition_healed")
            self._rebalance()

    def _refresh_digests(self) -> None:
        with self._lock:
            cells = [c for c in self._cells.values() if c.alive]
        for cell in cells:
            cell.publish_digest()
        with self._lock:
            self._rollup_tick += 1
            tick = self._rollup_tick
        if tick % self.config.telemetry_rollup_every == 0:
            self._publish_rollup(cells)

    def _publish_rollup(self, cells: Optional[List[ServingCell]] = None
                        ) -> None:
        """One telemetry rollup pass (monitor cadence, every
        ``telemetry_rollup_every``-th digest refresh): pull each live
        cell's telemetry digest delta, fold it into the region
        accumulator and the SLO tracker, then evaluate burn-rate
        alerts. Work is O(cells x digest rows) — independent of replica
        count, metered by ``rollup_work_last``. Deterministic: no RNG,
        no extra clock advance, stable cell order — the per-seed digest
        stream hashes bit-identically under DST (scripts/slo_lane.py)."""
        if cells is None:
            with self._lock:
                cells = [c for c in self._cells.values() if c.alive]
        now = self._clock.now()
        digests = []
        with self._lock:
            salvaged, self._salvaged_digests = self._salvaged_digests, []
        digests.extend(d for d in salvaged if not d.is_empty())
        for cell in cells:
            d = cell.publish_telemetry(now)
            if d is not None and not d.is_empty():
                digests.append(d)
        own = self._region_tel.publish(now)
        if not own.is_empty():
            digests.append(own)
        work = 0
        for d in digests:
            work += self._tel_rollup.absorb(d)
            self._slo.record(
                now, d.tenants, d.versions,
                ok=int(d.counters.get("slo_met", 0)),
                judged=int(d.counters.get("slo_judged", 0)))
            self._rollup_hasher.update(json.dumps(
                d.to_dict(), sort_keys=True).encode("utf-8"))
        with self._lock:
            self.rollup_count += len(digests)
            self.rollup_work_last = work
            self.rollup_work_total += work
        self._emit_slo_alerts(self._slo.check_alerts(now))
        t = self._telemetry
        if t.enabled and digests:
            r = t.registry
            for tenant in self._slo.tenants():
                _, ratio = self._slo.tenant_attainment(tenant, now)
                if ratio is not None:
                    r.gauge(
                        f"serving/region/slo/{tenant}/attainment"
                    ).set(ratio)
            # global-vs-local prefix hit rate (docs/serving.md "Global
            # KV tier"): the per-outcome routing counters ride the
            # fleet→cell→region digests absorbed above, so the region
            # can report what share of prefix-routable work landed on a
            # directory-confirmed holder vs the plain affinity ring
            res = self._tel_rollup.counter("route/residency_hit")
            aff = self._tel_rollup.counter("route/affinity_hit")
            stale = self._tel_rollup.counter("route/directory_stale")
            routed = res + aff + stale
            if routed > 0:
                r.gauge("serving/region/kvtier/global_hit_share").set(
                    res / routed)
                r.gauge("serving/region/kvtier/directory_stale_share").set(
                    stale / routed)
            cold = self._tel_rollup.counter("route/cold_readmit")
            if cold > 0:
                r.gauge("serving/region/kvtier/cold_readmits").set(cold)

    def _emit_slo_alerts(self, transitions: List[Dict[str, Any]]) -> None:
        """Mirror SLO alert transitions into the registry and flight
        recorder (the alert_log itself is the tracker's)."""
        if not transitions:
            return
        tracer = get_tracer()
        for tr in transitions:
            self._count(f"slo_alerts_{tr['state']}")
            logger.warning(
                f"Region: SLO burn-rate alert {tr['state']} "
                f"(tenant={tr['tenant']} window={tr['window']} "
                f"burn={tr['burn']:.2f})")
            if tracer.enabled:
                tracer.flight.note("slo_alert", tenant=tr["tenant"],
                                   window=tr["window"], state=tr["state"],
                                   burn=tr["burn"])

    def _check_dead_cells(self) -> None:
        """A cell whose digest reports zero healthy replicas and whose
        fleet will not respawn them is DEAD — declare it (flight-dump),
        harvest, re-place. Respawning fleets are left to self-heal: a
        premature declaration would double-place work the respawned
        replicas still own."""
        with self._lock:
            cells = [c for c in self._cells.values() if c.alive]
        for cell in cells:
            d = cell.digest
            if (d is not None and d.healthy_replicas == 0
                    and not cell.fleet.config.respawn):
                self.kill_cell(cell.name,
                               reason="no healthy replicas left")

    def _update_brownout(self) -> None:
        """Walk the brownout ladder from reachable-capacity pressure
        (queued per healthy reachable replica, digests only). The floor
        climbs immediately with pressure; it descends only through the
        ``brownout_exit_ratio`` hysteresis band, so the region does not
        flap at a threshold."""
        with self._lock:
            cells = [c for c in self._cells.values()
                     if c.alive and is_reachable(self.name, c.name)]
        queue = healthy = 0
        for c in cells:
            d = c.digest
            if d is None:
                continue
            queue += d.queue_depth
            healthy += d.healthy_replicas
        if healthy <= 0:
            pressure = float("inf") if queue else 0.0
        else:
            pressure = queue / healthy
        step = self.config.brownout_queue_per_replica
        level = (FLOOR_MAX if pressure == float("inf")
                 else min(FLOOR_MAX, int(pressure // step)))
        tracer = get_tracer()
        # SLO-plane coupling (telemetry/slo.py): while any tenant's FAST
        # burn-rate alert is firing, the ladder holds its floor — queue
        # pressure easing is not recovery if a tenant is still burning
        # error budget. The alert auto-clears when its window's samples
        # age out, so a quiet region always descends eventually.
        slo_hold = self._slo.has_fast_burn()
        with self._lock:
            cur = self._brownout_floor
            if level > cur:
                new = level
            elif level < cur and not slo_hold and pressure \
                    <= self.config.brownout_exit_ratio * cur * step:
                # <= not <: at exit_ratio 0 (a value validation allows)
                # a fully drained region (pressure 0.0) must still
                # descend, or one transient burst sheds low-priority
                # work forever
                new = level
            else:
                new = cur
            self._brownout_floor = new
        if new == cur:
            return
        if cur == 0 and new > 0:
            self._count("brownout_entered")
            logger.warning(f"Region: BROWNOUT entered (floor {new}, "
                           f"pressure {pressure:.1f}/replica)")
            if tracer.enabled:
                tracer.flight.note("brownout_entered", floor=new)
                tracer.flight.dump("brownout-entered")
        elif cur > 0 and new == 0:
            self._count("brownout_exited")
            logger.warning("Region: brownout exited")
            if tracer.enabled:
                tracer.flight.note("brownout_exited")
                tracer.flight.dump("brownout-exited")
        else:
            self._count("brownout_floor_moves")
            if tracer.enabled:
                tracer.flight.note("brownout_floor", floor=new)

    # -- chaos / failover -----------------------------------------------
    def kill_cell(self, name: str, reason: str = "killed") -> bool:
        """Whole-cell outage: correlated death of every replica in one
        failure domain. The cell leaves the ring, every admitted request
        is harvested (its KV discarded as suspect) and re-placed on
        reachable cells through the bit-exact re-prefill resume path —
        under load, zero admitted requests are lost: each finishes
        elsewhere or retires with a REJECTED span."""
        with self._lock:
            cell = self._cells.get(name)
            if cell is None or not cell.alive:
                return False
            self._ring.leave(name)
        logger.warning(f"Region: cell {name} died ({reason})")
        self._count("cell_outages")
        tracer = get_tracer()
        if tracer.enabled:
            tracer.flight.note("cell_outage", cell=name, reason=reason)
            tracer.flight.dump("cell-outage")
        orphans = cell.kill(reason)
        # salvage the dead cell's last unpublished telemetry delta
        # (publish_telemetry returns None once DEAD): spans the cell
        # emitted before dying must still reach the rollup plane, or
        # region sketches would silently undercount on outage seeds
        salvage = cell.fleet.collect_telemetry_digest(self._clock.now())
        if not salvage.is_empty():
            with self._lock:
                self._salvaged_digests.append(salvage)
        self._failover_orphans(orphans, source=name)
        self._update_brownout()     # reachable capacity just shrank
        self._update_gauges()
        return True

    def _failover_orphans(self, orphans: List[Request],
                          source: str) -> None:
        if orphans:
            self._count("cell_failovers", len(orphans))
        for req in orphans:
            request_event(req, "cell_failover", source=source)
            if req._cancel_requested:
                req.transition(RequestState.CANCELLED)
                self._count("cancelled")
                emit_request_span(self._telemetry, req,
                                  digest=self._region_tel)
                self._on_fleet_retire(req)
                continue
            self._route_request(req, requeue=True)
        self._flush_shed()

    def _rebalance(self) -> None:
        """Heal-time rebalance: re-spread QUEUED (stateless) work from
        cells that bore the partition onto rejoined capacity. Only
        requests holding no engine state move — live decodes stay where
        their KV lives. Conservative by design: steal only the excess
        above the reachable mean + threshold."""
        if self.config.rebalance_threshold <= 0:
            return
        self._refresh_digests()
        with self._lock:
            alive = [c for c in self._cells.values()
                     if c.alive and is_reachable(self.name, c.name)]
        # snapshot each digest ONCE: a concurrent mark_dead() nulls it
        snap = []
        for c in alive:
            d = c.digest
            if d is not None and d.healthy_replicas > 0:
                snap.append((c, d))
        if len(snap) < 2:
            return
        total_q = sum(d.queue_depth for _c, d in snap)
        total_h = sum(d.healthy_replicas for _c, d in snap)
        mean = total_q / max(1, total_h)
        moved = 0
        cells = [c for c, _d in snap]
        loads = {c.name: d.load_per_replica for c, d in snap}
        healthy = {c.name: d.healthy_replicas for c, d in snap}
        for cell in sorted(cells, key=lambda c: (-loads[c.name], c.name)):
            excess = loads[cell.name] - (mean
                                         + self.config.rebalance_threshold)
            if excess <= 0:
                continue
            n = int(excess * healthy[cell.name])
            if n <= 0:
                continue
            stolen = cell.fleet.steal_queued(n)
            with self._lock:
                for req in stolen:
                    self._requests.pop(req.uid, None)
            for req in stolen:
                request_event(req, "rebalance", source=cell.name)
                target = min((name for name in loads
                              if name != cell.name),
                             key=lambda name: (loads[name], name))
                # entry before placement (see _escalate_handoff): the
                # retire hook must always find the row to pop
                with self._lock:
                    self._requests[req.uid] = (req, target)
                placed = self._cells[target].fleet.route_request(
                    req, requeue=True, shed=False)
                if placed:
                    loads[target] += 1.0 / max(1, healthy[target])
                    moved += 1
                else:
                    # target refused (raced a stop): normal region
                    # re-route — places or sheds with a span
                    with self._lock:
                        ent = self._requests.get(req.uid)
                        if ent is not None and ent[1] == target:
                            del self._requests[req.uid]
                    self._route_request(req, requeue=True)
        if moved:
            self._count("rebalanced", moved)
            logger.info(f"Region: rebalanced {moved} queued requests "
                        f"after heal")
        self._flush_shed()

    # -- shutdown --------------------------------------------------------
    def drain(self, timeout: Optional[float] = None,
              reject_queued: bool = False) -> bool:
        """Stop admission region-wide and serve out every cell's
        backlog (partitioned cells included: in-process their fleets
        still run — a real deployment drains them when connectivity
        returns)."""
        with self._lock:
            self._accepting = False
            cells = [c for c in self._cells.values() if c.alive]
        budget = (timeout if timeout is not None
                  else self._serving_config.drain_timeout_s)
        deadline = self._clock.deadline(budget)
        ok = True
        for cell in cells:
            left = max(0.0, deadline - self._clock.now())
            ok = cell.fleet.drain(timeout=left,
                                  reject_queued=reject_queued) and ok
        return ok

    def close(self, timeout: Optional[float] = None) -> None:
        self.drain(timeout=timeout)
        self._stop_evt.set()
        if self._monitor is not None:
            self._monitor.join(timeout=5.0)
            self._monitor = None
        with self._lock:
            cells = [c for c in self._cells.values() if c.alive]
        for cell in cells:
            cell.fleet.close(timeout=timeout)
        self._flush_shed()
        # final rollup: absorb the tail of every cell's telemetry delta
        # (requests that retired after the last monitor pass) so the
        # region accumulator's counts match the pooled request stream
        self._publish_rollup()
        self._update_gauges()

    def __enter__(self) -> "Region":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # -- deterministic driving (tests / DST) -----------------------------
    def step(self) -> bool:
        """Manual-mode driver: one region poll plus one fleet step per
        live cell (the DST drive seam — docs/dst.md). Partitioned cells
        STILL step: their compute is local, only their network is cut."""
        self.poll()
        did = False
        with self._lock:
            cells = [c for c in self._cells.values() if c.alive]
        for cell in cells:
            did = cell.step() or did
        return did

    # -- rollout (serving/rollout.py) ------------------------------------
    def start_rollout(self, version: int,
                      fraction: Optional[float] = None,
                      load_fn=None) -> bool:
        """Begin a zero-downtime rollout to ``version`` (canary slice
        ``fraction``, defaulting to the configured one; ``load_fn``
        streams the new weights inside each replica's hot_swap). The
        controller advances on the monitor cadence — poll :attr:`rollout`
        for progress."""
        return self._rollout.start(version, fraction=fraction,
                                   load_fn=load_fn)

    def migrate_replica(self, cell_name: str, replica_name: str,
                        reason: str = "migration") -> bool:
        """Live-migrate one replica under traffic (first-class
        evacuate + re-place: drain admission, spawn the replacement on
        the victim's version, hand its KV over the quantized export
        wire, re-route the rest — zero requests lost)."""
        with self._lock:
            cell = self._cells.get(cell_name)
        if cell is None or not cell.alive:
            return False
        return cell.fleet.migrate_replica(replica_name, reason=reason)

    @property
    def rollout(self) -> RolloutController:
        return self._rollout

    @property
    def version_log(self) -> List[Dict[str, Any]]:
        """The rollout controller's justification ledger (the DST
        per-tenant monotonicity invariant reads it)."""
        return self._rollout.version_log

    # -- introspection ---------------------------------------------------
    @property
    def cells(self) -> List[ServingCell]:
        with self._lock:
            return list(self._cells.values())

    @property
    def live_cells(self) -> List[ServingCell]:
        with self._lock:
            return [c for c in self._cells.values() if c.alive]

    @property
    def brownout_floor(self) -> int:
        with self._lock:
            return self._brownout_floor

    @property
    def queue_depth(self) -> int:
        return sum(c.fleet.queue_depth for c in self.live_cells)

    @property
    def live_requests(self) -> int:
        return sum(c.fleet.live_requests for c in self.live_cells)

    def in_sla_ratio(self) -> Optional[float]:
        """Region-wide windowed SLO attainment, read from the rollup
        plane (None until a judged verdict lands in the window)."""
        return self._slo.attainment(self._clock.now())

    # -- telemetry plane (docs/observability.md "Region rollups") --------
    @property
    def slo(self) -> TenantSLOTracker:
        """The region's SLO tracker: per-tenant/per-version attainment,
        burn-rate alert state and the alert transition log."""
        return self._slo

    @property
    def slo_alert_log(self):
        return self._slo.alert_log

    @property
    def rollup_hash(self) -> str:
        """Running SHA-256 over every absorbed digest's canonical form —
        the DST lane's bit-identity witness for the digest stream."""
        return self._rollup_hasher.hexdigest()

    def telemetry_snapshot(self) -> Dict[str, Any]:
        """Region-scale merged telemetry view (counters + sketch
        summaries) — answered from the digest accumulator, never from a
        replica scan."""
        return self._tel_rollup.snapshot()

    def telemetry_percentile(self, metric: str,
                             p: float) -> Optional[float]:
        """Percentile of one hot-path metric over the MERGED region
        sketch (``alpha``-bounded relative error, docs/observability.md).
        Metrics use the digest short names: ``ttft_s``,
        ``request_latency_s``, ``tokens_per_s``, ``queue_wait_s``,
        ``tick_s``."""
        return self._tel_rollup.percentile(metric, p)

    def block_leaks(self) -> List[str]:
        """Region-wide KV leak audit: the union of every cell's fleet
        audit, dead cells included (their evacuations must balance)."""
        problems: List[str] = []
        for cell in self.cells:
            problems.extend(cell.block_leaks())
        return problems
