"""Gray-failure resilience plane: continuous health scoring, straggler
quarantine with probation, per-replica routing circuit breakers, and the
hedged-dispatch pairing ledger (docs/fault_tolerance.md "Gray failures",
docs/serving.md "Gray-failure resilience plane").

Every health decision the fleet made before this module was binary —
a replica is HEALTHY or it is DEAD — yet the failure mode that
dominates tail latency at scale is the replica that is *slow, flaky,
or intermittently stalled but not dead*: it passes every liveness
check while silently eating the p99.  The pieces here are deliberately
host-only state machines driven by the fleet monitor on the injected
clock (virtual time under DST, wall time in production), so every
transition is deterministic given the observation stream:

* :class:`ReplicaHealth` — per-replica continuous score.  The fleet
  feeds one *distress ratio* sample per monitor poll (the fraction of
  the replica's busy engine ticks since the last poll that were
  degraded: injected slowdowns, stall bursts, tick faults, flaky
  KV-import fallbacks).  Samples land in a mergeable
  :class:`~deepspeed_tpu.telemetry.registry.SketchHistogram` (the same
  sketch the digest plane rolls up, so region-level detection stays
  O(cells)) and fold into an EWMA score in [0, 1].  Sustained breach
  of the outlier band drives ACTIVE -> QUARANTINED (drained out of the
  NEW-work routing view only — live streams finish in place); after a
  dwell the replica enters PROBATION where real traffic is the canary
  probe; sustained clean polls re-admit.  Every RE-quarantine doubles
  the dwell (capped at 16x base) and readmission never resets it —
  hysteresis over the full cycle, so a noisy replica cannot flap.
* :class:`CircuitBreaker` — per-replica closed -> open -> half-open on
  consecutive route/serve failures, consulted by both routers ahead of
  the ring walk (the fleet filters its routing view, which is what the
  ring walks).  Half-open admits exactly ONE deterministic probe; the
  probe's outcome closes or re-opens the breaker.
* :class:`HedgePair` — the conservation contract for hedged dispatch:
  of the two legs racing one client request, the first to deliver a
  token wins, the loser's tokens are gated (never delivered), its span
  and SLO verdict are suppressed (the ledger judges the request ONCE),
  and its suspect KV is discarded without prefix-cache publication.

Nothing here takes fleet or engine locks: the fleet mutates these
objects under its own lock and publishes read-only snapshots.
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from ..telemetry.registry import SketchHistogram

__all__ = ["ReplicaHealth", "CircuitBreaker", "HedgePair",
           "HealthState", "BreakerState"]


class HealthState:
    """Quarantine state-machine states (plain strings — they appear in
    transition logs, digests and DST traces, where enum reprs would
    churn the canonical hashes)."""

    ACTIVE = "active"
    QUARANTINED = "quarantined"
    PROBATION = "probation"


class ReplicaHealth:
    """Continuous health score + quarantine/probation state machine for
    one replica.  Driven by :meth:`observe` once per fleet monitor poll;
    all timing comes from the caller-supplied ``now`` (the injected
    clock), never the wall clock."""

    def __init__(self, name: str, *, threshold: float = 0.5,
                 breach_polls: int = 3, dwell_s: float = 8.0,
                 readmit_polls: int = 3, ewma: float = 0.45) -> None:
        self.name = name
        self.threshold = float(threshold)
        self.breach_polls = int(breach_polls)
        self.base_dwell_s = float(dwell_s)
        self.dwell_s = float(dwell_s)
        self.readmit_polls = int(readmit_polls)
        self.ewma = float(ewma)
        self.state = HealthState.ACTIVE
        self.score = 0.0
        # distress-ratio samples; mergeable up the digest plane
        self.sketch = SketchHistogram(f"serving/health/{name}/distress",
                                      alpha=0.01)
        self._breaches = 0
        self._clean = 0
        self._quarantines = 0      # lifetime quarantine entries
        self._since = 0.0          # entry time of the current state
        # (t, from, to) rows — the no-flap invariant's evidence
        self.transitions: List[Tuple[float, str, str]] = []

    # -- scoring -------------------------------------------------------
    def observe(self, distress_ratio: float, now: float,
                can_quarantine: bool = True) -> None:
        """Fold one poll's distress ratio (degraded busy ticks / busy
        ticks, in [0, 1]) into the score and advance the state machine.
        ``can_quarantine`` is the caller's capacity-floor headroom: a
        probation breach with the floor binding stays IN probation
        (clean streak reset, no readmission progress) instead of
        re-quarantining — degraded capacity beats no capacity, and a
        quarantine the floor would instantly release is pure churn."""
        r = min(1.0, max(0.0, float(distress_ratio)))
        self.sketch.observe(r)
        self.score += self.ewma * (r - self.score)
        breached = self.score > self.threshold
        if self.state == HealthState.ACTIVE:
            if breached:
                self._breaches += 1
            else:
                self._breaches = 0
        elif self.state == HealthState.QUARANTINED:
            if now - self._since >= self.dwell_s:
                self._move(HealthState.PROBATION, now)
        elif self.state == HealthState.PROBATION:
            if breached:
                if can_quarantine:
                    self._move(HealthState.QUARANTINED, now)
                else:
                    self._clean = 0
            else:
                self._clean += 1
                if self._clean >= self.readmit_polls:
                    self._move(HealthState.ACTIVE, now)

    def idle_decay(self) -> None:
        """An idle poll (no busy ticks) decays the score toward clean —
        a replica that serves nothing can produce no fresh evidence."""
        self.score *= (1.0 - self.ewma)

    # -- transitions (fleet calls these under ITS lock) ----------------
    def should_quarantine(self) -> bool:
        return (self.state == HealthState.ACTIVE
                and self._breaches >= self.breach_polls)  # dslint: disable=races -- fleet-lock-confined in production (every observe/transition runs in the fleet monitor under ServingFleet._lock); the lock-free caller dsrace traces is the single-threaded DST auditor reading between virtual-time steps

    def quarantine(self, now: float) -> None:
        self._move(HealthState.QUARANTINED, now)

    def release(self, now: float) -> None:
        """Capacity-floor release: the fleet dropped below
        ``min_replicas`` AFTER this replica was quarantined, so it goes
        back to probation early — degraded capacity beats no capacity."""
        if self.state == HealthState.QUARANTINED:
            self._move(HealthState.PROBATION, now)

    @property
    def since(self) -> float:
        """Entry time of the current state (floor release evicts the
        LONGEST-quarantined replica first — it has had the most dwell)."""
        return self._since

    def _move(self, to: str, now: float) -> None:
        # Every production mutation of this state machine runs in the
        # fleet monitor under ServingFleet._lock (see the module
        # docstring); the lock-free entry dsrace's lockset meet traces
        # is the single-threaded DST auditor / unit-test path driving
        # these objects on virtual time — hence the per-line waivers.
        if to == HealthState.QUARANTINED:
            if self._quarantines:
                # every RE-entry doubles the dwell (capped at 16x base)
                # and a clean readmission deliberately does NOT reset
                # it: hysteresis must bound churn through the FULL
                # quarantine -> probation -> active -> breach cycle,
                # not just a probation breach — a dwell reset on
                # readmit lets an intermittent straggler flap on a
                # fixed short period (the DST no-flap invariant caught
                # exactly that)
                # dslint: disable-next-line=races -- fleet-lock-confined (see _move's header comment)
                self.dwell_s = min(self.base_dwell_s * 16.0,
                                   self.dwell_s * 2.0)
            self._quarantines += 1  # dslint: disable=races -- fleet-lock-confined (see _move's header comment)
        self.transitions.append((float(now), self.state, to))  # dslint: disable=races -- fleet-lock-confined (see _move's header comment)
        self.state = to  # dslint: disable=races -- fleet-lock-confined (see _move's header comment)
        self._since = float(now)  # dslint: disable=races -- fleet-lock-confined (see _move's header comment)
        self._breaches = 0  # dslint: disable=races -- fleet-lock-confined (see _move's header comment)
        self._clean = 0  # dslint: disable=races -- fleet-lock-confined (see _move's header comment)

    @property
    def routable(self) -> bool:
        """Eligible for NEW work: ACTIVE and PROBATION route (probation
        traffic IS the canary probe); QUARANTINED is drained."""
        return self.state != HealthState.QUARANTINED

    def snapshot(self) -> Dict[str, Any]:
        return {"name": self.name, "state": self.state,
                "score": round(self.score, 6), "dwell_s": self.dwell_s,  # dslint: disable=races -- benign-stale snapshot read: gray_snapshot() holds the fleet lock around this call; any other reader tolerates one poll of staleness
                "p99": self.sketch.percentile(99.0),
                "transitions": list(self.transitions)}


class BreakerState:
    CLOSED = "closed"
    OPEN = "open"
    HALF_OPEN = "half_open"


class CircuitBreaker:
    """Per-replica routing circuit breaker: ``failure_limit``
    consecutive failures open it for ``cooldown_s`` (injected clock);
    once the cooldown elapses it goes half-open and admits exactly one
    deterministic probe — the probe's outcome closes or re-opens it."""

    def __init__(self, name: str, *, failure_limit: int = 4,
                 cooldown_s: float = 5.0) -> None:
        self.name = name
        self.failure_limit = int(failure_limit)
        self.cooldown_s = float(cooldown_s)
        self.state = BreakerState.CLOSED
        self._failures = 0
        self._opened_at = 0.0
        self._probe_out = False
        self.transitions: List[Tuple[float, str, str]] = []

    def record_failure(self, now: float) -> None:
        if self.state == BreakerState.HALF_OPEN:
            # the probe failed: straight back to open, fresh cooldown
            self._probe_out = False
            self._move(BreakerState.OPEN, now)
            self._opened_at = float(now)
            return
        self._failures += 1
        if (self.state == BreakerState.CLOSED
                and self._failures >= self.failure_limit):
            self._move(BreakerState.OPEN, now)
            self._opened_at = float(now)

    def record_success(self, now: float) -> None:
        self._failures = 0
        if self.state == BreakerState.HALF_OPEN:
            self._probe_out = False
            self._move(BreakerState.CLOSED, now)

    def admits(self, now: float) -> bool:
        """Routing-view eligibility. Open -> half-open happens here (the
        cooldown is checked against the injected clock); half-open
        admits only while its single probe slot is unclaimed."""
        if self.state == BreakerState.CLOSED:
            return True
        if self.state == BreakerState.OPEN:
            if now - self._opened_at >= self.cooldown_s:
                self._move(BreakerState.HALF_OPEN, now)
                self._probe_out = False
                return True
            return False
        return not self._probe_out

    def claim_probe(self) -> None:
        """The half-open probe slot was taken by a routed request; no
        second request is admitted until its outcome reports back."""
        if self.state == BreakerState.HALF_OPEN:
            self._probe_out = True

    def _move(self, to: str, now: float) -> None:
        self.transitions.append((float(now), self.state, to))
        self.state = to

    def snapshot(self) -> Dict[str, Any]:
        return {"name": self.name, "state": self.state,  # dslint: disable=races -- benign-stale snapshot read: gray_snapshot() holds the fleet lock around this call; any other reader tolerates one poll of staleness
                "failures": self._failures,  # dslint: disable=races -- benign-stale snapshot read (see state above)
                "transitions": list(self.transitions)}  # dslint: disable=races -- benign-stale snapshot read (see state above); the copy races at worst with one append, never a structural mutation (list append is atomic under the GIL)


class HedgePair:
    """The two legs of one hedged client request and the conservation
    gate between them.

    ``primary`` is the original request (the client's callback rides on
    it at submit time); ``shadow`` is the backup dispatched when the
    TTFT deadline came at risk.  The FIRST leg to deliver a token wins;
    from that point the loser's tokens are dropped at the gate (never
    delivered), its span and SLO verdict are suppressed, and the fleet
    cancels it with its KV discarded un-published.  If the primary goes
    terminal before any token was delivered, the primary wins by
    default — its reject/cancel/failure IS the client-visible outcome.
    The gate's lock is a private leaf (nothing is acquired under it).
    """

    def __init__(self, primary, shadow) -> None:
        self.primary = primary
        self.shadow = shadow
        self.winner_uid: Optional[int] = None
        self.resolved = False       # loser cancellation has been issued
        self._mu = threading.Lock()

    def deliver(self, leg_uid: int, inner, token: int) -> None:
        """The per-leg on_token gate: decide the winner on the first
        token ever delivered, then let only the winner through."""
        with self._mu:
            if self.winner_uid is None:
                self.winner_uid = leg_uid  # dslint: disable=races -- write-once under the _mu leaf: winner_uid only ever goes None -> uid, exactly once; the lock-free winner/loser property reads (fleet resolve pass, DST auditor) act only on a non-None value, and a stale None just defers hedge resolution to the next poll
            won = self.winner_uid == leg_uid
        if won and inner is not None:
            inner(token)

    def settle(self, leg_uid: int) -> None:
        """A leg went terminal while the race was undecided: that leg
        wins by default (primary terminal = the client-visible outcome;
        shadow terminal = the hedge quietly failed, primary continues)."""
        other = (self.shadow.uid if leg_uid == self.primary.uid
                 else self.primary.uid)
        with self._mu:
            if self.winner_uid is None:
                # a terminal PRIMARY wins by default; a terminal SHADOW
                # loses by default (the primary keeps serving)
                self.winner_uid = (leg_uid if leg_uid == self.primary.uid
                                   else other)

    @property
    def loser(self):
        if self.winner_uid is None:
            return None
        return (self.shadow if self.winner_uid == self.primary.uid
                else self.primary)

    @property
    def winner(self):
        if self.winner_uid is None:
            return None
        return (self.primary if self.winner_uid == self.primary.uid
                else self.shadow)

    def is_suppressed(self, uid: int) -> bool:
        """True when ``uid`` is a DECIDED loser: its span + SLO verdict
        must not be emitted (the ledger judges the request once)."""
        with self._mu:
            return self.winner_uid is not None and uid != self.winner_uid

    def snapshot(self) -> Dict[str, Any]:
        with self._mu:
            return {"client_request_id": self.primary.client_request_id,
                    "primary_uid": self.primary.uid,
                    "shadow_uid": self.shadow.uid,
                    "winner_uid": self.winner_uid,
                    "resolved": self.resolved}
