"""Request routing across serving replicas.

The fleet front-end (:mod:`.fleet`) holds N engine replicas; a router
decides which replica each incoming prompt lands on. Two policies ship:

* :class:`LeastLoadedRouter` — send to the healthy replica with the
  smallest load (queued + live requests). The throughput baseline: even
  spread, zero locality.
* :class:`PrefixAffinityRouter` — consistent hashing over the prompt's
  FULL-BLOCK prefix (the exact unit the engine's automatic prefix cache
  keys on: ``PrefixCache.match`` shares full ``kv_block_size`` pages,
  capped so at least one token remains to prefill). Repeat traffic with
  a shared prefix — chat system prompts, RAG templates, few-shot headers
  — lands on the replica that already holds those KV pages, so its
  prefill is mostly cache adoption instead of recompute. The ring is the
  classic consistent-hash construction (``vnodes`` virtual points per
  replica, sorted by hash; a key routes to the first point clockwise),
  which bounds key movement on membership change: adding one replica to
  N moves ~1/(N+1) of keys, and removing one moves ONLY the keys that
  mapped to it — the property the fleet's failover depends on (a dead
  replica must not reshuffle the healthy replicas' working sets).

Routers are deliberately engine-agnostic: they operate on *names* plus a
caller-supplied health/load view, so the hash-ring properties are
testable without building a single engine. That view is also where the
gray-failure plane plugs in: the fleet's ``_view`` drops quarantined
replicas and open circuit breakers (serving/health.py) BEFORE either
router walks the ring, so ahead-of-the-ring-walk breaker consultation
costs the routers nothing and changes no routing code here.
"""

from __future__ import annotations

import bisect
import hashlib
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple


class NoHealthyReplica(RuntimeError):
    """Every replica is dead or draining — nothing can take the request."""


def _hash64(data: str) -> int:
    """Stable 64-bit hash (sha256-derived: identical across processes and
    runs — python's ``hash()`` is salted per process and would reshuffle
    the ring on every restart, defeating affinity)."""
    return int.from_bytes(
        hashlib.sha256(data.encode("utf-8")).digest()[:8], "big")


def prefix_key(prompt: Sequence[int], block_size: int) -> Tuple[int, ...]:
    """The routing key for a prompt: its longest cacheable full-block
    prefix (mirrors ``PrefixCache.match`` — full blocks only, capped at
    ``len(prompt) - 1`` so the key matches what a replica could actually
    hold). Prompts shorter than one full block key on the whole prompt:
    identical short prompts should still co-locate."""
    k = (len(prompt) - 1) // block_size
    if k <= 0:
        return tuple(int(t) for t in prompt)
    return tuple(int(t) for t in prompt[: k * block_size])


def least_loaded_pick(replicas: Dict[str, float]) -> str:
    """THE least-loaded selection (ties break by name for determinism) —
    one definition shared by the baseline router, the affinity router's
    degrade/spill paths, and the fleet's prefill/handoff placement."""
    if not replicas:
        raise NoHealthyReplica("no healthy replica to route to")
    return min(replicas.items(), key=lambda kv: (kv[1], kv[0]))[0]


class ConsistentHashRing:
    """The classic consistent-hash ring over member names — the ONE
    construction both routing tiers use: the fleet's replica ring
    (:class:`PrefixAffinityRouter`) and the region's cell ring
    (:class:`~.region.Region`). ``vnodes`` virtual points per member,
    sorted by a process-stable sha256-derived hash; a key routes to the
    first point clockwise. Membership changes move a bounded key set:
    a join moves ~1/(N+1) of keys (all TO the joiner), a leave moves
    only the leaver's own keys — the property failover at BOTH tiers
    depends on (one dead cell must not reshuffle the healthy cells'
    working sets any more than one dead replica may)."""

    def __init__(self, vnodes: int = 64):
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.vnodes = int(vnodes)
        self._ring: List[Tuple[int, str]] = []   # (point, member) sorted
        self._points: List[int] = []             # parallel sorted points
        self._members: set = set()

    @property
    def members(self) -> set:
        return set(self._members)

    def join(self, member: str) -> None:
        if member in self._members:
            return
        self._members.add(member)
        for i in range(self.vnodes):
            point = _hash64(f"{member}#{i}")
            j = bisect.bisect_left(self._points, point)
            # dslint: disable-next-line=races -- every post-construction ring mutation/walk runs under the OWNING router's lock (the fleet's for PrefixAffinityRouter, the region's for the cell ring — docs/serving.md "Threading model"); the construction-time join precedes thread start, and dsrace's entry-lockset meet over both owners' call contexts is instance-blind
            self._points.insert(j, point)
            # dslint: disable-next-line=races -- same owning-router lock discipline as _points above
            self._ring.insert(j, (point, member))

    def leave(self, member: str) -> None:
        if member not in self._members:
            return
        self._members.discard(member)
        keep = [(p, r) for p, r in self._ring if r != member]
        self._ring = keep
        self._points = [p for p, _ in keep]

    def walk(self, h: int,
             eligible: Optional[Callable[[str], bool]] = None
             ) -> Optional[str]:
        """First member clockwise from ``h``, skipping ones ``eligible``
        rejects (each DISTINCT member is offered to ``eligible`` at most
        once — the walk's cost is O(distinct members examined), not
        O(vnodes)). None when the ring is empty or nothing qualifies."""
        if not self._ring:
            return None
        start = bisect.bisect_right(self._points, h) % len(self._ring)
        seen: set = set()
        for off in range(len(self._ring)):
            _, rep = self._ring[(start + off) % len(self._ring)]
            if rep in seen:
                continue
            seen.add(rep)
            if eligible is None or eligible(rep):
                return rep
            if len(seen) == len(self._members):
                break
        return None


class RouterPolicy:
    """Base router: pick a replica name for a prompt.

    ``replicas`` is the caller's current view: an ordered mapping of
    name -> load (smaller = less loaded) restricted to replicas that can
    accept work — health filtering happens before the router sees them.
    """

    name = "base"

    def route(self, replicas: Dict[str, float],
              prompt: Sequence[int]) -> str:
        raise NotImplementedError

    def route_info(self) -> Dict[str, Any]:
        """Attrs describing the LAST ``route()`` verdict — consumed by
        the fleet's per-request "route" tracer span (telemetry/
        tracing.py) so the affinity hit/miss decision is visible on the
        request's timeline. Stateless routers report nothing."""
        return {}

    # membership hooks (stateful routers maintain a ring)
    def on_join(self, replica: str) -> None:
        pass

    def on_leave(self, replica: str) -> None:
        pass


class LeastLoadedRouter(RouterPolicy):
    """Route to the least-loaded healthy replica (ties break by name for
    determinism)."""

    name = "least_loaded"

    def route(self, replicas: Dict[str, float],
              prompt: Sequence[int]) -> str:
        return least_loaded_pick(replicas)


class PrefixAffinityRouter(RouterPolicy):
    """Consistent-hash routing on the prompt's full-block prefix.

    ``spill_load`` (0 = off) is the load-shedding valve: when the ring's
    choice already carries at least that much load AND some other healthy
    replica is strictly less loaded, the request spills to least-loaded
    instead — affinity is a throughput optimisation, not a hostage
    situation. Spills are reported as affinity misses.
    """

    name = "prefix_affinity"

    def __init__(self, block_size: int, vnodes: int = 64,
                 spill_load: int = 0):
        if block_size < 1:
            raise ValueError(f"block_size must be >= 1, got {block_size}")
        if vnodes < 1:
            raise ValueError(f"vnodes must be >= 1, got {vnodes}")
        self.block_size = int(block_size)
        self.vnodes = int(vnodes)
        self.spill_load = int(spill_load)
        self._hash_ring = ConsistentHashRing(vnodes=vnodes)
        # set by route(): True when the last pick was the ring's primary
        # owner (an affinity hit), False on ring-walk fallback or spill
        self.last_was_primary: Optional[bool] = None
        # set by route(): True when the spill valve redirected the pick
        self.last_spilled: bool = False

    # -- membership ------------------------------------------------------
    def on_join(self, replica: str) -> None:
        self._hash_ring.join(replica)

    def on_leave(self, replica: str) -> None:
        self._hash_ring.leave(replica)

    # -- routing ---------------------------------------------------------
    def _hash_for(self, prompt: Sequence[int]) -> int:
        return _hash64(",".join(map(str,
                                    prefix_key(prompt, self.block_size))))

    def owner(self, prompt: Sequence[int],
              eligible: Optional[Callable[[str], bool]] = None
              ) -> Optional[str]:
        """The ring's pick for this prompt: the first replica clockwise
        from the key's hash, skipping ones ``eligible`` rejects. None
        when the ring is empty or nothing is eligible."""
        return self.owner_from_hash(self._hash_for(prompt), eligible)

    def owner_from_hash(self, h: int,
                        eligible: Optional[Callable[[str], bool]] = None
                        ) -> Optional[str]:
        """Ring walk from a precomputed key hash (``route`` needs both
        the unconditional primary and the health-filtered pick — hashing
        the prompt once serves both walks)."""
        return self._hash_ring.walk(h, eligible)

    def route(self, replicas: Dict[str, float],
              prompt: Sequence[int]) -> str:
        if not replicas:
            raise NoHealthyReplica("no healthy replica to route to")
        # the ring may know replicas the health view excludes (draining /
        # dead): walk past them. Primary = first ring owner regardless of
        # health — routing to anyone else counts as an affinity miss.
        h = self._hash_for(prompt)
        primary = self.owner_from_hash(h)
        chosen = self.owner_from_hash(h, eligible=lambda r: r in replicas)
        if chosen is None:
            # membership drifted (replica joined the fleet but not the
            # ring yet, or vice versa): degrade to least-loaded
            chosen = least_loaded_pick(replicas)
        self.last_spilled = False
        if self.spill_load > 0 and replicas[chosen] >= self.spill_load:
            alt = least_loaded_pick(replicas)
            if replicas[alt] < replicas[chosen]:
                chosen = alt
                self.last_spilled = True
        self.last_was_primary = (chosen == primary)
        return chosen

    def route_info(self) -> Dict[str, Any]:
        return {"affinity_hit": self.last_was_primary,
                "spilled": self.last_spilled}


class ResidencyAwareRouter(PrefixAffinityRouter):
    """Prefix-affinity routing that consults the global KV tier's
    :class:`~.kvtier.PrefixDirectory` FIRST (docs/serving.md "Global KV
    tier"): when a bounded-staleness-fresh directory entry says some
    healthy replica already holds the prompt's full-block prefix, the
    request routes to the least-loaded such holder — *residency* beats
    pure hash affinity, because the pages are where they are, not where
    the ring says they should be (failover, spills and adoption all move
    pages off the ring owner). The fallback matrix:

    * fresh holder in the health view  -> residency pick
    * entries exist but all stale      -> affinity ring (outcome
      ``directory_stale`` — the directory lied or lagged; the ring is
      never wrong about *where to build* the prefix, only about where it
      already exists)
    * no entry / no healthy holder     -> affinity ring (plain miss)
    * residency pick over ``spill_load`` while someone idles -> affinity
      ring path with its spill valve (residency is a throughput
      optimisation, not a hostage situation — same rule as affinity)

    The directory is attached after construction (``set_directory``) —
    the fleet builds it only when ``serving.kv_tier.enabled``; without
    one this router IS a ``PrefixAffinityRouter``, bit-for-bit."""

    name = "residency"

    def __init__(self, block_size: int, vnodes: int = 64,
                 spill_load: int = 0, directory=None, now_fn=None):
        super().__init__(block_size=block_size, vnodes=vnodes,
                         spill_load=spill_load)
        self.directory = directory
        self.now_fn = now_fn if now_fn is not None else (lambda: 0.0)
        # set by route(): "residency" | "affinity" | "directory_stale"
        self.last_outcome: Optional[str] = None

    def set_directory(self, directory, now_fn) -> None:
        self.directory = directory
        self.now_fn = now_fn

    def route(self, replicas: Dict[str, float],
              prompt: Sequence[int]) -> str:
        if not replicas:
            raise NoHealthyReplica("no healthy replica to route to")
        stale_only = False
        if self.directory is not None:
            h = self._hash_for(prompt)
            fresh, stale_only = self.directory.holders(h, self.now_fn())
            eligible = [m for m in fresh if m in replicas]
            if eligible:
                chosen = min(eligible, key=lambda n: (replicas[n], n))
                over = (self.spill_load > 0
                        and replicas[chosen] >= self.spill_load
                        and min(replicas.values()) < replicas[chosen])
                if not over:
                    self.last_spilled = False
                    self.last_was_primary = \
                        (chosen == self.owner_from_hash(h))
                    self.last_outcome = "residency"
                    return chosen
        chosen = super().route(replicas, prompt)
        self.last_outcome = "directory_stale" if stale_only else "affinity"
        return chosen

    def route_info(self) -> Dict[str, Any]:
        info = super().route_info()
        info["outcome"] = self.last_outcome
        return info


def make_router(name: str, *, block_size: int = 16, vnodes: int = 64,
                spill_load: int = 0) -> RouterPolicy:
    """Router factory for config-driven selection."""
    if name == "least_loaded":
        return LeastLoadedRouter()
    if name == "prefix_affinity":
        return PrefixAffinityRouter(block_size=block_size, vnodes=vnodes,
                                    spill_load=spill_load)
    if name == "residency":
        return ResidencyAwareRouter(block_size=block_size, vnodes=vnodes,
                                    spill_load=spill_load)
    raise ValueError(f"unknown router '{name}' (expected 'least_loaded', "
                     "'prefix_affinity' or 'residency')")
