"""Global KV tier: the region-scoped prefix-reuse plane.

At scale the hot KV working set (system prompts, few-shot preambles,
multi-turn histories) is massively shared, yet each replica's
:class:`~deepspeed_tpu.inference.ragged.PrefixCache` is private. This
module promotes prefix residency to a fleet/region resource with three
cooperating pieces (docs/serving.md "Global KV tier"):

* :class:`PrefixDirectory` — a bounded-staleness map of *full-block
  prefix hash -> holders*. Replicas publish their residency set on the
  existing digest/health poll cadence (one locked swap per replica per
  publish — per-tick work independent of replica count), and entries
  are invalidated synchronously on eviction and dropped wholesale on
  replica death/migration, so a directory entry never outlives its
  pages. The directory is advisory: routing treats it as a hint with a
  freshness bound and falls back to the affinity ring when it lies.
* :class:`PrefixExport` — the wire form of an adopted prefix: quantized
  pages + scales (the PR-14 KV wire format) plus geometry and a
  checksum, so adoption-wire corruption is *detected* at the importer
  and degrades to local re-prefill instead of landing poisoned pages.
* :class:`ColdTier` — a host-memory LRU of evicted prefixes, capacity-
  accounted in KV pages. Entries are immutable host copies holding NO
  device-pool references (spill copies pages out before the device
  blocks are released), so no double-free across tiers is possible by
  construction; re-admission goes through the same import/checksum path
  as remote adoption.

Locking: ``PrefixDirectory._lock`` and ``ColdTier._lock`` are LEAF
locks (locksan-registered): nothing blocking runs under them and no
other lock is ever taken inside them, so they may be entered from any
point in the documented Region -> Cell -> Fleet -> Engine order —
including the eviction hook that fires under a driver's serving lock.

Everything here is deterministic: no RNG, no wall-clock reads (callers
pass ``now``), stable iteration orders — the DST auditor's directory
and cold-tier invariants (docs/dst.md #17/#18/#19) replay bit-
identically per seed.
"""

from __future__ import annotations

from collections import OrderedDict
from typing import Any, Dict, Iterable, List, Optional, Sequence, Tuple

from ..resilience.locksan import named_lock
from .router import _hash64

__all__ = ["PrefixExport", "PrefixDirectory", "ColdTier", "KVTier",
           "CorruptExport", "prefix_hash"]


class CorruptExport(ValueError):
    """An adopted export failed its checksum at the importer — the
    corruption gate fired. Subclasses ValueError so every existing
    "degrade to local re-prefill" handler already covers it; callers
    that want to meter corruption separately catch this first."""


def prefix_hash(tokens: Sequence[int]) -> int:
    """Directory key for a full-block prefix: the SAME process-stable
    64-bit hash the affinity ring walks (router._hash64 over the
    comma-joined tokens), so a router-side key and an engine-side
    residency publication meet on identical values."""
    return _hash64(",".join(map(str, tokens)))


def _fold64(acc: int, value: int) -> int:
    """One FNV-1a fold step over a 64-bit accumulator."""
    return ((acc ^ (value & 0xFFFFFFFFFFFFFFFF))
            * 0x100000001B3) & 0xFFFFFFFFFFFFFFFF


def export_checksum(tokens: Sequence[int],
                    payloads: Iterable[bytes] = ()) -> int:
    """Content checksum for a :class:`PrefixExport`: FNV-1a over the
    token stream, then over each payload buffer's bytes. Payload-free
    exports (the DST sim) checksum the tokens alone — enough to catch
    the injected wire corruption, which flips a token."""
    import hashlib

    acc = 0xCBF29CE484222325
    for t in tokens:
        acc = _fold64(acc, int(t))
    for buf in payloads:
        digest = hashlib.sha256(buf).digest()[:8]
        acc = _fold64(acc, int.from_bytes(digest, "big"))
    return acc


class PrefixExport:
    """A prefix's KV pages in wire form, for cross-replica adoption and
    cold-tier storage. ``pages``/``scales`` are host arrays in the
    engine's quantized layout (None in the payload-free DST sim); the
    geometry tuple mirrors ``SimKVExport``/``KVExport`` so the importer
    can refuse a mismatched donor before touching its pool."""

    __slots__ = ("tokens", "n_pages", "block_size", "n_layers",
                 "n_kv_heads", "head_dim", "dtype", "kv_quant",
                 "pages", "scales", "checksum", "wire_bytes",
                 "logical_bytes", "source")

    def __init__(self, tokens: Sequence[int], n_pages: int,
                 block_size: int, n_layers: int, n_kv_heads: int,
                 head_dim: int, dtype: str, kv_quant: str,
                 pages: Optional[Any] = None,
                 scales: Optional[Any] = None,
                 wire_bytes: int = 0, logical_bytes: int = 0,
                 source: str = "", checksum: Optional[int] = None):
        self.tokens = tuple(int(t) for t in tokens)
        self.n_pages = int(n_pages)
        self.block_size = int(block_size)
        self.n_layers = int(n_layers)
        self.n_kv_heads = int(n_kv_heads)
        self.head_dim = int(head_dim)
        self.dtype = str(dtype)
        self.kv_quant = str(kv_quant)
        self.pages = pages
        self.scales = scales
        self.wire_bytes = int(wire_bytes)
        self.logical_bytes = int(logical_bytes)
        self.source = source
        self.checksum = (int(checksum) if checksum is not None
                         else self.compute_checksum())

    def geometry(self) -> Tuple[int, int, int, int, str, str]:
        return (self.block_size, self.n_layers, self.n_kv_heads,
                self.head_dim, self.dtype, self.kv_quant)

    def _payload_buffers(self) -> List[bytes]:
        out: List[bytes] = []
        for arr in (self.pages, self.scales):
            if arr is None:
                continue
            if isinstance(arr, (list, tuple)):
                out.extend(a.tobytes() for a in arr if a is not None)
            else:
                out.append(arr.tobytes())
        return out

    def compute_checksum(self) -> int:
        return export_checksum(self.tokens, self._payload_buffers())

    def verify(self) -> bool:
        """True when the content still matches the stamped checksum —
        the importer's corruption gate (invariant #19: a corrupted
        export must never land)."""
        return self.compute_checksum() == self.checksum

    @property
    def key(self) -> Tuple[int, ...]:
        return self.tokens

    @property
    def hash(self) -> int:
        return prefix_hash(self.tokens)


class PrefixDirectory:
    """Bounded-staleness map of prefix hash -> {holder: t_published}.

    ``publish`` is a full replacement of one member's residency set
    (snapshot semantics: the set is whatever the replica's driver saw
    at its last publish tick), ``invalidate`` removes one entry
    synchronously (the eviction hook), ``drop_member`` removes a dead
    or migrated replica wholesale. ``holders`` answers routing: the
    fresh holder list plus a flag for "entries exist but all exceeded
    the staleness bound" — the router's signal to count a
    ``directory_stale`` outcome and fall back to the affinity ring.

    The lock is a private LEAF (see module docstring).
    """

    def __init__(self, staleness_s: float):
        self.staleness_s = float(staleness_s)
        self._lock = named_lock("PrefixDirectory._lock")
        # hash -> {member: t_published}
        self._holders: Dict[int, Dict[str, float]] = {}
        # member -> set of hashes (reverse index for O(set) publish/drop)
        self._by_member: Dict[str, set] = {}
        self.publishes = 0
        self.invalidations = 0

    # -- writes ----------------------------------------------------------
    def publish(self, member: str, hashes: Iterable[int],
                now: float) -> None:
        new = set(int(h) for h in hashes)
        with self._lock:
            self.publishes += 1
            old = self._by_member.get(member, set())
            for h in old - new:
                ent = self._holders.get(h)
                if ent is not None:
                    ent.pop(member, None)
                    if not ent:
                        del self._holders[h]
            for h in new:
                self._holders.setdefault(h, {})[member] = float(now)
            if new:
                self._by_member[member] = new
            else:
                self._by_member.pop(member, None)

    def invalidate(self, member: str, h: int) -> None:
        """Synchronous single-entry removal — the eviction/spill hook.
        Fires under the evicting driver's serving lock; legal because
        this lock is a leaf."""
        h = int(h)
        with self._lock:
            self.invalidations += 1
            ent = self._holders.get(h)
            if ent is not None and member in ent:
                del ent[member]
                if not ent:
                    del self._holders[h]
            mh = self._by_member.get(member)
            if mh is not None:
                mh.discard(h)
                if not mh:
                    del self._by_member[member]

    def drop_member(self, member: str) -> int:
        """Remove every entry a dead/migrated replica published (its
        pages are gone or untrusted — the entry must not outlive them).
        Returns the number of entries dropped."""
        with self._lock:
            hashes = self._by_member.pop(member, set())
            for h in hashes:
                ent = self._holders.get(h)
                if ent is not None:
                    ent.pop(member, None)
                    if not ent:
                        del self._holders[h]
            return len(hashes)

    # -- reads -----------------------------------------------------------
    def holders(self, h: int, now: float) -> Tuple[List[str], bool]:
        """(fresh holder names sorted, stale_only) for a prefix hash.
        ``stale_only`` is True when the directory HAS entries for the
        hash but every one exceeded the staleness bound — distinct from
        "no entry" so routing can meter directory lies separately from
        plain misses."""
        with self._lock:
            ent = self._holders.get(int(h))
            if not ent:
                return [], False
            fresh = sorted(m for m, t in ent.items()
                           if now - t <= self.staleness_s)
            return fresh, not fresh

    def has_fresh(self, h: int, now: float) -> bool:
        return bool(self.holders(h, now)[0])

    def entries_for(self, member: str) -> set:
        with self._lock:
            return set(self._by_member.get(member, set()))

    def members(self) -> List[str]:
        with self._lock:
            return sorted(self._by_member)

    def size(self) -> int:
        with self._lock:
            return len(self._holders)

    def snapshot(self) -> Dict[str, Any]:
        with self._lock:
            return {
                "entries": len(self._holders),
                "members": {m: len(hs)
                            for m, hs in sorted(self._by_member.items())},
                "publishes": self.publishes,
                "invalidations": self.invalidations,
            }


class ColdTier:
    """Host-memory LRU of evicted prefixes, capacity-accounted in KV
    pages (the ZeRO-Offload discipline: host DRAM is a slower, bigger
    pool with its own explicit budget). Entries are immutable
    :class:`PrefixExport` host copies — no device references, so cold
    eviction is a plain ``del`` and cross-tier double-free cannot
    exist. ``put`` evicts LRU victims until the newcomer fits and
    refuses (counted) entries bigger than the whole tier; the chaos
    ``cold_pressure`` knob drops every Nth put, modelling a host under
    memory pressure. The lock is a private LEAF (module docstring)."""

    def __init__(self, capacity_pages: int):
        if capacity_pages < 1:
            raise ValueError(
                f"cold-tier capacity must be >= 1 page, got "
                f"{capacity_pages}")
        self.capacity_pages = int(capacity_pages)
        self._lock = named_lock("ColdTier._lock")
        self._entries: "OrderedDict[Tuple[int, ...], PrefixExport]" = \
            OrderedDict()
        self._used = 0
        self.puts = 0
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        self.rejects = 0
        self.chaos_drops = 0

    def put(self, export: PrefixExport) -> bool:
        """Admit an evicted prefix. Returns False when refused (bigger
        than the tier, or dropped by injected cold pressure)."""
        from ..resilience.chaos import get_fault_injector

        inj = get_fault_injector()
        if inj is not None and inj.on_cold_put():
            with self._lock:
                self.chaos_drops += 1
            return False
        with self._lock:
            self.puts += 1
            if export.n_pages > self.capacity_pages:
                self.rejects += 1
                return False
            old = self._entries.pop(export.key, None)
            if old is not None:
                self._used -= old.n_pages
            while self._used + export.n_pages > self.capacity_pages:
                _, victim = self._entries.popitem(last=False)
                self._used -= victim.n_pages
                self.evictions += 1
            self._entries[export.key] = export
            self._used += export.n_pages
            return True

    def get(self, tokens: Sequence[int]) -> Optional[PrefixExport]:
        key = tuple(int(t) for t in tokens)
        with self._lock:
            ent = self._entries.get(key)
            if ent is None:
                self.misses += 1
                return None
            self._entries.move_to_end(key)
            self.hits += 1
            return ent

    def contains(self, tokens: Sequence[int]) -> bool:
        with self._lock:
            return tuple(int(t) for t in tokens) in self._entries

    def invalidate(self, tokens: Sequence[int]) -> bool:
        key = tuple(int(t) for t in tokens)
        with self._lock:
            ent = self._entries.pop(key, None)
            if ent is None:
                return False
            self._used -= ent.n_pages
            return True

    @property
    def used_pages(self) -> int:
        with self._lock:
            return self._used

    def __len__(self) -> int:
        with self._lock:
            return len(self._entries)

    def entry_pages(self) -> List[int]:
        """Per-entry page counts in LRU order — the DST accounting
        invariant's witness (#18: used == sum(entries), used <=
        capacity)."""
        with self._lock:
            return [e.n_pages for e in self._entries.values()]

    def keys(self) -> List[Tuple[int, ...]]:
        with self._lock:
            return list(self._entries.keys())

    def entries_snapshot(self) -> List[PrefixExport]:
        """Entries in LRU order WITHOUT touching recency or hit
        counters — the invariant auditor's read-only view (``get``
        would reorder the LRU and perturb replay determinism)."""
        with self._lock:
            return list(self._entries.values())

    def drop_all(self) -> None:
        with self._lock:
            self._entries.clear()
            self._used = 0

    def stats(self) -> Dict[str, int]:
        with self._lock:
            return {"entries": len(self._entries), "used_pages": self._used,
                    "capacity_pages": self.capacity_pages,
                    "puts": self.puts, "hits": self.hits,
                    "misses": self.misses, "evictions": self.evictions,
                    "rejects": self.rejects,
                    "chaos_drops": self.chaos_drops}


class KVTier:
    """One fleet's slice of the global KV tier: the shared directory
    plus (optionally) the shared host cold tier, built from a validated
    :class:`~deepspeed_tpu.config.KVTierConfig`. The fleet owns one and
    hands it to every replica at spawn; the cold tier is fleet-wide
    (one host pool per node), so a prefix spilled by one replica can be
    re-admitted by any sibling."""

    def __init__(self, config: Any):
        self.config = config
        self.directory = PrefixDirectory(config.directory_staleness_s)
        self.cold: Optional[ColdTier] = (
            ColdTier(config.cold_capacity_pages) if config.cold_tier
            else None)

    def drop_member(self, member: str) -> int:
        """Death/migration hook: the member's directory entries must not
        outlive its pages. The cold tier is NOT dropped — its entries
        are host copies that survived the donor by construction."""
        return self.directory.drop_member(member)

    def snapshot(self) -> Dict[str, Any]:
        out = {"directory": self.directory.snapshot()}
        if self.cold is not None:
            out["cold"] = self.cold.stats()
        return out
