"""Zero-downtime model rollout: canary, auto-rollback, drain-and-flip.

The :class:`RolloutController` turns a model-version change from a cold
restart into a first-class, invariant-guarded fleet operation
(docs/serving.md "Rollout, canary, and migration"). It owns one state
machine, stepped from the region monitor (``Region.poll``), that moves
a region from serving version ``v`` to version ``v+1`` — or provably
back to ``v``:

    IDLE --start()--> CANARY --warm--> OBSERVING --window clean--> PROMOTING
                         |                 |                          |
                         |                 | SLO regression           |
                         v                 v                          v
                     ROLLING_BACK <--- ROLLING_BACK              DONE (all
                         |          (swap-retry / flip-attempt    replicas
                         v           budgets spent roll back too)  flipped)
                    ROLLED_BACK

* **CANARY** — one replica (first live cell, first healthy replica;
  deterministic order) is drained behind ``stop_admission``, its weights
  hot-swapped (``ServingEngine.hot_swap``: checkpoint-streamed load +
  AOT warmup before admission re-opens), and a tenant-sticky
  ``canary_fraction`` slice of new traffic is routed to the new version
  through the fleet's version-aware ring view.
* **OBSERVING** — for ``canary_observe_ticks`` controller steps the
  canary's per-version in-SLA window is compared against the stable
  version's; a regression past ``slo_regression_threshold`` (with at
  least ``min_canary_samples`` canary verdicts) triggers automatic
  rollback.
* **PROMOTING** — remaining replicas are drained and flipped one at a
  time, cell-by-cell in sorted order, each serving out its admitted
  work first (zero requests lost, bounded capacity dip). New capacity
  (respawns, scale-ups) already spawns on the new version.
* **ROLLING_BACK** — the canary slice closes, fleet version returns to
  stable, and every replica serving the abandoned version is drained
  and flipped back. The rollout converges to ROLLED_BACK — the DST
  rollback-convergence invariant audits that it neither wedges nor
  leaves a replica stranded on the rolled-back version.

Every version decision lands in :attr:`version_log` — the justification
ledger the DST per-tenant version-monotonicity invariant checks a
version DECREASE against (a tenant may only ever move backwards across
a logged rollback; anything else is a routing bug).

Faults the controller must survive (``resilience/chaos.py``): a corrupt
new-version checkpoint (``hot_swap`` falls back to the old weights; the
controller retries up to ``swap_retry_limit`` then rolls back), the
flip victim dying mid-flip (re-target, up to ``max_flip_attempts``),
and an injected canary SLO regression (must roll back, and the
rollback must converge).
"""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Optional, Tuple

from ..resilience.chaos import get_fault_injector
from ..resilience.locksan import named_rlock
from ..telemetry.tracing import get_tracer
from ..utils.logging import log_dist, logger


class RolloutPhase:
    """Controller phases (str constants, same idiom as ReplicaState)."""

    IDLE = "idle"
    CANARY = "canary"
    OBSERVING = "observing"
    PROMOTING = "promoting"
    ROLLING_BACK = "rolling_back"
    DONE = "done"
    ROLLED_BACK = "rolled_back"


#: phases a new rollout may start from
_STARTABLE = (RolloutPhase.IDLE, RolloutPhase.DONE, RolloutPhase.ROLLED_BACK)
#: terminal phases (the rollout is over; the controller is re-armable)
TERMINAL_PHASES = (RolloutPhase.DONE, RolloutPhase.ROLLED_BACK)

#: numeric phase encoding for the ``serving/rollout/phase`` gauge
_PHASE_GAUGE = {RolloutPhase.IDLE: 0, RolloutPhase.CANARY: 1,
                RolloutPhase.OBSERVING: 2, RolloutPhase.PROMOTING: 3,
                RolloutPhase.ROLLING_BACK: 4, RolloutPhase.DONE: 5,
                RolloutPhase.ROLLED_BACK: 6}


class RolloutController:
    """One in-flight rollout for a :class:`~.region.Region`.

    Stepped from the region monitor (``Region.poll`` -> :meth:`step`);
    all fleet/engine access happens through the public fleet surface,
    so the lock order stays ``RolloutController._lock`` ->
    ``ServingFleet._lock`` -> ``ServingEngine._lock`` (the controller
    is never called from under a fleet lock). ``load_fn`` (optional,
    from :meth:`start`) is invoked inside each replica's ``hot_swap``
    to stream the new version's weights — in DST it stays None and the
    flip is a pure version change."""

    def __init__(self, region, config: Any, clock) -> None:
        self._region = region
        self.config = config
        self._clock = clock
        self._lock = named_rlock("RolloutController._lock")
        self._phase = RolloutPhase.IDLE
        self.target_version: Optional[int] = None
        self.stable_version: Optional[int] = None
        self._fraction = 0.0
        self._load_fn: Optional[Callable[[], None]] = None
        #: in-progress flip: {"cell", "name", "target", "retries",
        #: "stopped"} — one replica at a time, by design (bounded dip)
        self._flip: Optional[Dict[str, Any]] = None
        self._flip_attempts = 0
        self._observe_left = 0
        #: justification ledger: {"t", "kind", "version"} rows. Kinds:
        #: start / canary_live / promote / done / swap_failed /
        #: flip_death / rollback / rolled_back. The DST monotonicity
        #: auditor accepts a tenant's version DECREASE only across a
        #: "rollback" row for the abandoned version.
        self.version_log: List[Dict[str, Any]] = []

    # -- telemetry -------------------------------------------------------
    def _count(self, name: str, n: float = 1.0) -> None:
        from ..telemetry import get_telemetry

        get_telemetry().registry.counter(f"serving/rollout/{name}").inc(n)

    def _update_gauges(self) -> None:
        from ..telemetry import get_telemetry

        t = get_telemetry()
        if not t.enabled:
            return
        with self._lock:
            phase, target = self._phase, self.target_version
        t.registry.gauge("serving/rollout/phase").set(_PHASE_GAUGE[phase])
        t.registry.gauge("serving/rollout/target_version").set(
            -1 if target is None else target)

    def _log(self, kind: str, version: int) -> None:
        """Append a version_log row (controller lock held)."""
        self.version_log.append(
            {"t": self._clock.now(), "kind": kind, "version": int(version)})

    # -- introspection ---------------------------------------------------
    @property
    def phase(self) -> str:
        with self._lock:
            return self._phase

    @property
    def active(self) -> bool:
        with self._lock:
            return self._phase not in (RolloutPhase.IDLE,) + TERMINAL_PHASES

    def _fleets(self):
        """Live cells' fleets, sorted by cell name (deterministic)."""
        return [c.fleet for c in sorted(self._region.live_cells,
                                        key=lambda c: c.name)]

    def _version_counts(self) -> Dict[int, int]:
        out: Dict[int, int] = {}
        for fleet in self._fleets():
            for v, n in fleet.version_counts().items():
                out[v] = out.get(v, 0) + n
        return out

    def _version_sla(self, version: int) -> Tuple[int, Optional[float]]:
        """Region-wide (samples, in-SLA ratio) for one version, read
        from the region's SLO plane (telemetry/slo.py): one windowed
        read of rollup-fed verdicts instead of a per-fleet deque scan —
        the canary judge's cost no longer grows with fleet count."""
        return self._region.slo.version_attainment(version,
                                                   self._clock.now())

    # -- lifecycle -------------------------------------------------------
    def start(self, version: int, fraction: Optional[float] = None,
              load_fn: Optional[Callable[[], None]] = None) -> bool:
        """Begin rolling the region to ``version``. Refused (False) when
        a rollout is already in flight or the version does not move
        forward — versions are monotonic by contract; only a ROLLBACK
        (controller-logged) ever lowers what a tenant sees."""
        fleets = self._fleets()
        if not fleets:
            return False
        stable = fleets[0].fleet_version
        with self._lock:
            if self._phase not in _STARTABLE:
                logger.warning(
                    f"rollout: refusing start({version}) mid-rollout "
                    f"(phase {self._phase})")
                return False
            if int(version) <= stable:
                logger.warning(
                    f"rollout: refusing start({version}): not ahead of "
                    f"stable version {stable}")
                return False
            self._phase = RolloutPhase.CANARY
            self.target_version = int(version)
            self.stable_version = stable
            self._fraction = (self.config.canary_fraction
                              if fraction is None
                              else max(0.0, min(1.0, float(fraction))))
            self._load_fn = load_fn
            self._flip = None
            self._flip_attempts = 0
            self._observe_left = int(self.config.canary_observe_ticks)
            self._log("start", self.target_version)
        for fleet in fleets:
            fleet.set_canary(int(version), self._fraction)
        self._count("starts")
        tracer = get_tracer()
        if tracer.enabled:
            tracer.flight.note("rollout_start", version=int(version),
                               stable=stable)
        log_dist(f"rollout: {stable} -> {version} started "
                 f"(canary {self._fraction:.0%})")
        self._update_gauges()
        return True

    def step(self) -> None:
        """One controller step (region monitor cadence). Cheap when
        idle; at most one replica is mid-flip at any time."""
        with self._lock:
            phase = self._phase
        if phase in (RolloutPhase.IDLE,) + TERMINAL_PHASES:
            return
        if phase == RolloutPhase.CANARY:
            self._step_canary()
        elif phase == RolloutPhase.OBSERVING:
            self._step_observing()
        elif phase == RolloutPhase.PROMOTING:
            self._step_promoting()
        elif phase == RolloutPhase.ROLLING_BACK:
            self._step_rolling_back()
        self._update_gauges()

    # -- flip engine (one replica at a time) -----------------------------
    def _pick_flip_target(self, to_version: int) -> Optional[Dict[str, Any]]:
        """First healthy replica NOT serving ``to_version``, cells in
        sorted order — the cell-by-cell discipline."""
        for cell in sorted(self._region.live_cells, key=lambda c: c.name):
            for rep in sorted(cell.fleet.healthy_replicas,
                              key=lambda r: r.name):
                if rep.version != to_version:
                    return {"cell": cell.name, "name": rep.name,
                            "target": to_version, "retries": 0,
                            "stopped": False}
        return None

    def _find_replica(self, flip: Dict[str, Any]):
        """(cell, replica) for an in-progress flip, or (None, None) when
        either side died under us."""
        for cell in self._region.live_cells:
            if cell.name != flip["cell"]:
                continue
            for rep in cell.fleet.replicas:
                if rep.name == flip["name"]:
                    from .fleet import ReplicaState

                    if rep.state == ReplicaState.DEAD:
                        return cell, None
                    return cell, rep
            return cell, None
        return None, None

    def _step_flip(self, to_version: int) -> str:
        """Advance the current flip by one step. Returns:

        * ``"flipping"`` — in progress (draining / warming / retrying);
        * ``"flipped"``  — one replica finished flipping this step;
        * ``"clean"``    — nothing left to flip to ``to_version``;
        * ``"failed"``   — budgets spent (swap retries / flip attempts).
        """
        with self._lock:
            flip = self._flip
            load_fn = self._load_fn
        if flip is None:
            flip = self._pick_flip_target(to_version)
            if flip is None:
                return "clean"
            with self._lock:
                if self._flip_attempts >= self.config.max_flip_attempts:
                    return "failed"
                self._flip_attempts += 1
                self._flip = flip
        cell, rep = self._find_replica(flip)
        if rep is None:
            # the victim (or its whole cell) died mid-flip: the fleet's
            # failover already harvested its work; re-target next step
            with self._lock:
                self._flip = None
            self._count("flip_retargets")
            return "flipping"
        if not flip["stopped"]:
            rep.serving.stop_admission()
            flip["stopped"] = True
            return "flipping"
        if rep.load > 0:
            return "flipping"   # admission stopped; serving out
        if rep.version == flip["target"]:
            # swap landed on an earlier step; wait out the AOT warmup
            # (admission re-opens when the countdown hits zero)
            if not rep.accepting:
                return "flipping"
            with self._lock:
                self._flip = None
                self._flip_attempts = 0
            return "flipped"
        inj = get_fault_injector()
        if inj is not None and inj.should_die_at_flip():
            # chaos: the replica process dies exactly at the swap point.
            # Kill through the fleet so failover/respawn run the normal
            # death path; the flip re-targets (attempt-budgeted).
            self._count("flip_deaths")
            with self._lock:
                self._log("flip_death", flip["target"])
                self._flip = None
            cell.fleet.kill_replica(rep.name,
                                    reason="chaos: death mid-flip")
            return "flipping"
        try:
            ok = rep.serving.hot_swap(flip["target"], load_fn=load_fn)
        except RuntimeError:
            # raced a late continuation between the drain check and the
            # swap (production interleaving; impossible under DST's
            # single-threaded drive): still busy, try next step
            return "flipping"
        if ok:
            self._count("flips")
            return "flipping"   # now warming; "flipped" once accepting
        # corrupt/failed weight load: hot_swap already fell back to the
        # old weights and re-opened admission — the replica is serving,
        # never stranded. Retry (re-drain) up to the budget.
        self._count("swap_failures")
        with self._lock:
            self._log("swap_failed", flip["target"])
            flip["retries"] += 1
            flip["stopped"] = False
            if flip["retries"] > self.config.swap_retry_limit:
                self._flip = None
                return "failed"
        return "flipping"

    # -- phase steps -----------------------------------------------------
    def _step_canary(self) -> None:
        with self._lock:
            target = self.target_version
        outcome = self._step_flip(target)
        if outcome == "failed":
            self._begin_rollback("canary flip budgets spent")
            return
        counts = self._version_counts()
        if counts.get(target, 0) > 0 \
                and outcome in ("flipped", "clean"):
            with self._lock:
                self._phase = RolloutPhase.OBSERVING
                self._log("canary_live", target)
            self._count("canaries_live")
            log_dist(f"rollout: canary live on version "
                     f"{target}; observing")

    def _step_observing(self) -> None:
        with self._lock:
            target = self.target_version
            stable = self.stable_version
        counts = self._version_counts()
        if counts.get(target, 0) == 0:
            # canary capacity died; re-flip one (attempt-budgeted)
            with self._lock:
                self._phase = RolloutPhase.CANARY
            return
        c_n, c_ratio = self._version_sla(target)
        s_n, s_ratio = self._version_sla(stable)
        if (c_n >= self.config.min_canary_samples
                and c_ratio is not None and s_ratio is not None
                and (s_ratio - c_ratio)
                > self.config.slo_regression_threshold):
            self._count("canary_regressions")
            tracer = get_tracer()
            if tracer.enabled:
                tracer.flight.note("canary_regression",
                                   canary=round(c_ratio, 4),
                                   stable=round(s_ratio, 4))
            self._begin_rollback(
                f"canary in-SLA {c_ratio:.2f} vs stable {s_ratio:.2f}")
            return
        with self._lock:
            self._observe_left -= 1
            done = self._observe_left <= 0
        if done:
            with self._lock:
                self._phase = RolloutPhase.PROMOTING
                self._log("promote", target)
            # new capacity (respawns, scale-ups) now spawns on the new
            # version, and BOTH sides of the former split prefer it —
            # tenants only ever move up from here
            for fleet in self._fleets():
                fleet.set_fleet_version(target)
                fleet.clear_canary()
            self._count("promotions")
            log_dist(f"rollout: canary window clean; promoting "
                     f"version {target}")

    def _step_promoting(self) -> None:
        with self._lock:
            target = self.target_version
        outcome = self._step_flip(target)
        if outcome == "failed":
            self._begin_rollback("promote flip budgets spent")
            return
        if outcome == "clean":
            with self._lock:
                self._phase = RolloutPhase.DONE
                self._log("done", target)
                self._flip = None
            self._count("completed")
            tracer = get_tracer()
            if tracer.enabled:
                tracer.flight.note("rollout_done", version=target)
            log_dist(f"rollout: version {target} fully promoted")

    def _begin_rollback(self, reason: str) -> None:
        with self._lock:
            target = self.target_version
            stable = self.stable_version
            self._phase = RolloutPhase.ROLLING_BACK
            self._log("rollback", target)
            self._flip = None
            # rollback gets a fresh flip-attempt budget: the budget that
            # was spent belongs to the FORWARD direction's bad luck, and
            # rollback must converge even after it
            self._flip_attempts = 0
        for fleet in self._fleets():
            fleet.clear_canary()
            fleet.set_fleet_version(stable)
        self._count("rollbacks")
        tracer = get_tracer()
        if tracer.enabled:
            tracer.flight.note("rollout_rollback", version=target,
                               reason=reason)
            tracer.flight.dump("rollout-rollback")
        logger.warning(f"rollout: ROLLING BACK version {target} "
                       f"({reason})")

    def _step_rolling_back(self) -> None:
        with self._lock:
            target = self.target_version
            stable = self.stable_version
        outcome = self._step_flip(stable)
        if outcome == "failed":
            # even rollback flips are budgeted, but a rollback that
            # gives up would strand replicas on the abandoned version —
            # reset the budget and keep draining (the DST convergence
            # invariant bounds this with the liveness slack)
            with self._lock:
                self._flip_attempts = 0
            self._count("rollback_retries")
            return
        if outcome == "clean":
            with self._lock:
                self._phase = RolloutPhase.ROLLED_BACK
                self._log("rolled_back", target)
                self._flip = None
            self._count("rolled_back")
            log_dist(f"rollout: rolled back to version "
                     f"{stable}; no replica serves {target}")
