"""Pluggable admission / preemption policies for the serving driver.

The driver loop (:mod:`.server`) runs one engine tick at a time; a policy
decides, per tick, *which* queued requests to admit and *which* live
decodes to evict under KV pressure. The engine's own Dynamic-SplitFuse
packing then fits the admitted set into the one static step shape — a
policy never touches the token budget directly, only the request set, so
every tick still compiles to the same program.

Two policies ship:

* :class:`FCFSPolicy` — strict arrival order with head-of-line blocking
  (the request at the head that does not fit stalls everyone behind it),
  no rejection, no preemption. This is the reference baseline: what the
  FastGen/MII front-end does absent any SLO machinery, and the A/B
  control the evidence lane measures against.
* :class:`SLOPolicy` — deadline-aware serving: admission ordered by
  (priority tier, earliest absolute deadline); queued requests whose
  deadline already passed are rejected instead of burning engine capacity
  on guaranteed SLO misses; smaller feasible requests may overtake a
  misfit (no head-of-line blocking); and under KV-pool pressure — or
  outright slot exhaustion — the lowest-priority / latest-deadline live
  decodes are preempted to make room for strictly-higher-priority
  arrivals. Preempted requests re-queue
  with their generated tokens and resume bit-exactly (re-prefill rides
  the prefix cache when enabled).

A policy sees capacity only through :class:`CapacityView` — a per-tick
closure over the engine's ``can_schedule`` that accounts for requests
already admitted earlier in the same tick.
"""

from __future__ import annotations

from typing import List, Optional, Sequence

from .request import Request, RequestState


class CapacityView:
    """Read-only admission oracle for one tick: slots + KV blocks,
    charged incrementally as the driver admits. When reserving output,
    LIVE requests' not-yet-materialised growth (admitted on an earlier
    tick, still decoding toward max_new_tokens) is charged too —
    otherwise the reservation only binds on the admitting tick and two
    requests admitted one tick apart can still exhaust the pool
    mid-decode."""

    def __init__(self, engine, reserve_output: bool = True,
                 live: Sequence[Request] = ()):
        self._engine = engine
        self._reserve_output = reserve_output
        self._admitted_uids: List[int] = []
        self._admitted_lens: List[int] = []
        self._live_reserved: dict = {}        # uid -> future-growth blocks
        if reserve_output:
            for r in live:
                seq = engine.seqs.get(r.uid)
                if seq is None:
                    continue
                need = engine.blocks_needed(len(r.prompt) + r.max_new_tokens)
                self._live_reserved[r.uid] = max(0, need - len(seq.blocks))

    def _length_for(self, req: Request) -> int:
        """Blocks to charge at admission: the resume context plus (when
        reserving) the whole remaining output, so a request admitted now
        cannot exhaust the pool mid-decode."""
        ctx = len(req.prompt) + len(req.tokens)
        if self._reserve_output:
            ctx += max(0, req.max_new_tokens - len(req.tokens))
        return ctx

    @property
    def free_slots(self) -> int:
        return (len(self._engine._free_slots)
                - len(self._admitted_uids))

    def fits(self, req: Request) -> bool:
        if self.free_slots < 1:
            return False
        if self._length_for(req) > self._engine.config.max_context:
            return False
        if not self._engine.can_schedule(
                self._admitted_uids + [req.uid],
                self._admitted_lens + [self._length_for(req)]):
            return False
        return self.blocks_short(req) <= 0

    def charge(self, req: Request) -> None:
        """Record an admission so later ``fits`` calls see the cost."""
        self._admitted_uids.append(req.uid)
        self._admitted_lens.append(self._length_for(req))

    def uncharge_live(self, req: Request) -> None:
        """Drop a live request's future-growth reservation (it was
        preempted this tick: its blocks and reservation are gone)."""
        self._live_reserved.pop(req.uid, None)

    def blocks_short(self, req: Request) -> int:
        """KV blocks missing for ``req`` (0 when it fits the pool),
        counting this tick's admissions AND live requests' reserved
        future growth. Drives how much the preemption pass must evict."""
        need = self._engine.blocks_needed(self._length_for(req))
        for length in self._admitted_lens:  # dslint: disable=races -- CapacityView is tick-local: built, charged and read on the single ticking thread inside one _admit pass, then dropped; it is never published to another thread (dsrace sees both driving roles, not the one-tick confinement)
            need += self._engine.blocks_needed(length)
        need += sum(self._live_reserved.values())
        return max(0, need - self._engine._available_blocks())

    # -- speculative token-credit math (docs/serving.md "Speculative
    # scheduling"): drafting consumes only token-budget SLACK, sized by
    # the class acceptance-rate EMA — the feed builder's one arithmetic,
    # tested directly.
    def draft_budget(self, n_decodes: int, prefill_tokens: int) -> int:
        """Token-budget slack draft chains may add this tick: the engine
        budget minus one guaranteed token per live decode minus the
        prefill backlog's claim (pending prompt tokens, capped at the
        budget — SplitFuse spreads longer prompts over later ticks, and
        every such tick re-runs this arithmetic). Prefill's claim comes
        off the top, so drafting can never starve prefill admission or
        progress; with zero slack the tick degrades to plain decode."""
        budget = self._engine.config.token_budget
        claim = min(max(0, int(prefill_tokens)), budget)
        return max(0, budget - max(0, int(n_decodes)) - claim)

    @staticmethod
    def chain_len_for(accept_ema: float, lookahead: int) -> int:
        """Per-request draft length under the class acceptance EMA:
        scale the configured lookahead by the EMA (rounded) — the class
        CREDIT, in tokens. A cold class keeps a ONE-token probe rather
        than freezing at zero: with no proposals the EMA could never
        update and the class would lose drafting for the server's whole
        lifetime — per-REQUEST hopelessness is the fallback latch's job
        (`spec_accept_floor`), the class credit only sizes chains."""
        if lookahead < 1:
            return 0
        c = min(1.0, max(0.0, float(accept_ema)))
        return max(1, min(int(lookahead), int(c * lookahead + 0.5)))

    def evictable_blocks(self, seq) -> int:
        """Pages that actually become schedulable if ``seq`` is evicted:
        those whose every non-cache reference is this sequence's own
        (they end up free, or cache-only-held — which admission reclaims
        on demand). Pages shared with another live sequence stay held
        and must not be credited, or preemption evicts decodes without
        making the candidate fit."""
        alloc = self._engine.allocator
        cache = self._engine.prefix_cache
        cache_refs = cache._block_refs if cache is not None else {}
        counts: dict = {}
        for b in seq.blocks:
            counts[int(b)] = counts.get(int(b), 0) + 1
        return sum(1 for b, n in counts.items()
                   if alloc.refcount(b) <= n + cache_refs.get(b, 0))

    @property
    def occupancy(self) -> float:
        return self._engine.kv_occupancy()


class SchedulerPolicy:
    """Base policy: order the queue; optionally reject and preempt."""

    name = "base"
    #: stop admitting at the first queued request that does not fit
    #: (True = strict FIFO semantics with head-of-line blocking)
    head_of_line_blocking = True

    def admission_order(self, queued: Sequence[Request],
                        now: float) -> List[Request]:
        raise NotImplementedError

    def should_reject(self, req: Request, now: float) -> Optional[str]:
        """Reject reason for a queued request, or None to keep it."""
        return None

    def preemption_victims(self, candidate: Request,
                           live: Sequence[Request],
                           capacity: CapacityView,
                           now: float) -> List[Request]:
        """Live requests to evict so ``candidate`` can be admitted.
        Empty list = do not preempt (candidate stays queued)."""
        return []


class FCFSPolicy(SchedulerPolicy):
    """First-come-first-served: the no-SLO baseline."""

    name = "fcfs"
    head_of_line_blocking = True

    def admission_order(self, queued, now):
        return sorted(queued, key=lambda r: (r.t_submit, r.uid))


class SLOPolicy(SchedulerPolicy):
    """Deadline-aware admission (priority tiers, then EDF) with expired-
    request rejection and preemption of lower-priority decodes under KV
    pressure or slot exhaustion."""

    name = "slo"
    head_of_line_blocking = False

    def __init__(self, kv_pressure: float = 0.90,
                 reject_expired: bool = True,
                 preemption: bool = True):
        # preempt only when the pool is genuinely tight — below this
        # occupancy a misfit is a transient (e.g. slot exhaustion) and
        # eviction would thrash the cache for nothing
        self.kv_pressure = float(kv_pressure)
        self.reject_expired = bool(reject_expired)
        self.preemption = bool(preemption)

    @staticmethod
    def _deadline_key(req: Request) -> float:
        dl = req.absolute_deadline()
        return dl if dl is not None else float("inf")

    def admission_order(self, queued, now):
        # higher priority first; within a tier, earliest deadline first
        # (EDF is optimal for feasible single-machine deadline schedules);
        # deadline-less requests trail their tier in arrival order
        return sorted(queued, key=lambda r: (-r.priority,
                                             self._deadline_key(r),
                                             r.t_submit, r.uid))

    def should_reject(self, req: Request, now: float) -> Optional[str]:
        if not self.reject_expired:
            return None
        dl = req.absolute_deadline()
        if dl is not None and now > dl:
            return "deadline expired in queue"
        if (req.ttft_deadline_s is not None and req.t_submit is not None
                and req.t_first_token is None
                and now > req.t_submit + req.ttft_deadline_s):
            # the SLO verdict requires EVERY deadline to hold, so a
            # missed TTFT is unsalvageable even with a live end-to-end
            # deadline: serving it is pure goodput loss
            return "ttft deadline expired in queue"
        return None

    def preemption_victims(self, candidate, live, capacity, now):
        if not self.preemption:
            return []
        # two distinct shortages trigger eviction: KV-pool pressure (the
        # occupancy gate keeps transient misfits from thrashing the cache)
        # and SLOT exhaustion — every sequence slot held by a
        # lower-priority decode. Slot shortage bypasses the occupancy
        # gate: one eviction frees exactly one slot, and without it a
        # high-priority arrival could starve behind low-priority decodes
        # while the KV pool sits half empty.
        slot_short = capacity.free_slots < 1
        if not slot_short and capacity.occupancy < self.kv_pressure:
            return []
        # victims: DECODE-state requests of strictly lower priority —
        # never equal-tier (thrash: two peers evicting each other), never
        # mid-prefill (their KV is the most expensive to rebuild per
        # token emitted so far). Latest deadline dies first.
        pool = [r for r in live
                if r.state is RequestState.DECODE
                and r.priority < candidate.priority]
        pool.sort(key=lambda r: (r.priority, -self._deadline_key(r),
                                 -(r.t_submit or 0.0)))
        short = capacity.blocks_short(candidate)
        victims: List[Request] = []
        freed = 0
        for r in pool:
            if freed >= short and (victims or not slot_short):
                break
            victims.append(r)
            # credit only pages that genuinely become schedulable —
            # pages shared with another live sequence stay held — plus
            # the victim's reserved-but-unmaterialised future growth
            seq = capacity._engine.seqs.get(r.uid)
            freed += capacity.evictable_blocks(seq) if seq is not None else 0
            freed += capacity._live_reserved.get(r.uid, 0)
        if freed < short or (slot_short and not victims):
            return []          # evicting would not make the candidate fit
        return victims


def make_policy(name: str, **kwargs) -> SchedulerPolicy:
    """Policy factory for config-driven selection."""
    if name == "fcfs":
        return FCFSPolicy()
    if name == "slo":
        return SLOPolicy(**kwargs)
    raise ValueError(f"unknown scheduler policy '{name}' "
                     "(expected 'fcfs' or 'slo')")
