"""Request descriptor and lifecycle state machine for the serving layer.

A :class:`Request` is the unit the serving front-end schedules: one
prompt, one output stream, one SLO. The state machine is the contract
every scheduler policy and the driver loop must respect:

    QUEUED -> PREFILL -> DECODE -> FINISHED
       |         |          |
       |         +----------+--> QUEUED     (preemption / tick-fault retry)
       |         |          |
       +---------+----------+--> CANCELLED  (user cancel; fault budget spent)
       |
       +--> REJECTED                        (full queue; hopeless deadline)

FINISHED / CANCELLED / REJECTED are terminal; any other transition is a
programming error and raises :class:`InvalidTransition` instead of
silently corrupting accounting. The re-queue edge (preemption) carries
the tokens generated so far: on re-admission the engine prefills
``prompt + emitted`` — with the prefix cache on, mostly from cached KV
pages — and greedy decode continues the stream bit-exactly.

The reference's serving front-end (MII / FastGen,
``mii/batching/ragged_batching.py``) tracks the same lifecycle across
several ad-hoc queues; here it is one explicit, validated enum.
"""

from __future__ import annotations

import enum
import itertools
import threading
from dataclasses import dataclass, field
from typing import Callable, List, Optional

from ..resilience.clock import get_clock


class RequestState(enum.Enum):
    QUEUED = "queued"        # submitted, not yet admitted to the engine
    PREFILL = "prefill"      # admitted; prompt KV being built (SplitFuse)
    DECODE = "decode"        # prompt done; generating one token per tick
    FINISHED = "finished"    # max_new_tokens or EOS reached
    CANCELLED = "cancelled"  # user cancel or fault budget exhausted
    REJECTED = "rejected"    # never admitted (full queue / hopeless SLO)


TERMINAL_STATES = frozenset(
    {RequestState.FINISHED, RequestState.CANCELLED, RequestState.REJECTED})

_VALID_TRANSITIONS = {
    RequestState.QUEUED: {RequestState.PREFILL, RequestState.CANCELLED,
                          RequestState.REJECTED},
    RequestState.PREFILL: {RequestState.DECODE, RequestState.QUEUED,
                           RequestState.CANCELLED},
    RequestState.DECODE: {RequestState.FINISHED, RequestState.QUEUED,
                          RequestState.CANCELLED},
    RequestState.FINISHED: set(),
    RequestState.CANCELLED: set(),
    RequestState.REJECTED: set(),
}


class InvalidTransition(RuntimeError):
    """An illegal request state transition (driver/scheduler bug)."""


_uid_counter = itertools.count(1)


@dataclass
class Request:
    """One serving request: prompt in, token stream out, SLO attached.

    ``priority`` — larger is more important; the SLO policy admits higher
    tiers first and preempts lower tiers under KV pressure. ``deadline_s``
    / ``ttft_deadline_s`` are RELATIVE to submission; absolute clocks are
    derived at submit time. ``on_token`` is invoked from the driver thread
    once per emitted token — it must be cheap and must not call back into
    the serving engine (deadlock: the driver holds the engine lock).
    """

    prompt: List[int]
    max_new_tokens: int = 128
    eos_token_id: Optional[int] = None
    priority: int = 0
    deadline_s: Optional[float] = None       # end-to-end SLO, from submit
    ttft_deadline_s: Optional[float] = None  # first-token SLO, from submit
    on_token: Optional[Callable[[int], None]] = None
    uid: int = field(default_factory=lambda: next(_uid_counter))
    # stable LOGICAL id: survives re-routing, fail-over and prefill→decode
    # hand-off across replicas, so one request is one id in requests.jsonl
    # no matter how many engines touched it. Defaults to a uid-derived
    # string; callers pass their own to correlate with client-side logs.
    client_request_id: Optional[str] = None
    # tenant key for canary routing (serving/rollout.py): requests from
    # one tenant land on one side of the canary split for the whole
    # rollout — a tenant never sees the version ping-pong a per-request
    # coin flip would produce. None falls back to client_request_id.
    tenant: Optional[str] = None

    # -- lifecycle bookkeeping (driver-owned; read-only for callers) ----
    state: RequestState = RequestState.QUEUED
    tokens: List[int] = field(default_factory=list)   # emitted so far
    error: Optional[str] = None
    preemptions: int = 0
    retries: int = 0          # tick-fault re-queues (distinct from preempts)
    # speculative-decoding ledger (serving tick, docs/serving.md):
    # draft tokens proposed/accepted for THIS request across its whole
    # life (they travel with it through failover/hand-off) — stamped
    # into the terminal RequestStats record
    spec_proposed: int = 0
    spec_accepted: int = 0
    # model-version ledger (serving/rollout.py): ``model_version`` is the
    # version this request was ROUTED to (stamped at placement; may be
    # re-stamped while no tokens are out yet), ``served_versions`` the
    # distinct versions that actually EMITTED tokens, in order — the DST
    # two-version-stream invariant audits len(set(served_versions)) <= 1
    model_version: Optional[int] = None
    served_versions: List[int] = field(default_factory=list)
    t_submit: Optional[float] = None     # clock.now() stamps
    t_admit: Optional[float] = None      # last admission (re-set on resume)
    t_first_admit: Optional[float] = None
    t_first_token: Optional[float] = None
    t_finish: Optional[float] = None

    def __post_init__(self):
        self.prompt = [int(t) for t in self.prompt]
        if not self.prompt:
            raise ValueError("Request needs a non-empty prompt")
        if self.max_new_tokens < 1:
            raise ValueError(
                f"max_new_tokens must be >= 1, got {self.max_new_tokens}")
        if self.client_request_id is None:
            self.client_request_id = f"req-{self.uid:08d}"
        elif not isinstance(self.client_request_id, str):
            raise ValueError("client_request_id must be a string")
        if self.tenant is not None and not isinstance(self.tenant, str):
            raise ValueError("tenant must be a string")
        self._done = threading.Event()
        # the clock this request's whole lifecycle is timed on, captured
        # at construction: deadlines, terminal stamps and SLO verdicts
        # must all read ONE timebase even if the global seam is swapped
        # mid-flight (a request submitted under a SimClock is judged
        # under it to the end)
        self._clock = get_clock()
        # driver-internal: the next token to feed the engine (produced by
        # the previous tick's logits, not yet admitted as context)
        self._pending_token: Optional[int] = None
        self._cancel_requested = False
        # fleet-internal: hand this request from its prefill replica to a
        # decode replica once its first token resolves (disaggregated mode)
        self._handoff_requested = False
        # routing witness: the SOFT canary/stable version preference had
        # no accepting capacity and this request spilled to whatever
        # could serve (availability over version affinity) — the DST
        # per-tenant monotonicity auditor exempts spilled requests
        self._canary_spilled = False
        # speculative-decoding driver state: rolling per-request
        # acceptance EMA (optimistic start — a fresh request gets full
        # drafts until it proves unpredictable) and the per-request
        # fallback latch (below the configured floor drafting stops for
        # good; the stream stays token-identical either way)
        self._spec_ema = 1.0
        self._spec_disabled = False
        # distributed tracing (telemetry/tracing.py): the request's open
        # root span and its current lifecycle segment. Both stay None
        # with tracing off; the tree travels WITH the request across
        # replicas (failover, disaggregated hand-off) so its whole life
        # is one connected trace.
        self._trace_root = None
        self._trace_seg = None

    # -- state machine --------------------------------------------------
    def transition(self, new: RequestState) -> None:
        if new not in _VALID_TRANSITIONS[self.state]:
            raise InvalidTransition(
                f"request {self.uid}: illegal transition "
                f"{self.state.name} -> {new.name}")
        self.state = new  # dslint: disable=races -- single-owner protocol (docs/serving.md "Threading model"): a request is mutated only by its CURRENT owner — the owning replica's ticking thread, or the harvesting fleet/region thread strictly after kill() has joined the old owner's driver; dsrace sees the many owner roles but not the ownership hand-off ordering between them
        if new in TERMINAL_STATES:
            self.t_finish = self._clock.now()  # dslint: disable=races -- single-owner protocol (see state above): terminal stamps are written once by the retiring owner before _done publishes them; waiters read them only after _done.set()
            self._done.set()

    @property
    def is_terminal(self) -> bool:
        return self.state in TERMINAL_STATES

    @property
    def is_live(self) -> bool:
        """Admitted to the engine (holds a slot + KV blocks)."""
        return self.state in (RequestState.PREFILL, RequestState.DECODE)

    # -- deadlines ------------------------------------------------------
    def absolute_deadline(self) -> Optional[float]:
        if self.deadline_s is None or self.t_submit is None:
            return None
        return self.t_submit + self.deadline_s

    def in_slo(self, now: Optional[float] = None) -> Optional[bool]:
        """Whether the request met its SLO (None when it carries none).
        For a finished request this judges the finish time; for a live
        one, whether the SLO is still achievable as of ``now``."""
        dl = self.absolute_deadline()
        verdicts = []
        if dl is not None:
            t = self.t_finish if self.t_finish is not None else \
                (now if now is not None else self._clock.now())
            verdicts.append(t <= dl)
        if self.ttft_deadline_s is not None and self.t_submit is not None:
            t = self.t_first_token
            if t is None:
                t = now if now is not None else self._clock.now()
            verdicts.append(t <= self.t_submit + self.ttft_deadline_s)
        if not verdicts:
            return None
        return all(verdicts)

    # -- results --------------------------------------------------------
    def wait(self, timeout: Optional[float] = None) -> bool:
        """Block until terminal. Returns False on timeout. Waits on the
        request's clock: under a SimClock this pumps the simulation's
        drive function instead of parking the thread."""
        return self._clock.wait_event(self._done, timeout)

    def result(self, timeout: Optional[float] = None) -> List[int]:
        """Wait and return the emitted tokens. Raises on non-FINISHED
        terminal states (cancelled / rejected requests have no result)."""
        if not self.wait(timeout):
            raise TimeoutError(f"request {self.uid} still {self.state.name}")
        if self.state is not RequestState.FINISHED:
            raise RuntimeError(
                f"request {self.uid} ended {self.state.name}"
                + (f": {self.error}" if self.error else ""))
        return list(self.tokens)

    # -- spans ----------------------------------------------------------
    @property
    def queue_wait_s(self) -> Optional[float]:
        if self.t_submit is None or self.t_first_admit is None:
            return None
        return self.t_first_admit - self.t_submit

    @property
    def ttft_s(self) -> Optional[float]:
        if self.t_submit is None or self.t_first_token is None:
            return None
        return self.t_first_token - self.t_submit

    @property
    def latency_s(self) -> Optional[float]:
        if self.t_submit is None or self.t_finish is None:
            return None
        return self.t_finish - self.t_submit
