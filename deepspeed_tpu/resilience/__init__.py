"""Resilience primitives: retries, restart accounting, preemption capture.

TPU pods get preempted and collectives occasionally wedge; production
training survives by retrying transient failures, restarting from the
latest checkpoint (launcher/agent.py ElasticAgent), and draining cleanly
on a preemption signal. Every such event is counted in the shared
telemetry registry (``resilience/*`` series) so restart storms are
visible in the same exporters as step time.
"""

from .retry import RetryError, RetryPolicy, retry_call  # noqa: F401
from .preemption import PreemptionGuard  # noqa: F401
from .counters import (  # noqa: F401
    record_failure,
    record_restart,
    record_retry,
    restart_count_from_env,
)
