"""Resilience primitives: retries, restart accounting, preemption capture,
divergence guards, and deterministic fault injection.

TPU pods get preempted and collectives occasionally wedge; production
training survives by retrying transient failures, restarting from the
latest checkpoint (launcher/agent.py ElasticAgent), draining cleanly on a
preemption signal, and refusing to stream NaNs into the optimizer state.
Every such event is counted in the shared telemetry registry
(``resilience/*`` series) so restart storms are visible in the same
exporters as step time. The chaos harness (:mod:`.chaos`) makes each
failure mode a seeded, deterministic event so the recovery paths stay
tested (tests/test_fault_tolerance.py, scripts/chaos_smoke.py).
"""

from .clock import (  # noqa: F401
    Clock,
    SimClock,
    WallClock,
    get_clock,
    set_clock,
    use_clock,
)
from .locksan import (  # noqa: F401
    DOCUMENTED_LOCK_ORDER,
    LockOrderViolation,
    LockSanitizer,
    get_locksan,
    install_locksan,
    named_lock,
    named_rlock,
    use_locksan,
)
from .retry import RetryBudget, RetryError, RetryPolicy, retry_call  # noqa: F401
from .preemption import PreemptionGuard  # noqa: F401
from .divergence import DivergenceError, DivergenceGuard  # noqa: F401
from .chaos import (  # noqa: F401
    CollectiveFault,
    FaultInjector,
    InjectedFault,
    TickFault,
    corrupt_tag,
    get_fault_injector,
    install_fault_injector,
)
from .counters import (  # noqa: F401
    record_attempt,
    record_emergency_save,
    record_failure,
    record_restart,
    record_retry,
    record_rollback,
    restart_count_from_env,
)
