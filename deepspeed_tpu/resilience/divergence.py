"""Divergence guards: NaN/Inf and loss-spike detection for the step path.

A production run that NaNs at 3am must not burn its remaining budget
streaming NaNs into the optimizer state. Two detectors, per config
(``resilience.divergence`` — config.py):

* **NaN/Inf guard** — ``nan_action``:
  - ``"skip"`` compiles into the train step itself: the non-finite check
    reuses the fp16 overflow machinery in ``TrainEngine._update`` (grads
    checked, ``where`` keeps old params/opt state), so a NaN step is
    dropped on-device with ZERO extra host synchronization;
  - ``"rollback"`` / ``"halt"`` run host-side: the engine fetches the loss
    each step (one host sync — the guard's documented cost) and either
    reloads the newest valid checkpoint or raises :class:`DivergenceError`.
* **Loss-spike guard** — ``spike_action`` ``"warn" | "rollback" | "halt"``:
  flags any finite loss exceeding ``spike_factor`` x the rolling median of
  recent losses (the telemetry stall-detector shape — median, not mean, so
  one spike can't poison the baseline it is judged against; compile/warmup
  noise absorbed by ``warmup_steps``). Spikes cannot be "skipped": the
  update is already applied by the time the host sees the loss, so the
  honest recovery is a rollback to the last checkpoint.

With every action ``"off"`` the engine constructs no guard and the step
path is byte-identical to the unguarded one.
"""

from __future__ import annotations

import math
import statistics
from collections import deque
from typing import Deque, Optional, Tuple

from ..utils.logging import logger

NAN_ACTIONS = ("off", "skip", "rollback", "halt")
SPIKE_ACTIONS = ("off", "warn", "rollback", "halt")


class DivergenceError(RuntimeError):
    """Raised when a guard's action is 'halt' (or a rollback is impossible)."""


class DivergenceGuard:
    """Host-side detector: feed it each step's loss; it returns the
    triggered ``(kind, action)`` or None.

    ``observe`` appends finite losses to the window *after* judging them
    (a genuine regime change flags once, then the median adapts); non-
    finite losses never enter the window, so a NaN burst can't drag the
    spike baseline to NaN.
    """

    def __init__(self, nan_action: str = "halt", spike_action: str = "off",
                 spike_factor: float = 10.0, window: int = 20,
                 warmup_steps: int = 5):
        if nan_action not in NAN_ACTIONS:
            raise ValueError(f"nan_action must be one of {NAN_ACTIONS}, "
                             f"got {nan_action!r}")
        if spike_action not in SPIKE_ACTIONS:
            raise ValueError(f"spike_action must be one of {SPIKE_ACTIONS}, "
                             f"got {spike_action!r}")
        if spike_action != "off" and spike_factor <= 1.0:
            raise ValueError(
                f"spike_factor must exceed 1.0, got {spike_factor}")
        self.nan_action = nan_action
        self.spike_action = spike_action
        self.spike_factor = float(spike_factor)
        self.warmup_steps = int(warmup_steps)
        self._window: Deque[float] = deque(maxlen=max(2, int(window)))
        self._seen = 0
        self.nan_count = 0
        self.spike_count = 0

    def reset(self) -> None:
        """Clear the baseline (after a rollback: the pre-divergence window
        no longer describes the restored trajectory's neighborhood)."""
        self._window.clear()
        self._seen = 0

    def observe(self, step: int, loss: float) -> Optional[Tuple[str, str]]:
        if not math.isfinite(loss):
            self.nan_count += 1
            logger.warning(f"divergence: non-finite loss {loss} at step {step}")
            # 'skip' is handled inside the compiled step (the engine's
            # traced finite-check already kept the old params); 'off' means
            # the user accepted NaNs — neither needs host action
            if self.nan_action in ("rollback", "halt"):
                return ("nan", self.nan_action)
            return None
        verdict: Optional[Tuple[str, str]] = None
        self._seen += 1
        if (self.spike_action != "off" and self._seen > self.warmup_steps
                and len(self._window) >= 2):
            median = statistics.median(self._window)
            if loss > self.spike_factor * median:
                self.spike_count += 1
                logger.warning(
                    f"divergence: loss spike at step {step}: {loss:.4g} > "
                    f"{self.spike_factor:g}x rolling median {median:.4g}")
                verdict = ("spike", self.spike_action)
        self._window.append(loss)
        return verdict
