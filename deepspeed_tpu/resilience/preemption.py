"""Preemption signal capture: drain at the next step boundary.

Cloud TPU preemptions deliver SIGTERM with a grace window. The guard
latches the signal into a flag the training loop polls between steps
(``should_stop``) — checkpoint, flush telemetry, exit cleanly — instead
of dying mid-step with an unflushed monitor and a torn checkpoint.
"""

from __future__ import annotations

import signal
import threading
from typing import Callable, Iterable, Optional

from ..telemetry.registry import get_registry
from ..utils.logging import logger


class PreemptionGuard:
    """Latch SIGTERM/SIGINT (configurable) into a poll-able stop flag.

    Use as a context manager around the training loop; previous handlers
    are restored on exit. Only valid from the main thread (signal module
    restriction); elsewhere it degrades to a manually-set flag.
    """

    def __init__(self, signals: Iterable[int] = (signal.SIGTERM,),
                 on_preempt: Optional[Callable[[int], None]] = None):
        self.signals = tuple(signals)
        self.on_preempt = on_preempt
        self.last_signal: Optional[int] = None  # which signal latched us
        self._stop = threading.Event()
        self._pending: list = []  # signums not yet counted (see below)
        self._previous = {}

    @property
    def should_stop(self) -> bool:
        # registry counting is deferred from the handler to this poll: the
        # registry/Counter locks are plain (non-reentrant) threading.Locks,
        # and a handler firing while the step path holds one would deadlock
        # the main thread. List append is GIL-atomic; draining here runs in
        # normal (interruptible-but-lock-safe) context.
        while self._pending:
            signum = self._pending.pop(0)
            get_registry().counter("resilience/preemptions").inc()
            try:
                name = signal.Signals(signum).name
            except ValueError:
                name = str(signum)
            get_registry().counter(f"resilience/preemptions/{name}").inc()
        return self._stop.is_set()

    def request_stop(self) -> None:
        """Manual trigger (tests; non-signal preemption notices)."""
        self._stop.set()

    def _handler(self, signum, frame) -> None:
        logger.warning(f"preemption signal {signum} received; draining at "
                       f"the next step boundary")
        self.last_signal = signum
        self._pending.append(signum)
        self._stop.set()
        if self.on_preempt is not None:
            self.on_preempt(signum)

    def __enter__(self) -> "PreemptionGuard":
        try:
            for s in self.signals:
                self._previous[s] = signal.signal(s, self._handler)
        except ValueError:  # not the main thread
            logger.warning("PreemptionGuard: not on the main thread; "
                           "signals not hooked (flag-only mode)")
            self._previous.clear()
        return self

    def __exit__(self, *exc) -> None:
        for s, prev in self._previous.items():
            signal.signal(s, prev)
        self._previous.clear()
