"""Restart/retry/failure counters in the shared telemetry registry."""

from __future__ import annotations

import os

from ..telemetry.registry import get_registry


def record_restart(n: int = 1) -> None:
    """Count a worker restart (ElasticAgent calls this per relaunch)."""
    get_registry().counter("resilience/restarts").inc(n)


def record_retry(op: str = "default") -> None:
    get_registry().counter(f"resilience/retries/{op}").inc()


def record_attempt(op: str = "default") -> None:
    """Count every retry_call attempt (first tries included), so attempt
    volume and retry volume can be ratioed into a flakiness rate."""
    get_registry().counter(f"resilience/attempts/{op}").inc()


def record_rollback() -> None:
    """Count a divergence-triggered rollback to the last checkpoint."""
    get_registry().counter("resilience/rollbacks").inc()


def record_emergency_save() -> None:
    """Count a preemption-triggered emergency checkpoint."""
    get_registry().counter("resilience/emergency_saves").inc()


def record_failure(op: str = "default") -> None:
    get_registry().counter(f"resilience/failures/{op}").inc()


def restart_count_from_env() -> int:
    """The restart generation this process is running as, from the
    ``DST_ELASTIC_RESTART`` env the ElasticAgent exports. A trainee calls
    this once at startup to seed its restart gauge — the agent's own
    counter lives in the agent process, not here."""
    try:
        n = int(os.environ.get("DST_ELASTIC_RESTART", "0"))
    except ValueError:
        return 0
    if n > 0:
        get_registry().gauge("resilience/restart_generation").set(n)
    return n
