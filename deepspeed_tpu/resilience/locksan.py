"""Runtime lock-order sanitizer — the dynamic half of dsrace.

dslint's lock-discipline and races rules model locks statically; this
module checks what threads actually DO. When a :class:`LockSanitizer`
is installed (tests, the DST soak's sanitizer leg — never production),
the serving tier's locks — built through :func:`named_lock` /
:func:`named_rlock` instead of bare ``threading.Lock()`` — become
instrumented wrappers that record every acquisition:

* **order** — acquiring lock B while holding lock A records the edge
  ``A -> B``. Edges between documented tiers are checked against the
  region -> cell -> fleet -> replica order (docs/serving.md); an
  inversion is a violation.
* **cycles** — every new edge runs a DFS over the accumulated edge
  graph; a cycle is a deadlock two schedules away, flagged immediately
  with the virtual-time stamp of the closing edge (the DST soak runs
  on ``SimClock``, so "when" is deterministic).
* **same-tier nesting** — two different INSTANCES of the same lock
  name held together (replica lock under replica lock) has no defined
  order and is flagged.
* **self-deadlock** — re-acquiring a held non-reentrant ``Lock``
  raises immediately instead of hanging the run.

Cross-validation (scripts/race_lane.py, the dst_soak sanitizer leg):
every runtime-observed edge must exist in dslint's static lock graph
(:func:`deepspeed_tpu.analysis.rules.locks.collect_lock_graph`) — a
miss means the static model has a false negative and fails the lane —
and the static graph's documented-tier edges must be exercised by the
soak (the coverage half of the report).

With no sanitizer installed, :func:`named_lock`/:func:`named_rlock`
return plain ``threading`` primitives: zero production overhead, and
dslint's model treats the construction seam as the lock it wraps.
"""

from __future__ import annotations

import contextlib
import threading
from dataclasses import dataclass, field
from typing import Dict, Iterator, List, Optional, Sequence, Set, Tuple

from .clock import get_clock

#: the documented serving-tier lock order, outermost first — mirrored
#: from analysis/rules/locks.py (suffix-matched display names)
DOCUMENTED_LOCK_ORDER: Sequence[str] = (
    "Region._lock",
    "ServingCell._lock",
    "ServingFleet._lock",
    "ServingEngine._lock",
)


class LockOrderViolation(RuntimeError):
    """Raised on acquisition in strict mode (and always for a
    self-deadlock, which would otherwise hang the process)."""


@dataclass
class EdgeInfo:
    outer: str
    inner: str
    count: int = 0
    first_vt: float = 0.0       # clock.now() at first observation
    threads: Set[str] = field(default_factory=set)


class LockSanitizer:
    """Acquisition-order recorder + checker. Thread-safe; its own
    bookkeeping is guarded by a private raw mutex (never itself
    sanitized)."""

    def __init__(self, order: Sequence[str] = DOCUMENTED_LOCK_ORDER,
                 strict: bool = False) -> None:
        self.order = tuple(order)
        self.strict = strict
        self.edges: Dict[Tuple[str, str], EdgeInfo] = {}
        self.violations: List[Dict[str, object]] = []
        self.acquires: Dict[str, int] = {}
        self._graph: Dict[str, Set[str]] = {}
        self._mu = threading.Lock()
        self._tls = threading.local()

    # -- per-thread held stack -------------------------------------------
    def _held(self) -> List[Tuple[int, str]]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = []
            self._tls.stack = stack
        return stack

    def _order_pos(self, name: str) -> Optional[int]:
        for i, suffix in enumerate(self.order):
            if name == suffix or name.endswith("." + suffix):
                return i
        return None

    def _violation(self, kind: str, **fields) -> None:
        rec = {"kind": kind, "vt": get_clock().now(),
               "thread": threading.current_thread().name, **fields}
        with self._mu:
            self.violations.append(rec)
        if self.strict:
            raise LockOrderViolation(f"{kind}: {fields}")

    def _find_cycle(self, start: str, target: str) -> Optional[List[str]]:
        """Path target -> ... -> start in the edge graph (caller adds
        start -> target, closing the cycle). Caller holds _mu."""
        stack = [(target, [target])]
        seen: Set[str] = set()
        while stack:
            cur, path = stack.pop()
            if cur == start:
                return path
            if cur in seen:
                continue
            seen.add(cur)
            for nxt in sorted(self._graph.get(cur, ())):
                stack.append((nxt, path + [nxt]))
        return None

    # -- wrapper callbacks ------------------------------------------------
    def on_acquired(self, lock: "_SanLockBase") -> None:
        """Called by a wrapper AFTER its real lock is acquired."""
        held = self._held()
        name = lock.san_name
        with self._mu:
            self.acquires[name] = self.acquires.get(name, 0) + 1
        if any(ident == id(lock) for ident, _ in held):
            # re-entrant acquire of the same instance: no new edges
            held.append((id(lock), name))
            return
        vt = get_clock().now()
        outer_names = []
        seen: Set[str] = set()
        for ident, outer in held:
            if outer in seen:
                continue
            seen.add(outer)
            outer_names.append(outer)
        for outer in outer_names:
            if outer == name:
                # a DIFFERENT instance with the same name: same-tier
                # nesting has no defined order (replica under replica)
                self._violation("same-tier-nesting", lock=name)
                continue
            new_edge = False
            cycle = None
            with self._mu:
                info = self.edges.get((outer, name))
                if info is None:
                    info = EdgeInfo(outer=outer, inner=name, first_vt=vt)
                    self.edges[(outer, name)] = info
                    new_edge = True
                info.count += 1
                info.threads.add(threading.current_thread().name)
                if new_edge:
                    cycle = self._find_cycle(outer, name)
                    self._graph.setdefault(outer, set()).add(name)
            po, pi = self._order_pos(outer), self._order_pos(name)
            if po is not None and pi is not None and pi < po:
                self._violation("order-inversion", outer=outer,
                                inner=name,
                                documented=" -> ".join(self.order))
            if cycle is not None:
                self._violation(
                    "lock-cycle",
                    cycle=" -> ".join([outer] + cycle))
        held.append((id(lock), name))

    def on_released(self, lock: "_SanLockBase") -> None:
        held = self._held()
        for i in range(len(held) - 1, -1, -1):
            if held[i][0] == id(lock):
                del held[i]
                return
        self._violation("release-unheld", lock=lock.san_name)

    def held_names(self) -> List[str]:
        """This thread's currently held lock names, outermost first."""
        return [name for _, name in self._held()]

    # -- reporting --------------------------------------------------------
    def edge_pairs(self) -> Set[Tuple[str, str]]:
        with self._mu:
            return set(self.edges)

    def report(self) -> Dict[str, object]:
        with self._mu:
            return {
                "edges": [{"outer": e.outer, "inner": e.inner,
                           "count": e.count, "first_vt": e.first_vt,
                           "threads": sorted(e.threads)}
                          for e in sorted(self.edges.values(),
                                          key=lambda e: (e.outer,
                                                         e.inner))],
                "violations": list(self.violations),
                "acquires": dict(sorted(self.acquires.items())),
                "order": list(self.order),
            }


class _SanLockBase:
    """Shared wrapper shape over a real threading lock. Supports the
    ``with`` protocol plus acquire/release/locked, which is everything
    the serving tier uses."""

    _REENTRANT = False

    def __init__(self, name: str, san: LockSanitizer) -> None:
        self.san_name = name
        self._san = san
        self._real = (threading.RLock() if self._REENTRANT
                      else threading.Lock())

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        if not self._REENTRANT:
            held = self._san._held()
            if any(ident == id(self) for ident, _ in held):
                # acquiring a held non-reentrant Lock deadlocks for
                # real — surface it instead of hanging the run
                self._san._violation("self-deadlock", lock=self.san_name)
                raise LockOrderViolation(
                    f"self-deadlock on non-reentrant {self.san_name}")
        got = self._real.acquire(blocking, timeout)
        if got:
            try:
                self._san.on_acquired(self)
            except LockOrderViolation:
                # strict mode raised mid-bookkeeping: the stack entry
                # was never pushed, so release the REAL lock before
                # propagating — a caught strict violation must leave no
                # lock held and no inconsistent per-thread stack
                self._real.release()
                raise
        return got

    def release(self) -> None:
        try:
            self._san.on_released(self)
        finally:
            # a strict-mode release-unheld raise must still release the
            # real lock (it was held by contract of calling release)
            self._real.release()

    def locked(self) -> bool:
        return self._real.locked()

    def __enter__(self) -> "_SanLockBase":
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()


class SanLock(_SanLockBase):
    _REENTRANT = False


class SanRLock(_SanLockBase):
    _REENTRANT = True

    def locked(self) -> bool:          # RLock has no .locked() pre-3.12
        if self._real.acquire(blocking=False):
            self._real.release()
            return False
        return True


# ----------------------------------------------------------------------
_SANITIZER: Optional[LockSanitizer] = None


def get_locksan() -> Optional[LockSanitizer]:
    return _SANITIZER


def install_locksan(san: Optional[LockSanitizer]) -> Optional[LockSanitizer]:
    """Install (or, with None, remove) the process-global sanitizer.
    Only locks CONSTRUCTED while a sanitizer is installed are
    instrumented — install before building the stack under test."""
    global _SANITIZER
    prev = _SANITIZER
    _SANITIZER = san
    return prev


@contextlib.contextmanager
def use_locksan(order: Sequence[str] = DOCUMENTED_LOCK_ORDER,
                strict: bool = False) -> Iterator[LockSanitizer]:
    """Scoped sanitizer install — the DST soak / test entry seam:

        with use_locksan() as san:
            report = run_schedule(schedule)
        assert not san.violations
    """
    san = LockSanitizer(order=order, strict=strict)
    prev = install_locksan(san)
    try:
        yield san
    finally:
        install_locksan(prev)


def named_lock(name: str):
    """A ``threading.Lock`` — or, when a sanitizer is installed, an
    instrumented wrapper reporting to it under ``name`` (the static
    lock model's display name, e.g. ``"ServingEngine._lock"``)."""
    san = _SANITIZER
    if san is None:
        return threading.Lock()
    return SanLock(name, san)


def named_rlock(name: str):
    """A ``threading.RLock`` — or its instrumented wrapper (see
    :func:`named_lock`)."""
    san = _SANITIZER
    if san is None:
        return threading.RLock()
    return SanRLock(name, san)
