"""The clock seam: every timing decision goes through an injectable clock.

Wall-clock reads scattered through the serving / resilience / telemetry
layers (``time.perf_counter`` deadlines, ``time.sleep`` backoffs, raw
``Event.wait`` polls) are what make failure-handling untestable: a test
either races real time (flaky) or sleeps through it (slow), and every
evidence lane has to build jitter-tolerance bands around host noise.
This module is the single seam that removes the problem at the root:

* :class:`Clock` — the protocol every timing consumer uses: ``now()``
  (monotonic seconds, the deadline/latency timebase), ``time()`` (epoch
  seconds, the telemetry-timestamp timebase), ``sleep()``, and
  ``wait_event()`` (the clocked replacement for ``threading.Event.wait``).
* :class:`WallClock` — production behavior, byte-for-byte the calls the
  code made before the seam existed.
* :class:`SimClock` — a virtual-time event loop for deterministic
  simulation testing (:mod:`.dst`): time advances only when the program
  says so, timers fire in order at exact virtual instants, and blocking
  waits *pump* a registered drive function instead of parking a thread.
  Two runs of the same seeded schedule see bit-identical timestamps.

Consumers hold a clock (constructor-injected, defaulting to
:func:`get_clock`) or call :func:`get_clock` at use time. Tests install a
``SimClock`` via :func:`set_clock` / :func:`use_clock`. The dslint
``wall-clock`` rule enforces that no code in ``serving/``,
``resilience/`` or ``telemetry/`` bypasses this seam (this module is the
one exemption — it IS the seam).
"""

from __future__ import annotations

import contextlib
import heapq
import itertools
import threading
import time
from typing import Callable, Iterator, List, Optional, Tuple


class Clock:
    """Injectable time source + waiter (see module docstring)."""

    def now(self) -> float:
        """Monotonic seconds — the timebase for deadlines and latencies.
        Only differences are meaningful."""
        raise NotImplementedError

    def time(self) -> float:
        """Epoch seconds — the timebase for telemetry timestamps."""
        raise NotImplementedError

    def sleep(self, seconds: float) -> None:
        raise NotImplementedError

    def deadline(self, timeout: float) -> float:
        """Absolute ``now()``-based deadline ``timeout`` seconds out."""
        return self.now() + timeout

    def wait_event(self, event: threading.Event,
                   timeout: Optional[float] = None) -> bool:
        """Clocked ``event.wait``: True when the event is set before
        ``timeout`` (clock) seconds elapse."""
        raise NotImplementedError


class WallClock(Clock):
    """Production clock: real monotonic/epoch time, real sleeps."""

    def now(self) -> float:
        return time.perf_counter()

    def time(self) -> float:
        return time.time()

    def sleep(self, seconds: float) -> None:
        time.sleep(seconds)

    def wait_event(self, event: threading.Event,
                   timeout: Optional[float] = None) -> bool:
        return event.wait(timeout)


class SimClock(Clock):
    """Virtual-time event loop for deterministic simulation.

    ``now()`` returns virtual seconds since construction; nothing moves
    until :meth:`advance` (or a clocked ``sleep``/``wait_event``) is
    called. :meth:`call_at` schedules callbacks on a timer heap; an
    ``advance`` that crosses their due times fires them IN ORDER with
    ``now()`` set to each timer's exact instant — so causality inside
    the simulation is a pure function of the schedule, never of host
    scheduling.

    ``pump`` is the single-threaded substitute for background threads: a
    drive function (e.g. ``fleet.step``) that blocking waits invoke while
    virtual time passes. Re-entrant pumping is suppressed (a sleep inside
    a pumped step only advances time) because the driven code — one
    serving tick — is not re-entrant.

    Virtual time is monotone by construction; :meth:`advance` rejects
    negative deltas instead of silently rewinding history.
    """

    #: cap for ``wait_event(timeout=None)``: a simulated wait-forever on
    #: an event nothing will ever set must terminate, not loop eternally
    max_untimed_wait: float = 1e6

    def __init__(self, start: float = 0.0,
                 epoch: float = 1_700_000_000.0) -> None:
        self._now = float(start)
        self._epoch = float(epoch)
        self._timers: List[Tuple[float, int, Callable[[], None]]] = []
        self._timer_seq = itertools.count()
        self.pump: Optional[Callable[[], object]] = None
        self._pumping = False
        #: total virtual seconds ever advanced (monotony audit surface)
        self.ticks_fired = 0

    # -- time -----------------------------------------------------------
    def now(self) -> float:
        return self._now

    def time(self) -> float:
        return self._epoch + self._now

    def advance(self, seconds: float) -> None:
        """Move virtual time forward, firing due timers in order."""
        if seconds < 0:
            raise ValueError(f"virtual time cannot rewind ({seconds})")
        target = self._now + seconds
        while self._timers and self._timers[0][0] <= target:
            t, _, fn = heapq.heappop(self._timers)
            self._now = max(self._now, t)   # exact due instant
            fn()
        self._now = target

    def call_at(self, when: float, fn: Callable[[], None]) -> None:
        """Schedule ``fn`` to fire when virtual time reaches ``when``."""
        if when < self._now:
            raise ValueError(
                f"cannot schedule at {when}: virtual time is {self._now}")
        heapq.heappush(self._timers, (float(when), next(self._timer_seq), fn))

    # -- blocking surfaces ----------------------------------------------
    #: sentinel distinguishing "no pump installed / re-entrant" from a
    #: pump that ran and returned None
    _NOT_PUMPED = object()
    #: consecutive no-work pump rounds (pump returned False, no timers)
    #: before a wait gives up and jumps to its limit — without this, a
    #: wait_event(timeout=None) on an event nothing will set would grind
    #: through ~max_untimed_wait pump iterations instead of failing fast
    idle_pump_limit: int = 8

    def _run_pump(self):
        if self.pump is None or self._pumping:
            return SimClock._NOT_PUMPED
        self._pumping = True
        try:
            return self.pump()
        finally:
            self._pumping = False

    def sleep(self, seconds: float) -> None:
        self.advance(seconds)
        self._run_pump()

    def wait_event(self, event: threading.Event,
                   timeout: Optional[float] = None) -> bool:
        limit = self._now + (timeout if timeout is not None
                             else self.max_untimed_wait)
        idle_rounds = 0
        while not event.is_set() and self._now < limit:
            result = self._run_pump()
            if event.is_set():
                break
            if result is SimClock._NOT_PUMPED and not self._timers:
                # nothing can change state: burn the wait in one jump
                self._now = limit
                break
            if result is False and not self._timers:
                # the pump explicitly reported no work (e.g. fleet.step
                # when idle): after a few confirming rounds, stop
                # grinding and burn the remaining wait in one jump
                idle_rounds += 1
                if idle_rounds >= self.idle_pump_limit:
                    self._now = limit
                    break
            else:
                idle_rounds = 0
            self.advance(min(1.0, limit - self._now))
        return event.is_set()


# ----------------------------------------------------------------------
_CLOCK: Clock = WallClock()


def get_clock() -> Clock:
    """The process-global clock (WallClock unless a test/sim installed
    another)."""
    return _CLOCK


def set_clock(clock: Optional[Clock]) -> Clock:
    """Install ``clock`` process-globally (None restores WallClock).
    Returns the previously installed clock."""
    global _CLOCK
    prev = _CLOCK
    _CLOCK = clock if clock is not None else WallClock()
    return prev


@contextlib.contextmanager
def use_clock(clock: Clock) -> Iterator[Clock]:
    """Scoped :func:`set_clock` — the simulation harness's entry seam."""
    prev = set_clock(clock)
    try:
        yield clock
    finally:
        set_clock(prev)
