"""Deterministic fault injection for exercising recovery paths.

Production failure modes — a SIGTERM mid-save, a torn shard, a wedged
collective — are rare and unreproducible in the wild, which makes the
recovery code that handles them the least-tested code in the stack. The
:class:`FaultInjector` turns each of them into a seeded, deterministic
event so tests (tests/test_fault_tolerance.py) and the chaos smoke loop
(scripts/chaos_smoke.py) can prove every recovery path:

* ``crash_before_commit_at_save`` / ``crash_after_commit_at_save`` — die at
  the Nth checkpoint save, on the chosen side of the atomic-rename commit
  (runtime/checkpoint.py calls :meth:`on_save_phase` at both points);
* ``corrupt_shard_at_save`` — after the Nth commit, flip bytes in a
  seeded-random file inside the committed tag (manifest verification must
  catch it on load);
* ``sigterm_at_step`` / ``crash_at_step`` — raise SIGTERM (drains through
  PreemptionGuard) or die outright before training step K;
* ``collective_fail_op`` / ``collective_delay_s`` — fail or delay facade
  collectives through the comm-facade hook (``comm.comm._CHAOS_HOOK``,
  fired at trace time where the facade records the op);
* ``serving_tick_fail_at`` / ``serving_tick_fail_every`` — fail serving
  engine ticks (:class:`TickFault`, a *recoverable* RuntimeError: the
  ServingEngine's request-level retry-or-fail path is the code under
  test, so unlike the faults above it must be catchable);
* ``replica_die_at_tick`` / ``replica_die_index`` — kill one serving
  replica of a :class:`~deepspeed_tpu.serving.ServingFleet` once it has
  run N engine ticks (polled by the fleet health monitor via
  :meth:`should_kill_replica`; the fleet's failover re-queues the dead
  replica's in-flight requests on the survivors);
* ``cell_die_at_tick`` / ``cell_die_index`` — kill a whole
  :class:`~deepspeed_tpu.serving.ServingCell` (correlated replica death:
  the region's failure domain goes dark at once; polled by the region
  monitor via :meth:`should_kill_cell`);
* :meth:`sever` / :meth:`heal_partitions` — a network-partition model
  over named nodes (cells plus the region front-end): routing and
  cross-cell KV hand-off consult :meth:`reachable` and fail with typed
  errors across a severed pair instead of silently succeeding in one
  process (docs/serving.md "Region & cells");
* :meth:`set_autoscaler_lag` — delays every fleet autoscaler decision by
  a fixed virtual interval (controller lag: real autoscalers observe,
  deliberate and boot capacity minutes behind the demand curve);
* gray-failure faults (serving/health.py, docs/fault_tolerance.md "Gray
  failures"): :meth:`degrade_replica` arms a per-replica k x-slowdown
  (k-1 of every k busy ticks stall — a limping-but-alive straggler),
  :meth:`arm_stall_burst` stalls a replica's next N busy ticks
  (intermittent flapping), and ``flaky_import_every`` /
  :meth:`on_import_kv` fails every Nth serving KV import with a
  *recoverable* error (the adoption-fallback requeue is the code under
  test); every injected degraded tick is booked per replica in
  ``straggler_evidence`` — the DST quarantine-convergence invariant's
  ground truth;
* rollout-targeted faults (serving/rollout.py): ``corrupt_swap_count`` /
  :meth:`should_corrupt_swap` corrupts the next N hot-swap weight loads
  (the swap must fall back to the old version and the controller must
  retry or roll back — never strand the replica), ``die_at_flip`` /
  :meth:`should_die_at_flip` kills the replica being flipped on the Nth
  drained flip, and ``degrade_version`` / :meth:`should_degrade_tick`
  stalls every other engine tick of one model version (the injected
  canary SLO regression that auto-rollback is gated on).

Faults raise :class:`InjectedFault` (a ``BaseException``) so retry helpers
and broad ``except Exception`` recovery code never swallow an injected
crash, or — with ``exit_process`` on — call ``os._exit(exit_code)`` so a
supervising ElasticAgent sees a real worker death. Every injection is
counted under ``resilience/chaos/<kind>`` in the telemetry registry.
"""

from __future__ import annotations

import json
import os
import random
import signal
import threading
from typing import Any, Dict, Optional

from ..utils.logging import logger
from .clock import get_clock

CHAOS_ENV = "DST_CHAOS"


class InjectedFault(BaseException):
    """A deliberately injected fault. Derives from BaseException so the
    retry helper (which retries OSError/RuntimeError) and defensive
    ``except Exception`` blocks can never absorb it — an injected crash
    must behave like a real one."""

    def __init__(self, kind: str):
        super().__init__(f"injected fault: {kind}")
        self.kind = kind


class CollectiveFault(InjectedFault):
    """An injected collective failure (flaky fabric simulation)."""


class TickFault(RuntimeError):
    """An injected SERVING-TICK failure. Deliberately a plain
    ``RuntimeError`` — unlike :class:`InjectedFault` — because it
    simulates the *recoverable* class of device-step errors (transient
    XLA failure, allocator hiccup) that the serving driver is REQUIRED to
    absorb: the recovery path under test is the catcher, so the fault
    must be catchable. Process-killing faults stay BaseException."""


class FaultInjector:
    """Seeded fault schedule. All ``*_at_save`` indices are 1-based save
    counts; ``*_at_step`` match the engine's ``global_steps`` value at the
    start of a ``train_batch`` call. ``-1`` disables a fault."""

    def __init__(self, config: Any = None, *,
                 seed: int = 0,
                 crash_before_commit_at_save: int = -1,
                 crash_after_commit_at_save: int = -1,
                 corrupt_shard_at_save: int = -1,
                 sigterm_at_step: int = -1,
                 crash_at_step: int = -1,
                 exit_process: bool = False,
                 exit_code: int = 113,
                 collective_fail_op: str = "",
                 collective_fail_at_call: int = -1,
                 collective_delay_s: float = 0.0,
                 collective_delay_every: int = 0,
                 serving_tick_fail_at: int = -1,
                 serving_tick_fail_every: int = 0,
                 replica_die_at_tick: int = -1,
                 replica_die_index: int = 0,
                 cell_die_at_tick: int = -1,
                 cell_die_index: int = 0,
                 autoscaler_lag_s: float = 0.0,
                 corrupt_swap_count: int = 0,
                 die_at_flip: int = -1,
                 degrade_version: int = -1,
                 flaky_import_every: int = 0,
                 stale_directory_every: int = 0,
                 corrupt_adopt_every: int = 0,
                 cold_pressure_every: int = 0):
        fields = {
            "seed": seed,
            "crash_before_commit_at_save": crash_before_commit_at_save,
            "crash_after_commit_at_save": crash_after_commit_at_save,
            "corrupt_shard_at_save": corrupt_shard_at_save,
            "sigterm_at_step": sigterm_at_step,
            "crash_at_step": crash_at_step,
            "exit_process": exit_process,
            "exit_code": exit_code,
            "collective_fail_op": collective_fail_op,
            "collective_fail_at_call": collective_fail_at_call,
            "collective_delay_s": collective_delay_s,
            "collective_delay_every": collective_delay_every,
            "serving_tick_fail_at": serving_tick_fail_at,
            "serving_tick_fail_every": serving_tick_fail_every,
            "replica_die_at_tick": replica_die_at_tick,
            "replica_die_index": replica_die_index,
            "cell_die_at_tick": cell_die_at_tick,
            "cell_die_index": cell_die_index,
            "autoscaler_lag_s": autoscaler_lag_s,
            "corrupt_swap_count": corrupt_swap_count,
            "die_at_flip": die_at_flip,
            "degrade_version": degrade_version,
            "flaky_import_every": flaky_import_every,
            "stale_directory_every": stale_directory_every,
            "corrupt_adopt_every": corrupt_adopt_every,
            "cold_pressure_every": cold_pressure_every,
        }
        for name, default in fields.items():
            setattr(self, name,
                    getattr(config, name, default) if config is not None
                    else default)
        self.rng = random.Random(self.seed)
        self.save_count = 0
        self.injected: Dict[str, int] = {}
        self._collective_calls: Dict[str, int] = {}
        # rollout-fault state: drained-flip ordinal counter (1-based,
        # counted only while die_at_flip is armed) and the degraded
        # version's tick parity counter
        self._flip_calls = 0
        self._degrade_calls = 0
        # gray-failure state (docs/fault_tolerance.md "Gray failures"):
        # per-replica k x-slowdowns (name -> k, with a per-name busy-tick
        # counter: k-1 of every k busy ticks stall), finite stall bursts
        # (name -> remaining stalled ticks), the flaky-import call
        # counter, and the per-replica ledger of injected degraded ticks
        # — the DST quarantine-convergence invariant's evidence stream
        self._degrade_replicas: Dict[str, int] = {}
        self._degrade_replica_calls: Dict[str, int] = {}
        self._stall_bursts: Dict[str, int] = {}
        self._import_calls = 0
        self.straggler_evidence: Dict[str, int] = {}
        # global-KV-tier fault state (docs/serving.md "Global KV tier"):
        # publish/export/cold-put call counters for the every-Nth knobs,
        # plus the ground-truth ledgers the DST auditor reads — the set
        # of (member, hash) directory lies currently injected (so the
        # entries-never-outlive-pages invariant can exempt them) and the
        # count of corrupted exports produced (every one must be caught
        # by the importer's checksum — none may land)
        self._directory_publishes = 0
        self._prefix_exports = 0
        self._cold_puts = 0
        self.injected_stale: set = set()
        self.corrupted_exports = 0
        # active network partitions: (group_a, group_b) name sets. Nodes
        # in different groups of any active partition cannot reach each
        # other; nodes a partition does not mention are unaffected by it.
        self._partitions: List[Tuple[frozenset, frozenset]] = []
        # bumped on every sever/heal so observers (the region monitor)
        # can detect connectivity changes without diffing group sets
        self.partition_epoch = 0
        # the injector is polled from fleet/region monitor threads while
        # the driving thread arms faults and severs partitions: the
        # injection ledger and partition list are shared state (dsrace
        # finding, PR 15) — one small mutex covers both
        self._mu = threading.Lock()

    # ------------------------------------------------------------------
    @classmethod
    def from_env(cls, env: Optional[Dict[str, str]] = None) -> Optional["FaultInjector"]:
        """Build from the ``DST_CHAOS`` env var (a JSON object of the
        constructor's keyword fields), or None when unset/empty. This is
        how a supervised worker process (scripts/chaos_smoke.py) receives
        its fault schedule."""
        raw = (env if env is not None else os.environ).get(CHAOS_ENV, "")
        if not raw.strip():
            return None
        try:
            spec = json.loads(raw)
        except json.JSONDecodeError as e:
            logger.warning(f"{CHAOS_ENV} is not valid JSON ({e}); chaos disabled")
            return None
        if not isinstance(spec, dict):
            logger.warning(f"{CHAOS_ENV} must be a JSON object; chaos disabled")
            return None
        # accept (and strip) the config block's master switch so a raw
        # ChaosConfig dict can be exported into DST_CHAOS verbatim
        if not spec.pop("enabled", True):
            return None
        # unknown keys degrade like every other malformed input — warn and
        # drop, never TypeError a supervised worker into a restart storm
        known = {"seed", "crash_before_commit_at_save",
                 "crash_after_commit_at_save", "corrupt_shard_at_save",
                 "sigterm_at_step", "crash_at_step", "exit_process",
                 "exit_code", "collective_fail_op",
                 "collective_fail_at_call", "collective_delay_s",
                 "collective_delay_every", "serving_tick_fail_at",
                 "serving_tick_fail_every", "replica_die_at_tick",
                 "replica_die_index", "cell_die_at_tick",
                 "cell_die_index", "autoscaler_lag_s",
                 "corrupt_swap_count", "die_at_flip", "degrade_version",
                 "flaky_import_every", "stale_directory_every",
                 "corrupt_adopt_every", "cold_pressure_every"}
        unknown = set(spec) - known
        if unknown:
            logger.warning(f"{CHAOS_ENV}: ignoring unknown keys {sorted(unknown)}")
        return cls(**{k: v for k, v in spec.items() if k in known})

    # ------------------------------------------------------------------
    def _count(self, kind: str) -> None:
        with self._mu:
            self.injected[kind] = self.injected.get(kind, 0) + 1
        self._record_injection(kind)

    def _record_injection(self, kind: str) -> None:
        """Telemetry/flight side effects of an injection — OUTSIDE
        ``_mu`` (the registry and recorder take their own locks)."""
        from ..telemetry.registry import get_registry

        get_registry().counter(f"resilience/chaos/{kind}").inc()
        # injected faults land in the flight recorder's black box too,
        # so a post-mortem dump shows the injection next to its fallout
        from ..telemetry.tracing import get_tracer

        tracer = get_tracer()
        if tracer.enabled:
            tracer.flight.note("injected_fault", fault=kind)

    def _crash(self, kind: str) -> None:
        self._count(kind)
        logger.warning(f"chaos: injecting crash '{kind}'")
        if self.exit_process:
            # flush logging before dying like a kill -9'd worker would
            os._exit(self.exit_code)
        raise InjectedFault(kind)

    # ------------------------------------------------------------------
    # hooks (called by checkpoint engine / train engine / comm facade)
    def on_save_phase(self, phase: str, tag: str) -> None:
        if phase == "before_commit":
            self.save_count += 1
            if self.save_count == self.crash_before_commit_at_save:
                self._crash("crash_before_commit")
        elif phase == "after_commit":
            if self.save_count == self.crash_after_commit_at_save:
                self._crash("crash_after_commit")

    def maybe_corrupt(self, tag_path: str) -> bool:
        """Flip bytes in one seeded-random file of a committed tag.
        Returns True when corruption was injected (the checkpoint engine
        must not mark such a tag as verified)."""
        if self.save_count != self.corrupt_shard_at_save:
            return False
        corrupt_tag(tag_path, rng=self.rng)
        self._count("corrupt_shard")
        return True

    def on_step(self, step: int) -> None:
        if step == self.sigterm_at_step:
            self._count("sigterm_at_step")
            logger.warning(f"chaos: raising SIGTERM at step {step}")
            signal.raise_signal(signal.SIGTERM)
        if step == self.crash_at_step:
            self._crash("crash_at_step")

    def on_serving_tick(self, tick: int) -> None:
        """Fail serving ticks: at exactly ``serving_tick_fail_at``
        (1-based tick count) and/or every ``serving_tick_fail_every``-th
        tick. Raises :class:`TickFault` — the recoverable class: the
        serving driver's retry-or-fail path is the code under test."""
        if (tick == self.serving_tick_fail_at
                or (self.serving_tick_fail_every > 0
                    and tick % self.serving_tick_fail_every == 0)):
            self._count("serving_tick_fail")
            logger.warning(f"chaos: failing serving tick {tick}")
            raise TickFault(f"injected serving tick fault at tick {tick}")

    def should_kill_replica(self, replica_index: int, ticks: int) -> bool:
        """Injected serving-replica death: True once, for the replica
        whose index matches ``replica_die_index``, as soon as it has run
        ``replica_die_at_tick`` engine ticks (>= 0 enables). The fleet's
        health monitor polls this and performs the actual kill+failover —
        death is a FLEET-level event (the whole replica process/host is
        gone), not a per-tick fault the ServingEngine could retry."""
        if self.replica_die_at_tick < 0:
            return False
        if replica_index != self.replica_die_index:
            return False
        if ticks < self.replica_die_at_tick:
            return False
        with self._mu:
            # one-shot check AND ledger flip in the same mutex section:
            # split, two monitor threads could both pass the check and
            # double-kill a single configured death
            if self.injected.get("replica_death"):
                return False
            self.injected["replica_death"] = 1
        self._record_injection("replica_death")
        logger.warning(
            f"chaos: killing serving replica {replica_index} at tick {ticks}")
        return True

    def should_kill_cell(self, cell_index: int, ticks: int) -> bool:
        """Injected whole-cell outage: True once, for the cell whose
        index matches ``cell_die_index``, as soon as any of its replicas
        has run ``cell_die_at_tick`` engine ticks (>= 0 enables). The
        region's monitor polls this and performs the kill + cross-cell
        failover — a cell outage is a REGION-level event (the entire
        failure domain went dark: power, ToR switch, pod), the one-tier-
        up analog of :meth:`should_kill_replica`."""
        if self.cell_die_at_tick < 0:
            return False
        if cell_index != self.cell_die_index:
            return False
        if ticks < self.cell_die_at_tick:
            return False
        with self._mu:
            # same atomic check-and-flip as should_kill_replica
            if self.injected.get("cell_outage"):
                return False
            self.injected["cell_outage"] = 1
        self._record_injection("cell_outage")
        logger.warning(
            f"chaos: killing serving cell {cell_index} at tick {ticks}")
        return True

    # -- network partitions ---------------------------------------------
    def sever(self, group_a, group_b) -> None:
        """Partition the network between two named node groups (cell
        names, plus ``\"region\"`` for the front-end itself). Active
        until :meth:`heal_partitions`. Groups must be disjoint."""
        a, b = frozenset(map(str, group_a)), frozenset(map(str, group_b))
        if not a or not b:
            raise ValueError("partition groups must be non-empty")
        if a & b:
            raise ValueError(f"partition groups overlap: {sorted(a & b)}")
        with self._mu:
            self._partitions = self._partitions + [(a, b)]
            self.partition_epoch += 1
        self._count("partition")
        logger.warning(f"chaos: partition {sorted(a)} | {sorted(b)}")

    def heal_partitions(self) -> None:
        """Heal every active partition (connectivity restored at once)."""
        with self._mu:
            if not self._partitions:
                return
            self._partitions = []
            self.partition_epoch += 1
        self._count("partition_heal")
        logger.warning("chaos: all partitions healed")

    @property
    def partitioned(self) -> bool:
        with self._mu:
            return bool(self._partitions)

    def reachable(self, a: str, b: str) -> bool:
        """False when any active partition separates ``a`` from ``b``."""
        with self._mu:
            parts = self._partitions    # rebound on sever/heal, never
        for ga, gb in parts:            # mutated: safe to scan unlocked
            if (a in ga and b in gb) or (a in gb and b in ga):
                return False
        return True

    def set_autoscaler_lag(self, lag_s: float) -> None:
        """Delay every autoscaler decision by ``lag_s`` (virtual)
        seconds — fleets add it to their decision interval, so demand
        runs ahead of capacity exactly like a real control loop lags."""
        if lag_s < 0:
            raise ValueError(f"autoscaler lag must be >= 0, got {lag_s}")
        self.autoscaler_lag_s = float(lag_s)
        self._count("autoscaler_lag")
        logger.warning(f"chaos: autoscaler decisions lagged by {lag_s}s")

    # -- rollout faults (serving/rollout.py) -----------------------------
    def arm_corrupt_swap(self, n: int = 1) -> None:
        """Arm corruption of the next ``n`` hot-swap weight loads."""
        with self._mu:
            self.corrupt_swap_count = max(0, int(n))
        logger.warning(f"chaos: next {n} hot-swap weight loads corrupt")

    def should_corrupt_swap(self) -> bool:
        """Injected corrupt new-version checkpoint, consumed one arm per
        call. The hot-swap path must fall back to the OLD weights and
        report failure — the replica keeps serving its current version,
        never stranded half-swapped."""
        with self._mu:
            if self.corrupt_swap_count <= 0:
                return False
            self.corrupt_swap_count -= 1
        self._count("corrupt_swap")
        logger.warning("chaos: corrupting hot-swap weight load")
        return True

    def arm_flip_death(self, ordinal: int = 1) -> None:
        """Kill the replica being flipped on the ``ordinal``-th (1-based)
        drained flip attempted from now on; -1 disarms."""
        with self._mu:
            self.die_at_flip = int(ordinal)
            self._flip_calls = 0
        logger.warning(f"chaos: armed replica death at flip #{ordinal}")

    def should_die_at_flip(self) -> bool:
        """Injected replica death mid-flip: True exactly once, when the
        rollout controller attempts its ``die_at_flip``-th drained flip.
        The controller must re-target the flip (or roll back), never
        wedge on the corpse."""
        with self._mu:
            if self.die_at_flip < 1:
                return False
            # ordinal equality is the one-shot: counted only while armed
            self._flip_calls += 1
            if self._flip_calls != self.die_at_flip:
                return False
        self._count("flip_death")
        logger.warning("chaos: killing replica mid-flip")
        return True

    def degrade_model_version(self, version: int) -> None:
        """Arm the injected canary SLO regression: every other engine
        tick of replicas serving ``version`` makes no scheduling progress
        (virtual time still advances), so the canary's in-SLA window
        regresses ORGANICALLY while its work still completes — the
        auto-rollback drain must be able to finish. -1 disarms."""
        with self._mu:
            self.degrade_version = int(version)
            self._degrade_calls = 0
        if int(version) >= 0:
            self._count("canary_degrade")
            logger.warning(f"chaos: degrading model version {version} "
                           f"(every other tick stalls)")

    def should_degrade_tick(self, version: int) -> bool:
        """Whether THIS engine tick of a replica serving ``version``
        should stall (see :meth:`degrade_model_version`)."""
        with self._mu:
            if self.degrade_version < 0 or version != self.degrade_version:
                return False
            self._degrade_calls += 1
            return self._degrade_calls % 2 == 0

    # -- gray-failure faults (serving/health.py) -------------------------
    def degrade_replica(self, name: str, k: int) -> None:
        """Arm a k x-slowdown of one named replica: k-1 of every k of its
        busy engine ticks stall (virtual time advances, no scheduling
        progress), so the replica limps at 1/k throughput while passing
        every binary health check — the canonical gray failure the
        quarantine plane must detect. ``k < 2`` disarms."""
        k = int(k)
        with self._mu:
            if k < 2:
                self._degrade_replicas.pop(str(name), None)
            else:
                self._degrade_replicas[str(name)] = k
                self._degrade_replica_calls.setdefault(str(name), 0)
        if k >= 2:
            self._count("degraded_tick_armed")
            logger.warning(f"chaos: replica {name} degraded {k}x "
                           f"({k - 1} of every {k} busy ticks stall)")

    def arm_stall_burst(self, name: str, n: int) -> None:
        """Arm an intermittent stall burst: the named replica's next
        ``n`` busy engine ticks stall outright, then it runs clean —
        the flapping-straggler pattern hysteresis is gated on."""
        with self._mu:
            self._stall_bursts[str(name)] = (
                self._stall_bursts.get(str(name), 0) + max(0, int(n)))
        self._count("stall_burst_armed")
        logger.warning(f"chaos: replica {name} stall burst of {n} ticks")

    def should_degrade_replica(self, name: Optional[str]) -> bool:
        """Whether THIS busy engine tick of replica ``name`` should
        stall (burst arms drain first, then the k x-slowdown parity).
        Every True is booked as straggler evidence against the replica —
        the DST quarantine-convergence invariant's ground truth."""
        if name is None:
            return False
        name = str(name)
        kind = None
        with self._mu:
            if self._stall_bursts.get(name, 0) > 0:
                self._stall_bursts[name] -= 1
                kind = "stall_burst"
            else:
                k = self._degrade_replicas.get(name)
                if k:
                    calls = self._degrade_replica_calls.get(name, 0) + 1
                    self._degrade_replica_calls[name] = calls
                    if calls % k != 0:
                        kind = "degraded_tick"
            if kind is not None:
                self.straggler_evidence[name] = (
                    self.straggler_evidence.get(name, 0) + 1)
        if kind is None:
            return False
        self._count(kind)
        return True

    def on_import_kv(self) -> None:
        """Flaky KV-import hook (serving adoption / disaggregated
        hand-off): every ``flaky_import_every``-th call raises a
        recoverable RuntimeError — the importer's fallback path (requeue
        and re-prefill) is the code under test, so the fault must be
        catchable, exactly like :class:`TickFault`."""
        if self.flaky_import_every <= 0:
            return
        with self._mu:
            self._import_calls += 1
            hit = self._import_calls % self.flaky_import_every == 0
        if hit:
            self._count("flaky_import")
            raise RuntimeError("chaos: injected flaky KV import")

    def on_directory_publish(self, member: str) -> Optional[int]:
        """Stale-directory-entry hook (global KV tier): every
        ``stale_directory_every``-th residency publish returns a bogus
        prefix hash for the publisher to ALSO claim — a directory lie
        (no pages back it). The (member, hash) pair is remembered in
        ``injected_stale`` as the DST auditor's exemption ground truth;
        routing must treat the lie as any other stale entry (fall back
        to the affinity ring / local prefill, never wedge)."""
        if self.stale_directory_every <= 0:
            return None
        with self._mu:
            self._directory_publishes += 1
            hit = (self._directory_publishes
                   % self.stale_directory_every == 0)
            if hit:
                # deterministic bogus hash: derived from the publish
                # ordinal so replays inject the identical lie
                bogus = (0xDEAD0000_00000000
                         | (self._directory_publishes & 0xFFFFFFFF))
                self.injected_stale.add((member, bogus))
        if not hit:
            return None
        self._count("stale_directory")
        return bogus

    def on_prefix_export(self) -> bool:
        """Adoption-wire-corruption hook (global KV tier): every
        ``corrupt_adopt_every``-th prefix export should have its wire
        content corrupted AFTER the checksum is stamped. Returns True
        when the caller must corrupt; ``corrupted_exports`` counts the
        ground truth for the none-may-land invariant (#19)."""
        if self.corrupt_adopt_every <= 0:
            return False
        with self._mu:
            self._prefix_exports += 1
            hit = self._prefix_exports % self.corrupt_adopt_every == 0
            if hit:
                self.corrupted_exports += 1
        if hit:
            self._count("corrupt_adopt")
        return hit

    def on_cold_put(self) -> bool:
        """Cold-tier-pressure hook (global KV tier): every
        ``cold_pressure_every``-th cold-tier admission is dropped —
        the evicted prefix is simply lost to the cold tier (host under
        memory pressure) and later demand re-prefills. Returns True
        when the put must be dropped."""
        if self.cold_pressure_every <= 0:
            return False
        with self._mu:
            self._cold_puts += 1
            hit = self._cold_puts % self.cold_pressure_every == 0
        if hit:
            self._count("cold_pressure")
        return hit

    def injected_stale_snapshot(self) -> set:
        """The (member, bogus-hash) directory lies currently injected."""
        with self._mu:
            return set(self.injected_stale)

    def straggler_evidence_snapshot(self) -> Dict[str, int]:
        """Per-replica count of injected degraded/stalled busy ticks."""
        with self._mu:
            return dict(self.straggler_evidence)

    def on_collective(self, op: str) -> None:
        n = self._collective_calls.get(op, 0) + 1
        self._collective_calls[op] = n
        if (self.collective_delay_s > 0 and self.collective_delay_every > 0
                and n % self.collective_delay_every == 0):
            self._count(f"collective_delay/{op}")
            # through the injectable clock: under a SimClock the delay
            # advances virtual time instead of stalling the soak host
            get_clock().sleep(self.collective_delay_s)
        if op == self.collective_fail_op and n == self.collective_fail_at_call:
            self._count(f"collective_fail/{op}")
            raise CollectiveFault(f"collective_fail:{op}")


def corrupt_tag(tag_path: str, rng: Optional[random.Random] = None) -> str:
    """XOR-flip 64 bytes in the middle of one (seeded-random) data file of
    a checkpoint tag. Returns the corrupted file's path. Standalone so
    tests can corrupt without a full injector."""
    rng = rng or random.Random(0)
    candidates = []
    for dirpath, _d, filenames in os.walk(tag_path):
        for name in filenames:
            full = os.path.join(dirpath, name)
            # corrupt payload, not the protocol files that detect it
            if name in ("COMMITTED", "manifest.json"):
                continue
            if os.path.getsize(full) > 0:
                candidates.append(full)
    if not candidates:
        raise ValueError(f"no corruptible files under {tag_path}")
    target = rng.choice(sorted(candidates))
    size = os.path.getsize(target)
    off = max(0, size // 2 - 32)
    with open(target, "r+b") as f:
        f.seek(off)
        chunk = f.read(min(64, size - off))
        f.seek(off)
        f.write(bytes(b ^ 0xFF for b in chunk))
    logger.warning(f"chaos: corrupted {target} at offset {off}")
    return target


# ----------------------------------------------------------------------
_INJECTOR: Optional[FaultInjector] = None


def get_fault_injector() -> Optional[FaultInjector]:
    return _INJECTOR


def is_reachable(a: str, b: str) -> bool:
    """Whether nodes ``a`` and ``b`` can reach each other under the
    installed injector's partition model (always True with no injector:
    chaos off means the network is whole). The region/cell layer's one
    connectivity oracle — routing, cross-cell hand-off and KV adoption
    all consult it so a severed pair fails TYPED, never silently."""
    inj = _INJECTOR
    return True if inj is None else inj.reachable(a, b)


def install_fault_injector(inj: Optional[FaultInjector]) -> Optional[FaultInjector]:
    """Install ``inj`` process-globally (None to clear) and point the comm
    facade's chaos hook at it."""
    global _INJECTOR
    _INJECTOR = inj
    from ..comm import comm as comm_mod

    comm_mod._CHAOS_HOOK = inj.on_collective if inj is not None else None
    return inj
