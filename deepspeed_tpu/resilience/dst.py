"""Deterministic simulation testing (DST) for the serving stack.

A FoundationDB-style harness: the whole :class:`~deepspeed_tpu.serving.ServingFleet`
— router, replicas, scheduler policies, failover, disaggregated
hand-off, autoscaler — runs single-threaded under a virtual-time
:class:`~.clock.SimClock`, driven tick by tick against a *seeded fault
schedule* (request arrivals, cancellations, injected tick faults,
replica deaths, preemption latches, scale events, load gaps). No real
threads, no wall clock, no jitter: the entire execution is a pure
function of the schedule, so

* one CI run soaks hundreds of randomized schedules
  (``scripts/dst_soak.py``);
* every event is followed by an **invariant audit** (KV block-balance
  partition, request state-machine legality, no-lost-request
  conservation, span/SLO-ledger consistency, stream-delivery
  completeness, monotone virtual time);
* a failure reproduces from ``(seed)`` alone — and
  :func:`shrink_schedule` delta-debugs the failing schedule down to a
  minimal event list, emitted as a regression artifact;
* the same seed produces a **bit-identical event-trace hash**, asserted
  in tests/test_dst.py.

The same discipline runs one failure-domain up:
:func:`generate_region_schedule` / :func:`run_region_schedule` drive
the real :class:`~deepspeed_tpu.serving.Region` (cells of fleets,
two-tier routing) through region-scale chaos — whole-cell outages,
inter-cell partitions + heals, autoscaler lag — audited by
:class:`RegionInvariantAuditor` (every fleet invariant region-wide,
plus heal convergence / single ownership and shed-span). See
docs/dst.md "Region-scale events".

The device is replaced by :class:`SimEngine` — a host-only model of the
ragged engine's serving contract that *reuses the real*
:class:`~deepspeed_tpu.inference.ragged.BlockedAllocator`,
:class:`~deepspeed_tpu.inference.ragged.PrefixCache` and
:class:`~deepspeed_tpu.inference.ragged.SequenceDescriptor`, so the
block-balance audit exercises the actual refcount accounting the
serving layer must keep balanced; only the model math is replaced by a
deterministic next-token function of the context. Everything above the
engine — ``serving/``, the schedulers, the fleet — is the real shipped
code. See docs/dst.md.
"""

from __future__ import annotations

import hashlib
import json
from dataclasses import dataclass, field
from typing import Any, Callable, Dict, List, Optional, Sequence, Tuple

import numpy as np

from ..inference.ragged import (BlockedAllocator, NgramIndex, PoolExhausted,
                                PrefixCache, SequenceDescriptor,
                                block_balance_report)
from ..telemetry.registry import MetricsRegistry
from ..telemetry.telemetry import Telemetry, set_telemetry
from ..telemetry.tracing import Tracer, trace_tree_problems, use_tracer
from ..utils.logging import logger
from .chaos import (FaultInjector, TickFault, get_fault_injector,
                    install_fault_injector)
from .clock import SimClock, use_clock

__all__ = ["SimConfig", "SimEngine", "SimKVExport", "SimEvent", "Schedule",
           "RegionSchedule", "SimReport", "generate_schedule",
           "generate_region_schedule", "run_schedule",
           "run_region_schedule", "shrink_schedule", "dump_repro",
           "load_repro", "spec_identity_problems"]


# ----------------------------------------------------------------------
# the simulated engine
# ----------------------------------------------------------------------

@dataclass
class SimConfig:
    """Geometry of a :class:`SimEngine` — the same knobs as
    :class:`~deepspeed_tpu.inference.ragged.RaggedConfig`, sized small so
    slot/pool pressure is reachable within a short schedule."""

    token_budget: int = 32
    max_seqs: int = 4
    kv_block_size: int = 4
    n_kv_blocks: int = 40
    max_context: int = 96
    enable_prefix_cache: bool = True
    vocab: int = 48
    # declared KV storage mode: the sim has no payload to quantize —
    # carrying the knob keeps the serving-layer validation and the
    # export/import geometry contract (mode must match across the
    # disaggregated hand-off) exercised at fleet scale, and the
    # token-identity audit witnesses that quantized runs stay
    # greedy-bit-exact (tokens are a pure function of context)
    kv_quant: str = "none"

    def to_dict(self) -> Dict[str, Any]:
        return dict(self.__dict__)


@dataclass
class SimKVExport:
    """The simulation's stand-in for
    :class:`~deepspeed_tpu.inference.ragged.KVExport`: same bookkeeping
    fields and import-side validation, no page payload (there are no
    pages to copy — the importer re-charges the allocator exactly like
    the real importer does)."""

    uid: int
    tokens: List[int]
    seen: int
    prompt_len: int
    kv_block_size: int
    n_pages: int
    kv_quant: str = "none"      # must match the importer's declared mode


def _next_token(ctx: Sequence[int], vocab: int) -> int:
    """The simulated model: a deterministic pure function of the full
    context (FNV-1a fold), so preempt/failover/hand-off resumes are
    bit-exact iff the serving layer reconstructs the context exactly."""
    h = 2166136261
    for t in ctx:
        h = ((h ^ (int(t) & 0xFFFFFFFF)) * 16777619) & 0xFFFFFFFF
    return h % vocab


class SimEngine:
    """Host-only ragged engine standing in for
    :class:`~deepspeed_tpu.inference.ragged.RaggedInferenceEngine` under
    the serving layer: identical serving-facing surface (``put`` /
    ``flush`` / ``preempt`` / ``discard`` / ``clear_resume`` /
    ``export_kv`` / ``import_kv`` / capacity queries) with the real
    allocator + prefix-cache accounting and Dynamic-SplitFuse admission
    semantics — tokens are admitted to descriptors BEFORE the pool
    check, exactly like the device engine, so ``PoolExhausted`` recovery
    retries with empty continuation chunks."""

    def __init__(self, config: Optional[SimConfig] = None):
        self.config = config if config is not None else SimConfig()
        cfg = self.config
        self.allocator = BlockedAllocator(cfg.n_kv_blocks)
        self.prefix_cache = (PrefixCache(cfg.kv_block_size)
                             if cfg.enable_prefix_cache else None)
        self.seqs: Dict[int, SequenceDescriptor] = {}
        self._free_slots: List[int] = list(range(cfg.max_seqs))
        self._resume_uids: set = set()
        self.tick_count = 0
        # speculative-decoding surface (mirrors the ragged engine):
        # per-uid memoized n-gram indices + the acceptance-stats dict
        self._ngram_idx: Dict[int, NgramIndex] = {}
        self.spec_stats = {"proposed": 0, "accepted": 0, "rounds": 0}
        # global KV tier seams (mirrors RaggedInferenceEngine; wired by
        # ServingEngine.enable_kv_tier when serving.kv_tier is on)
        self._cold_tier = None
        self._on_prefix_invalidate = None
        self._kv_tier_member = ""
        self.kvtier_cold_spills = 0
        self.kvtier_cold_readmits = 0
        self.kvtier_adopt_imports = 0
        self.kvtier_corrupt_landed = 0

    # -- capacity queries (formulas identical to the ragged engine) -----
    def _available_blocks(self) -> int:
        free = self.allocator.free_blocks
        if self.prefix_cache is not None:
            free += self.prefix_cache.reclaimable_blocks(self.allocator)
        return free

    def blocks_needed(self, n_tokens: int) -> int:
        return -(-int(n_tokens) // self.config.kv_block_size) + 1

    def can_schedule(self, uids: Sequence[int],
                     lengths: Sequence[int]) -> bool:
        bs = self.config.kv_block_size
        new = [u for u in uids if u not in self.seqs]
        need_blocks = 0
        for uid, length in zip(uids, lengths):
            if uid in self.seqs:
                seq = self.seqs[uid]
                total = seq.seen + length
                need_blocks += max(0, -(-total // bs) - len(seq.blocks))
            else:
                need_blocks += self.blocks_needed(length)
        return (len(new) <= len(self._free_slots)
                and need_blocks <= self._available_blocks())

    def kv_occupancy(self) -> float:
        return 1.0 - self.allocator.free_blocks / self.allocator.n_blocks

    def kv_demand(self) -> float:
        return 1.0 - self._available_blocks() / self.allocator.n_blocks

    # -- lifecycle -------------------------------------------------------
    def flush(self, uids: Sequence[int]) -> None:
        for uid in uids:
            seq = self.seqs.pop(uid, None)
            self._ngram_idx.pop(uid, None)
            if seq is not None:
                if self.prefix_cache is not None:
                    self.prefix_cache.publish(seq.tokens, seq.blocks,
                                              seq.seen, self.allocator)
                self.allocator.free(seq.blocks)
                self._free_slots.append(seq.slot)

    def preempt(self, uid: int) -> List[int]:
        seq = self.seqs.get(uid)
        if seq is None:
            return []
        toks = list(seq.tokens[:seq.seen])
        self.flush([uid])
        self._resume_uids.add(uid)
        return toks

    def discard(self, uid: int) -> None:
        seq = self.seqs.pop(uid, None)
        self._ngram_idx.pop(uid, None)
        if seq is None:
            return
        self.allocator.free(seq.blocks)
        self._free_slots.append(seq.slot)
        self._resume_uids.add(uid)

    def trim(self, uid: int, length: int) -> None:
        """Mirror of the ragged engine's ``trim`` minus the device page
        copy: rewind to ``length`` tokens, free now-unused blocks, and —
        refcount parity with the real copy-on-write — swap the boundary
        block for a private one when it is shared, so the block-balance
        audit exercises identical accounting on the spec-decode rewind
        path."""
        seq = self.seqs[uid]
        if not 0 <= length <= seq.seen:
            raise ValueError(
                f"uid {uid}: trim length {length} outside [0, "
                f"seen={seq.seen}]")
        bs = self.config.kv_block_size
        keep = -(-length // bs) if length else 0
        cow_new = None
        if (length % bs and keep <= len(seq.blocks)
                and self.allocator.refcount(seq.blocks[keep - 1]) > 1):
            if (self.allocator.free_blocks < 1
                    and self.prefix_cache is not None):
                self.prefix_cache.evict_for(self.allocator, 1)
            if self.allocator.refcount(seq.blocks[keep - 1]) > 1:
                cow_new = self.allocator.allocate(1)[0]
        seq.tokens = seq.tokens[:length]
        seq.seen = length
        ngi = self._ngram_idx.get(uid)
        if ngi is not None:
            ngi.truncate(length)
        if keep < len(seq.blocks):
            self.allocator.free(seq.blocks[keep:])
            del seq.blocks[keep:]
        if cow_new is not None:
            old = seq.blocks[keep - 1]
            self.allocator.release([old])
            seq.blocks[keep - 1] = cow_new

    # -- speculative drafting (same surface as the ragged engine) -------
    def draft_tokens(self, uid: int, next_token: Optional[int],
                     ngram: int, k: int) -> List[int]:
        seq = self.seqs[uid]
        idx = self._ngram_idx.get(uid)
        if idx is None or idx.ngram != int(ngram):
            idx = NgramIndex(ngram)
            self._ngram_idx[uid] = idx
        idx.sync(seq.tokens)
        return idx.lookup([] if next_token is None else [int(next_token)], k)

    def record_spec(self, proposed: int = 0, accepted: int = 0,
                    rounds: int = 0) -> None:
        from ..telemetry import get_telemetry

        s = self.spec_stats
        s["proposed"] += int(proposed)
        s["accepted"] += int(accepted)
        s["rounds"] += int(rounds)
        t = get_telemetry()
        if t.enabled and s["proposed"]:
            t.registry.gauge("inference/spec_acceptance").set(
                s["accepted"] / s["proposed"])

    def clear_resume(self, uid: int) -> None:
        self._resume_uids.discard(uid)

    # -- KV hand-off seam ------------------------------------------------
    def export_kv(self, uid: int) -> SimKVExport:
        seq = self.seqs.get(uid)
        if seq is None:
            raise KeyError(f"uid {uid} has no live sequence to export")
        if seq.pending:
            raise ValueError(f"uid {uid}: {seq.pending} tokens still "
                             "pending prefill")
        if seq.seen == 0 or not seq.blocks:
            raise ValueError(f"uid {uid}: nothing prefilled yet")
        return SimKVExport(uid=uid, tokens=list(seq.tokens), seen=seq.seen,
                           prompt_len=seq.prompt_len,
                           kv_block_size=self.config.kv_block_size,
                           n_pages=len(seq.blocks),
                           kv_quant=self.config.kv_quant)

    def import_kv(self, uid: int, export: SimKVExport) -> None:
        cfg = self.config
        if uid in self.seqs:
            raise ValueError(f"uid {uid} already live in this engine")
        if export.kv_block_size != cfg.kv_block_size:
            raise ValueError("KV geometry mismatch")
        if getattr(export, "kv_quant", "none") != cfg.kv_quant:
            raise ValueError(
                f"KV quant-mode mismatch: engine '{cfg.kv_quant}' vs "
                f"export '{getattr(export, 'kv_quant', 'none')}'")
        if export.seen != len(export.tokens):
            raise ValueError(
                f"export seen {export.seen} != tokens {len(export.tokens)}")
        if export.seen > cfg.max_context:
            raise ValueError("export context exceeds max_context")
        need = -(-export.seen // cfg.kv_block_size)
        if export.n_pages != need:
            raise ValueError(
                f"export carries {export.n_pages} pages for "
                f"{export.seen} tokens")
        if not self._free_slots:
            raise RuntimeError("no free sequence slots; flush() first")
        if need > self.allocator.free_blocks and self.prefix_cache is not None:
            self.prefix_cache.evict_for(self.allocator, need)
        blocks = self.allocator.allocate(need)    # may raise PoolExhausted
        self.seqs[uid] = SequenceDescriptor(
            uid=uid, slot=self._free_slots.pop(),
            tokens=[int(t) for t in export.tokens], seen=int(export.seen),
            blocks=blocks, t_admitted=None, t_created=None,
            prompt_len=int(export.prompt_len))
        self._resume_uids.discard(uid)

    # -- global KV tier (payload-free mirror of the ragged engine) -------
    def enable_kv_tier(self, *, member: str = "", cold_tier=None,
                       on_invalidate=None) -> None:
        """Same seam as the ragged engine: record the tier hooks and
        attach the eviction callback. Sim exports carry no pages — the
        checksum covers the token stream, which is exactly what the
        injected wire corruption flips."""
        self._kv_tier_member = member
        self._cold_tier = cold_tier
        self._on_prefix_invalidate = on_invalidate
        if self.prefix_cache is not None and (
                cold_tier is not None or on_invalidate is not None):
            self.prefix_cache.on_evict = self._on_prefix_evict

    def _sim_geometry(self):
        cfg = self.config
        return (cfg.kv_block_size, 1, 1, 1, "sim", cfg.kv_quant)

    def _make_prefix_export(self, key, blocks):
        from ..serving.kvtier import PrefixExport

        cfg = self.config
        return PrefixExport(
            tokens=key, n_pages=len(blocks),
            block_size=cfg.kv_block_size, n_layers=1, n_kv_heads=1,
            head_dim=1, dtype="sim", kv_quant=cfg.kv_quant,
            wire_bytes=len(blocks) * cfg.kv_block_size,
            logical_bytes=2 * len(blocks) * cfg.kv_block_size,
            source=self._kv_tier_member)

    def _on_prefix_evict(self, key, blocks) -> None:
        # invalidate FIRST (the directory entry must not outlive the
        # pages), then spill a host copy — same order as the real engine
        if self._on_prefix_invalidate is not None:
            from ..serving.kvtier import prefix_hash

            self._on_prefix_invalidate(prefix_hash(key))
        if self._cold_tier is not None:
            if self._cold_tier.put(self._make_prefix_export(key, blocks)):
                self.kvtier_cold_spills += 1

    def prefix_residency_hashes(self) -> List[int]:
        if self.prefix_cache is None:
            return []
        from ..serving.kvtier import prefix_hash

        return [prefix_hash(k) for k in self.prefix_cache._entries]

    def export_prefix(self, tokens: Sequence[int]):
        """Donor side of cross-replica adoption: longest resident
        full-block prefix of ``tokens`` as a payload-free PrefixExport
        (None on a miss). The ``corrupt_adopt`` chaos knob flips a
        token AFTER the checksum is stamped — the importer's verify
        must catch it."""
        if self.prefix_cache is None:
            return None
        key, blocks = self.prefix_cache.lookup(tokens)
        if key is None:
            return None
        export = self._make_prefix_export(key, blocks)
        inj = get_fault_injector()
        if inj is not None and inj.on_prefix_export():
            export.tokens = ((export.tokens[0] ^ 0x1,) + export.tokens[1:])
        return export

    def import_prefix(self, export) -> bool:
        """Importer side: checksum FIRST (invariant #19), geometry,
        capacity, publish — identical discipline to the ragged engine,
        with the same ``_kvtier_skip_verify`` planted-bug seam."""
        from ..serving.kvtier import CorruptExport

        if self.prefix_cache is None:
            raise ValueError("prefix cache disabled; nothing to adopt into")
        cfg = self.config
        if not export.verify():
            if not getattr(self, "_kvtier_skip_verify", False):
                raise CorruptExport(
                    "prefix export failed checksum verification "
                    "(corrupted in transit)")
            self.kvtier_corrupt_landed += 1
        if export.geometry() != self._sim_geometry():
            raise ValueError(
                f"prefix KV geometry mismatch: engine "
                f"{self._sim_geometry()} vs export {export.geometry()}")
        need = export.n_pages
        if need <= 0 or need != len(export.tokens) // cfg.kv_block_size \
                or len(export.tokens) % cfg.kv_block_size:
            raise ValueError(
                f"prefix export carries {need} pages for "
                f"{len(export.tokens)} tokens (full blocks required)")
        if len(export.tokens) > cfg.max_context:
            raise ValueError("prefix length exceeds max_context")
        if tuple(export.tokens) in self.prefix_cache._entries:
            return False
        if need > self.allocator.free_blocks:
            self.prefix_cache.evict_for(self.allocator, need)
        blocks = self.allocator.allocate(need)    # may raise PoolExhausted
        self.prefix_cache.publish(list(export.tokens), blocks,
                                  len(export.tokens), self.allocator)
        self.allocator.release(blocks)
        self.kvtier_adopt_imports += 1
        return True

    def _cold_readmit(self, tokens: Sequence[int]) -> None:
        bs = self.config.kv_block_size
        for k in range((len(tokens) - 1) // bs, 0, -1):
            key = tuple(int(t) for t in tokens[:k * bs])
            if key in self.prefix_cache._entries:
                return
            export = self._cold_tier.get(key)
            if export is None:
                continue
            try:
                if self.import_prefix(export):
                    self.kvtier_cold_readmits += 1
            except (ValueError, RuntimeError):
                pass
            return

    # -- the step --------------------------------------------------------
    def _admit_tokens(self, uids: Sequence[int],
                      tokens: Sequence[Sequence[int]]) -> None:
        """Admission shared by put()/put_spec() (the mirror of the real
        engine's same-named helper): fresh uids get a slot + cached
        prefix adoption, existing ones append their chunk."""
        for uid, toks in zip(uids, tokens):
            new = uid not in self.seqs
            if new:
                if not self._free_slots:
                    raise RuntimeError("no free sequence slots; flush() first")
                self._resume_uids.discard(uid)
                self.seqs[uid] = SequenceDescriptor(
                    uid=uid, slot=self._free_slots.pop())
            seq = self.seqs[uid]
            seq.tokens.extend(int(t) for t in toks)
            if new:
                seq.prompt_len = len(seq.tokens)
                if self.prefix_cache is not None and seq.tokens:
                    if self._cold_tier is not None:
                        # re-admission BEFORE the match: a spilled prefix
                        # comes back through the checksummed import path
                        # and the match below finds it like a local one
                        self._cold_readmit(seq.tokens)
                    shared, blocks = self.prefix_cache.match(seq.tokens)
                    if shared:
                        self.allocator.retain(blocks)
                        seq.blocks = list(blocks)
                        seq.seen = shared

    def _pack_splitfuse(self) -> List[Tuple[SequenceDescriptor, int]]:
        """Dynamic SplitFuse packing: shortest-pending first into the one
        token budget (same policy as the device engine)."""
        sched: List[Tuple[SequenceDescriptor, int]] = []
        budget = self.config.token_budget
        pending = sorted((s for s in self.seqs.values() if s.pending > 0),
                         key=lambda s: s.pending)
        for seq in pending:
            take = min(seq.pending, budget)
            if take == 0:
                break
            sched.append((seq, take))
            budget -= take
        return sched

    def _validate_sched(self, sched) -> List[int]:
        """Context bound + whole-schedule pool check BEFORE any
        allocation, evicting cached prefixes first — an exhausted pool
        must leave every descriptor consistent (tokens admitted, seen
        unchanged) for the retry path. Returns per-entry block needs."""
        cfg = self.config
        needs = []
        for seq, take in sched:
            total = seq.seen + take
            if total > cfg.max_context:
                raise ValueError(
                    f"uid {seq.uid}: context {total} exceeds max_context")
            needs.append(max(0, -(-total // cfg.kv_block_size)
                             - len(seq.blocks)))
        need_total = sum(needs)
        if (need_total > self.allocator.free_blocks
                and self.prefix_cache is not None):
            self.prefix_cache.evict_for(self.allocator, need_total)
        if need_total > self.allocator.free_blocks:
            raise PoolExhausted(
                f"KV pool exhausted: need {need_total}, have "
                f"{self.allocator.free_blocks}")
        return needs

    def put(self, uids: Sequence[int],
            tokens: Sequence[Sequence[int]]) -> np.ndarray:
        cfg = self.config
        self._admit_tokens(uids, tokens)
        sched = self._pack_splitfuse()
        if not sched:
            raise ValueError("put() called with no pending tokens")
        needs = self._validate_sched(sched)
        for (seq, take), n in zip(sched, needs):
            if n:
                seq.blocks.extend(self.allocator.allocate(n))
            seq.seen += take
        self.tick_count += 1
        scheduled = {seq.uid for seq, _ in sched}
        out = np.full((len(uids), cfg.vocab), np.nan, np.float32)
        for i, uid in enumerate(uids):
            seq = self.seqs[uid]
            if seq.pending == 0 and uid in scheduled:
                out[i] = 0.0
                out[i, _next_token(seq.tokens, cfg.vocab)] = 1.0
        return out

    def put_spec(self, uids: Sequence[int],
                 tokens: Sequence[Sequence[int]],
                 drafts: Sequence[Sequence[int]]):
        """Mirror of the ragged engine's ``put_spec``: one step verifying
        draft chains alongside prefill/decode traffic, same all-or-strip
        budget semantics and the same strip-on-PoolExhausted contract.
        Rows are the sim's one-hot "logits": row ``j`` is
        ``onehot(next(context through chain[j]))``, so greedy acceptance
        in the serving layer reproduces EXACTLY the plain tick-by-tick
        stream — the token-identity invariant's witness at fleet scale."""
        cfg = self.config
        self._admit_tokens(uids, tokens)
        # validate EVERY chain before appending ANY draft token (the
        # real engine's discipline: a raise mid-append would leave
        # earlier uids' unverified drafts in their streams)
        for uid, d in zip(uids, drafts):
            if d and self.seqs[uid].pending != 1:
                raise ValueError(
                    f"uid {uid}: a draft chain continues exactly one "
                    f"pending decode token, found "
                    f"pending={self.seqs[uid].pending}")
        appended: Dict[int, int] = {}
        for uid, d in zip(uids, drafts):
            if not d:
                continue
            self.seqs[uid].tokens.extend(int(t) for t in d)
            appended[uid] = len(d)
        try:
            sched = self._pack_splitfuse()
            if not sched:
                raise ValueError("put_spec() called with no pending tokens")
            take_of = {seq.uid: take for seq, take in sched}
            for uid in list(appended):       # all-or-strip under budget
                seq = self.seqs[uid]
                chain_len = 1 + appended[uid]
                take = take_of.get(uid, 0)
                if take < chain_len:
                    strip = chain_len - max(take, 1)
                    if strip:
                        del seq.tokens[len(seq.tokens) - strip:]
                        appended[uid] -= strip
                    if appended[uid] <= 0:
                        appended.pop(uid)
            sched = [(seq, min(take, seq.pending))
                     for seq, take in sched if seq.pending > 0]
            needs = self._validate_sched(sched)
        except BaseException:
            # strip every remaining draft token: the recovery retry is a
            # PLAIN put of the admitted feed, exactly as the real engine
            for uid, n in appended.items():
                seq = self.seqs[uid]
                del seq.tokens[len(seq.tokens) - n:]
            raise
        seen0: Dict[int, int] = {}
        for (seq, take), n in zip(sched, needs):
            if n:
                seq.blocks.extend(self.allocator.allocate(n))
            seen0[seq.uid] = seq.seen
            seq.seen += take
        self.tick_count += 1
        scheduled = {seq.uid for seq, _ in sched}
        out = np.full((len(uids), cfg.vocab), np.nan, np.float32)
        for i, uid in enumerate(uids):
            seq = self.seqs[uid]
            if seq.pending == 0 and uid in scheduled:
                out[i] = 0.0
                out[i, _next_token(seq.tokens, cfg.vocab)] = 1.0
        verified: Dict[int, Tuple[List[int], np.ndarray]] = {}
        for seq, take in sched:
            if seq.uid in appended:
                s0 = seen0[seq.uid]
                chain = [int(t) for t in seq.tokens[s0:s0 + take]]
                rows = np.zeros((take, cfg.vocab), np.float32)
                for j in range(take):
                    rows[j, _next_token(seq.tokens[:s0 + j + 1],
                                        cfg.vocab)] = 1.0
                verified[seq.uid] = (chain, rows)
        return out, verified


# ----------------------------------------------------------------------
# schedules
# ----------------------------------------------------------------------

@dataclass
class SimEvent:
    """One scheduled simulation event at virtual time ``t``. Kinds:

    * ``submit`` — one request (``ix`` is its stable logical id);
    * ``cancel`` — cancel submit ``target`` if still live;
    * ``tick_fault`` — arm ``n`` injected :class:`TickFault` ticks;
    * ``replica_death`` — kill the ``which``-th healthy replica;
    * ``latch`` — trip the preemption guard (graceful drain);
    * ``scale`` — ``fleet.scale_to(n)``;
    * ``stall`` — advance virtual time by ``dt`` without ticking (a load
      gap: queued deadlines keep running).
    """

    t: float
    kind: str
    payload: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        return {"t": self.t, "kind": self.kind, **self.payload}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "SimEvent":
        d = dict(d)
        return cls(t=float(d.pop("t")), kind=str(d.pop("kind")), payload=d)


@dataclass
class Schedule:
    """A complete, replayable simulation input: configs + event list.
    ``run_schedule(generate_schedule(seed))`` is a pure function — same
    seed, same trace hash."""

    seed: int
    horizon: float
    engine_cfg: Dict[str, Any]
    fleet_cfg: Dict[str, Any]
    serving_cfg: Dict[str, Any]
    events: List[SimEvent]

    def to_dict(self) -> Dict[str, Any]:
        return {"seed": self.seed, "horizon": self.horizon,
                "engine_cfg": self.engine_cfg, "fleet_cfg": self.fleet_cfg,
                "serving_cfg": self.serving_cfg,
                "events": [e.to_dict() for e in self.events]}

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "Schedule":
        return cls(seed=int(d["seed"]), horizon=float(d["horizon"]),
                   engine_cfg=dict(d["engine_cfg"]),
                   fleet_cfg=dict(d["fleet_cfg"]),
                   serving_cfg=dict(d["serving_cfg"]),
                   events=[SimEvent.from_dict(e) for e in d["events"]])

    def replace_events(self, events: List[SimEvent]) -> "Schedule":
        return Schedule(seed=self.seed, horizon=self.horizon,
                        engine_cfg=dict(self.engine_cfg),
                        fleet_cfg=dict(self.fleet_cfg),
                        serving_cfg=dict(self.serving_cfg),
                        events=list(events))


@dataclass
class RegionSchedule(Schedule):
    """A region-scale schedule: the base fields plus the
    :class:`~deepspeed_tpu.config.RegionConfig` dict and region-scale
    event kinds (``cell_outage``, ``partition``, ``heal``,
    ``autoscaler_lag`` — docs/dst.md "Region-scale events").
    ``run_region_schedule(generate_region_schedule(seed))`` is a pure
    function, same as the fleet tier."""

    region_cfg: Dict[str, Any] = field(default_factory=dict)

    def to_dict(self) -> Dict[str, Any]:
        d = super().to_dict()
        d["region_cfg"] = dict(self.region_cfg)
        return d

    @classmethod
    def from_dict(cls, d: Dict[str, Any]) -> "RegionSchedule":
        return cls(seed=int(d["seed"]), horizon=float(d["horizon"]),
                   engine_cfg=dict(d["engine_cfg"]),
                   fleet_cfg=dict(d["fleet_cfg"]),
                   serving_cfg=dict(d["serving_cfg"]),
                   region_cfg=dict(d.get("region_cfg", {})),
                   events=[SimEvent.from_dict(e) for e in d["events"]])

    def replace_events(self, events: List[SimEvent]) -> "RegionSchedule":
        return RegionSchedule(seed=self.seed, horizon=self.horizon,
                              engine_cfg=dict(self.engine_cfg),
                              fleet_cfg=dict(self.fleet_cfg),
                              serving_cfg=dict(self.serving_cfg),
                              region_cfg=dict(self.region_cfg),
                              events=list(events))


def _event_order(e: SimEvent):
    """Deterministic total order for schedule events (repr-keyed payload
    tie-break: payload values are mixed types, so direct comparison
    could raise)."""
    return (e.t, e.kind, sorted(map(repr, e.payload.items())))


def generate_schedule(seed: int) -> Schedule:
    """Expand a seed into a randomized fault schedule: fleet/serving
    config draws plus a time-ordered event list composing the existing
    injectors (tick faults, replica death, preemption latch) with
    request traffic sized to hit slot/KV pressure, deadline expiry,
    rejection and cancellation paths."""
    import random

    rng = random.Random(seed)
    engine_cfg = SimConfig().to_dict()
    disaggregated = rng.random() < 0.20
    replicas = rng.randint(1, 3)
    fleet_cfg: Dict[str, Any] = {
        "replicas": replicas,
        "router": rng.choice(["least_loaded", "prefix_affinity"]),
        "failover": True,
        "respawn": rng.random() < 0.5,
        "autoscale": rng.random() < 0.25,
        "autoscale_interval_s": 4.0,
        "min_replicas": 1,
        "max_replicas": 4,
    }
    if disaggregated:
        fleet_cfg.update(disaggregated=True, prefill_replicas=1,
                         replicas=max(1, replicas - 1))
    serving_cfg: Dict[str, Any] = {
        "policy": "slo" if rng.random() < 0.8 else "fcfs",
        "max_queue": rng.choice([4, 8, 32]),
        "tick_retry_limit": rng.randint(0, 2),
        "reserve_output_blocks": rng.random() < 0.7,
        "kv_pressure": rng.choice([0.5, 0.8, 0.9]),
        "stuck_tick_timeout_s": 0.0,      # no watchdog thread in the sim
        "drain_timeout_s": 600.0,
        # drain loops sleep this long per pump step; the default 2ms
        # would take 60k pumped fleet steps to burn a virtual timeout
        "poll_interval_s": 0.25,
    }
    horizon = float(rng.randint(30, 70))
    vocab = engine_cfg["vocab"]
    events: List[SimEvent] = []
    n_req = rng.randint(6, 16)
    # a few shared prefixes so prefix-cache adoption + affinity routing
    # actually trigger
    prefixes = [[rng.randrange(1, vocab) for _ in range(8)]
                for _ in range(2)]
    for ix in range(n_req):
        t = round(rng.uniform(0.0, horizon * 0.6), 3)
        if rng.random() < 0.3:
            prompt = list(rng.choice(prefixes)) + [
                rng.randrange(1, vocab) for _ in range(rng.randint(1, 4))]
        else:
            prompt = [rng.randrange(1, vocab)
                      for _ in range(rng.randint(3, 14))]
        payload: Dict[str, Any] = {
            "ix": ix, "prompt": prompt,
            "max_new": rng.randint(1, 12),
            "priority": rng.randint(0, 2),
        }
        if rng.random() < 0.5:
            payload["deadline"] = round(rng.uniform(4.0, 40.0), 3)
        if rng.random() < 0.3:
            payload["ttft_deadline"] = round(rng.uniform(2.0, 12.0), 3)
        if rng.random() < 0.2:
            payload["eos"] = rng.randrange(0, vocab)
        if rng.random() < 0.06:
            # hopeless geometry: exercises the up-front reject paths
            payload["max_new"] = engine_cfg["max_context"] * 2
        events.append(SimEvent(t=t, kind="submit", payload=payload))
        if rng.random() < 0.15:
            events.append(SimEvent(
                t=round(t + rng.uniform(0.5, 10.0), 3), kind="cancel",
                payload={"target": ix}))
    for _ in range(rng.randint(0, 3)):
        events.append(SimEvent(t=round(rng.uniform(1.0, horizon * 0.7), 3),
                               kind="tick_fault",
                               payload={"n": rng.randint(1, 2)}))
    for _ in range(rng.randint(0, 2) if replicas > 1 or fleet_cfg["respawn"]
                   else 0):
        events.append(SimEvent(t=round(rng.uniform(2.0, horizon * 0.8), 3),
                               kind="replica_death",
                               payload={"which": rng.randint(0, 3)}))
    if rng.random() < 0.10:
        events.append(SimEvent(t=round(rng.uniform(horizon * 0.5,
                                                   horizon * 0.9), 3),
                               kind="latch", payload={}))
    if not disaggregated and rng.random() < 0.3:
        for _ in range(rng.randint(1, 2)):
            events.append(SimEvent(
                t=round(rng.uniform(2.0, horizon * 0.8), 3), kind="scale",
                payload={"n": rng.randint(1, 3)}))
    if rng.random() < 0.25:
        events.append(SimEvent(t=round(rng.uniform(1.0, horizon * 0.6), 3),
                               kind="stall",
                               payload={"dt": round(rng.uniform(3.0,
                                                                20.0), 3)}))
    events.sort(key=_event_order)
    # speculative serving + quantized-KV draws — appended AFTER the event
    # stream so pre-existing seeds keep their exact event sequences (the
    # regression-seed corpus stays meaningful). The invariants must hold
    # with drafts verifying inside the tick (multiple tokens per request
    # per tick) and with the quantized pool/wire mode declared end to
    # end; invariant #10 (token identity) witnesses that neither changes
    # WHICH tokens any request emits.
    if rng.random() < 0.35:
        serving_cfg.update(
            speculative=True,
            spec_lookahead=rng.choice([2, 4]),
            spec_ngram=2,
            spec_accept_floor=rng.choice([0.0, 0.3]),
            spec_floor_min_proposed=8)
    kvq = rng.choice(["none", "none", "int8", "int4"])
    engine_cfg["kv_quant"] = kvq
    serving_cfg["kv_quant"] = kvq
    # gray-failure plane draws (serving/health.py) — appended AFTER
    # every pre-existing draw, same regression-corpus rationale as
    # above. Config and fault draws are INDEPENDENT on purpose:
    # quarantine may run under clean traffic (the no-flap invariant's
    # null case) and a straggler may limp with the plane off (the
    # mitigation-off baseline gray_lane's TTFT gate compares against).
    if rng.random() < 0.55:
        fleet_cfg.update(
            quarantine=True,
            quarantine_threshold=rng.choice([0.4, 0.5]),
            quarantine_after=rng.choice([2, 3]),
            quarantine_dwell_s=rng.choice([6.0, 10.0]),
            quarantine_readmit_polls=rng.choice([2, 3]))
    if rng.random() < 0.5:
        fleet_cfg.update(
            breakers=True,
            breaker_failures=rng.choice([3, 4]),
            breaker_cooldown_s=rng.choice([4.0, 8.0]))
    if rng.random() < 0.45:
        fleet_cfg.update(hedge=True,
                         hedge_ttft_fraction=rng.choice([0.5, 0.6]))
    if replicas > 1 and rng.random() < 0.45:
        events.append(SimEvent(
            t=round(rng.uniform(1.0, horizon * 0.5), 3),
            kind="degraded_tick",
            payload={"which": rng.randint(0, 3), "k": rng.randint(2, 4)}))
    if rng.random() < 0.3:
        events.append(SimEvent(
            t=round(rng.uniform(1.0, horizon * 0.6), 3),
            kind="stall_burst",
            payload={"which": rng.randint(0, 3), "n": rng.randint(2, 6)}))
    if rng.random() < 0.25:
        events.append(SimEvent(
            t=round(rng.uniform(0.0, horizon * 0.4), 3),
            kind="flaky_import", payload={"every": rng.choice([2, 3])}))
    # global KV tier draws (serving/kvtier.py; docs/dst.md #17-#19) —
    # appended at the very end, same regression-corpus rationale: the
    # directory, residency routing, cross-replica adoption and the cold
    # tier run with their three fault kinds (stale directory entries,
    # adoption-wire corruption, cold-tier pressure drops). Independent
    # of every earlier draw, so old seeds replay bit-identically with
    # the tier off.
    if rng.random() < 0.45:
        serving_cfg["kv_tier"] = {
            "enabled": True,
            "publish_interval_s": rng.choice([0.5, 1.0]),
            "directory_staleness_s": rng.choice([3.0, 6.0]),
            "adoption": rng.random() < 0.8,
            "cold_tier": rng.random() < 0.8,
            "cold_capacity_pages": rng.choice([16, 64, 128]),
        }
        # a tiered seed must actually EXERCISE the tier: residency
        # routing needs a prefix router and a second replica, adoption
        # needs concurrent same-prefix load spilling off the affinity
        # pick, and cold spill/readmit needs pool pressure. Tiered
        # seeds are new schedules, so reshaping them here does not
        # perturb the pre-existing corpus.
        fleet_cfg["router"] = rng.choice(["prefix_affinity", "residency"])
        if not fleet_cfg.get("disaggregated"):
            fleet_cfg["replicas"] = max(fleet_cfg["replicas"], 2)
        engine_cfg["n_kv_blocks"] = rng.choice([20, 28, 40])
        # stragglers land deep in the run, AFTER pressure evictions
        # spilled the shared prefixes — the cold-readmit path's
        # trigger. The tail of the burst REPEATS earlier burst prompts
        # verbatim: a repeat's block-aligned prefix keys are exactly
        # the keys the earlier request's cache levels spilled under
        # pressure, so the repeat rides cold re-admission (or the
        # device cache, when the level survived) instead of a cold
        # re-prefill.
        burst_prompts: List[List[int]] = []
        for j in range(rng.randint(4, 8)):
            if burst_prompts and rng.random() < 0.4:
                prompt = list(rng.choice(burst_prompts))
            else:
                prompt = list(rng.choice(prefixes)) + [
                    rng.randrange(1, vocab)
                    for _ in range(rng.randint(1, 3))]
                burst_prompts.append(prompt)
            events.append(SimEvent(
                t=round(rng.uniform(horizon * 0.1, horizon * 0.95), 3),
                kind="submit",
                payload={"ix": n_req + j, "prompt": prompt,
                         "max_new": rng.randint(1, 8),
                         "priority": rng.randint(0, 2)}))
        if rng.random() < 0.5:
            events.append(SimEvent(
                t=round(rng.uniform(1.0, horizon * 0.6), 3),
                kind="stale_directory",
                payload={"every": rng.choice([2, 3])}))
        if rng.random() < 0.5:
            events.append(SimEvent(
                t=round(rng.uniform(1.0, horizon * 0.6), 3),
                kind="corrupt_adopt",
                payload={"every": rng.choice([1, 2])}))
        if rng.random() < 0.4:
            events.append(SimEvent(
                t=round(rng.uniform(1.0, horizon * 0.6), 3),
                kind="cold_pressure",
                payload={"every": rng.choice([2, 3])}))
    return Schedule(seed=seed, horizon=horizon, engine_cfg=engine_cfg,
                    fleet_cfg=fleet_cfg, serving_cfg=serving_cfg,
                    events=events)


def generate_region_schedule(seed: int) -> RegionSchedule:
    """Expand a seed into a REGION-scale fault schedule: N cells of M
    replicas behind the two-tier router, request traffic (with bursts
    sized to trip the brownout ladder), and the failure modes that
    dominate at pod scale — whole-cell outages, inter-cell partitions
    (with and without the region front-end on the severed side), heals,
    and autoscaler lag — composed with every fleet-tier fault kind."""
    import random

    # a distinct stream from generate_schedule: region seed N must not
    # be the fleet-tier seed N wearing a different config
    rng = random.Random(f"region-{seed}")
    engine_cfg = SimConfig().to_dict()
    n_cells = rng.randint(2, 3)
    replicas = rng.randint(1, 2)
    disaggregated = rng.random() < 0.25
    fleet_cfg: Dict[str, Any] = {
        "replicas": replicas,
        "router": rng.choice(["least_loaded", "prefix_affinity"]),
        "failover": True,
        "respawn": rng.random() < 0.4,
        "autoscale": rng.random() < 0.25,
        "autoscale_interval_s": 4.0,
        "min_replicas": 1,
        "max_replicas": 3,
        "route_backoff_s": 0.05,
    }
    if disaggregated:
        fleet_cfg.update(disaggregated=True, prefill_replicas=1,
                         replicas=max(1, replicas - 1))
    region_cfg: Dict[str, Any] = {
        "cells": n_cells,
        "cell_ring_vnodes": 16,
        "brownout_queue_per_replica": rng.choice([2.0, 4.0, 8.0]),
        "rebalance_threshold": rng.choice([0.0, 1.0, 2.0]),
        "cell_spill_load": rng.choice([0, 0, 6]),
    }
    serving_cfg: Dict[str, Any] = {
        "policy": "slo" if rng.random() < 0.8 else "fcfs",
        "max_queue": rng.choice([8, 32]),
        "tick_retry_limit": rng.randint(0, 2),
        "reserve_output_blocks": rng.random() < 0.7,
        "kv_pressure": rng.choice([0.5, 0.8, 0.9]),
        "stuck_tick_timeout_s": 0.0,
        "drain_timeout_s": 600.0,
        "poll_interval_s": 0.25,
    }
    horizon = float(rng.randint(40, 80))
    vocab = engine_cfg["vocab"]
    events: List[SimEvent] = []
    prefixes = [[rng.randrange(1, vocab) for _ in range(8)]
                for _ in range(2)]

    def add_submit(ix: int, t: float) -> None:
        if rng.random() < 0.3:
            prompt = list(rng.choice(prefixes)) + [
                rng.randrange(1, vocab) for _ in range(rng.randint(1, 4))]
        else:
            prompt = [rng.randrange(1, vocab)
                      for _ in range(rng.randint(3, 14))]
        payload: Dict[str, Any] = {
            "ix": ix, "prompt": prompt,
            "max_new": rng.randint(1, 10),
            "priority": rng.randint(0, 2),
        }
        if rng.random() < 0.5:
            payload["deadline"] = round(rng.uniform(4.0, 40.0), 3)
        if rng.random() < 0.25:
            payload["ttft_deadline"] = round(rng.uniform(2.0, 12.0), 3)
        if rng.random() < 0.2:
            payload["eos"] = rng.randrange(0, vocab)
        if rng.random() < 0.04:
            payload["max_new"] = engine_cfg["max_context"] * 2
        events.append(SimEvent(t=t, kind="submit", payload=payload))
        if rng.random() < 0.12:
            events.append(SimEvent(
                t=round(t + rng.uniform(0.5, 10.0), 3), kind="cancel",
                payload={"target": ix}))

    ix = 0
    for _ in range(rng.randint(8, 18)):
        add_submit(ix, round(rng.uniform(0.0, horizon * 0.6), 3))
        ix += 1
    if rng.random() < 0.45:
        # a correlated burst: the brownout ladder's natural trigger
        t0 = round(rng.uniform(2.0, horizon * 0.5), 3)
        for _ in range(rng.randint(6, 14)):
            add_submit(ix, round(t0 + rng.uniform(0.0, 1.5), 3))
            ix += 1
    for _ in range(rng.randint(0, 2)):
        events.append(SimEvent(t=round(rng.uniform(1.0, horizon * 0.7), 3),
                               kind="tick_fault",
                               payload={"n": rng.randint(1, 2)}))
    for _ in range(rng.randint(0, 2)):
        events.append(SimEvent(t=round(rng.uniform(2.0, horizon * 0.8), 3),
                               kind="replica_death",
                               payload={"cell": rng.randint(0, 3),
                                        "which": rng.randint(0, 3)}))
    if n_cells > 1 and rng.random() < 0.5:
        events.append(SimEvent(t=round(rng.uniform(3.0, horizon * 0.7), 3),
                               kind="cell_outage",
                               payload={"which": rng.randint(0, 3)}))
    if n_cells > 1 and rng.random() < 0.55:
        t_p = round(rng.uniform(2.0, horizon * 0.6), 3)
        far = sorted(rng.sample(range(n_cells),
                                rng.randint(1, n_cells - 1)))
        events.append(SimEvent(t=t_p, kind="partition",
                               payload={"far": far,
                                        "sever_region":
                                        rng.random() < 0.6}))
        if rng.random() < 0.85:
            events.append(SimEvent(
                t=round(t_p + rng.uniform(4.0, 25.0), 3), kind="heal",
                payload={}))
    if rng.random() < 0.3:
        events.append(SimEvent(t=round(rng.uniform(1.0, horizon * 0.5), 3),
                               kind="autoscaler_lag",
                               payload={"dt": rng.choice([5.0, 10.0,
                                                          20.0])}))
    if rng.random() < 0.08:
        events.append(SimEvent(t=round(rng.uniform(horizon * 0.5,
                                                   horizon * 0.9), 3),
                               kind="latch", payload={}))
    if not disaggregated and rng.random() < 0.2:
        events.append(SimEvent(t=round(rng.uniform(2.0, horizon * 0.8), 3),
                               kind="scale",
                               payload={"cell": rng.randint(0, 3),
                                        "n": rng.randint(1, 3)}))
    if rng.random() < 0.2:
        events.append(SimEvent(t=round(rng.uniform(1.0, horizon * 0.6), 3),
                               kind="stall",
                               payload={"dt": round(rng.uniform(3.0,
                                                                15.0), 3)}))
    events.sort(key=_event_order)
    # speculative + kv-quant draws appended after the event stream (same
    # rationale as generate_schedule): region chaos — cell outages,
    # partitions, cross-cell adoption — must preserve token identity
    # with drafts and quantized hand-offs in play
    if rng.random() < 0.3:
        serving_cfg.update(
            speculative=True, spec_lookahead=rng.choice([2, 4]),
            spec_ngram=2, spec_accept_floor=rng.choice([0.0, 0.3]),
            spec_floor_min_proposed=8)
    kvq = rng.choice(["none", "none", "int8", "int4"])
    engine_cfg["kv_quant"] = kvq
    serving_cfg["kv_quant"] = kvq
    # rollout / canary / migration draws — appended AFTER every
    # pre-existing draw, same regression-corpus rationale as above.
    # Tenants are stamped onto the already-generated submits in list
    # order (payload keys only; the run-time sort's repr tie-break is
    # deterministic either way), then the version-flip machinery is
    # composed with the chaos the rest of the schedule already throws:
    # rollouts mid-death, migrations mid-partition, injected canary SLO
    # regressions, corrupt new-version checkpoints and deaths mid-flip.
    for e in events:
        if e.kind == "submit" and rng.random() < 0.8:
            e.payload["tenant"] = f"tenant-{rng.randrange(0, 6)}"
    serving_cfg["rollout"] = {
        "canary_fraction": rng.choice([0.25, 0.5]),
        "canary_observe_ticks": rng.choice([40, 80, 160]),
        "slo_regression_threshold": rng.choice([0.15, 0.25]),
        "min_canary_samples": rng.choice([2, 3]),
        "warmup_ticks": rng.choice([0, 1, 2]),
        "swap_retry_limit": 2,
        "max_flip_attempts": 4,
    }
    if rng.random() < 0.55:
        t_r = round(rng.uniform(2.0, horizon * 0.5), 3)
        events.append(SimEvent(t=t_r, kind="rollout",
                               payload={"version": 1,
                                        "fraction": rng.choice(
                                            [0.3, 0.5, 1.0])}))
        if rng.random() < 0.45:
            events.append(SimEvent(
                t=round(t_r + rng.uniform(1.0, 10.0), 3),
                kind="canary_regress", payload={}))
        if rng.random() < 0.30:
            events.append(SimEvent(
                t=round(t_r - rng.uniform(0.1, 1.5), 3),
                kind="corrupt_swap", payload={"n": rng.randint(1, 2)}))
        if rng.random() < 0.25:
            events.append(SimEvent(
                t=round(t_r - rng.uniform(0.1, 1.5), 3),
                kind="flip_death",
                payload={"ordinal": rng.randint(1, 2)}))
    for _ in range(rng.randint(0, 2)):
        events.append(SimEvent(t=round(rng.uniform(2.0, horizon * 0.8), 3),
                               kind="migrate",
                               payload={"cell": rng.randint(0, 3),
                                        "replica": rng.randint(0, 3)}))
    # gray-failure plane draws — appended after every pre-existing draw
    # (same corpus rationale); the region tier composes quarantine,
    # breakers and hedging with cell outages, partitions and rollouts
    if rng.random() < 0.5:
        fleet_cfg.update(
            quarantine=True,
            quarantine_threshold=rng.choice([0.4, 0.5]),
            quarantine_after=rng.choice([2, 3]),
            quarantine_dwell_s=rng.choice([6.0, 10.0]),
            quarantine_readmit_polls=rng.choice([2, 3]))
    if rng.random() < 0.4:
        fleet_cfg.update(
            breakers=True,
            breaker_failures=rng.choice([3, 4]),
            breaker_cooldown_s=rng.choice([4.0, 8.0]))
    if rng.random() < 0.35:
        fleet_cfg.update(hedge=True,
                         hedge_ttft_fraction=rng.choice([0.5, 0.6]))
    if rng.random() < 0.4:
        events.append(SimEvent(
            t=round(rng.uniform(1.0, horizon * 0.5), 3),
            kind="degraded_tick",
            payload={"cell": rng.randint(0, 3),
                     "which": rng.randint(0, 3),
                     "k": rng.randint(2, 4)}))
    if rng.random() < 0.25:
        events.append(SimEvent(
            t=round(rng.uniform(1.0, horizon * 0.6), 3),
            kind="stall_burst",
            payload={"cell": rng.randint(0, 3),
                     "which": rng.randint(0, 3),
                     "n": rng.randint(2, 6)}))
    if rng.random() < 0.2:
        events.append(SimEvent(
            t=round(rng.uniform(0.0, horizon * 0.4), 3),
            kind="flaky_import", payload={"every": rng.choice([2, 3])}))
    # global KV tier draws — appended at the very end (see
    # generate_schedule); at region scale the tier additionally
    # composes with cell outages/partitions (whole-member directory
    # drops) and the cell-residency routing preference
    if rng.random() < 0.40:
        serving_cfg["kv_tier"] = {
            "enabled": True,
            "publish_interval_s": rng.choice([0.5, 1.0]),
            "directory_staleness_s": rng.choice([3.0, 6.0]),
            "adoption": rng.random() < 0.8,
            "cold_tier": rng.random() < 0.8,
            "cold_capacity_pages": rng.choice([16, 64, 128]),
        }
        # same reshaping as the fleet tier: tiered region seeds get a
        # prefix router, a second replica per cell, pool pressure, and
        # a shared-prefix burst so the directory/adoption/cold paths
        # run hot (tiered seeds are new schedules — no corpus impact)
        fleet_cfg["router"] = rng.choice(["prefix_affinity", "residency"])
        if not fleet_cfg.get("disaggregated"):
            fleet_cfg["replicas"] = max(fleet_cfg["replicas"], 2)
        engine_cfg["n_kv_blocks"] = rng.choice([20, 28, 40])
        burst_prompts: List[List[int]] = []
        for _ in range(rng.randint(4, 8)):
            if burst_prompts and rng.random() < 0.4:
                prompt = list(rng.choice(burst_prompts))
            else:
                prompt = list(rng.choice(prefixes)) + [
                    rng.randrange(1, vocab)
                    for _ in range(rng.randint(1, 3))]
                burst_prompts.append(prompt)
            events.append(SimEvent(
                t=round(rng.uniform(horizon * 0.1, horizon * 0.95), 3),
                kind="submit",
                payload={"ix": ix, "prompt": prompt,
                         "max_new": rng.randint(1, 8),
                         "priority": rng.randint(0, 2)}))
            ix += 1
        if rng.random() < 0.5:
            events.append(SimEvent(
                t=round(rng.uniform(1.0, horizon * 0.6), 3),
                kind="stale_directory",
                payload={"every": rng.choice([2, 3])}))
        if rng.random() < 0.5:
            events.append(SimEvent(
                t=round(rng.uniform(1.0, horizon * 0.6), 3),
                kind="corrupt_adopt",
                payload={"every": rng.choice([1, 2])}))
        if rng.random() < 0.4:
            events.append(SimEvent(
                t=round(rng.uniform(1.0, horizon * 0.6), 3),
                kind="cold_pressure",
                payload={"every": rng.choice([2, 3])}))
    return RegionSchedule(seed=seed, horizon=horizon,
                          engine_cfg=engine_cfg, fleet_cfg=fleet_cfg,
                          serving_cfg=serving_cfg, region_cfg=region_cfg,
                          events=events)


# ----------------------------------------------------------------------
# harness internals
# ----------------------------------------------------------------------

class _ScheduledFaultInjector(FaultInjector):
    """The soak's tick-fault arm: schedule events arm N failures, the
    next N serving ticks (fleet-wide) raise :class:`TickFault` through
    the production injector hook."""

    def __init__(self) -> None:
        super().__init__()
        self._armed = 0

    def arm(self, n: int) -> None:
        self._armed += int(n)

    def on_serving_tick(self, tick: int) -> None:
        if self._armed > 0:
            self._armed -= 1
            self._count("serving_tick_fail")
            raise TickFault(f"dst: injected serving tick fault (tick {tick})")


class _CaptureTelemetry(Telemetry):
    """Enabled telemetry with a fresh registry and an in-memory span
    capture instead of file sinks — the auditor's ledger view."""

    def __init__(self) -> None:
        super().__init__(config=None, registry=MetricsRegistry())
        self.enabled = True
        self.spans: List[Any] = []

    def record_request_span(self, stats):
        record = super().record_request_span(stats)
        self.spans.append(stats)
        return record


class _SimGuard:
    """Preemption-latch stand-in (the production guard is signal-bound)."""

    def __init__(self) -> None:
        self.should_stop = False


@dataclass
class _Tracked:
    """Harness-side bookkeeping for one submitted request."""

    ix: int
    req: Any
    delivered: List[int] = field(default_factory=list)


class _Trace:
    """Canonical event trace; its hash is the determinism witness."""

    def __init__(self) -> None:
        self.rows: List[tuple] = []

    def event(self, vt: float, kind: str, payload: Dict[str, Any]) -> None:
        canon = tuple(sorted((k, self._c(v)) for k, v in payload.items()))
        self.rows.append(("E", round(vt, 6), kind, canon))

    def tick(self, n: int, vt: float, fleet, tracked: List[_Tracked]) -> None:
        reps = tuple((r.name, r.state, r.serving._tick_count,
                      len(r.serving._queue), len(r.serving._live),
                      r.serving.pending_work)
                     for r in fleet.replicas)
        states: Dict[str, int] = {}
        total_tokens = 0
        for t in tracked:
            states[t.req.state.value] = states.get(t.req.state.value, 0) + 1
            total_tokens += len(t.req.tokens)
        self.rows.append(("T", n, round(vt, 6), reps,
                          tuple(sorted(states.items())), total_tokens))

    def tick_region(self, n: int, vt: float, region,
                    tracked: List[_Tracked]) -> None:
        cells = tuple(
            (c.name, c.state, tuple(
                (r.name, r.state, r.serving._tick_count,
                 len(r.serving._queue), len(r.serving._live),
                 r.serving.pending_work)
                for r in c.fleet.replicas))
            for c in region.cells)
        states: Dict[str, int] = {}
        total_tokens = 0
        for t in tracked:
            states[t.req.state.value] = states.get(t.req.state.value, 0) + 1
            total_tokens += len(t.req.tokens)
        self.rows.append(("T", n, round(vt, 6), cells,
                          tuple(sorted(states.items())), total_tokens,
                          region.brownout_floor))

    def finish(self, tracked: List[_Tracked]) -> None:
        self.rows.append(("F", tuple(
            (t.ix, t.req.state.value, tuple(t.req.tokens),
             round(t.req.t_finish, 6) if t.req.t_finish is not None else None)
            for t in tracked)))

    def hash(self) -> str:
        payload = "\n".join(repr(r) for r in self.rows)
        return hashlib.sha256(payload.encode("utf-8")).hexdigest()

    @staticmethod
    def _c(v):
        if isinstance(v, list):
            return tuple(v)
        return v


#: virtual seconds a score-breaching replica may stay ACTIVE while the
#: capacity floor has headroom before quarantine convergence (#15) is
#: violated — the honest monitor acts on the very poll it observes the
#: breach, so anything past a few polls is a detector that never fires
QUARANTINE_SLACK_S = 30.0
#: virtual seconds the routable pool may transiently sit below the
#: capacity floor (a death mid-event is repaired at the next monitor
#: poll's floor-release pass)
FLOOR_SLACK_S = 5.0
#: no-flap bound (#16): max quarantine entries per replica inside any
#: FLAP_WINDOW_S of virtual time. Doubled-dwell hysteresis caps the
#: honest machine at 5 entries per 100 virtual seconds even with the
#: shortest drawn dwell and a breach on every probation poll.
FLAP_WINDOW_S = 100.0
FLAP_LIMIT = 6


class InvariantAuditor:
    """The post-event audits. Each returns a list of violation strings;
    an empty list after every event of every schedule is the soak's
    pass condition."""

    def __init__(self, fleet, clock, capture: _CaptureTelemetry,
                 tracer: Optional[Tracer] = None,
                 vocab: Optional[int] = None,
                 injector: Optional[FaultInjector] = None) -> None:
        self.fleet = fleet
        self.clock = clock
        self.capture = capture
        self.tracer = tracer
        # the run's injector: #15's ground truth for WHICH replica the
        # schedule degraded (straggler_evidence_snapshot)
        self.injector = injector
        # sim vocab arms invariant #10 (greedy token-identity): the
        # expected stream is recomputable from the prompt alone because
        # the sim model is a pure function of context
        self.vocab = vocab
        self._expected: Dict[int, List[int]] = {}
        # trace_ids whose tree was already audited: each request's tree
        # is checked ONCE, when it first turns terminal — re-scanning
        # the whole span ring per terminal request per tick would make
        # the soak quadratic in run length
        self._trees_checked: set = set()
        self._last_now = clock.now()
        # gray-plane audit state (#15): replica -> first audit instant
        # a should-quarantine breach was seen with floor headroom, and
        # fleet-pool -> first audit instant the floor was seen broken
        self._q_pending: Dict[str, float] = {}
        self._floor_breach: Dict[str, float] = {}

    def _replicas(self):
        """Every replica under audit. The region subclass widens this to
        all cells' fleets — every invariant below then holds REGION-wide
        for free (conservation across cell death, ownership across
        partitions)."""
        return list(self.fleet.replicas)

    def _fleets(self):
        """Every fleet under audit (the gray-plane invariants #14-#16
        read per-fleet health/breaker/hedge ledgers). The region
        subclass widens this to all cells' fleets."""
        return [self.fleet]

    def _hedge_pairs(self):
        """Every HedgePair the audited fleets ever minted (live uid rows
        plus the both-terminal ledger), deduplicated."""
        pairs = []
        seen: set = set()
        for fleet in self._fleets():
            for p in list(fleet._hedges.values()) + list(fleet._hedge_done):
                if id(p) in seen:
                    continue
                seen.add(id(p))
                pairs.append(p)
        return pairs

    def audit(self, tracked: List[_Tracked]) -> List[str]:
        from ..serving.request import RequestState

        v: List[str] = []
        # 5. monotone virtual time
        now = self.clock.now()
        if now < self._last_now:
            v.append(f"[time] virtual time went backwards: "
                     f"{self._last_now} -> {now}")
        self._last_now = now
        # 1. KV block-balance partition, every replica incl. dead ones
        for rep in self._replicas():
            for p in block_balance_report(rep.engine)["problems"]:
                v.append(f"[block-balance] {rep.name}: {p}")
        # 2. request state-machine legality / containment
        for rep in self._replicas():
            srv = rep.serving
            for r in srv._queue:
                if r.state is not RequestState.QUEUED:
                    v.append(f"[state] {rep.name}: request {r.uid} in queue "
                             f"with state {r.state.name}")
            for uid, r in srv._live.items():
                if r.state not in (RequestState.PREFILL, RequestState.DECODE):
                    v.append(f"[state] {rep.name}: live request {uid} in "
                             f"state {r.state.name}")
            for uid, r in srv._requests.items():
                if r.is_terminal:
                    v.append(f"[state] {rep.name}: terminal request {uid} "
                             f"still registered")
        # 3. conservation: every submitted request is terminal or owned
        # by exactly one replica (no lost, no duplicated requests)
        for t in tracked:
            owners = [rep.name for rep in self._replicas()
                      if t.req.uid in rep.serving._requests]
            if t.req.is_terminal:
                if owners:
                    v.append(f"[conservation] r{t.ix} terminal but still "
                             f"owned by {owners}")
            elif len(owners) != 1:
                v.append(f"[conservation] r{t.ix} ({t.req.state.name}) "
                         f"owned by {owners} — expected exactly one owner")
        # 4. span / SLO ledger consistency. Hedged requests are judged
        # PAIR-wise by invariant #14 below (the two legs share one
        # ledger slot — the winner's); the per-uid rules here cover the
        # unhedged ones, with shadow uids admitted as known emitters.
        pairs = self._hedge_pairs()
        hedged = {p.primary.uid: p for p in pairs}
        shadow_uids = {p.shadow.uid for p in pairs}
        span_count: Dict[int, int] = {}
        for s in self.capture.spans:
            span_count[s.uid] = span_count.get(s.uid, 0) + 1
        known = {t.req.uid for t in tracked} | shadow_uids
        for uid in span_count:
            if uid not in known:
                v.append(f"[span-ledger] span for unknown uid {uid}")
        for t in tracked:
            if t.req.uid in hedged:
                continue
            n = span_count.get(t.req.uid, 0)
            if t.req.is_terminal and n != 1:
                v.append(f"[span-ledger] r{t.ix} terminal with {n} spans "
                         f"(exactly one expected)")
            elif not t.req.is_terminal and n != 0:
                v.append(f"[span-ledger] r{t.ix} live with {n} spans")
        judged = sum(1 for s in self.capture.spans if s.in_slo is not None)
        met = sum(1 for s in self.capture.spans if s.in_slo is True)
        reg = self.capture.registry
        if reg.counter("serving/slo_judged").value != judged:
            v.append(f"[slo-ledger] slo_judged counter "
                     f"{reg.counter('serving/slo_judged').value} != "
                     f"{judged} judged spans")
        if reg.counter("serving/slo_met").value != met:
            v.append(f"[slo-ledger] slo_met counter "
                     f"{reg.counter('serving/slo_met').value} != {met} "
                     f"met spans")
        # 6. stream-delivery completeness: on_token delivered exactly the
        # emitted stream, in order, across preempt/retry/failover. For a
        # hedged request the client-visible stream is the WINNER leg's —
        # the loser may have emitted tokens into its Request before the
        # gate dropped them, and that is exactly what must never leak.
        for t in tracked:
            pair = hedged.get(t.req.uid)
            if pair is not None:
                w = pair.winner
                want = list(w.tokens) if w is not None else []
                if t.delivered != want:
                    v.append(f"[delivery] r{t.ix} (hedged): delivered "
                             f"{t.delivered} != winner leg's emitted "
                             f"{want}")
                continue
            if t.delivered != list(t.req.tokens):
                v.append(f"[delivery] r{t.ix}: delivered {t.delivered} != "
                         f"emitted {list(t.req.tokens)}")
        # 10. greedy token-identity: every emitted stream is a PREFIX of
        # the pure-function greedy expectation recomputed from the
        # prompt alone — speculative decoding, quantized KV, preemption,
        # failover and disaggregated hand-off may change WHEN tokens
        # emit, never WHICH (docs/serving.md's token-identity contract,
        # witnessed at fleet scale on every audit)
        if self.vocab:
            for t in tracked:
                n = len(t.req.tokens)
                if not n:
                    continue
                want = self._expected_stream(t.req, n)
                if list(t.req.tokens) != want:
                    v.append(f"[token-identity] r{t.ix}: emitted "
                             f"{list(t.req.tokens)} != greedy expectation "
                             f"{want}")
        # 11. version-stream atomicity: one request's token stream is
        # emitted by ONE model version end to end (serving/rollout.py's
        # hot-swap contract). A flip that lets a swapped replica resume
        # a mid-stream request, or a version-blind failover resume,
        # would splice two versions into one stream — the continuation
        # gate must refuse and re-route instead.
        for t in tracked:
            if len(set(t.req.served_versions)) > 1:
                v.append(f"[version-stream] r{t.ix}: stream served by "
                         f"versions {t.req.served_versions} — a request "
                         f"is one version end to end")
        # 7. trace-tree connectivity: a terminal request's spans — across
        # however many replicas served it (failover, disagg hand-off) —
        # must form ONE closed connected tree: exactly one root, no
        # orphan parents, nothing left open
        if self.tracer is not None and self.tracer.enabled:
            for t in tracked:
                root = getattr(t.req, "_trace_root", None)
                if not t.req.is_terminal or root is None or root.is_noop \
                        or root.trace_id in self._trees_checked:
                    continue
                self._trees_checked.add(root.trace_id)
                for p in trace_tree_problems(
                        self.tracer.spans_for_trace(root.trace_id)):
                    v.append(f"[trace-tree] r{t.ix}: {p}")
        v.extend(self._audit_gray(pairs, span_count, now))
        v.extend(self._audit_kvtier())
        return v

    def _audit_gray(self, pairs, span_count: Dict[int, int],
                    now: float) -> List[str]:
        """The gray-failure plane's invariants (docs/dst.md):

        * **#14 hedge conservation** — of a hedged pair's two legs,
          exactly one wins; the loser's span/SLO verdict never reaches
          the ledger (at most one span across the pair, exactly one
          once both legs are terminal, and it is the winner's).
        * **#15 quarantine convergence + capacity floor** — a replica
          whose health machine demands quarantine while the floor has
          headroom is drained within ``QUARANTINE_SLACK_S``; the
          routable pool never sits below the floor for more than
          ``FLOOR_SLACK_S`` (quarantine defers/releases around it).
        * **#16 no-flap** — doubled-dwell hysteresis bounds quarantine
          churn: more than ``FLAP_LIMIT`` quarantine entries for one
          replica inside any ``FLAP_WINDOW_S`` of virtual time means
          the machine is flapping.
        """
        from ..serving.fleet import ReplicaState
        from ..serving.health import HealthState

        v: List[str] = []
        # 14. hedge conservation
        for pair in pairs:
            cid = pair.primary.client_request_id
            n = (span_count.get(pair.primary.uid, 0)
                 + span_count.get(pair.shadow.uid, 0))
            if n > 1:
                v.append(f"[hedge] {cid}: {n} spans across the two legs "
                         f"— the ledger judged the request more than "
                         f"once")
            if pair.winner_uid is not None:
                if pair.winner_uid not in (pair.primary.uid,
                                           pair.shadow.uid):
                    v.append(f"[hedge] {cid}: winner uid "
                             f"{pair.winner_uid} is neither leg")
                loser = pair.loser
                if loser is not None and span_count.get(loser.uid, 0):
                    v.append(f"[hedge] {cid}: decided LOSER leg "
                             f"{loser.uid} emitted a span — its verdict "
                             f"must be suppressed")
            if pair.primary.is_terminal and pair.shadow.is_terminal:
                if pair.winner_uid is None:
                    v.append(f"[hedge] {cid}: both legs terminal with "
                             f"no winner decided")
                elif n != 1:
                    v.append(f"[hedge] {cid}: both legs terminal with "
                             f"{n} spans (exactly one — the winner's — "
                             f"expected)")
        # 15. quarantine convergence + capacity floor
        for fi, fleet in enumerate(self._fleets()):
            cfg = fleet.config
            if not cfg.quarantine:
                continue
            ftag = fleet.name or f"fleet{fi}"
            pending_keys: set = set()
            pools = ((False,) if not cfg.disaggregated else (False, True))
            for prefill in pools:
                routable = pool = 0
                breaching: List[str] = []
                for r in fleet.replicas:
                    if (r.state is not ReplicaState.HEALTHY
                            or (r.role == "prefill") != prefill):
                        continue
                    pool += 1
                    h = fleet._health.get(r.name)
                    if h is None or h.routable:
                        routable += 1
                    if h is not None and h.should_quarantine():
                        breaching.append(r.name)
                floor = min(cfg.prefill_replicas if prefill
                            else cfg.min_replicas, pool)
                pkey = f"{ftag}/{'prefill' if prefill else 'decode'}"
                if routable < floor:
                    first = self._floor_breach.setdefault(pkey, now)
                    if now - first > FLOOR_SLACK_S:
                        v.append(f"[quarantine-floor] {pkey}: {routable} "
                                 f"routable < floor {floor} for "
                                 f"{now - first:.0f} virtual seconds — "
                                 f"quarantine drained below the "
                                 f"capacity floor")
                else:
                    self._floor_breach.pop(pkey, None)
                headroom = routable - 1 >= floor
                for name in breaching:
                    key = f"{ftag}/{name}"
                    if not headroom:
                        # the floor binds: deferral is the CORRECT
                        # behavior, restart the convergence timer
                        continue
                    pending_keys.add(key)
                    first = self._q_pending.setdefault(key, now)
                    if now - first > QUARANTINE_SLACK_S:
                        v.append(f"[quarantine] {key}: health machine "
                                 f"demanded quarantine for "
                                 f"{now - first:.0f} virtual seconds "
                                 f"with floor headroom, never drained")
            for key in list(self._q_pending):
                if key.startswith(f"{ftag}/") and key not in pending_keys:
                    self._q_pending.pop(key)
        # 16. no-flap
        for fleet in self._fleets():
            for h in fleet._health.values():
                entries = [t for (t, _frm, to) in h.transitions
                           if to == HealthState.QUARANTINED]
                for i in range(len(entries)):
                    j = i
                    while (j + 1 < len(entries)
                           and entries[j + 1] - entries[i]
                           <= FLAP_WINDOW_S):
                        j += 1
                    if j - i + 1 > FLAP_LIMIT:
                        v.append(f"[flap] {h.name}: {j - i + 1} "
                                 f"quarantine entries within "
                                 f"{FLAP_WINDOW_S:.0f} virtual seconds "
                                 f"— hysteresis is not bounding churn")
                        break
        return v

    def _audit_kvtier(self) -> List[str]:
        """The global KV tier's invariants (docs/dst.md):

        * **#17 directory-residency containment** — a directory entry
          never outlives its pages: every (member, hash) entry names a
          LIVE (non-DEAD) replica whose prefix cache currently holds
          that full-block prefix. The only exemption is a hash the
          fault injector itself planted (``stale_directory`` lies) —
          those must age out via the staleness bound, never be trusted,
          and are bookkept in ``injector.injected_stale``.
        * **#18 cold-tier accounting + integrity** — the host cold
          tier's page accounting is exact (``used == sum(entries)``,
          ``used <= capacity``) and every resident export still passes
          its checksum (spills gather from live pages, so a cold entry
          that fails verify() was corrupted INSIDE the tier).
        * **#19 corruption never lands** — a prefix export that fails
          checksum verification is NEVER imported into a device pool:
          ``kvtier_corrupt_landed`` stays zero on every engine (the
          ``corrupt_adopt`` fault kind feeds the wire-corruption side;
          the ``_kvtier_skip_verify`` seam is the planted-bug tooth).
        """
        from ..serving.fleet import ReplicaState

        v: List[str] = []
        injected = (self.injector.injected_stale_snapshot()
                    if self.injector is not None else set())
        for fi, fleet in enumerate(self._fleets()):
            tier = getattr(fleet, "kv_tier", None)
            if tier is None:
                continue
            ftag = fleet.name or f"fleet{fi}"
            reps = {r.name: r for r in fleet.replicas}
            # 17. directory-residency containment
            for member in tier.directory.members():
                rep = reps.get(member)
                if rep is None or rep.state is ReplicaState.DEAD:
                    v.append(f"[kv-directory] {ftag}: entries for "
                             f"{'unknown' if rep is None else 'dead'} "
                             f"member {member} — the entries outlived "
                             f"their replica")
                    continue
                resident = set(rep.engine.prefix_residency_hashes()) \
                    if hasattr(rep.engine, "prefix_residency_hashes") \
                    else set()
                for h in tier.directory.entries_for(member):
                    if h not in resident and (member, h) not in injected:
                        v.append(f"[kv-directory] {ftag}/{member}: entry "
                                 f"{h:#018x} not resident in the "
                                 f"member's prefix cache — the entry "
                                 f"outlived its pages")
            # 18. cold-tier accounting + integrity
            cold = tier.cold
            if cold is not None:
                pages = cold.entry_pages()
                used = cold.used_pages
                if used != sum(pages):
                    v.append(f"[kv-cold] {ftag}: used_pages {used} != "
                             f"sum of entries {sum(pages)} — page "
                             f"accounting drifted")
                if used > cold.capacity_pages:
                    v.append(f"[kv-cold] {ftag}: used_pages {used} over "
                             f"capacity {cold.capacity_pages} — LRU "
                             f"pressure valve failed")
                for e in cold.entries_snapshot():
                    if not e.verify():
                        v.append(f"[kv-cold] {ftag}: entry for "
                                 f"{len(e.tokens)}-token prefix fails "
                                 f"checksum — corrupted inside the "
                                 f"cold tier")
        # 19. corruption never lands (all replicas, dead included — a
        # corrupt import that landed before the kill still landed)
        for rep in self._replicas():
            landed = getattr(rep.engine, "kvtier_corrupt_landed", 0)
            if landed:
                v.append(f"[kv-adopt] {rep.name}: {landed} corrupt "
                         f"prefix export(s) imported into the device "
                         f"pool — verify-before-import is breached")
        return v

    def _expected_stream(self, req, n: int) -> List[int]:
        """First ``n`` tokens of the sim model's greedy stream for
        ``req`` — grown lazily and memoized per uid (the audit runs
        after every event; recomputing the FNV chain from scratch each
        time would be quadratic in run length)."""
        exp = self._expected.setdefault(req.uid, [])
        if len(exp) < n:
            ctx = list(req.prompt) + exp
            while len(exp) < n:
                t = _next_token(ctx, self.vocab)
                exp.append(t)
                ctx.append(t)
        return exp[:n]

    def final(self, tracked: List[_Tracked], engines: List[SimEngine]
              ) -> List[str]:
        """Post-close audit: everything terminal, zero leaked pages on
        every engine ever built (dead replicas included)."""
        v: List[str] = []
        for t in tracked:
            if not t.req.is_terminal:
                v.append(f"[liveness] r{t.ix} not terminal after close "
                         f"({t.req.state.name})")
        for i, eng in enumerate(engines):
            for p in block_balance_report(eng)["problems"]:
                v.append(f"[leak] engine{i}: {p}")
            if eng.prefix_cache is not None:
                eng.prefix_cache.drop_all(eng.allocator)
            if eng.allocator.free_blocks != eng.allocator.n_blocks:
                v.append(f"[leak] engine{i}: "
                         f"{eng.allocator.n_blocks - eng.allocator.free_blocks}"
                         f" pages never freed")
        return v


class RegionInvariantAuditor(InvariantAuditor):
    """The region tier's audits: every base invariant widened to ALL
    cells' replicas (conservation now holds across cell death and
    partitions for free), plus three region-specific invariants
    (docs/dst.md):

    * **#8 heal convergence / single ownership** — a request is never
      owned by replicas of two cells (the double-ownership a fenceless
      cross-partition failover would mint), and the region's routing
      table always names the cell that actually owns it: after a heal,
      both sides agree — nothing stranded on both, nothing stranded on
      neither (the zero-owner half is base invariant #3). Terminal
      requests linger in NO table, region or cell fleet — a stale
      ownership row is a leak in the making.
    * **#9 shed-span** — every REJECTED request (brownout sheds
      included) retired with exactly one span whose recorded state is
      ``rejected`` and a human-readable reason: load shedding is
      explicit, never silent.
    * The base liveness rail doubles as the partition-tolerance check:
      requests on a severed-but-alive cell must still finish (the cell
      computes locally) — a harness or region bug that stalls them
      trips [liveness].
    * **#12 per-tenant version monotonicity** — once a tenant has been
      served by model version V, no later request of theirs is served
      by an older one, UNLESS the rollout controller logged a rollback
      of the newer version (its justification ledger,
      ``region.version_log``) or the request spilled off its version
      preference for availability (``_canary_spilled``).
    * **#13 rollback convergence** — a controller that enters
      ROLLING_BACK must reach ROLLED_BACK within the liveness slack,
      and a terminal phase must MATCH the fleet: DONE ⇒ every live
      replica on the target version, ROLLED_BACK ⇒ every live replica
      back on stable (the leaky-promote / phantom-rollback detector).
    """

    def __init__(self, region, clock, capture: _CaptureTelemetry,
                 tracer: Optional[Tracer] = None,
                 vocab: Optional[int] = None,
                 injector: Optional[FaultInjector] = None) -> None:
        super().__init__(fleet=None, clock=clock, capture=capture,
                         tracer=tracer, vocab=vocab, injector=injector)
        self.region = region
        # rollout-invariant state (#12/#13): per tenant, the noted
        # (submit-order, served-version) entries; the uids whose FIRST
        # served version was already folded in (one note per request —
        # the audit runs after every event); and when the controller
        # was first seen ROLLING_BACK (the convergence timer)
        self._tenant_seen: Dict[str, List[Dict[str, Any]]] = {}
        self._version_noted: set = set()
        self._rb_since: Optional[float] = None

    def _replicas(self):
        out = []
        for cell in self.region.cells:
            out.extend(cell.fleet.replicas)
        return out

    def _fleets(self):
        return [cell.fleet for cell in self.region.cells]

    def audit(self, tracked: List[_Tracked]) -> List[str]:
        from ..serving.request import RequestState

        v = super().audit(tracked)
        region = self.region
        # 8. convergence: cell-level ownership vs the region table
        owner_cells: Dict[int, List[str]] = {}
        for cell in region.cells:
            for rep in cell.fleet.replicas:
                for uid in rep.serving._requests:
                    cells = owner_cells.setdefault(uid, [])
                    if cell.name not in cells:
                        cells.append(cell.name)
        with region._lock:
            table = {uid: name for uid, (_r, name)
                     in region._requests.items()}
        fleet_tables: Dict[str, set] = {}
        for cell in region.cells:
            with cell.fleet._lock:
                fleet_tables[cell.name] = set(cell.fleet._requests)
        for t in tracked:
            uid = t.req.uid
            if t.req.is_terminal:
                if uid in table:
                    v.append(f"[convergence] r{t.ix} terminal but still "
                             f"in the region table ({table[uid]})")
                # a terminal request must not linger in any cell's FLEET
                # table either — escalation paths that hand ownership up
                # to the region must drop the source fleet's row, or the
                # row leaks for the fleet's lifetime
                stale = [name for name, uids in fleet_tables.items()
                         if uid in uids]
                if stale:
                    v.append(f"[convergence] r{t.ix} terminal but still "
                             f"in fleet table(s) {stale} — stale "
                             f"ownership row")
                continue
            cells = owner_cells.get(uid, [])
            if len(cells) > 1:
                v.append(f"[convergence] r{t.ix} owned by replicas of "
                         f"{cells} — double ownership across cells")
            elif cells:
                if uid not in table:
                    v.append(f"[convergence] r{t.ix} owned by "
                             f"{cells[0]} but missing from the region "
                             f"table")
                elif table[uid] != cells[0]:
                    v.append(f"[convergence] r{t.ix}: region table says "
                             f"{table[uid]} but {cells[0]} owns it")
        # 9. shed-span: rejects carry exactly one 'rejected' span + a
        # reason (the silent-shed detector)
        spans_by_uid: Dict[int, List[Any]] = {}
        for s in self.capture.spans:
            spans_by_uid.setdefault(s.uid, []).append(s)
        for t in tracked:
            if t.req.state is not RequestState.REJECTED:
                continue
            spans = spans_by_uid.get(t.req.uid, [])
            if len(spans) != 1 or spans[0].state != "rejected":
                v.append(f"[shed-span] r{t.ix} rejected with "
                         f"{[s.state for s in spans]} span(s) — "
                         f"expected exactly one 'rejected'")
            elif not t.req.error:
                v.append(f"[shed-span] r{t.ix} rejected without a "
                         f"reason — silent shed")
        # 12. per-tenant version monotonicity, in SUBMISSION order: for
        # any two of a tenant's requests, the earlier-submitted one must
        # not be served by a NEWER version than the later-submitted one
        # (canary stickiness means one tenant sees one side of the split
        # for a whole rollout; emission order is explicitly NOT the
        # contract — an in-flight pre-rollout request legally finishes
        # on the old version after the tenant's canary requests saw the
        # new one). The two licenses for a decrease: a controller-logged
        # "rollback" row for the newer version (the justification
        # ledger), or EITHER endpoint spilling off its version
        # preference for availability (a spill onto the canary version
        # never moved the tenant forward, and a spill off it is not a
        # downgrade — availability beat affinity, witnessed on the
        # request).
        rolled_back = {row["version"] for row in region.version_log
                       if row["kind"] == "rollback"}
        for t in tracked:
            if t.req.uid in self._version_noted or not t.req.served_versions:
                continue
            self._version_noted.add(t.req.uid)
            key = t.req.tenant or t.req.client_request_id
            me = {"order": (t.req.t_submit if t.req.t_submit is not None
                            else 0.0, t.req.uid),
                  "ver": t.req.served_versions[0],
                  "spilled": bool(getattr(t.req, "_canary_spilled",
                                          False)),
                  "ix": t.ix}
            entries = self._tenant_seen.setdefault(key, [])
            for o in entries:
                early, late = ((o, me) if o["order"] <= me["order"]
                               else (me, o))
                if (early["ver"] > late["ver"]
                        and early["ver"] not in rolled_back
                        and not early["spilled"] and not late["spilled"]):
                    v.append(f"[version-monotonic] tenant {key}: "
                             f"r{late['ix']} served by version "
                             f"{late['ver']} though earlier-submitted "
                             f"r{early['ix']} saw {early['ver']} with "
                             f"no rollback logged")
            entries.append(me)
        # 13. rollback convergence: ROLLING_BACK is a transient, never a
        # destination — it must reach ROLLED_BACK within the liveness
        # slack; and a terminal phase must agree with the fleet's actual
        # versions (checked on every audit while terminal, so a respawn
        # or autoscale that resurrects the abandoned version trips too)
        from ..serving.fleet import ReplicaState
        from ..serving.rollout import RolloutPhase, TERMINAL_PHASES
        ro = region.rollout
        phase = ro.phase
        now = self.clock.now()
        if phase == RolloutPhase.ROLLING_BACK:
            if self._rb_since is None:
                self._rb_since = now
            elif now - self._rb_since > LIVENESS_SLACK_TICKS:
                v.append(f"[rollback-convergence] controller stuck "
                         f"ROLLING_BACK for {now - self._rb_since:.0f} "
                         f"virtual seconds — rollback never converges")
        else:
            self._rb_since = None
        if phase in TERMINAL_PHASES and ro.target_version is not None:
            want = (ro.target_version if phase == RolloutPhase.DONE
                    else ro.stable_version)
            wrong = sorted(r.name for r in self._replicas()
                           if r.state is not ReplicaState.DEAD
                           and r.version != want)
            if wrong:
                v.append(f"[rollback-convergence] phase {phase} but "
                         f"replica(s) {wrong} not on version {want}")
        return v


# ----------------------------------------------------------------------
# the simulation driver
# ----------------------------------------------------------------------

@dataclass
class SimReport:
    """Outcome of one schedule run."""

    seed: int
    trace_hash: str
    violations: List[str]
    n_ticks: int
    n_events: int
    submitted: int
    finished: int
    cancelled: int
    rejected: int
    tokens: Dict[int, List[int]]          # logical ix -> emitted stream
    # logical ix -> terminal state value ("finished"/"cancelled"/...) —
    # the spec-on/off identity gate compares streams exactly for
    # requests finished in BOTH runs and prefix-wise otherwise (spec
    # changes WHEN a timing-dependent cancel/fault lands, never WHICH
    # tokens precede it)
    states: Dict[int, str] = field(default_factory=dict)
    # canonical hash of the run's span tree (telemetry/tracing.py): the
    # second determinism witness — same seed, same request timelines
    span_hash: str = ""
    n_spans: int = 0
    # the span timeline (span dicts), kept only for failing runs so
    # dump_repro can ship the event timeline with the repro
    spans: Optional[List[Dict[str, Any]]] = None
    # region runs only: the brownout admit/shed rows — the soak's
    # strictly-priority-ordered shedding gate reads these
    brownout_log: Optional[List[Dict[str, Any]]] = None
    # logical ix -> first-token latency in virtual seconds, for
    # requests that streamed at least one token — gray_lane's p99 TTFT
    # mitigation-on/off gate reads these
    ttfts: Dict[int, float] = field(default_factory=dict)
    # gray-failure plane snapshot (health scores, breakers, hedge
    # ledger): the fleet's for fleet runs, per-cell for region runs
    gray: Optional[Dict[str, Any]] = None

    @property
    def ok(self) -> bool:
        return not self.violations

    def summary(self) -> Dict[str, Any]:
        return {"seed": self.seed, "trace_hash": self.trace_hash,
                "span_hash": self.span_hash, "n_spans": self.n_spans,
                "violations": self.violations, "ticks": self.n_ticks,
                "events": self.n_events, "submitted": self.submitted,
                "finished": self.finished, "cancelled": self.cancelled,
                "rejected": self.rejected}


#: extra virtual ticks past the last event before a non-quiescent fleet
#: counts as a liveness violation (a request parked forever IS a lost
#: request — the conservation invariant's temporal half)
LIVENESS_SLACK_TICKS = 600


def run_schedule(schedule: Schedule,
                 engine_factory: Optional[Callable[[], SimEngine]] = None,
                 stop_on_violation: bool = True) -> SimReport:
    """Execute one schedule under virtual time and audit every event.
    Pure: same schedule, same report (bit-identical ``trace_hash``)."""
    from ..serving.fleet import ServingFleet
    from ..serving.request import RequestState
    from ..telemetry.registry import get_registry, set_registry
    from ..telemetry.telemetry import get_telemetry

    clock = SimClock()
    capture = _CaptureTelemetry()
    injector = _ScheduledFaultInjector()
    # a FRESH tracer per run: span/trace ids restart from 1, so two runs
    # of the same schedule in one process produce identical canonical
    # hashes (the bit-determinism witness trace_smoke gates); the flight
    # recorder stays in-memory (no dump dir) and auto-dumps on the first
    # invariant violation so a repro carries the black box too
    tracer = Tracer(enabled=True, ring_size=16384, flight_capacity=2048)
    prev_telemetry = get_telemetry()
    # set_telemetry(capture) below also swaps the process-default
    # registry; restoring telemetry alone would leave the default
    # registry pointing at the sim's capture forever (set_telemetry(None)
    # deliberately does not touch the registry) — save it explicitly
    prev_registry = get_registry()
    engines: List[SimEngine] = []
    sim_cfg = SimConfig(**schedule.engine_cfg)

    def factory() -> SimEngine:
        eng = (engine_factory() if engine_factory is not None
               else SimEngine(sim_cfg))
        engines.append(eng)
        return eng

    trace = _Trace()
    tracked: List[_Tracked] = []
    violations: List[str] = []
    n_ticks = 0
    with use_clock(clock), use_tracer(tracer):
        set_telemetry(capture)
        install_fault_injector(injector)
        try:
            guard = _SimGuard()
            fleet = ServingFleet(factory, dict(schedule.fleet_cfg),
                                 dict(schedule.serving_cfg),
                                 preemption_guard=guard, start=False)
            auditor = InvariantAuditor(fleet, clock, capture,
                                       tracer=tracer, vocab=sim_cfg.vocab,
                                       injector=injector)
            events = sorted(schedule.events, key=_event_order)
            i = 0
            while True:
                while i < len(events) and events[i].t <= clock.now() + 1e-9:
                    ev = events[i]
                    i += 1
                    _apply_event(fleet, ev, tracked, guard, injector, clock)
                    trace.event(clock.now(), ev.kind, ev.payload)
                    step_violations = auditor.audit(tracked)
                    violations.extend(step_violations)
                    if step_violations and stop_on_violation:
                        break
                if violations and stop_on_violation:
                    break
                did = fleet.step()
                clock.advance(1.0)
                n_ticks += 1
                step_violations = auditor.audit(tracked)
                violations.extend(step_violations)
                trace.tick(n_ticks, clock.now(), fleet, tracked)
                if step_violations and stop_on_violation:
                    break
                quiescent = (not did and fleet.queue_depth == 0
                             and all(t.req.is_terminal for t in tracked))
                if i >= len(events) and quiescent:
                    break
                if not did and i < len(events) and events[i].t > clock.now():
                    clock.advance(events[i].t - clock.now())
                if n_ticks > schedule.horizon + LIVENESS_SLACK_TICKS:
                    stuck = [t.ix for t in tracked if not t.req.is_terminal]
                    violations.append(
                        f"[liveness] simulation did not quiesce within "
                        f"{n_ticks} ticks; live requests: {stuck}")
                    break
            # shutdown: the drain loops sleep on the clock; the pump
            # steps the fleet so virtual time AND work both progress
            clock.pump = fleet.step
            fleet.close(timeout=30.0)
            clock.pump = None
            violations.extend(auditor.audit(tracked))
            violations.extend(auditor.final(tracked, engines))
            trace.finish(tracked)
            if violations:
                # invariant-audit failure: snapshot the black box (in
                # memory — dump_repro ships it with the repro artifact)
                tracer.flight.note("invariant_audit_failed",
                                   n_violations=len(violations))
                tracer.flight.dump("invariant-audit")
        finally:
            install_fault_injector(None)
            set_telemetry(prev_telemetry
                          if prev_telemetry is not None
                          and prev_telemetry.enabled else None)
            set_registry(prev_registry)
    states = [t.req.state for t in tracked]
    return SimReport(
        seed=schedule.seed, trace_hash=trace.hash(),
        violations=violations, n_ticks=n_ticks, n_events=len(schedule.events),
        submitted=len(tracked),
        finished=sum(s is RequestState.FINISHED for s in states),
        cancelled=sum(s is RequestState.CANCELLED for s in states),
        rejected=sum(s is RequestState.REJECTED for s in states),
        tokens={t.ix: list(t.req.tokens) for t in tracked},
        states={t.ix: t.req.state.value for t in tracked},
        span_hash=tracer.canonical_hash(), n_spans=len(tracer.spans()),
        spans=([s.to_dict() for s in tracer.spans()]
               if violations else None),
        ttfts={t.ix: round(t.req.t_first_token - t.req.t_submit, 6)
               for t in tracked
               if t.req.t_first_token is not None
               and t.req.t_submit is not None},
        gray=fleet.gray_snapshot())


def _apply_event(fleet, ev: SimEvent, tracked: List[_Tracked], guard,
                 injector: _ScheduledFaultInjector, clock: SimClock) -> None:
    p = ev.payload
    if ev.kind == "submit":
        entry = _Tracked(ix=int(p["ix"]), req=None)
        entry.req = fleet.submit(
            list(p["prompt"]), max_new_tokens=int(p["max_new"]),
            priority=int(p.get("priority", 0)),
            deadline_s=p.get("deadline"),
            ttft_deadline_s=p.get("ttft_deadline"),
            eos_token_id=p.get("eos"),
            tenant=p.get("tenant"),
            on_token=entry.delivered.append)
        tracked.append(entry)
    elif ev.kind == "cancel":
        target = int(p["target"])
        for t in tracked:
            if t.ix == target and not t.req.is_terminal:
                fleet.cancel(t.req)
                break
    elif ev.kind == "tick_fault":
        injector.arm(int(p.get("n", 1)))
    elif ev.kind == "replica_death":
        healthy = sorted(r.name for r in fleet.healthy_replicas)
        if healthy:
            name = healthy[int(p.get("which", 0)) % len(healthy)]
            fleet.kill_replica(name, reason="dst: scheduled death")
    elif ev.kind == "latch":
        guard.should_stop = True
    elif ev.kind == "scale":
        fleet.scale_to(int(p["n"]))
    elif ev.kind == "stall":
        clock.advance(float(p.get("dt", 1.0)))
    elif ev.kind == "degraded_tick":
        healthy = sorted(r.name for r in fleet.healthy_replicas)
        if healthy:
            name = healthy[int(p.get("which", 0)) % len(healthy)]
            injector.degrade_replica(name, int(p.get("k", 2)))
    elif ev.kind == "stall_burst":
        healthy = sorted(r.name for r in fleet.healthy_replicas)
        if healthy:
            name = healthy[int(p.get("which", 0)) % len(healthy)]
            injector.arm_stall_burst(name, int(p.get("n", 1)))
    elif ev.kind == "flaky_import":
        injector.flaky_import_every = int(p.get("every", 0))
    elif ev.kind == "stale_directory":
        injector.stale_directory_every = int(p.get("every", 0))
    elif ev.kind == "corrupt_adopt":
        injector.corrupt_adopt_every = int(p.get("every", 0))
    elif ev.kind == "cold_pressure":
        injector.cold_pressure_every = int(p.get("every", 0))
    else:
        raise ValueError(f"unknown simulation event kind '{ev.kind}'")


def run_region_schedule(schedule: RegionSchedule,
                        engine_factory: Optional[Callable[[], SimEngine]] = None,
                        region_factory=None,
                        stop_on_violation: bool = True) -> SimReport:
    """Execute one REGION schedule under virtual time, auditing after
    every event and tick with :class:`RegionInvariantAuditor`. Pure:
    same schedule, same (trace_hash, span_hash). ``region_factory``
    lets tests plant region-layer bugs (the auditor's teeth), exactly
    as ``engine_factory`` plants engine bugs one tier down."""
    from ..serving.region import Region
    from ..serving.request import RequestState
    from ..telemetry.registry import get_registry, set_registry
    from ..telemetry.telemetry import get_telemetry

    clock = SimClock()
    capture = _CaptureTelemetry()
    injector = _ScheduledFaultInjector()
    tracer = Tracer(enabled=True, ring_size=32768, flight_capacity=2048)
    prev_telemetry = get_telemetry()
    prev_registry = get_registry()
    engines: List[SimEngine] = []
    sim_cfg = SimConfig(**schedule.engine_cfg)

    def factory() -> SimEngine:
        eng = (engine_factory() if engine_factory is not None
               else SimEngine(sim_cfg))
        engines.append(eng)
        return eng

    trace = _Trace()
    tracked: List[_Tracked] = []
    violations: List[str] = []
    n_ticks = 0
    with use_clock(clock), use_tracer(tracer):
        set_telemetry(capture)
        install_fault_injector(injector)
        try:
            guard = _SimGuard()
            builder = (region_factory if region_factory is not None
                       else Region)
            region = builder(factory, dict(schedule.region_cfg),
                             dict(schedule.fleet_cfg),
                             dict(schedule.serving_cfg),
                             preemption_guard=guard, start=False)
            auditor = RegionInvariantAuditor(region, clock, capture,
                                             tracer=tracer,
                                             vocab=sim_cfg.vocab,
                                             injector=injector)
            events = sorted(schedule.events, key=_event_order)
            i = 0
            while True:
                while i < len(events) and events[i].t <= clock.now() + 1e-9:
                    ev = events[i]
                    i += 1
                    _apply_region_event(region, ev, tracked, guard,
                                        injector, clock)
                    trace.event(clock.now(), ev.kind, ev.payload)
                    step_violations = auditor.audit(tracked)
                    violations.extend(step_violations)
                    if step_violations and stop_on_violation:
                        break
                if violations and stop_on_violation:
                    break
                did = region.step()
                clock.advance(1.0)
                n_ticks += 1
                step_violations = auditor.audit(tracked)
                violations.extend(step_violations)
                trace.tick_region(n_ticks, clock.now(), region, tracked)
                if step_violations and stop_on_violation:
                    break
                quiescent = (not did and region.queue_depth == 0
                             and all(t.req.is_terminal for t in tracked))
                if i >= len(events) and quiescent:
                    break
                if not did and i < len(events) and events[i].t > clock.now():
                    clock.advance(events[i].t - clock.now())
                if n_ticks > schedule.horizon + LIVENESS_SLACK_TICKS:
                    stuck = [t.ix for t in tracked if not t.req.is_terminal]
                    violations.append(
                        f"[liveness] region simulation did not quiesce "
                        f"within {n_ticks} ticks; live requests: {stuck}")
                    break
            clock.pump = region.step
            region.close(timeout=30.0)
            clock.pump = None
            violations.extend(auditor.audit(tracked))
            violations.extend(auditor.final(tracked, engines))
            trace.finish(tracked)
            if violations:
                tracer.flight.note("invariant_audit_failed",
                                   n_violations=len(violations))
                tracer.flight.dump("invariant-audit")
        finally:
            install_fault_injector(None)
            set_telemetry(prev_telemetry
                          if prev_telemetry is not None
                          and prev_telemetry.enabled else None)
            set_registry(prev_registry)
    states = [t.req.state for t in tracked]
    return SimReport(
        seed=schedule.seed, trace_hash=trace.hash(),
        violations=violations, n_ticks=n_ticks, n_events=len(schedule.events),
        submitted=len(tracked),
        finished=sum(s is RequestState.FINISHED for s in states),
        cancelled=sum(s is RequestState.CANCELLED for s in states),
        rejected=sum(s is RequestState.REJECTED for s in states),
        tokens={t.ix: list(t.req.tokens) for t in tracked},
        states={t.ix: t.req.state.value for t in tracked},
        span_hash=tracer.canonical_hash(), n_spans=len(tracer.spans()),
        spans=([s.to_dict() for s in tracer.spans()]
               if violations else None),
        brownout_log=list(region.brownout_log),
        ttfts={t.ix: round(t.req.t_first_token - t.req.t_submit, 6)
               for t in tracked
               if t.req.t_first_token is not None
               and t.req.t_submit is not None},
        gray={c.name: c.fleet.gray_snapshot() for c in region.cells})


def _apply_region_event(region, ev: SimEvent, tracked: List[_Tracked],
                        guard, injector: _ScheduledFaultInjector,
                        clock: SimClock) -> None:
    p = ev.payload
    if ev.kind == "submit":
        entry = _Tracked(ix=int(p["ix"]), req=None)
        entry.req = region.submit(
            list(p["prompt"]), max_new_tokens=int(p["max_new"]),
            priority=int(p.get("priority", 0)),
            deadline_s=p.get("deadline"),
            ttft_deadline_s=p.get("ttft_deadline"),
            eos_token_id=p.get("eos"),
            tenant=p.get("tenant"),
            on_token=entry.delivered.append)
        tracked.append(entry)
    elif ev.kind == "cancel":
        target = int(p["target"])
        for t in tracked:
            if t.ix == target and not t.req.is_terminal:
                region.cancel(t.req)
                break
    elif ev.kind == "tick_fault":
        injector.arm(int(p.get("n", 1)))
    elif ev.kind == "replica_death":
        cells = sorted((c for c in region.live_cells),
                       key=lambda c: c.name)
        if cells:
            cell = cells[int(p.get("cell", 0)) % len(cells)]
            healthy = sorted(r.name for r in cell.fleet.healthy_replicas)
            if healthy:
                name = healthy[int(p.get("which", 0)) % len(healthy)]
                cell.fleet.kill_replica(name, reason="dst: scheduled death")
    elif ev.kind == "cell_outage":
        cells = sorted(c.name for c in region.live_cells)
        if cells:
            region.kill_cell(cells[int(p.get("which", 0)) % len(cells)],
                             reason="dst: scheduled cell outage")
    elif ev.kind == "partition":
        names = sorted(c.name for c in region.cells)
        far = {names[int(ix) % len(names)] for ix in p.get("far", [])}
        near = set(names) - far
        if p.get("sever_region", True):
            near.add(region.name)
        if far and near:
            injector.sever(sorted(near), sorted(far))
    elif ev.kind == "heal":
        injector.heal_partitions()
    elif ev.kind == "autoscaler_lag":
        injector.set_autoscaler_lag(float(p.get("dt", 5.0)))
    elif ev.kind == "latch":
        guard.should_stop = True
    elif ev.kind == "scale":
        cells = sorted((c for c in region.live_cells),
                       key=lambda c: c.name)
        if cells:
            cell = cells[int(p.get("cell", 0)) % len(cells)]
            cell.fleet.scale_to(int(p["n"]))
    elif ev.kind == "stall":
        clock.advance(float(p.get("dt", 1.0)))
    elif ev.kind == "rollout":
        # start() refuses mid-rollout / non-advancing versions itself —
        # a schedule may legally draw a rollout that lands as a no-op
        region.start_rollout(int(p["version"]), fraction=p.get("fraction"))
    elif ev.kind == "migrate":
        cells = sorted((c for c in region.live_cells),
                       key=lambda c: c.name)
        if cells:
            cell = cells[int(p.get("cell", 0)) % len(cells)]
            healthy = sorted(r.name for r in cell.fleet.healthy_replicas)
            if healthy:
                name = healthy[int(p.get("replica", 0)) % len(healthy)]
                region.migrate_replica(cell.name, name,
                                       reason="dst: scheduled migration")
    elif ev.kind == "canary_regress":
        # injected canary SLO regression: the new version stalls every
        # other busy tick from here on — the observe window must catch
        # the ratio gap and the controller must roll back
        ro = region.rollout
        target = ro.target_version
        if ro.active and target is not None:
            injector.degrade_model_version(int(target))
    elif ev.kind == "corrupt_swap":
        injector.arm_corrupt_swap(int(p.get("n", 1)))
    elif ev.kind == "flip_death":
        injector.arm_flip_death(int(p.get("ordinal", 1)))
    elif ev.kind in ("degraded_tick", "stall_burst"):
        cells = sorted((c for c in region.live_cells),
                       key=lambda c: c.name)
        if cells:
            cell = cells[int(p.get("cell", 0)) % len(cells)]
            healthy = sorted(r.name for r in cell.fleet.healthy_replicas)
            if healthy:
                name = healthy[int(p.get("which", 0)) % len(healthy)]
                if ev.kind == "degraded_tick":
                    injector.degrade_replica(name, int(p.get("k", 2)))
                else:
                    injector.arm_stall_burst(name, int(p.get("n", 1)))
    elif ev.kind == "flaky_import":
        injector.flaky_import_every = int(p.get("every", 0))
    elif ev.kind == "stale_directory":
        injector.stale_directory_every = int(p.get("every", 0))
    elif ev.kind == "corrupt_adopt":
        injector.corrupt_adopt_every = int(p.get("every", 0))
    elif ev.kind == "cold_pressure":
        injector.cold_pressure_every = int(p.get("every", 0))
    else:
        raise ValueError(f"unknown region simulation event '{ev.kind}'")


# ----------------------------------------------------------------------
# shrinking + regression artifacts
# ----------------------------------------------------------------------

def spec_identity_problems(rep_on: "SimReport",
                           rep_off: "SimReport") -> List[str]:
    """Token-identity comparison of one schedule run spec-on vs spec-off
    (the satellite gate dst_soak and the regression seeds share): every
    request's two streams must agree on their common prefix (speculation
    may move WHEN a timing-dependent cancel/fault/deadline lands, never
    WHICH tokens precede it), and a request FINISHED in both runs must
    emit the exact same stream."""
    problems: List[str] = []
    for ix in sorted(set(rep_on.tokens) | set(rep_off.tokens)):
        a = rep_on.tokens.get(ix, [])
        b = rep_off.tokens.get(ix, [])
        n = min(len(a), len(b))
        if a[:n] != b[:n]:
            problems.append(f"r{ix}: spec-on prefix {a[:n]} != spec-off "
                            f"{b[:n]}")
        elif (rep_on.states.get(ix) == "finished"
                and rep_off.states.get(ix) == "finished" and a != b):
            problems.append(f"r{ix}: finished in both runs but spec-on "
                            f"emitted {a} vs spec-off {b}")
    return problems


def shrink_schedule(schedule: Schedule,
                    fails: Optional[Callable[[Schedule], bool]] = None,
                    max_runs: int = 500) -> Schedule:
    """Delta-debug a failing schedule to a minimal reproduction (ddmin
    over the event list; configs are kept — they are part of the seed's
    identity). ``fails(schedule) -> bool`` defaults to "run_schedule
    reports violations". The result still fails, and is 1-minimal up to
    the run budget: removing any single remaining event makes it pass."""
    if fails is None:
        def fails(s: Schedule) -> bool:
            runner = (run_region_schedule if isinstance(s, RegionSchedule)
                      else run_schedule)
            return bool(runner(s).violations)

    events = list(schedule.events)
    if not fails(schedule.replace_events(events)):
        raise ValueError("shrink_schedule needs a failing schedule")
    runs = 0
    n = 2
    while len(events) >= 2 and runs < max_runs:
        chunk = max(1, len(events) // n)
        reduced = False
        for start in range(0, len(events), chunk):
            candidate = events[:start] + events[start + chunk:]
            if not candidate:
                continue
            runs += 1
            if fails(schedule.replace_events(candidate)):
                events = candidate
                n = max(2, n - 1)
                reduced = True
                break
            if runs >= max_runs:
                break
        if not reduced:
            if chunk == 1:
                break
            n = min(len(events), n * 2)
    # final 1-minimality pass: try dropping each remaining event once
    i = 0
    while i < len(events) and runs < max_runs and len(events) > 1:
        candidate = events[:i] + events[i + 1:]
        runs += 1
        if fails(schedule.replace_events(candidate)):
            events = candidate
        else:
            i += 1
    logger.info(f"dst: shrank schedule from {len(schedule.events)} to "
                f"{len(events)} events in {runs} runs")
    return schedule.replace_events(events)


def dump_repro(schedule: Schedule, violations: List[str],
               path: str,
               timeline: Optional[List[Dict[str, Any]]] = None) -> str:
    """Write a failing (ideally shrunk) schedule as a JSON regression
    artifact; ``load_repro`` + ``run_schedule`` replays it exactly.
    ``timeline`` (``SimReport.spans``) attaches the failing run's span
    timeline, so the repro says not just *what* broke but *when/where*
    along each request's life."""
    payload: Dict[str, Any] = {"version": 1, "violations": violations,
                               "schedule": schedule.to_dict()}
    if timeline is not None:
        payload["timeline"] = timeline
    with open(path, "w", encoding="utf-8") as fh:
        json.dump(payload, fh, indent=1, sort_keys=True)
        fh.write("\n")
    return path


def load_repro(path: str) -> Tuple[Schedule, List[str]]:
    with open(path, "r", encoding="utf-8") as fh:
        data = json.load(fh)
    sched = data["schedule"]
    cls = RegionSchedule if "region_cfg" in sched else Schedule
    return (cls.from_dict(sched), list(data.get("violations", [])))
