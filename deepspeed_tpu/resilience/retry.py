"""Bounded retry with exponential backoff, instrumented.

For transient host-side failures around the training loop: checkpoint
writes to flaky filesystems, coordinator reconnects, KV-store fetches.
NOT for device-side errors inside a compiled step — those need a restart
(launcher/agent.py), not a retry.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Any, Callable, Tuple, Type

from ..utils.logging import logger
from .counters import record_failure, record_retry


class RetryError(RuntimeError):
    """All attempts exhausted; ``__cause__`` is the last failure."""


@dataclass(frozen=True)
class RetryPolicy:
    max_attempts: int = 3
    backoff_s: float = 0.5
    backoff_multiplier: float = 2.0
    max_backoff_s: float = 30.0
    retry_on: Tuple[Type[BaseException], ...] = (OSError, RuntimeError)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")


def retry_call(fn: Callable[..., Any], *args,
               policy: RetryPolicy = RetryPolicy(),
               op: str = "default",
               sleep: Callable[[float], None] = time.sleep,
               **kwargs) -> Any:
    """Call ``fn(*args, **kwargs)``; on a ``policy.retry_on`` exception,
    back off and retry up to ``policy.max_attempts`` total attempts.
    Retries/failures are counted under ``resilience/{retries,failures}/{op}``.
    """
    delay = policy.backoff_s
    last: BaseException
    for attempt in range(1, policy.max_attempts + 1):
        try:
            return fn(*args, **kwargs)
        except policy.retry_on as e:
            last = e
            if attempt == policy.max_attempts:
                record_failure(op)
                raise RetryError(
                    f"{op}: {attempt} attempts failed; last: {e!r}") from e
            record_retry(op)
            logger.warning(
                f"resilience: {op} attempt {attempt}/{policy.max_attempts} "
                f"failed ({e!r}); retrying in {delay:.2f}s")
            sleep(delay)
            delay = min(delay * policy.backoff_multiplier,
                        policy.max_backoff_s)
    raise AssertionError("unreachable")  # loop always returns or raises
