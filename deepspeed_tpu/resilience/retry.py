"""Bounded retry with jittered exponential backoff, instrumented.

For transient host-side failures around the training loop: checkpoint
writes to flaky filesystems (GCS/NFS), coordinator reconnects, KV-store
fetches. NOT for device-side errors inside a compiled step — those need a
restart (launcher/agent.py), not a retry.

Jitter decorrelates the retry storms a shared filesystem hiccup would
otherwise synchronize across a pod; a :class:`RetryBudget` shared between
call sites caps the *total* retries a flaky backend may consume, so a
degraded filesystem fails the job promptly instead of stretching every
checkpoint op to its per-call maximum.
"""

from __future__ import annotations

import random
import threading
from dataclasses import dataclass
from typing import Any, Callable, Optional, Tuple, Type

from ..utils.logging import logger
from .clock import get_clock
from .counters import record_attempt, record_failure, record_retry


class RetryError(RuntimeError):
    """All attempts exhausted; ``__cause__`` is the last failure."""


@dataclass(frozen=True)
class RetryPolicy:
    max_attempts: int = 3
    backoff_s: float = 0.5
    backoff_multiplier: float = 2.0
    max_backoff_s: float = 30.0
    jitter: float = 0.0  # uniform extra delay, as a fraction of the backoff
    retry_on: Tuple[Type[BaseException], ...] = (OSError, RuntimeError)

    def __post_init__(self):
        if self.max_attempts < 1:
            raise ValueError(f"max_attempts must be >= 1, got {self.max_attempts}")
        if self.jitter < 0:
            raise ValueError(f"jitter must be >= 0, got {self.jitter}")


class RetryBudget:
    """A shared, thread-safe cap on total retries across many call sites.

    Checkpoint save/load wraps several filesystem ops; each gets its own
    per-call ``RetryPolicy``, but they can all draw from one budget so a
    persistently failing backend exhausts quickly. ``take()`` consumes one
    retry and returns False when nothing is left.
    """

    def __init__(self, max_retries: int):
        if max_retries < 0:
            raise ValueError(f"max_retries must be >= 0, got {max_retries}")
        self.max_retries = int(max_retries)
        self._remaining = int(max_retries)
        self._lock = threading.Lock()

    @property
    def remaining(self) -> int:
        with self._lock:
            return self._remaining

    def take(self, op: str = "default") -> bool:
        with self._lock:
            if self._remaining <= 0:
                return False
            self._remaining -= 1
        return True


_JITTER_RNG = random.Random()


def retry_call(fn: Callable[..., Any], *args,
               policy: RetryPolicy = RetryPolicy(),
               op: str = "default",
               sleep: Optional[Callable[[float], None]] = None,
               budget: Optional[RetryBudget] = None,
               rng: Optional[random.Random] = None,
               **kwargs) -> Any:
    """Call ``fn(*args, **kwargs)``; on a ``policy.retry_on`` exception,
    back off (with up to ``policy.jitter`` fractional random extra) and
    retry up to ``policy.max_attempts`` total attempts, or until ``budget``
    is exhausted. Every attempt is counted under
    ``resilience/attempts/{op}``; retries/failures under
    ``resilience/{retries,failures}/{op}``. ``sleep`` defaults to the
    injectable clock's sleep (:mod:`.clock`), so simulated backoff
    advances virtual time instead of stalling the host.
    """
    if sleep is None:
        sleep = get_clock().sleep
    delay = policy.backoff_s
    last: BaseException
    for attempt in range(1, policy.max_attempts + 1):
        record_attempt(op)
        try:
            return fn(*args, **kwargs)
        except policy.retry_on as e:
            last = e
            exhausted = attempt == policy.max_attempts
            if not exhausted and budget is not None and not budget.take(op):
                exhausted = True
                logger.warning(f"resilience: {op} retry budget exhausted")
            if exhausted:
                record_failure(op)
                raise RetryError(
                    f"{op}: {attempt} attempts failed; last: {e!r}") from e
            record_retry(op)
            d = delay
            if policy.jitter > 0:
                d *= 1.0 + (rng or _JITTER_RNG).uniform(0.0, policy.jitter)
            logger.warning(
                f"resilience: {op} attempt {attempt}/{policy.max_attempts} "
                f"failed ({e!r}); retrying in {d:.2f}s")
            sleep(d)
            delay = min(delay * policy.backoff_multiplier,
                        policy.max_backoff_s)
    raise AssertionError("unreachable")  # loop always returns or raises
