"""Environment/compatibility report (the ``ds_report`` CLI —
reference deepspeed/env_report.py: op compatibility matrix + version/env
table). The reference reports which CUDA extensions can build; here the
"ops" are Pallas kernels and XLA features, reported per detected platform.
"""

from __future__ import annotations

import sys
from typing import List, Tuple

GREEN_OK = "[OKAY]"
RED_NO = "[NO]"


def op_compatibility() -> List[Tuple[str, bool, str]]:
    """(op, available, note) rows — the DS_BUILD_* matrix analog."""
    import jax

    try:
        platform = jax.devices()[0].platform
    except Exception:
        platform = "none"
    on_tpu = platform == "tpu"
    rows = [
        ("flash_attention (pallas)", True, "compiled on TPU; interpret elsewhere"),
        ("paged/ragged attention", True, "jnp path everywhere; pallas on TPU"),
        ("fused optimizers (jit)", True, "optax-style fused update under jit"),
        ("sequence parallel (ulysses a2a)", True, ""),
        ("ring attention (ppermute)", True, ""),
        ("pipeline (shard_map+ppermute)", True, ""),
        ("moe a2a dispatch", True, ""),
        ("bf16 matmul on MXU", on_tpu, "requires TPU" if not on_tpu else ""),
        ("int8 quantization kernels", True, "jnp path; pallas on TPU"),
        ("async checkpoint (orbax)", _has("orbax.checkpoint"), ""),
    ]
    # genuinely-native (C++) ops: report per-op buildability like the
    # reference's DS_BUILD matrix does for its extensions (absolute import
    # so `python deepspeed_tpu/env_report.py` works script-style too)
    try:
        from deepspeed_tpu.ops.op_builder import op_report

        for name, compatible, built in sorted(op_report()):
            note = "prebuilt" if built else \
                ("jit-builds on first use" if compatible else "sources missing")
            rows.append((f"native {name} (C++)", compatible, note))
    except Exception as e:  # report, never crash the report
        rows.append(("native ops registry", False, str(e)[:60]))
    return rows


def _has(mod: str) -> bool:
    try:
        __import__(mod)
        return True
    except Exception:
        return False


def main(argv=None) -> int:
    import jax

    import deepspeed_tpu

    lines = ["-" * 72, "DeepSpeed-TPU C compatibility report", "-" * 72]
    lines.append(f"deepspeed_tpu version ... {deepspeed_tpu.__version__}")
    lines.append(f"python version .......... {sys.version.split()[0]}")
    lines.append(f"jax version ............. {jax.__version__}")
    try:
        import jaxlib

        lines.append(f"jaxlib version .......... {jaxlib.__version__}")
    except Exception:
        pass
    try:
        devs = jax.devices()
        lines.append(f"platform ................ {devs[0].platform}")
        lines.append(f"devices ................. {len(devs)} x {devs[0].device_kind}")
    except Exception as e:
        lines.append(f"platform ................ unavailable ({type(e).__name__})")
    lines.append("-" * 72)
    lines.append("op compatibility (the DS_BUILD_* matrix analog):")
    for op, ok, note in op_compatibility():
        status = GREEN_OK if ok else RED_NO
        lines.append(f"  {op:38s} {status:7s} {note}")
    lines.append("-" * 72)
    text = "\n".join(lines)
    print(text)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
