"""Telemetry sinks: where step records and registry snapshots go.

A sink is anything with ``write(record: dict)`` and ``close()``. The
``Telemetry`` facade fans each step record out to every configured sink:

* :class:`JsonlSink` — structured machine-readable log, one JSON object
  per line (the format the smoke test and golden-file test validate).
* :class:`PrometheusTextExporter` — renders the metrics registry in the
  Prometheus text exposition format to a file on every ``export_every``-th
  record (atomic rename, so a scraper never reads a torn file).
* :class:`MonitorSink` — adapts :class:`~deepspeed_tpu.monitor.monitor.
  MonitorMaster` (TensorBoard/CSV/W&B) into this fan-out, making the
  legacy monitor one telemetry sink among several.
"""

from __future__ import annotations

import json
import os
import re
from typing import Any, Dict, Optional

from ..utils.logging import logger
from .registry import (Counter, Gauge, Histogram, MetricsRegistry,
                       SketchHistogram)


class JsonlSink:
    """Append-only JSONL writer; ``flush_every`` bounds record loss on
    crash (1 = flush per record, the default for small step counts)."""

    def __init__(self, path: str, flush_every: int = 1):
        self.path = path
        self.flush_every = max(1, int(flush_every))
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        self._f = open(path, "a", encoding="utf-8")
        self._pending = 0

    def write(self, record: Dict[str, Any]) -> None:
        self._f.write(json.dumps(record, default=_json_default) + "\n")
        self._pending += 1
        if self._pending >= self.flush_every:
            self._f.flush()
            self._pending = 0

    def close(self) -> None:
        if self._f is not None and not self._f.closed:
            self._f.flush()
            self._f.close()


def _json_default(x):
    # numpy / jax scalars that slipped into a record
    if hasattr(x, "item"):
        return x.item()
    return str(x)


_PROM_NAME = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    return _PROM_NAME.sub("_", name)


def render_prometheus(registry: MetricsRegistry,
                      prefix: str = "dst") -> str:
    """Render every metric in ``registry`` in the Prometheus text format.
    Exact-window :class:`Histogram` exports as a summary (count/sum +
    p50/p90/p99 quantiles); :class:`SketchHistogram` exports as a native
    Prometheus histogram — cumulative ``_bucket{le=...}`` series straight
    from the sketch's log-bucket upper bounds, so server-side quantile
    math (``histogram_quantile``) and cross-scrape aggregation work."""
    lines = []
    for name, m in sorted(registry.metrics().items()):
        pname = f"{prefix}_{_prom_name(name)}"
        if isinstance(m, Counter):
            lines.append(f"# TYPE {pname} counter")
            lines.append(f"{pname} {m.value}")
        elif isinstance(m, Gauge):
            if m.value is None:
                continue
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {m.value}")
        elif isinstance(m, SketchHistogram):
            lines.append(f"# TYPE {pname} histogram")
            cum = 0
            for ub, n in m.bucket_bounds():
                cum += n
                lines.append(
                    f"{pname}_bucket{{le=\"{ub}\"}} {cum}")
            lines.append(f"{pname}_bucket{{le=\"+Inf\"}} {m.count}")
            lines.append(f"{pname}_sum {m.sum}")
            lines.append(f"{pname}_count {m.count}")
        elif isinstance(m, Histogram):
            lines.append(f"# TYPE {pname} summary")
            for q in (50, 90, 99):
                v = m.percentile(q)
                if v is not None:
                    lines.append(
                        f"{pname}{{quantile=\"{q / 100}\"}} {v}")
            lines.append(f"{pname}_sum {m.sum}")
            lines.append(f"{pname}_count {m.count}")
    return "\n".join(lines) + ("\n" if lines else "")


class PrometheusTextExporter:
    """Writes the registry to ``path`` in text exposition format. With
    ``path=None`` it only serves :meth:`render` (pull-style use)."""

    def __init__(self, registry: MetricsRegistry, path: Optional[str] = None,
                 export_every: int = 1, prefix: str = "dst"):
        self.registry = registry
        self.path = path
        self.export_every = max(1, int(export_every))
        self.prefix = prefix
        self._since_export = 0
        if path:
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)

    def render(self) -> str:
        return render_prometheus(self.registry, prefix=self.prefix)

    def export(self) -> None:
        if not self.path:
            return
        tmp = self.path + ".tmp"
        with open(tmp, "w", encoding="utf-8") as f:
            f.write(self.render())
        os.replace(tmp, self.path)

    # sink protocol: a step record arriving is the export trigger; the
    # content comes from the registry, not the record
    def write(self, record: Dict[str, Any]) -> None:
        self._since_export += 1
        if self._since_export >= self.export_every:
            self.export()
            self._since_export = 0

    def close(self) -> None:
        try:
            self.export()
        except OSError as e:  # closing must not mask the real failure
            logger.warning(f"prometheus export on close failed: {e}")


class MonitorSink:
    """Adapter: step records -> MonitorMaster scalar events. This is how
    the legacy TensorBoard/CSV/W&B writers keep receiving the same
    Train/* series they always did, now fed from the unified pipeline."""

    # record field -> legacy event name (the series the reference's
    # _write_monitor emitted, plus the new throughput/memory series)
    SCALARS = (
        ("loss", "Train/loss"),
        ("lr", "Train/lr"),
        ("grad_norm", "Train/grad_norm"),
        ("wall_time_s", "Train/step_time_s"),
        ("tokens_per_s", "Train/tokens_per_s"),
        ("samples_per_s", "Train/samples_per_s"),
        ("mfu", "Train/mfu"),
    )

    def __init__(self, monitor: Any):
        self.monitor = monitor

    def write(self, record: Dict[str, Any]) -> None:
        step = int(record.get("step", 0))
        events = []
        for field_name, event_name in self.SCALARS:
            v = record.get(field_name)
            if v is not None:
                events.append((event_name, float(v), step))
        for k, v in (record.get("memory") or {}).items():
            events.append((f"Memory/{k}", float(v), step))
        if events:
            self.monitor.write_events(events)

    def close(self) -> None:
        close = getattr(self.monitor, "close", None)
        if close is not None:
            close()
