"""Metrics registry: counters, gauges, histograms with percentile summaries.

The reference scatters its numbers across ``utils/timer.py`` aggregates,
``monitor/`` event tuples and the CommsLogger's ad-hoc dicts. This registry
is the one shared store they all feed: plain host-side Python (no device
traffic, no jax import), safe to update from the training loop, the
inference engines and the comm facade alike. Exporters
(:mod:`deepspeed_tpu.telemetry.sinks`) render snapshots of it.

Metric names are ``/``-separated paths (``train/step_time_s``,
``comm/all_reduce/bytes``); the Prometheus exporter flattens them to
``_``-separated series names.
"""

from __future__ import annotations

import math
import threading
from typing import Any, Dict, List, Optional, Tuple


class Counter:
    """Monotonically increasing count (events, bytes, retries)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: negative increment {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-observed value (occupancy, loss scale, free blocks)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value: Optional[float] = None
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    @property
    def value(self) -> Optional[float]:
        with self._lock:
            return self._value


class Histogram:
    """Streaming distribution with percentile summaries.

    Keeps exact count/sum/min/max plus a bounded window of the most recent
    ``window`` observations for percentile estimates — deterministic (no
    sampling) and the right bias for operational telemetry, where "p99 over
    the recent past" beats "p99 since process start".
    """

    __slots__ = ("name", "count", "sum", "min", "max", "_window", "_buf",
                 "_pos", "_lock")

    def __init__(self, name: str, window: int = 1024):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._window = window
        self._buf: List[float] = []
        self._pos = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            if len(self._buf) < self._window:
                self._buf.append(v)
            else:  # ring: overwrite oldest
                self._buf[self._pos] = v
                self._pos = (self._pos + 1) % self._window

    @staticmethod
    def _rank(data: List[float], p: float) -> Optional[float]:
        """Linear-interpolated percentile of an already-sorted list."""
        if not data:
            return None
        if len(data) == 1:
            return data[0]
        rank = (p / 100.0) * (len(data) - 1)
        lo = int(rank)
        hi = min(lo + 1, len(data) - 1)
        frac = rank - lo
        return data[lo] * (1.0 - frac) + data[hi] * frac

    def percentile(self, p: float) -> Optional[float]:
        """Linear-interpolated percentile over the recent window.
        ``p`` in [0, 100]."""
        with self._lock:
            data = sorted(self._buf)
        return self._rank(data, p)

    def percentiles(self, ps: List[float]) -> List[Optional[float]]:
        """Several percentiles from ONE sorted copy of the window."""
        with self._lock:
            data = sorted(self._buf)
        return [self._rank(data, p) for p in ps]

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def summary(self) -> Dict[str, Optional[float]]:
        p50, p90, p99 = self.percentiles([50, 90, 99])
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "mean": self.mean,
            "p50": p50,
            "p90": p90,
            "p99": p99,
        }


class SketchHistogram:
    """Mergeable log-bucketed quantile sketch (DDSketch-style).

    Values map to geometric buckets ``(gamma^(i-1), gamma^i]`` with
    ``gamma = (1+alpha)/(1-alpha)``, so any quantile read from bucket
    midpoints carries a guaranteed relative error ``<= alpha`` — no
    sample window, no sort. ``observe`` is O(1) (a dict increment),
    ``percentile`` is O(buckets), and ``merge`` is bucket-count
    addition: associative, commutative, with the empty sketch as
    identity. That algebra is what makes replica→fleet→cell→region
    digest rollups exact — merging per-cell sketches gives the SAME
    bucket counts as observing the pooled stream directly.

    Negative values mirror into a second bucket map; magnitudes below
    ``ZERO_EPS`` land in a dedicated zero bucket. ``count``/``sum``/
    ``min``/``max`` stay exact. Everything is deterministic: bucket
    index is a pure function of the value, and :meth:`serialize`
    emits index-sorted rows, so equal observation multisets produce
    bit-identical serialized forms regardless of arrival order.
    """

    ZERO_EPS = 1e-12

    __slots__ = ("name", "alpha", "count", "sum", "min", "max", "_gamma",
                 "_ln_gamma", "_zero", "_pos", "_neg", "_lock")

    def __init__(self, name: str, alpha: float = 0.01):
        if not 0.0 < alpha < 1.0:
            raise ValueError(f"sketch {name}: alpha must be in (0, 1), "
                             f"got {alpha}")
        self.name = name
        self.alpha = float(alpha)
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._gamma = (1.0 + self.alpha) / (1.0 - self.alpha)
        self._ln_gamma = math.log(self._gamma)
        self._zero = 0
        self._pos: Dict[int, int] = {}
        self._neg: Dict[int, int] = {}
        self._lock = threading.Lock()

    def _index(self, magnitude: float) -> int:
        return int(math.ceil(math.log(magnitude) / self._ln_gamma))

    def _midpoint(self, index: int) -> float:
        # midpoint of (gamma^(i-1), gamma^i] that bounds relative error
        # by alpha: 2*gamma^i / (gamma + 1)
        return 2.0 * math.pow(self._gamma, index) / (self._gamma + 1.0)

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            a = abs(v)
            if a < self.ZERO_EPS:
                self._zero += 1
            elif v > 0:
                i = self._index(a)
                self._pos[i] = self._pos.get(i, 0) + 1
            else:
                i = self._index(a)
                self._neg[i] = self._neg.get(i, 0) + 1

    def _walk(self) -> List[Tuple[float, int]]:
        """Buckets in ascending value order as ``(estimate, count)``
        rows. Caller holds the lock."""
        rows: List[Tuple[float, int]] = []
        for i in sorted(self._neg, reverse=True):
            rows.append((-self._midpoint(i), self._neg[i]))
        if self._zero:
            rows.append((0.0, self._zero))
        for i in sorted(self._pos):
            rows.append((self._midpoint(i), self._pos[i]))
        return rows

    def percentile(self, p: float) -> Optional[float]:
        """Bucket-walk percentile, ``p`` in [0, 100]. The returned
        estimate is within ``alpha`` relative error of the exact
        same-rank order statistic (rank ``floor(p/100 * (n-1))``)."""
        with self._lock:
            return self._percentile_locked(p)

    def _percentile_locked(self, p: float) -> Optional[float]:
        if self.count == 0:
            return None
        target = int(math.floor((p / 100.0) * (self.count - 1) + 1e-9))
        seen = 0
        for est, n in self._walk():
            seen += n
            if seen > target:
                return est
        return self.max  # unreachable unless float drift; stay safe

    @property
    def mean(self) -> Optional[float]:
        with self._lock:
            return self.sum / self.count if self.count else None

    def merge(self, other: "SketchHistogram") -> "SketchHistogram":
        """Fold ``other`` into this sketch. Bucket addition — associative
        and commutative, so any rollup tree order gives one answer."""
        if abs(other.alpha - self.alpha) > 1e-12:
            raise ValueError(
                f"sketch {self.name}: cannot merge alpha={other.alpha} "
                f"into alpha={self.alpha}")
        # lock ordering: acquire other's snapshot first, then mutate
        # under our own lock — never hold both
        with other._lock:
            o_count, o_sum = other.count, other.sum
            o_min, o_max = other.min, other.max
            o_zero = other._zero
            o_pos = dict(other._pos)
            o_neg = dict(other._neg)
        with self._lock:
            self.count += o_count
            self.sum += o_sum
            if o_min is not None:
                self.min = o_min if self.min is None else min(self.min, o_min)
            if o_max is not None:
                self.max = o_max if self.max is None else max(self.max, o_max)
            self._zero += o_zero
            for i, n in o_pos.items():
                self._pos[i] = self._pos.get(i, 0) + n
            for i, n in o_neg.items():
                self._neg[i] = self._neg.get(i, 0) + n
        return self

    def serialize(self) -> Dict[str, Any]:
        """Stable wire form: index-sorted bucket rows, exact aggregates.
        Equal observation multisets serialize bit-identically."""
        with self._lock:
            return {
                "alpha": self.alpha,
                "count": self.count,
                "sum": self.sum,
                "min": self.min,
                "max": self.max,
                "zero": self._zero,
                "pos": [[i, self._pos[i]] for i in sorted(self._pos)],
                "neg": [[i, self._neg[i]] for i in sorted(self._neg)],
            }

    @classmethod
    def deserialize(cls, name: str, d: Dict[str, Any]) -> "SketchHistogram":
        s = cls(name, alpha=float(d["alpha"]))
        s.count = int(d["count"])
        s.sum = float(d["sum"])
        s.min = None if d.get("min") is None else float(d["min"])
        s.max = None if d.get("max") is None else float(d["max"])
        s._zero = int(d.get("zero", 0))
        s._pos = {int(i): int(n) for i, n in d.get("pos", [])}
        s._neg = {int(i): int(n) for i, n in d.get("neg", [])}
        return s

    def bucket_bounds(self) -> List[Tuple[float, int]]:
        """``(upper_bound, count)`` rows in ascending bound order for
        cumulative-bucket exporters: negative buckets close at
        ``-gamma^(i-1)``, the zero bucket at ``ZERO_EPS``, positive
        buckets at ``gamma^i``."""
        with self._lock:
            rows: List[Tuple[float, int]] = []
            for i in sorted(self._neg, reverse=True):
                rows.append((-math.pow(self._gamma, i - 1), self._neg[i]))
            if self._zero:
                rows.append((self.ZERO_EPS, self._zero))
            for i in sorted(self._pos):
                rows.append((math.pow(self._gamma, i), self._pos[i]))
            return rows

    def summary(self) -> Dict[str, Optional[float]]:
        with self._lock:   # one consistent snapshot (lock is not
            return {       # reentrant: use the _locked percentile)
                "count": self.count,
                "sum": self.sum,
                "min": self.min,
                "max": self.max,
                "mean": self.sum / self.count if self.count else None,
                "p50": self._percentile_locked(50),
                "p90": self._percentile_locked(90),
                "p99": self._percentile_locked(99),
            }


class MetricsRegistry:
    """Get-or-create store of named metrics.

    A name is bound to one metric kind for the registry's lifetime;
    re-requesting it with a different kind is a programming error and
    raises instead of silently shadowing.
    """

    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric '{name}' already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, window: int = 1024) -> Histogram:
        return self._get(name, Histogram, window=window)

    def sketch(self, name: str, alpha: float = 0.01) -> SketchHistogram:
        return self._get(name, SketchHistogram, alpha=alpha)

    def metrics(self) -> Dict[str, object]:
        with self._lock:
            return dict(self._metrics)

    def snapshot(self) -> Dict[str, object]:
        """JSON-serializable view of every metric: counters/gauges as
        scalars, histograms as their summary dict."""
        out: Dict[str, object] = {}
        for name, m in self.metrics().items():
            if isinstance(m, (Histogram, SketchHistogram)):
                out[name] = m.summary()
            else:
                out[name] = m.value
        return out

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


# ----------------------------------------------------------------------
# default registry: the shared store the comm facade, inference engines and
# resilience counters feed when not handed an explicit one
_DEFAULT = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _DEFAULT


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    global _DEFAULT
    _DEFAULT = registry
    return registry
