"""Metrics registry: counters, gauges, histograms with percentile summaries.

The reference scatters its numbers across ``utils/timer.py`` aggregates,
``monitor/`` event tuples and the CommsLogger's ad-hoc dicts. This registry
is the one shared store they all feed: plain host-side Python (no device
traffic, no jax import), safe to update from the training loop, the
inference engines and the comm facade alike. Exporters
(:mod:`deepspeed_tpu.telemetry.sinks`) render snapshots of it.

Metric names are ``/``-separated paths (``train/step_time_s``,
``comm/all_reduce/bytes``); the Prometheus exporter flattens them to
``_``-separated series names.
"""

from __future__ import annotations

import threading
from typing import Dict, List, Optional


class Counter:
    """Monotonically increasing count (events, bytes, retries)."""

    __slots__ = ("name", "_value", "_lock")

    def __init__(self, name: str):
        self.name = name
        self._value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name}: negative increment {n}")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        with self._lock:
            return self._value


class Gauge:
    """Last-observed value (occupancy, loss scale, free blocks)."""

    __slots__ = ("name", "_value")

    def __init__(self, name: str):
        self.name = name
        self._value: Optional[float] = None

    def set(self, v: float) -> None:
        self._value = float(v)

    @property
    def value(self) -> Optional[float]:
        return self._value


class Histogram:
    """Streaming distribution with percentile summaries.

    Keeps exact count/sum/min/max plus a bounded window of the most recent
    ``window`` observations for percentile estimates — deterministic (no
    sampling) and the right bias for operational telemetry, where "p99 over
    the recent past" beats "p99 since process start".
    """

    __slots__ = ("name", "count", "sum", "min", "max", "_window", "_buf",
                 "_pos", "_lock")

    def __init__(self, name: str, window: int = 1024):
        self.name = name
        self.count = 0
        self.sum = 0.0
        self.min: Optional[float] = None
        self.max: Optional[float] = None
        self._window = window
        self._buf: List[float] = []
        self._pos = 0
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            self.min = v if self.min is None else min(self.min, v)
            self.max = v if self.max is None else max(self.max, v)
            if len(self._buf) < self._window:
                self._buf.append(v)
            else:  # ring: overwrite oldest
                self._buf[self._pos] = v
                self._pos = (self._pos + 1) % self._window

    def percentile(self, p: float) -> Optional[float]:
        """Linear-interpolated percentile over the recent window.
        ``p`` in [0, 100]."""
        with self._lock:
            data = sorted(self._buf)
        if not data:
            return None
        if len(data) == 1:
            return data[0]
        rank = (p / 100.0) * (len(data) - 1)
        lo = int(rank)
        hi = min(lo + 1, len(data) - 1)
        frac = rank - lo
        return data[lo] * (1.0 - frac) + data[hi] * frac

    @property
    def mean(self) -> Optional[float]:
        return self.sum / self.count if self.count else None

    def summary(self) -> Dict[str, Optional[float]]:
        return {
            "count": self.count,
            "sum": self.sum,
            "min": self.min,
            "max": self.max,
            "mean": self.mean,
            "p50": self.percentile(50),
            "p90": self.percentile(90),
            "p99": self.percentile(99),
        }


class MetricsRegistry:
    """Get-or-create store of named metrics.

    A name is bound to one metric kind for the registry's lifetime;
    re-requesting it with a different kind is a programming error and
    raises instead of silently shadowing.
    """

    def __init__(self):
        self._metrics: Dict[str, object] = {}
        self._lock = threading.Lock()

    def _get(self, name: str, cls, **kwargs):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, **kwargs)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric '{name}' already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def histogram(self, name: str, window: int = 1024) -> Histogram:
        return self._get(name, Histogram, window=window)

    def metrics(self) -> Dict[str, object]:
        with self._lock:
            return dict(self._metrics)

    def snapshot(self) -> Dict[str, object]:
        """JSON-serializable view of every metric: counters/gauges as
        scalars, histograms as their summary dict."""
        out: Dict[str, object] = {}
        for name, m in self.metrics().items():
            if isinstance(m, Histogram):
                out[name] = m.summary()
            else:
                out[name] = m.value
        return out

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()


# ----------------------------------------------------------------------
# default registry: the shared store the comm facade, inference engines and
# resilience counters feed when not handed an explicit one
_DEFAULT = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return _DEFAULT


def set_registry(registry: MetricsRegistry) -> MetricsRegistry:
    global _DEFAULT
    _DEFAULT = registry
    return registry
