"""Request-scoped distributed tracing + flight recorder.

The serving stack spans a router, N replicas, disaggregated KV
hand-off, failover re-routes and retries; flat counters and per-
lifecycle span *records* (spans.py) say what happened to a request but
not *when/where* along its timeline. This module is the causal layer:

* :class:`Span` — one timed node in a trace tree (``trace_id`` /
  ``span_id`` / ``parent_id``), with point :meth:`~Tracer.event` marks
  attached to open spans. A request's whole life — router decision,
  queue wait, prefill, KV hand-off, decode, retries, failover
  re-routes, terminal — is ONE tree even when it crosses replicas.
* :class:`Tracer` — the per-process span store: bounded ring buffer of
  finished spans, Chrome-trace/Perfetto JSON export
  (:meth:`~Tracer.export_chrome_trace`), and a canonical trace hash
  (:meth:`~Tracer.canonical_hash`). Every timestamp comes from the
  injectable clock seam (:mod:`deepspeed_tpu.resilience.clock`), so
  traces are **bit-deterministic under SimClock**: the same DST seed
  produces the same canonical hash (gated by ``scripts/trace_smoke.py``).
* :class:`FlightRecorder` — a bounded in-memory ring of recent
  spans/events that :meth:`~FlightRecorder.dump`\\ s on demand. The
  serving layer auto-dumps it on invariant-audit failure (DST),
  watchdog fire, tick-fault retry exhaustion and ``PreemptionGuard``
  latch, so the moments *before* a failure are on disk without anyone
  attaching a debugger. ``heartbeat.py`` exports its depth / dropped
  count / last-dump path for external watchers.

Tracing is **off by default**: :func:`get_tracer` returns a disabled
tracer whose entry points return a shared no-op span and touch neither
the clock nor any lock — the serving tick path and the fused
``train_steps`` scan pay one attribute check (pinned by
tests/test_tracing.py, same zero-sync contract as PR 2's telemetry).
The dslint ``trace-hygiene`` rule bans ``span()`` / ``event()`` /
flight-recorder ``note()`` calls inside jitted code: spans observe the
HOST side of the program, never live inside it.

Determinism contract (docs/observability.md): span/trace ids are drawn
from per-tracer counters (never wall entropy), timestamps from the
clock seam, and :meth:`~Tracer.canonical_hash` normalizes ids to
first-seen order and drops volatile attrs (``uid``,
``client_request_id``) — so two runs of the same seeded schedule on
fresh tracers hash identically even in one process.
"""

from __future__ import annotations

import contextlib
import itertools
import json
import threading
from collections import deque
from typing import Any, Callable, Dict, Iterator, List, Optional, Tuple

#: attr keys excluded from the canonical hash: process-lifetime counters
#: (request uids keep incrementing across runs) and filesystem paths
VOLATILE_ATTRS = frozenset({"uid", "client_request_id", "path",
                            "shadow_uid"})


def _clock_time() -> float:
    """Span timestamps ride the injectable clock seam (lazy import:
    telemetry loads before resilience in some import orders)."""
    from ..resilience.clock import get_clock

    return get_clock().time()


class Span:
    """One node of a trace tree. Mutated only through its Tracer."""

    __slots__ = ("trace_id", "span_id", "parent_id", "name", "track",
                 "t_start", "t_end", "attrs", "events", "_annotation")

    def __init__(self, trace_id: str, span_id: str,
                 parent_id: Optional[str], name: str,
                 track: Optional[str], t_start: Optional[float],
                 attrs: Optional[Dict[str, Any]] = None):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.name = name
        self.track = track
        self.t_start = t_start
        self.t_end: Optional[float] = None
        self.attrs: Dict[str, Any] = dict(attrs) if attrs else {}
        self.events: List[Tuple[float, str, Dict[str, Any]]] = []
        # open jax.profiler.TraceAnnotation when the XLA bridge wrapped
        # this span (scoped spans only — annotations are thread-bound)
        self._annotation = None

    @property
    def is_noop(self) -> bool:
        return self.span_id == ""

    @property
    def open(self) -> bool:
        return self.t_end is None and not self.is_noop

    def to_dict(self) -> Dict[str, Any]:
        return {"trace_id": self.trace_id, "span_id": self.span_id,
                "parent_id": self.parent_id, "name": self.name,
                "track": self.track, "t_start": self.t_start,
                "t_end": self.t_end, "attrs": dict(self.attrs),
                "events": [{"t": t, "name": n, "attrs": dict(a)}
                           for t, n, a in self.events]}


#: the shared do-nothing span every disabled-tracer entry point returns
_NOOP_SPAN = Span(trace_id="", span_id="", parent_id=None, name="",
                  track=None, t_start=None)


def _ring_append(ring: deque, capacity: int, item: Any) -> int:
    """Bounded-ring append (caller holds the owning lock). Returns the
    number of evicted records so every ring keeps the same
    drop-accounting invariant (`dropped += _ring_append(...)`)."""
    evicted = 1 if len(ring) == capacity else 0
    ring.append(item)
    return evicted


class FlightRecorder:
    """Bounded ring of recent span/event records (black box). Appends
    are lock-protected list ops; :meth:`dump` snapshots under the lock
    and does its file I/O OUTSIDE it (dslint lock-discipline)."""

    def __init__(self, capacity: int = 512,
                 dump_dir: Optional[str] = None):
        self.capacity = max(1, int(capacity))
        self.dump_dir = dump_dir
        self._ring: deque = deque(maxlen=self.capacity)
        self._lock = threading.Lock()
        self.dropped = 0
        self.dumps = 0
        self.last_dump_path: Optional[str] = None
        self.last_dump_reason: Optional[str] = None
        self.last_dump: Optional[Dict[str, Any]] = None
        self._dump_seq = itertools.count()

    @property
    def depth(self) -> int:
        with self._lock:
            return len(self._ring)

    def note(self, kind: str, **fields: Any) -> None:
        """Append one event record to the ring (the flight-recorder
        entry point the dslint trace-hygiene rule bans inside jitted
        code — recorder appends are host-side observability)."""
        rec = {"kind": kind, "t": _clock_time(), **fields}
        with self._lock:
            self.dropped += _ring_append(self._ring, self.capacity, rec)

    def note_span(self, span: Span) -> None:
        rec = {"kind": "span", **span.to_dict()}
        with self._lock:
            self.dropped += _ring_append(self._ring, self.capacity, rec)

    def snapshot(self) -> List[Dict[str, Any]]:
        with self._lock:
            return list(self._ring)

    def dump(self, reason: str, path: Optional[str] = None
             ) -> Optional[str]:
        """Write the ring to a JSON file (auto-named under ``dump_dir``
        when ``path`` is None). With neither configured, the payload is
        kept on ``self.last_dump`` instead — callers that only want the
        in-memory black box (the DST harness) never touch disk."""
        with self._lock:
            records = list(self._ring)
            n = next(self._dump_seq)
            dropped = self.dropped   # written under the lock by note()
        payload = {"version": 1, "reason": reason, "t": _clock_time(),
                   "depth": len(records), "dropped": dropped,
                   "records": records}
        if path is None and self.dump_dir is not None:
            import os

            os.makedirs(self.dump_dir, exist_ok=True)
            path = os.path.join(self.dump_dir,
                                f"flight_{n:03d}_{reason}.json")
        wrote = False
        if path is not None:
            try:
                # atomic temp+rename: dumps fire exactly at failure
                # moments (watchdog, latch, retry exhaustion) when the
                # process may die mid-write, and a torn JSON is useless
                # to a post-mortem
                from ..utils.fileio import write_json_atomic

                write_json_atomic(path, payload, indent=1)
                wrote = True
            except OSError as e:
                from ..utils.logging import logger

                logger.warning(
                    f"flight recorder dump to {path} failed: {e}")
        with self._lock:
            # all published last_dump* state flips under ONE lock
            # section: concurrent dumps (watchdog vs driver thread) must
            # never tear reason/payload/path apart for a reader
            self.dumps += 1
            self.last_dump_reason = reason
            self.last_dump = payload
            if wrote:
                self.last_dump_path = path
        return path if wrote else None


class _TlsStack(threading.local):
    def __init__(self):
        self.stack: List[Span] = []


class Tracer:
    """Span-tree tracer with bounded storage (see module docstring).

    Two span surfaces:

    * :meth:`span` — a context manager for HOST-scoped work (one
      thread, begin and end in one frame). Nested ``span()`` calls on
      the same thread parent automatically. When a ``jax.profiler``
      trace is active (``profiling/trace.py``), the same name is
      emitted as a profiler host-track annotation so tracer spans line
      up with TensorBoard/Perfetto device timelines.
    * :meth:`begin_span` / :meth:`finish_span` — explicit segments for
      state machines whose phases start and end in different frames
      (or threads, or replicas): the serving request path. Explicit
      segments never touch the thread-local stack and are never
      bridged to the profiler (annotations are thread-bound).
    """

    def __init__(self, enabled: bool = False, ring_size: int = 4096,
                 flight_capacity: int = 512,
                 flight_dump_dir: Optional[str] = None,
                 xla_bridge: bool = True):
        self.enabled = bool(enabled)
        self.ring_size = max(1, int(ring_size))
        self.xla_bridge = bool(xla_bridge)
        self.flight = FlightRecorder(flight_capacity, flight_dump_dir)
        self._lock = threading.Lock()
        self._ring: deque = deque(maxlen=self.ring_size)
        self._open: Dict[str, Span] = {}
        self._trace_seq = itertools.count(1)
        self._span_seq = itertools.count(1)
        self._tls = _TlsStack()
        self.dropped = 0

    # -- span lifecycle --------------------------------------------------
    def new_trace(self, name: str, track: Optional[str] = None,
                  **attrs: Any) -> Span:
        """Open a new root span (a fresh trace_id)."""
        if not self.enabled:
            return _NOOP_SPAN
        with self._lock:
            tid = f"t{next(self._trace_seq)}"
            sid = f"s{next(self._span_seq)}"
            span = Span(tid, sid, None, name, track, _clock_time(), attrs)
            self._open[sid] = span
        return span

    def begin_span(self, name: str, parent: Optional[Span],
                   track: Optional[str] = None, **attrs: Any) -> Span:
        """Open a child span under ``parent`` (a root when parent is
        None/no-op — callers that lost their root still trace)."""
        if not self.enabled:
            return _NOOP_SPAN
        if parent is None or parent.is_noop:
            return self.new_trace(name, track=track, **attrs)
        with self._lock:
            sid = f"s{next(self._span_seq)}"
            span = Span(parent.trace_id, sid, parent.span_id, name,
                        track if track is not None else parent.track,
                        _clock_time(), attrs)
            self._open[sid] = span
        return span

    def finish_span(self, span: Optional[Span],
                    t_end: Optional[float] = None, **attrs: Any) -> None:
        """Close an open span: stamp its end, merge ``attrs``, move it
        into the ring and the flight recorder."""
        if span is None or span.is_noop or not self.enabled:
            return
        ann, span._annotation = span._annotation, None
        if ann is not None:
            ann.__exit__(None, None, None)
        with self._lock:
            if span.t_end is not None:      # double-finish: keep first
                return
            span.t_end = float(t_end) if t_end is not None \
                else _clock_time()
            if attrs:
                span.attrs.update(attrs)
            self._open.pop(span.span_id, None)
            self.dropped += _ring_append(self._ring, self.ring_size, span)
        self.flight.note_span(span)

    def span_complete(self, name: str, t_start: float, t_end: float,
                      parent: Optional[Span] = None,
                      track: Optional[str] = None, **attrs: Any) -> Span:
        """Record an already-timed span (measurement harnesses that
        compute their windows before reporting them)."""
        if not self.enabled:
            return _NOOP_SPAN
        with self._lock:
            if parent is not None and not parent.is_noop:
                tid, pid = parent.trace_id, parent.span_id
            else:
                tid, pid = f"t{next(self._trace_seq)}", None
            sid = f"s{next(self._span_seq)}"
            span = Span(tid, sid, pid, name, track, float(t_start), attrs)
            span.t_end = float(t_end)
            self.dropped += _ring_append(self._ring, self.ring_size, span)
        self.flight.note_span(span)
        return span

    def event(self, span: Optional[Span], name: str,
              **attrs: Any) -> None:
        """Point event attached to an open span (the request root,
        usually): retries, preemptions, failover re-routes, injected
        faults — the marks between phase boundaries."""
        if not self.enabled or span is None or span.is_noop:
            return
        with self._lock:
            if span.t_end is None:
                span.events.append((_clock_time(), name, dict(attrs)))

    @contextlib.contextmanager
    def span(self, name: str, parent: Optional[Span] = None,
             track: Optional[str] = None, **attrs: Any) -> Iterator[Span]:
        """Scoped span for same-thread work; nests via a thread-local
        stack and bridges to the XLA profiler host track when a
        profiler trace is active."""
        if not self.enabled:
            yield _NOOP_SPAN
            return
        if parent is None and self._tls.stack:
            parent = self._tls.stack[-1]
        sp = (self.begin_span(name, parent, track=track, **attrs)
              if parent is not None
              else self.new_trace(name, track=track, **attrs))
        if self.xla_bridge:
            from ..profiling import trace as xla_trace

            if xla_trace.trace_active():
                sp._annotation = xla_trace.annotate(name)
                sp._annotation.__enter__()
        self._tls.stack.append(sp)
        try:
            yield sp
        finally:
            self._tls.stack.pop()
            self.finish_span(sp)

    # -- introspection ---------------------------------------------------
    def spans(self) -> List[Span]:
        """Finished spans, oldest first (bounded by ``ring_size``)."""
        with self._lock:
            return list(self._ring)

    def open_spans(self) -> List[Span]:
        with self._lock:
            return list(self._open.values())

    def spans_for_trace(self, trace_id: str) -> List[Span]:
        with self._lock:
            out = [s for s in self._ring if s.trace_id == trace_id]
            out.extend(s for s in self._open.values()
                       if s.trace_id == trace_id)
        return out

    def reset(self) -> None:
        with self._lock:
            self._ring.clear()
            self._open.clear()
            self.dropped = 0

    # -- canonical hash --------------------------------------------------
    def canonical_rows(self) -> List[tuple]:
        """Normalized, order-stable rows for hashing: ids mapped to
        first-seen ordinals, volatile attrs dropped (see module
        docstring's determinism contract)."""
        spans = sorted(self.spans(),
                       key=lambda s: (s.t_start, s.trace_id, s.span_id))
        tid_ord: Dict[str, int] = {}
        sid_ord: Dict[str, int] = {}
        for s in spans:
            tid_ord.setdefault(s.trace_id, len(tid_ord))
            sid_ord.setdefault(s.span_id, len(sid_ord))
        rows = []
        for s in spans:
            attrs = tuple(sorted((k, repr(v)) for k, v in s.attrs.items()
                                 if k not in VOLATILE_ATTRS))
            events = tuple(
                (round(t, 9), n,
                 tuple(sorted((k, repr(v)) for k, v in a.items()
                              if k not in VOLATILE_ATTRS)))
                for t, n, a in s.events)
            rows.append((tid_ord[s.trace_id], sid_ord[s.span_id],
                         sid_ord.get(s.parent_id, -1), s.name, s.track,
                         round(s.t_start, 9),
                         round(s.t_end, 9) if s.t_end is not None else None,
                         attrs, events))
        return rows

    def canonical_hash(self) -> str:
        """sha256 over the canonical rows — the determinism witness:
        same seeded schedule on a fresh tracer, same hash."""
        import hashlib

        payload = "\n".join(repr(r) for r in self.canonical_rows())
        return hashlib.sha256(payload.encode("utf-8")).hexdigest()

    # -- export ----------------------------------------------------------
    def export_chrome_trace(self, path: Optional[str] = None
                            ) -> Dict[str, Any]:
        """Chrome-trace/Perfetto JSON (``chrome://tracing`` / ui.perfetto
        .dev): one complete ("X") event per finished span on a per-track
        tid, instant ("i") events for span marks, thread-name metadata
        per track. Span identity rides in ``args`` so the tree survives
        the flat event list."""
        spans = self.spans()
        tracks: Dict[str, int] = {}

        def tid_of(track: Optional[str]) -> int:
            return tracks.setdefault(track or "main", len(tracks))

        events: List[Dict[str, Any]] = []
        for s in spans:
            tid = tid_of(s.track)
            args = {"trace_id": s.trace_id, "span_id": s.span_id}
            if s.parent_id:
                args["parent_id"] = s.parent_id
            args.update({k: v for k, v in s.attrs.items()})
            events.append({
                "ph": "X", "name": s.name, "cat": "span",
                "ts": s.t_start * 1e6,
                "dur": max(0.0, (s.t_end - s.t_start) * 1e6),
                "pid": 0, "tid": tid, "args": args,
            })
            for t, name, attrs in s.events:
                events.append({
                    "ph": "i", "name": name, "cat": "event",
                    "ts": t * 1e6, "s": "t", "pid": 0, "tid": tid,
                    "args": {"trace_id": s.trace_id,
                             "span_id": s.span_id, **attrs},
                })
        for track, tid in tracks.items():
            events.append({"ph": "M", "name": "thread_name", "pid": 0,
                           "tid": tid, "args": {"name": track}})
        doc = {"traceEvents": events, "displayTimeUnit": "ms"}
        if path is not None:
            with open(path, "w", encoding="utf-8") as fh:
                json.dump(doc, fh, indent=1, sort_keys=True)
                fh.write("\n")
        return doc


def validate_chrome_trace(doc: Any) -> List[str]:
    """Structural validation of an exported Chrome-trace document (the
    trace lane's schema check). Returns violation strings; empty means
    valid."""
    errors: List[str] = []
    if not isinstance(doc, dict) or not isinstance(
            doc.get("traceEvents"), list):
        return ["document must be a dict with a traceEvents list"]
    for i, ev in enumerate(doc["traceEvents"]):
        where = f"traceEvents[{i}]"
        if not isinstance(ev, dict):
            errors.append(f"{where}: not a dict")
            continue
        ph = ev.get("ph")
        if ph not in ("X", "i", "M"):
            errors.append(f"{where}: unknown ph {ph!r}")
            continue
        if not isinstance(ev.get("name"), str) or not ev["name"]:
            errors.append(f"{where}: missing name")
        for k in ("pid", "tid"):
            if not isinstance(ev.get(k), int):
                errors.append(f"{where}: missing integer {k}")
        if ph in ("X", "i"):
            ts = ev.get("ts")
            if not isinstance(ts, (int, float)) or isinstance(ts, bool):
                errors.append(f"{where}: missing numeric ts")
        if ph == "X":
            dur = ev.get("dur")
            if (not isinstance(dur, (int, float))
                    or isinstance(dur, bool) or dur < 0):
                errors.append(f"{where}: X event needs dur >= 0")
            args = ev.get("args")
            if not isinstance(args, dict) or "span_id" not in args \
                    or "trace_id" not in args:
                errors.append(f"{where}: X event args need "
                              f"trace_id/span_id")
    return errors


def trace_tree_problems(spans: List[Span]) -> List[str]:
    """Connectivity audit over one trace's spans: exactly one root,
    every parent present (no orphans), every span closed. The DST
    auditor runs this per terminal request — a failover/disagg request
    must still be ONE connected tree."""
    problems: List[str] = []
    if not spans:
        return ["trace has no spans"]
    ids = {s.span_id for s in spans}
    roots = [s for s in spans if s.parent_id is None]
    if len(roots) != 1:
        problems.append(f"expected exactly one root span, found "
                        f"{len(roots)} ({[s.name for s in roots]})")
    for s in spans:
        if s.parent_id is not None and s.parent_id not in ids:
            problems.append(f"orphan span '{s.name}' ({s.span_id}): "
                            f"parent {s.parent_id} missing")
        if s.t_end is None:
            problems.append(f"span '{s.name}' ({s.span_id}) never "
                            f"finished")
    return problems


# ----------------------------------------------------------------------
# request-path helpers: the serving layer stores its trace state ON the
# request object (``_trace_root`` open root span, ``_trace_seg`` open
# lifecycle segment) so the tree follows the request across replicas.

def ensure_request_root(req: Any, **attrs: Any) -> None:
    """Open the request's root span if it has none (single-engine
    submissions; the fleet opens it earlier to capture routing)."""
    tr = get_tracer()
    if not tr.enabled or getattr(req, "_trace_root", None) is not None:
        return
    req._trace_root = tr.new_trace("request", **attrs)


def begin_request_segment(req: Any, name: str,
                          track: Optional[str] = None,
                          **attrs: Any) -> None:
    """Close the request's open lifecycle segment (if any) and begin
    the next one — queue → prefill → decode → handoff → ... — as a
    child of its root."""
    tr = get_tracer()
    root = getattr(req, "_trace_root", None)
    if not tr.enabled or root is None:
        return
    seg = getattr(req, "_trace_seg", None)
    if seg is not None:
        tr.finish_span(seg)
    req._trace_seg = tr.begin_span(name, root, track=track, **attrs)


def end_request_segment(req: Any, **attrs: Any) -> None:
    tr = get_tracer()
    seg = getattr(req, "_trace_seg", None)
    if seg is not None:
        tr.finish_span(seg, **attrs)
        req._trace_seg = None


def request_event(req: Any, name: str, **attrs: Any) -> None:
    """Point event on the request's root span (retry, preempt,
    failover, reroute, ...)."""
    tr = get_tracer()
    root = getattr(req, "_trace_root", None)
    if not tr.enabled or root is None:
        return
    tr.event(root, name, **attrs)


def finish_request_trace(req: Any, **attrs: Any) -> None:
    """Terminal closure: end the open segment and the root. Called from
    the one place every terminal request passes through
    (``serving.server.emit_request_span``) so exactly one closure per
    request."""
    tr = get_tracer()
    root = getattr(req, "_trace_root", None)
    if root is None or root.is_noop:
        return
    end_request_segment(req, outcome=attrs.get("state"))
    tr.finish_span(root, **attrs)


# ----------------------------------------------------------------------
_TRACER: Optional[Tracer] = None
_DISABLED: Optional[Tracer] = None


def get_tracer() -> Tracer:
    """The installed process-global tracer, or the shared disabled
    instance (every entry point a cheap no-op)."""
    global _DISABLED
    if _TRACER is not None:
        return _TRACER
    if _DISABLED is None:
        _DISABLED = Tracer(enabled=False)
    return _DISABLED


def set_tracer(tracer: Optional[Tracer]) -> Optional[Tracer]:
    """Install ``tracer`` process-globally (None restores the disabled
    default). Returns the previously installed tracer."""
    global _TRACER
    prev = _TRACER
    _TRACER = tracer
    return prev


@contextlib.contextmanager
def use_tracer(tracer: Tracer) -> Iterator[Tracer]:
    """Scoped :func:`set_tracer` — the DST harness's entry seam."""
    prev = set_tracer(tracer)
    try:
        yield tracer
    finally:
        set_tracer(prev)


def configure_tracing(config: Any = None) -> Optional[Tracer]:
    """Build + install a Tracer from a TelemetryConfig's tracing knobs
    (``telemetry.tracing`` et al., config.py). Returns the installed
    tracer, or None (and clears any installed one) when tracing is
    disabled."""
    if not bool(getattr(config, "tracing", False)):
        set_tracer(None)
        return None
    tracer = Tracer(
        enabled=True,
        ring_size=int(getattr(config, "trace_ring", 4096)),
        flight_capacity=int(getattr(config, "flight_capacity", 512)),
        flight_dump_dir=getattr(config, "flight_dump_dir", None),
    )
    set_tracer(tracer)
    return tracer
