"""Per-tenant SLO objectives and multi-window burn-rate alerting.

An :class:`SLOObjective` states the contract: a target fraction of
SLO-carrying requests in SLA over a rolling window. The
:class:`TenantSLOTracker` measures attainment against it per tenant
(and per model version, for the rollout canary judge) from the digest
rollup plane (:mod:`deepspeed_tpu.telemetry.digest`): the region feeds
it one ``(t, verdict-deltas)`` row per absorbed digest, so tracking
cost scales with digest count, never request count.

Alerting follows the multi-window burn-rate recipe (the SRE-workbook
shape, on VIRTUAL time): ``burn = miss_rate / error_budget`` where
``error_budget = 1 - target``. A *fast* window (5-minute-equivalent)
catches cliffs; a *slow* window (1-hour-equivalent) catches smolder.
Each (tenant, window) pair has fire/clear hysteresis — an alert fires
at its burn threshold and clears only below ``clear_ratio`` of it, or
when the window's samples age out entirely. Every transition is
appended to :attr:`TenantSLOTracker.alert_log` — a deterministic,
replayable stream the SLO lane hashes per DST seed — and mirrored into
the metrics registry and flight recorder by the region.

No RNG, no clock reads (``now`` is always passed in), stable iteration
orders: same digest stream, same alerts, bit-identical.
"""

from __future__ import annotations

import collections
from dataclasses import dataclass
from typing import Any, Deque, Dict, List, Optional, Tuple

#: window labels (stable wire strings in alert rows)
FAST, SLOW = "fast", "slow"


@dataclass(frozen=True)
class SLOObjective:
    """One SLO contract: ``target`` in-SLA ratio, measured over
    ``window_s`` of virtual time, alerted through fast/slow burn-rate
    windows. Defaults follow the classic 95%-target multiwindow page:
    fast threshold 14.4 burns a 30-day budget in ~2 days, slow 6 in ~5.
    """

    target: float = 0.95
    window_s: float = 240.0
    fast_window_s: float = 300.0
    slow_window_s: float = 3600.0
    fast_burn_threshold: float = 14.4
    slow_burn_threshold: float = 6.0
    clear_ratio: float = 0.5
    min_samples: int = 4

    def __post_init__(self):
        if not 0.0 < self.target < 1.0:
            raise ValueError(f"slo target must be in (0, 1), got "
                             f"{self.target}")
        for f in ("window_s", "fast_window_s", "slow_window_s"):
            if getattr(self, f) <= 0:
                raise ValueError(f"slo {f} must be > 0")
        for f in ("fast_burn_threshold", "slow_burn_threshold"):
            if getattr(self, f) <= 0:
                raise ValueError(f"slo {f} must be > 0")
        if not 0.0 < self.clear_ratio <= 1.0:
            raise ValueError(f"slo clear_ratio must be in (0, 1], got "
                             f"{self.clear_ratio}")
        if self.min_samples < 1:
            raise ValueError(f"slo min_samples must be >= 1, got "
                             f"{self.min_samples}")

    @property
    def error_budget(self) -> float:
        return 1.0 - self.target

    def burn_rate(self, attainment: float) -> float:
        return (1.0 - attainment) / self.error_budget

    def to_dict(self) -> Dict[str, Any]:
        return {
            "target": self.target, "window_s": self.window_s,
            "fast_window_s": self.fast_window_s,
            "slow_window_s": self.slow_window_s,
            "fast_burn_threshold": self.fast_burn_threshold,
            "slow_burn_threshold": self.slow_burn_threshold,
            "clear_ratio": self.clear_ratio,
            "min_samples": self.min_samples,
        }


#: one verdict-delta row: (t, in_slo_count, judged_count)
_Row = Tuple[float, int, int]


def _window_totals(rows: Deque[_Row], now: float,
                   window_s: float) -> Tuple[int, int]:
    """(ok, judged) over rows with ``t > now - window_s`` (rows are
    appended in non-decreasing t, so scan from the right)."""
    cutoff = now - window_s
    ok = judged = 0
    for t, o, n in reversed(rows):
        if t <= cutoff:
            break
        ok += o
        judged += n
    return ok, judged


class TenantSLOTracker:
    """Windowed SLO attainment per tenant / version / region-wide, with
    multi-window burn-rate alerting.

    Single-threaded by design: the region's rollup pass (monitor
    thread, or manual ``poll()``) is the only caller — the same
    discipline as :class:`~deepspeed_tpu.telemetry.digest.DigestAccumulator`.
    """

    def __init__(self, objective: Optional[SLOObjective] = None):
        self.objective = objective if objective is not None \
            else SLOObjective()
        self._tenants: Dict[str, Deque[_Row]] = {}
        self._versions: Dict[int, Deque[_Row]] = {}
        self._global: Deque[_Row] = collections.deque()
        #: {"t", "tenant", "window", "state", "burn"} transition rows —
        #: the lane's bit-identity witness. Bounded like brownout_log.
        self.alert_log: Deque[Dict[str, Any]] = collections.deque(
            maxlen=4096)
        self._active: Dict[Tuple[str, str], bool] = {}

    # -- feed (one call per absorbed digest) -----------------------------
    def record(self, t: float,
               tenants: Dict[str, List[int]],
               versions: Dict[int, List[int]],
               ok: int, judged: int) -> None:
        """Fold one digest's verdict deltas in at virtual time ``t``."""
        horizon = max(self.objective.slow_window_s,
                      self.objective.window_s)
        for k in sorted(tenants):
            o, n = tenants[k][0], tenants[k][1]
            if n:
                self._tenants.setdefault(  # dslint: disable=races -- rollup-thread confined by contract (class docstring): record/check_alerts run only on the region's single rollup thread; attainment reads tolerate a torn row at worst
                    k, collections.deque()).append((t, o, n))
        for k in sorted(versions):
            o, n = versions[k][0], versions[k][1]
            if n:
                self._versions.setdefault(  # dslint: disable=races -- rollup-thread confined by contract (see above)
                    k, collections.deque()).append((t, o, n))
        if judged:
            self._global.append((t, int(ok), int(judged)))
        self._prune(t - horizon)

    def _prune(self, cutoff: float) -> None:
        for rows in list(self._tenants.values()) \
                + list(self._versions.values()) + [self._global]:
            while rows and rows[0][0] <= cutoff:
                rows.popleft()

    # -- attainment reads ------------------------------------------------
    def attainment(self, now: float,
                   window_s: Optional[float] = None) -> Optional[float]:
        """Region-wide in-SLA ratio over the objective window (None
        until a verdict lands in it)."""
        ok, judged = _window_totals(
            self._global, now,
            self.objective.window_s if window_s is None else window_s)
        return ok / judged if judged else None

    def tenant_attainment(self, tenant: str, now: float,
                          window_s: Optional[float] = None
                          ) -> Tuple[int, Optional[float]]:
        rows = self._tenants.get(tenant)
        if not rows:
            return 0, None
        ok, judged = _window_totals(
            rows, now,
            self.objective.window_s if window_s is None else window_s)
        return judged, (ok / judged if judged else None)

    def version_attainment(self, version: int, now: float,
                           window_s: Optional[float] = None
                           ) -> Tuple[int, Optional[float]]:
        """(samples, ratio) for one model version — the rollout canary
        judge's signal, read from the plane instead of per-fleet deques."""
        rows = self._versions.get(int(version))
        if not rows:
            return 0, None
        ok, judged = _window_totals(
            rows, now,
            self.objective.window_s if window_s is None else window_s)
        return judged, (ok / judged if judged else None)

    def tenants(self) -> List[str]:
        return sorted(self._tenants)

    def active_alerts(self) -> List[Tuple[str, str]]:
        """Currently-firing (tenant, window) pairs, sorted."""
        return sorted(k for k, v in self._active.items() if v)

    def has_fast_burn(self) -> bool:
        """True while any tenant's FAST window alert is firing — the
        brownout ladder's descend-hold signal."""
        return any(v and k[1] == FAST for k, v in self._active.items())

    # -- alerting --------------------------------------------------------
    def check_alerts(self, now: float) -> List[Dict[str, Any]]:
        """Evaluate every (tenant, window) pair at ``now``; return (and
        log) the transitions. Deterministic: sorted tenant order, pure
        function of the recorded rows."""
        obj = self.objective
        transitions: List[Dict[str, Any]] = []
        windows = ((FAST, obj.fast_window_s, obj.fast_burn_threshold),
                   (SLOW, obj.slow_window_s, obj.slow_burn_threshold))
        for tenant in sorted(self._tenants):
            rows = self._tenants[tenant]
            for label, win_s, threshold in windows:
                key = (tenant, label)
                active = self._active.get(key, False)
                ok, judged = _window_totals(rows, now, win_s)
                if judged < obj.min_samples:
                    # not enough evidence to judge; an active alert
                    # whose samples aged out entirely auto-clears (the
                    # tenant went quiet — nothing is burning budget)
                    if active and judged == 0:
                        self._active[key] = False  # dslint: disable=races -- rollup-thread confined by contract (class docstring): check_alerts runs only on the region's single rollup thread; has_fast_burn/active_alerts read a bool flip atomically under the GIL
                        transitions.append(self._log(now, tenant, label,
                                                     "clear", 0.0))
                    continue
                burn = obj.burn_rate(ok / judged)
                if not active and burn >= threshold:
                    self._active[key] = True
                    transitions.append(self._log(now, tenant, label,
                                                 "firing", burn))
                elif active and burn <= threshold * obj.clear_ratio:
                    self._active[key] = False
                    transitions.append(self._log(now, tenant, label,
                                                 "clear", burn))
        return transitions

    def _log(self, t: float, tenant: str, window: str, state: str,
             burn: float) -> Dict[str, Any]:
        row = {"t": t, "tenant": tenant, "window": window,
               "state": state, "burn": round(burn, 6)}
        self.alert_log.append(row)
        return row
