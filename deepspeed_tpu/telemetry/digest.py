"""Hierarchical telemetry digests: replica → fleet → cell → region.

The flat registry keeps every replica's metrics as ``serving/<cell>/
replica-N/...`` names in one namespace, so any fleet/cell/region view is
a full-namespace scan — O(total replicas) per read, exactly the class of
scan ROADMAP item 1 says thousands of simulated replicas will expose.
This module is the publish-not-scan fix, the same discipline
``ServingCell.publish_digest`` already applies to routing state:

* each tier owns a :class:`DigestSource` — a leaf-locked collector of
  counter deltas, sketch observations and per-tenant/per-version SLO
  verdicts;
* ``publish()`` snapshots AND RESETS the source, so every published
  :class:`TelemetryDigest` is a *delta*: merging a stream of digests
  reproduces the total exactly (sketch bucket addition is associative
  and commutative — see :class:`SketchHistogram`), and no observation
  is ever counted twice;
* the region folds per-cell digests into one :class:`DigestAccumulator`
  whose ``percentile()``/``snapshot()`` answer region-scale questions
  from O(cells) merged state — per-tick rollup work is independent of
  replica count.

Everything here is deterministic on virtual time: no RNG, no clock
reads (timestamps are passed in by the caller), stable iteration
orders. Under DST the per-seed digest stream hashes bit-identically
(``scripts/slo_lane.py``).
"""

from __future__ import annotations

import threading
from typing import Any, Dict, List, Optional, Tuple

from .registry import SketchHistogram

# canonical short metric names carried inside digests (tier prefixes are
# added only at the region's registry boundary)
LATENCY_METRICS = ("queue_wait_s", "ttft_s", "request_latency_s",
                   "tokens_per_s", "tick_s")


class TelemetryDigest:
    """One tier's published telemetry delta: counter deltas, mergeable
    sketches, and per-tenant / per-model-version SLO verdict counts.

    Digests are created and merged on the publishing/rollup thread only
    (the region poll pulls them, mirroring ``publish_digest``); the
    sketches inside carry their own locks, the scalar maps need none.
    ``merge`` is associative and commutative with the empty digest as
    identity, so merge-of-digests equals digest-of-union.
    """

    __slots__ = ("t", "source", "alpha", "counters", "sketches",
                 "tenants", "versions")

    def __init__(self, t: float, source: str, alpha: float = 0.01):
        self.t = float(t)
        self.source = source
        self.alpha = float(alpha)
        self.counters: Dict[str, float] = {}
        self.sketches: Dict[str, SketchHistogram] = {}
        # tenant/version -> [in_slo_count, judged_count] deltas
        self.tenants: Dict[str, List[int]] = {}
        self.versions: Dict[int, List[int]] = {}

    @property
    def rows(self) -> int:
        """Bounded row count — the 'fixed-size' witness the rollup-cost
        gate meters (independent of how many requests fed the delta)."""
        return (len(self.counters) + len(self.sketches)
                + len(self.tenants) + len(self.versions))

    def is_empty(self) -> bool:
        return self.rows == 0

    def merge(self, other: "TelemetryDigest") -> "TelemetryDigest":
        for k, v in other.counters.items():
            self.counters[k] = self.counters.get(k, 0.0) + v  # dslint: disable=races -- rollup-thread confined by contract (class docstring): a digest is created and merged only on the single pulling thread (region monitor OR manual poll, never both); cross-thread writers go through DigestSource's lock instead
        for k, s in other.sketches.items():
            mine = self.sketches.get(k)
            if mine is None:
                mine = SketchHistogram(k, alpha=self.alpha)
                self.sketches[k] = mine  # dslint: disable=races -- rollup-thread confined by contract (see counters above)
            mine.merge(s)
        for k, v in other.tenants.items():
            row = self.tenants.setdefault(k, [0, 0])  # dslint: disable=races -- rollup-thread confined by contract (see counters above)
            row[0] += v[0]
            row[1] += v[1]
        for k, v in other.versions.items():
            row = self.versions.setdefault(k, [0, 0])  # dslint: disable=races -- rollup-thread confined by contract (see counters above)
            row[0] += v[0]
            row[1] += v[1]
        return self

    def to_dict(self) -> Dict[str, Any]:
        """Canonical (key-sorted) wire form — the bit-identity surface
        the SLO lane hashes per seed."""
        return {
            "t": self.t,
            "source": self.source,
            "alpha": self.alpha,
            "counters": {k: self.counters[k]
                         for k in sorted(self.counters)},
            "sketches": {k: self.sketches[k].serialize()
                         for k in sorted(self.sketches)},
            "tenants": {k: list(self.tenants[k])
                        for k in sorted(self.tenants)},
            "versions": {str(k): list(self.versions[k])
                         for k in sorted(self.versions)},
        }


class DigestSource:
    """Leaf-locked telemetry collector with snapshot-and-reset publish.

    One per tier (replica engine, fleet, region front-end). Writers call
    ``observe``/``count``/``slo_verdict`` from their own threads; the
    rollup thread calls ``publish`` on its cadence and gets the delta
    since the previous publish. The lock is a private leaf — nothing
    blocking runs under it and no other lock is ever taken inside it.
    """

    def __init__(self, source: str, alpha: float = 0.01):
        self.source = source
        self.alpha = float(alpha)
        self._lock = threading.Lock()
        self._counters: Dict[str, float] = {}
        self._sketches: Dict[str, SketchHistogram] = {}
        self._tenants: Dict[str, List[int]] = {}
        self._versions: Dict[int, List[int]] = {}

    def count(self, metric: str, n: float = 1.0) -> None:
        with self._lock:
            self._counters[metric] = self._counters.get(metric, 0.0) + n

    def observe(self, metric: str, v: Optional[float]) -> None:
        if v is None:
            return
        with self._lock:
            s = self._sketches.get(metric)
            if s is None:
                s = SketchHistogram(metric, alpha=self.alpha)
                self._sketches[metric] = s
        s.observe(v)   # sketch carries its own lock

    def slo_verdict(self, tenant: Optional[str], version: Optional[int],
                    ok: bool) -> None:
        """Record one judged SLO verdict (``ok`` = request met its SLO)
        against the request's tenant and model version."""
        with self._lock:
            if tenant is not None:
                row = self._tenants.setdefault(tenant, [0, 0])
                row[0] += 1 if ok else 0
                row[1] += 1
            if version is not None:
                row = self._versions.setdefault(int(version), [0, 0])
                row[0] += 1 if ok else 0
                row[1] += 1

    def publish(self, t: float) -> TelemetryDigest:
        """Snapshot-and-reset: return the delta since the last publish."""
        d = TelemetryDigest(t, self.source, alpha=self.alpha)
        with self._lock:
            d.counters = self._counters
            d.sketches = self._sketches
            d.tenants = self._tenants
            d.versions = self._versions
            self._counters = {}
            self._sketches = {}
            self._tenants = {}
            self._versions = {}
        return d


class DigestAccumulator:
    """Running merge of published digests — the region's O(cells) view.

    ``absorb`` returns the digest's bounded row count so callers can
    meter rollup work (the replica-independence gate). Reads answer from
    the merged state: ``percentile`` walks one merged sketch, never a
    pooled sample window.
    """

    def __init__(self, alpha: float = 0.01):
        self.alpha = float(alpha)
        self._total = TelemetryDigest(0.0, "accumulator", alpha=alpha)
        self.absorbed = 0

    def absorb(self, digest: TelemetryDigest) -> int:
        rows = digest.rows
        self._total.merge(digest)
        self.absorbed += 1  # dslint: disable=races -- rollup-thread confined by contract (class docstring): absorb runs only on the region's single rollup thread
        return rows

    def counter(self, metric: str) -> float:
        return self._total.counters.get(metric, 0.0)

    def sketch(self, metric: str) -> Optional[SketchHistogram]:
        return self._total.sketches.get(metric)

    def percentile(self, metric: str, p: float) -> Optional[float]:
        s = self._total.sketches.get(metric)
        return s.percentile(p) if s is not None else None

    def tenant_totals(self) -> Dict[str, Tuple[int, int]]:
        return {k: (v[0], v[1]) for k, v in self._total.tenants.items()}

    def version_totals(self) -> Dict[int, Tuple[int, int]]:
        return {k: (v[0], v[1]) for k, v in self._total.versions.items()}

    def snapshot(self) -> Dict[str, Any]:
        """JSON-ready region view: counters as scalars, sketches as
        summary dicts (count/sum/min/max/mean/p50/p90/p99)."""
        out: Dict[str, Any] = {}
        for k in sorted(self._total.counters):
            out[k] = self._total.counters[k]
        for k in sorted(self._total.sketches):
            out[k] = self._total.sketches[k].summary()
        return out
