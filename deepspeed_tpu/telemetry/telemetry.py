"""The telemetry facade: one pipeline from metric sources to sinks.

``Telemetry`` owns the metrics registry, the configured sinks and the
stall detector, and is the single object the engines talk to. The train
engine calls :meth:`record_step` once per optimizer step; the inference
engines call :meth:`record_request`; everything else (comm facade,
resilience counters) feeds the shared registry directly.

A process-global instance (installed by the first engine whose config
enables telemetry, or explicitly via :func:`configure_telemetry`) lets
code without a config handle — the comm facade, the ragged engine's KV
allocator — reach the same registry. When nothing installed one,
:func:`get_telemetry` returns a disabled instance whose hooks are cheap
no-ops, so instrumented call sites need no conditional imports.
"""

from __future__ import annotations

import os
import threading
from typing import Any, Dict, List, Optional

from ..utils.logging import logger
from .heartbeat import Heartbeat, StallDetector
from .registry import MetricsRegistry, get_registry
from .sinks import JsonlSink, MonitorSink, PrometheusTextExporter
from .spans import RequestStats, StepStats


class Telemetry:
    """Fan-out pipeline: StepStats / request metrics -> registry + sinks."""

    def __init__(self, config: Any = None, registry: Optional[MetricsRegistry] = None,
                 monitor: Any = None):
        # config is a config.TelemetryConfig (duck-typed to avoid a hard
        # dependency direction between the config and telemetry layers)
        self.config = config
        self.registry = registry if registry is not None else get_registry()
        self.sinks: List[Any] = []
        self.stall_detector: Optional[StallDetector] = None
        self.heartbeat: Optional[Heartbeat] = None
        self._closed = False
        self._requests_path: Optional[str] = None
        self._requests_sink: Optional[JsonlSink] = None
        # spans arrive concurrently from the serving driver thread and
        # client threads (submit/cancel emit outside the serving lock):
        # serialize sink creation + writes or lines tear
        self._requests_lock = threading.Lock()

        enabled = bool(getattr(config, "enabled", False))
        if enabled:
            # file sinks are rank-0-only (same discipline as log_dist): on
            # a multi-process pod every host sees the same global metrics,
            # and N writers appending to one steps.jsonl on shared storage
            # would interleave duplicate records and race the atomic
            # renames. In-registry series still update on every process.
            from ..utils.logging import _process_index

            writer_rank = _process_index() == 0
            out_dir = getattr(config, "output_dir", "telemetry") or "telemetry"
            jsonl_path = getattr(config, "jsonl_path", None)
            if jsonl_path is None:
                jsonl_path = os.path.join(out_dir, "steps.jsonl")
            if jsonl_path and writer_rank:  # "" disables the sink explicitly
                self.sinks.append(JsonlSink(
                    jsonl_path,
                    flush_every=getattr(config, "flush_every", 1)))
            prom_path = getattr(config, "prometheus_path", None)
            if prom_path and writer_rank:
                self.sinks.append(PrometheusTextExporter(
                    self.registry, prom_path,
                    export_every=getattr(config, "export_every", 10)))
            if getattr(config, "stall_detection", True):
                self.stall_detector = StallDetector(
                    window=getattr(config, "stall_window", 20),
                    factor=getattr(config, "stall_factor", 3.0),
                    warmup_steps=getattr(config, "stall_warmup_steps", 2))
            hb_path = getattr(config, "heartbeat_path", None)
            if hb_path and writer_rank:
                self.heartbeat = Heartbeat(hb_path)
            # serving-request spans get their own JSONL stream (a step
            # sink must see only step records — one file, one schema);
            # created lazily on the first span so train-only runs never
            # touch a requests.jsonl
            req_path = getattr(config, "requests_jsonl_path", None)
            if req_path is None:
                req_path = os.path.join(out_dir, "requests.jsonl")
            self._requests_path = req_path if writer_rank else None
            # request-scoped tracing + flight recorder (tracing.py):
            # installing the pipeline installs (or, with tracing off,
            # CLEARS) its tracer — same process-global discipline as
            # set_telemetry/set_registry, and re-initializing with
            # tracing=false must actually turn a previous tracer off.
            # Disabled Telemetry stubs (enabled=false) never touch the
            # tracer: a directly-installed one must survive them.
            from .tracing import configure_tracing

            configure_tracing(config)
        if monitor is not None:
            self.sinks.append(MonitorSink(monitor))
        self.enabled = enabled

    # -- training -------------------------------------------------------
    @property
    def wants_step_records(self) -> bool:
        """True when the engine must assemble per-step StepStats (and
        therefore fetch scalars / sync per step): any sink configured, or
        stall detection / heartbeat active (they consume records too, even
        with every file sink disabled or on non-writer ranks). The
        telemetry-off, monitor-off path must see False so it keeps the
        seed's sync discipline."""
        return not self._closed and bool(
            self.sinks or self.stall_detector is not None
            or self.heartbeat is not None)

    def record_step(self, stats: StepStats) -> Dict[str, Any]:
        """Run stall detection, update the registry, fan out to sinks.
        Returns the emitted record dict."""
        if self.stall_detector is not None:
            # normalized per optimizer step: mixing per-step records with
            # train_steps(k) blocks must not read as a k x stall
            stats.stalled = self.stall_detector.observe(
                stats.step,
                stats.wall_time_s / max(1, int(getattr(stats, "n_steps", 1) or 1)))
        n = max(1, int(getattr(stats, "n_steps", 1) or 1))
        r = self.registry
        r.counter("train/steps").inc(n)
        r.histogram("train/step_time_s").observe(stats.wall_time_s / n)
        # host-overhead ledger, normalized per optimizer step so per-step
        # and train_steps(k) records land in comparable distributions
        if stats.host_ms is not None:
            r.histogram("train/host_ms").observe(stats.host_ms / n)
        if stats.data_wait_ms is not None:
            r.histogram("train/data_wait_ms").observe(stats.data_wait_ms / n)
        if stats.dispatch_gap_ms is not None:
            r.histogram("train/dispatch_gap_ms").observe(stats.dispatch_gap_ms)
        if stats.tokens_per_s:
            r.gauge("train/tokens_per_s").set(stats.tokens_per_s)
        if stats.mfu:
            r.gauge("train/mfu").set(stats.mfu)
        if stats.loss is not None:
            r.gauge("train/loss").set(stats.loss)
        if stats.skipped:
            r.counter("train/skipped_steps").inc()
        if stats.stalled:
            r.counter("train/stalled_steps").inc()
        if self.heartbeat is not None:
            self.heartbeat.beat(stats.step)
        record = stats.to_record()
        for sink in self.sinks:
            try:
                sink.write(record)
            except Exception as e:  # a broken sink must not kill training
                logger.warning(f"telemetry sink {type(sink).__name__} "
                               f"failed: {e}")
        return record

    # -- inference ------------------------------------------------------
    def record_request(self, latency_s: Optional[float] = None,
                       ttft_s: Optional[float] = None,
                       new_tokens: int = 0,
                       decode_tokens_per_s: Optional[float] = None) -> None:
        """Each argument is observed independently, so engines that learn
        TTFT and completion at different times (the ragged engine: first
        logits vs. flush) report in two calls. A request counts as one
        request when its end-to-end ``latency_s`` is reported."""
        if not self.enabled:  # the nothing-configured global stub
            return
        r = self.registry
        if latency_s is not None:
            r.counter("inference/requests").inc()
            r.histogram("inference/request_latency_s").observe(latency_s)
        if ttft_s is not None:
            r.histogram("inference/ttft_s").observe(ttft_s)
        if new_tokens:
            r.counter("inference/generated_tokens").inc(new_tokens)
        if decode_tokens_per_s is not None:
            r.histogram("inference/decode_tokens_per_s").observe(
                decode_tokens_per_s)

    # -- serving --------------------------------------------------------
    def record_request_span(self, stats: RequestStats) -> Dict[str, Any]:
        """One serving request reached a terminal state: update the
        ``serving/*`` registry series and append the span record to the
        requests JSONL stream (validated by REQUEST_RECORD_SCHEMA).
        Returns the emitted record dict."""
        record = stats.to_record()
        if not self.enabled:
            return record
        r = self.registry
        # hot-path serving metrics are SKETCHES (docs/observability.md
        # "Sketch vs exact-window"): O(1) observe, mergeable up the
        # replica→region rollup, bounded relative error on percentiles.
        # Low-rate training metrics keep the exact-window Histogram.
        if stats.queue_wait_s is not None:
            r.sketch("serving/queue_wait_s").observe(stats.queue_wait_s)
        if stats.ttft_s is not None:
            r.sketch("serving/ttft_s").observe(stats.ttft_s)
        if stats.latency_s is not None:
            r.sketch("serving/request_latency_s").observe(stats.latency_s)
        if stats.tokens_per_s is not None:
            r.sketch("serving/tokens_per_s").observe(stats.tokens_per_s)
        if stats.new_tokens:
            r.counter("serving/generated_tokens").inc(stats.new_tokens)
        if stats.in_slo is not None:
            r.counter("serving/slo_judged").inc()
            if stats.in_slo:
                r.counter("serving/slo_met").inc()
        if not self._closed and self._requests_path:
            with self._requests_lock:
                try:
                    if self._requests_sink is None:
                        self._requests_sink = JsonlSink(self._requests_path)  # dslint: disable=lock-discipline -- _requests_lock is the dedicated sink mutex: it exists to serialize exactly this I/O and is never held together with serving/fleet locks
                    self._requests_sink.write(record)  # dslint: disable=lock-discipline -- dedicated sink mutex (see line above); spans are already emitted outside the serving lock
                except Exception as e:   # a broken sink must not kill serving
                    logger.warning(f"telemetry requests sink failed: {e}")
                    if self._requests_sink is None:
                        # the sink could not even be constructed (unwritable
                        # path): disable it instead of re-raising every span
                        self._requests_path = None
        return record

    # -- lifecycle ------------------------------------------------------
    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        for sink in self.sinks:
            try:
                sink.close()
            except Exception as e:
                logger.warning(f"telemetry sink {type(sink).__name__} "
                               f"close failed: {e}")
        self.sinks = []
        with self._requests_lock:
            if self._requests_sink is not None:
                try:
                    self._requests_sink.close()
                except Exception as e:
                    logger.warning(
                        f"telemetry requests sink close failed: {e}")
                self._requests_sink = None


# ----------------------------------------------------------------------
_GLOBAL: Optional[Telemetry] = None
_DISABLED = None  # lazy singleton for the nothing-configured path


def get_telemetry() -> Telemetry:
    """The installed global Telemetry, or a disabled no-op instance."""
    global _DISABLED
    if _GLOBAL is not None:
        return _GLOBAL
    if _DISABLED is None:
        _DISABLED = Telemetry(config=None)
    return _DISABLED


def set_telemetry(t: Optional[Telemetry]) -> Optional[Telemetry]:
    """Install ``t`` as the process-global telemetry (None to clear).

    Installing a pipeline also makes its registry the process default, so
    call sites that only know the registry (the comm facade, resilience
    counters) feed the same store the pipeline's exporters render."""
    global _GLOBAL
    _GLOBAL = t
    if t is not None:
        from .registry import set_registry

        set_registry(t.registry)
    return t


def configure_telemetry(config: Any = None,
                        registry: Optional[MetricsRegistry] = None,
                        monitor: Any = None) -> Telemetry:
    """Create a Telemetry from a TelemetryConfig and install it globally."""
    return set_telemetry(Telemetry(config, registry=registry, monitor=monitor))
