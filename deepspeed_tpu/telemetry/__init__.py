"""Unified telemetry subsystem.

One pipeline replacing the reference's scattered observability
(utils/timer aggregates, monitor/ event tuples, comms_logging dicts,
flops_profiler printouts): a shared :class:`MetricsRegistry`, per-step
:class:`StepStats` span records with a validated JSONL schema, exporters
(JSONL, Prometheus text, the legacy MonitorMaster as an adapter sink),
and heartbeat/stall detection. See docs/observability.md.
"""

from .registry import (  # noqa: F401
    Counter,
    Gauge,
    Histogram,
    MetricsRegistry,
    SketchHistogram,
    get_registry,
    set_registry,
)
from .digest import (  # noqa: F401
    DigestAccumulator,
    DigestSource,
    TelemetryDigest,
)
from .slo import (  # noqa: F401
    SLOObjective,
    TenantSLOTracker,
)
from .spans import (  # noqa: F401
    REQUEST_RECORD_SCHEMA,
    SCHEMA_VERSION,
    STEP_RECORD_SCHEMA,
    RequestStats,
    StepStats,
    validate_request_record,
    validate_step_record,
)
from .sinks import (  # noqa: F401
    JsonlSink,
    MonitorSink,
    PrometheusTextExporter,
    render_prometheus,
)
from .heartbeat import Heartbeat, StallDetector  # noqa: F401
from .telemetry import (  # noqa: F401
    Telemetry,
    configure_telemetry,
    get_telemetry,
    set_telemetry,
)
from .tracing import (  # noqa: F401
    FlightRecorder,
    Span,
    Tracer,
    configure_tracing,
    get_tracer,
    set_tracer,
    trace_tree_problems,
    use_tracer,
    validate_chrome_trace,
)
