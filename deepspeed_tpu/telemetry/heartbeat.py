"""Heartbeat + stall detection.

A hung collective or a wedged host thread shows up as a step that takes a
large multiple of the typical step time — or as no step at all. Two
complementary mechanisms:

* :class:`StallDetector` — flags any step exceeding ``factor`` x the
  rolling median of recent step wall times. Median (not mean) so one slow
  step doesn't poison the baseline it is judged against; compile steps at
  the front are absorbed by ``warmup_steps``.
* :class:`Heartbeat` — writes a tiny ``{step, time}`` JSON file (atomic
  rename) each step, so an external watchdog can detect "no heartbeat for
  N seconds" even when the process is too wedged to report a slow step.
"""

from __future__ import annotations

import os
import statistics
from collections import deque
from typing import Callable, Deque, Optional

from ..utils.logging import logger


class StallDetector:
    """Flag steps exceeding ``factor`` x the rolling median step time.

    ``observe(step, wall_time_s)`` returns True when the step is judged
    stalled. The stalled step's own time is still added to the window
    afterwards — a genuine regime change (e.g. sequence-length jump)
    flags once, then the median adapts instead of flagging forever.
    """

    def __init__(self, window: int = 20, factor: float = 3.0,
                 warmup_steps: int = 2,
                 on_stall: Optional[Callable[[int, float, float], None]] = None):
        if factor <= 1.0:
            raise ValueError(f"stall factor must exceed 1.0, got {factor}")
        self.window: Deque[float] = deque(maxlen=max(2, int(window)))
        self.factor = float(factor)
        self.warmup_steps = int(warmup_steps)
        self.on_stall = on_stall
        self.stall_count = 0
        self._seen = 0

    def rolling_median(self) -> Optional[float]:
        return statistics.median(self.window) if self.window else None

    def observe(self, step: int, wall_time_s: float) -> bool:
        self._seen += 1
        stalled = False
        median = self.rolling_median()
        # need a settled baseline: past warmup AND at least 2 samples
        if (self._seen > self.warmup_steps and median is not None
                and len(self.window) >= 2
                and wall_time_s > self.factor * median):
            stalled = True
            self.stall_count += 1
            logger.warning(
                f"stall detected: step {step} took {wall_time_s * 1e3:.1f} ms "
                f"(> {self.factor:g}x rolling median {median * 1e3:.1f} ms)")
            if self.on_stall is not None:
                self.on_stall(step, wall_time_s, median)
        if self._seen > self.warmup_steps:
            self.window.append(wall_time_s)
        return stalled


class Heartbeat:
    """Atomic per-step liveness file for external watchdogs.

    Carries a ``state`` field so watchers can distinguish a live trainer
    (``"running"``) from the supervising ElasticAgent's relaunch window
    (``"restarting"`` — launcher/agent.py overwrites the same file with
    restart count + reason while the worker is down) instead of treating
    every restart gap as a hang.
    """

    def __init__(self, path: str):
        self.path = path
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)

    def beat(self, step: int) -> None:
        from ..resilience.clock import get_clock  # lazy: import-order cycle
        from ..utils.fileio import write_json_atomic
        from .tracing import get_tracer

        # flight-recorder health rides the heartbeat so an external
        # watcher sees recorder depth / drops / the last auto-dump path
        # without attaching to the process (docs/observability.md).
        # With tracing off these are static zeros — same file shape.
        flight = get_tracer().flight
        write_json_atomic(self.path, {"step": int(step),
                                      "time": get_clock().time(),
                                      "state": "running",
                                      "flight_depth": flight.depth,
                                      "flight_dropped": flight.dropped,
                                      "flight_dumps": flight.dumps,
                                      "flight_last_dump":
                                          flight.last_dump_path})
