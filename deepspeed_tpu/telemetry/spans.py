"""Per-step span records and their machine-readable schema.

``StepStats`` is the one record answering "where did the step time go":
wall time, phase breakdown (where the execution model can attribute it),
throughput, MFU, comm-time breakdown, device-memory watermarks and the
training scalars. The JSONL sink writes one of these per step; the smoke
test and golden-file test validate every emitted line against
:data:`STEP_RECORD_SCHEMA`.

Phase attribution caveat (TPU-first honesty): the fused ``train_batch``
path compiles forward+backward+optimizer into ONE XLA program, so
``forward_s``/``backward_s``/``optimizer_s`` are ``null`` there — only the
compat ``forward()``/``backward()``/``step()`` path can time the phases
separately from the host. ``comm`` carries the CommsLogger's per-op
breakdown (bytes always; latencies once
:func:`deepspeed_tpu.comm.measure_comm_latencies` has backfilled them).

Host-overhead ledger (docs/performance.md): ``host_ms`` is the host time
from step entry to dispatch-complete (hooks, collate-side work, transfer +
execute dispatch — everything that serializes the Python loop but not the
device), ``data_wait_ms`` the host time spent waiting for / producing
input batches since the previous record, and ``dispatch_gap_ms`` the gap
between the previous step call returning and this one entering. A record
may cover ``n_steps`` optimizer steps when the engine ran a compiled
multi-step block (``train_steps(k)``); throughput fields are already
scaled, per-step host overhead is ``(host_ms + data_wait_ms) / n_steps``.
"""

from __future__ import annotations

import dataclasses
from dataclasses import dataclass, field
from typing import Any, Dict, List, Optional

SCHEMA_VERSION = 1


def _clock_timestamp() -> float:
    """Span timestamps come from the injectable clock seam — epoch
    seconds under the WallClock (so archived v1 JSONL streams keep
    validating unchanged), virtual-epoch seconds under a SimClock (so
    simulated runs are bit-reproducible). Imported lazily: telemetry
    loads before the resilience package in some import orders."""
    from ..resilience.clock import get_clock

    return get_clock().time()

# field -> (types, required). Required fields must be present and non-None
# in every emitted record; optional fields must type-check when present.
STEP_RECORD_SCHEMA: Dict[str, tuple] = {
    "schema_version": ((int,), True),
    "step": ((int,), True),
    "timestamp": ((float, int), True),
    "wall_time_s": ((float, int), True),
    "tokens_per_s": ((float, int), True),
    "samples_per_s": ((float, int), True),
    "mfu": ((float, int), True),
    "loss": ((float, int), False),
    "grad_norm": ((float, int), False),
    "loss_scale": ((float, int), False),
    "lr": ((float, int), False),
    "skipped": ((bool,), False),
    "forward_s": ((float, int), False),
    "backward_s": ((float, int), False),
    "optimizer_s": ((float, int), False),
    "comm_s": ((float, int), False),
    "comm": ((dict,), True),
    # max local quantization round-trip rel error across the step's
    # compressed collectives (comm_compression.error_stats)
    "quant_rel_err": ((float, int), False),
    "memory": ((dict,), True),
    "stalled": ((bool,), True),
    "n_steps": ((int,), False),
    "host_ms": ((float, int), False),
    "data_wait_ms": ((float, int), False),
    "dispatch_gap_ms": ((float, int), False),
    # distributed-tracing join keys (telemetry/tracing.py): present only
    # when a tracer is installed. Optional — NOT a schema-version bump —
    # with the same discipline as client_request_id/wire_bytes: archived
    # v1/v2 JSONL streams predate them and must keep validating.
    "trace_id": ((str,), False),
    "span_id": ((str,), False),
    # model version the step trained/served (rollout-aware runtimes
    # stamp it; optional — archived streams predate versioned serving)
    "model_version": ((int,), False),
}


@dataclass
class StepStats:
    """One training step's span record (see module docstring)."""

    step: int
    wall_time_s: float
    tokens_per_s: float = 0.0
    samples_per_s: float = 0.0
    mfu: float = 0.0
    loss: Optional[float] = None
    grad_norm: Optional[float] = None
    loss_scale: Optional[float] = None
    lr: Optional[float] = None
    skipped: Optional[bool] = None
    forward_s: Optional[float] = None
    backward_s: Optional[float] = None
    optimizer_s: Optional[float] = None
    comm_s: Optional[float] = None
    quant_rel_err: Optional[float] = None
    # optimizer steps covered by this record (>1 for train_steps(k) blocks)
    n_steps: int = 1
    # host-overhead ledger (see module docstring)
    host_ms: Optional[float] = None
    data_wait_ms: Optional[float] = None
    dispatch_gap_ms: Optional[float] = None
    # tracing join keys: the tracer's "train/step" span for this record
    trace_id: Optional[str] = None
    span_id: Optional[str] = None
    # model version in service when the step ran (None = unversioned)
    model_version: Optional[int] = None
    # per-op comm breakdown: {op: {"count": int, "bytes": int, "time_s": float}}
    comm: Dict[str, Dict[str, float]] = field(default_factory=dict)
    # device-memory watermarks from utils/memory.py (hbm_peak_gb, ...)
    memory: Dict[str, float] = field(default_factory=dict)
    stalled: bool = False
    timestamp: float = field(default_factory=_clock_timestamp)

    def to_record(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["schema_version"] = SCHEMA_VERSION
        return d


# ----------------------------------------------------------------------
# serving-request spans (docs/serving.md): one record per request reaching
# a terminal state (FINISHED / CANCELLED / REJECTED). Written by the
# ServingEngine through Telemetry.record_request_span into
# <output_dir>/requests.jsonl — a separate stream from steps.jsonl so each
# file validates against exactly one schema.
REQUEST_RECORD_SCHEMA: Dict[str, tuple] = {
    "schema_version": ((int,), True),
    "uid": ((int,), True),
    # the LOGICAL request id: stable across re-routing / fail-over /
    # prefill→decode hand-off between replicas, so one request stays one
    # id in requests.jsonl however many engines served it. Optional in
    # the schema (not a version bump): every record emitted since the
    # field landed carries it, but archived version-1 streams predate it
    # and must keep validating.
    "client_request_id": ((str,), False),
    "state": ((str,), True),
    "priority": ((int,), True),
    "prompt_tokens": ((int,), True),
    "new_tokens": ((int,), True),
    "timestamp": ((float, int), True),
    "queue_wait_s": ((float, int), False),
    "ttft_s": ((float, int), False),
    "latency_s": ((float, int), False),
    "tokens_per_s": ((float, int), False),
    "preemptions": ((int,), True),
    "retries": ((int,), True),
    # speculative-decoding ledger (serving tick): draft tokens proposed /
    # accepted over the request's life. Optional — NOT a schema-version
    # bump — same discipline as client_request_id: archived v1/v2
    # streams predate speculative serving and must keep validating.
    "spec_proposed": ((int,), False),
    "spec_accepted": ((int,), False),
    # model version that served the request (serving/rollout.py) —
    # Optional, NOT a schema-version bump, same discipline as
    # client_request_id: archived streams predate versioned serving.
    "model_version": ((int,), False),
    # tenant id the request was submitted under (per-tenant SLO
    # accounting, telemetry/slo.py). Optional — NOT a schema-version
    # bump — archived streams predate multi-tenant serving.
    "tenant": ((str,), False),
    "in_slo": ((bool,), False),
    "error": ((str,), False),
    # distributed-tracing join keys (telemetry/tracing.py): the request's
    # trace and its root span. Optional — archived v1/v2 streams predate
    # tracing and keep validating (same discipline as client_request_id).
    "trace_id": ((str,), False),
    "span_id": ((str,), False),
}

_REQUEST_STATES = ("finished", "cancelled", "rejected",
                   "queued", "prefill", "decode")


@dataclass
class RequestStats:
    """One serving request's span record: where its latency went
    (queue wait vs TTFT vs decode) and how it ended."""

    uid: int
    state: str
    client_request_id: str = ""
    priority: int = 0
    prompt_tokens: int = 0
    new_tokens: int = 0
    queue_wait_s: Optional[float] = None
    ttft_s: Optional[float] = None
    latency_s: Optional[float] = None
    tokens_per_s: Optional[float] = None
    preemptions: int = 0
    retries: int = 0
    # speculative drafting ledger: None when the request never drafted
    spec_proposed: Optional[int] = None
    spec_accepted: Optional[int] = None
    # serving model version (None predates versioned serving)
    model_version: Optional[int] = None
    # tenant id (None = untenanted; feeds per-tenant SLO attainment)
    tenant: Optional[str] = None
    in_slo: Optional[bool] = None      # None = request carried no SLO
    error: Optional[str] = None
    # tracing join keys: the request's trace and root span (tracer on)
    trace_id: Optional[str] = None
    span_id: Optional[str] = None
    timestamp: float = field(default_factory=_clock_timestamp)

    def to_record(self) -> Dict[str, Any]:
        d = dataclasses.asdict(self)
        d["schema_version"] = SCHEMA_VERSION
        return d


def validate_request_record(record: Dict[str, Any]) -> List[str]:
    """Validate one requests.jsonl record against
    :data:`REQUEST_RECORD_SCHEMA`. Returns violation strings; empty means
    valid."""
    errors = _validate_against(record, REQUEST_RECORD_SCHEMA)
    state = record.get("state") if isinstance(record, dict) else None
    if isinstance(state, str) and state not in _REQUEST_STATES:
        errors.append(f"unknown request state '{state}'")
    return errors


def _validate_against(record: Dict[str, Any],
                      schema: Dict[str, tuple]) -> List[str]:
    errors: List[str] = []
    if not isinstance(record, dict):
        return [f"record is {type(record).__name__}, expected dict"]
    for name, (types, required) in schema.items():
        if name not in record or record[name] is None:
            if required:
                errors.append(f"missing required field '{name}'")
            continue
        v = record[name]
        # bool is an int subclass; reject it where int means "number"
        if isinstance(v, bool) and bool not in types:
            errors.append(f"field '{name}' is bool, expected {types}")
        elif not isinstance(v, types):
            errors.append(
                f"field '{name}' is {type(v).__name__}, expected {types}")
    if record.get("schema_version") not in (None, SCHEMA_VERSION):
        errors.append(
            f"schema_version {record.get('schema_version')} != {SCHEMA_VERSION}")
    return errors


def validate_step_record(record: Dict[str, Any]) -> List[str]:
    """Validate one JSONL step record against :data:`STEP_RECORD_SCHEMA`.
    Returns a list of violation strings; empty means valid."""
    errors = _validate_against(record, STEP_RECORD_SCHEMA)
    if errors and not isinstance(record, dict):
        return errors
    if isinstance(record.get("comm"), dict):
        for op, entry in record["comm"].items():
            if not isinstance(entry, dict):
                errors.append(f"comm['{op}'] is not a dict")
                continue
            for k in ("count", "bytes", "time_s"):
                if not isinstance(entry.get(k), (int, float)) or \
                        isinstance(entry.get(k), bool):
                    errors.append(f"comm['{op}']['{k}'] missing or non-numeric")
            # v2 bytes-on-wire ledger field: optional so archived v1
            # snapshots keep validating, but must be numeric when present
            if "wire_bytes" in entry and (
                    not isinstance(entry["wire_bytes"], (int, float))
                    or isinstance(entry["wire_bytes"], bool)):
                errors.append(f"comm['{op}']['wire_bytes'] non-numeric")
    if isinstance(record.get("memory"), dict):
        for k, v in record["memory"].items():
            if not isinstance(v, (int, float)) or isinstance(v, bool):
                errors.append(f"memory['{k}'] non-numeric")
    return errors
