#!/usr/bin/env python
"""Region telemetry-plane DST lane: sketch accuracy, digest-stream
determinism, rollup cost, and per-tenant SLO burn-rate alerting
(docs/observability.md "Region rollups & SLO alerting").

CI evidence lane for the hierarchical telemetry plane (run by
run_tests.sh):

* runs >= 200 seeded REGION chaos schedules with every
  :class:`DigestSource` observation ALSO recorded into a pooled
  ground-truth stream, then gates, per seed:
  - conservation — every merged region sketch holds exactly as many
    samples as the pooled stream (cell outages, partitions, salvaged
    death-deltas and close-time tails included: nothing lost, nothing
    double-counted);
  - accuracy — region p50/p99 answered from merged digests land within
    the sketch's documented relative-error bound (alpha) of the exact
    pooled percentile at the same rank convention;
* gate: deterministic digest stream — a sample of seeds is replayed and
  the region's running rollup hash (canonical digest wire form), the
  SLO alert log, and the usual (trace, span) hashes must be
  bit-identical;
* gate: rollup cost — a scripted drive at 1 vs 4 replicas per cell
  shows per-poll rollup work (absorbed digest rows) bounded by the
  metric/tenant key count, independent of replica count;
* gate: burn-rate alerting — a scripted two-tenant burst trace (one
  tenant missing every deadline, one healthy) fires fast+slow alerts
  for exactly the burning tenant, auto-clears when it goes quiet, and
  replays bit-identically, clock ticks and all.

Pure host-side python on virtual time. Writes SLO_<round>.json (round
via DST_ROUND, default r01).

    python scripts/slo_lane.py [--schedules N] [--seed-base B]
"""

from __future__ import annotations

import argparse
import hashlib
import json
import logging
import os
import sys
import time

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)
sys.path.insert(0, os.path.join(HERE, "scripts"))

os.environ.setdefault("DST_ROUND", "r01")

#: every N-th seed is replayed for the determinism gate
REPLAY_STRIDE = 20

#: percentiles gated against pooled truth
GATED_PERCENTILES = (50.0, 99.0)

#: slack on top of alpha for float edge effects at bucket boundaries
ALPHA_EPS = 1e-9


def _exact_percentile(sorted_vals, p):
    """Same non-interpolated rank convention as SketchHistogram."""
    rank = int((p / 100.0) * (len(sorted_vals) - 1) + 1e-9)
    return sorted_vals[rank]


def _alert_log_blob(region) -> str:
    return json.dumps(list(region.slo_alert_log), sort_keys=True)


def _run_seed(seed, observed):
    """Run one region schedule, capturing the Region and the pooled
    observation stream (via the instrumented DigestSource)."""
    from deepspeed_tpu.resilience.dst import (generate_region_schedule,
                                              run_region_schedule)
    from deepspeed_tpu.serving.region import Region

    observed.clear()
    captured = {}

    def builder(*a, **kw):
        region = Region(*a, **kw)
        captured["region"] = region
        return region

    report = run_region_schedule(generate_region_schedule(seed),
                                 region_factory=builder)
    return report, captured["region"]


def _check_sketches(seed, region, observed, problems):
    """Conservation + accuracy gates for one finished run."""
    acc = region._tel_rollup
    for metric in sorted(observed):
        vals = observed[metric]
        sk = acc.sketch(metric)
        if sk is None:
            problems.append(f"seed {seed}: metric {metric}: "
                            f"{len(vals)} observed, no region sketch")
            continue
        if sk.count != len(vals):
            problems.append(
                f"seed {seed}: metric {metric}: sketch count "
                f"{sk.count} != pooled count {len(vals)}")
            continue
        svals = sorted(vals)
        for p in GATED_PERCENTILES:
            est = sk.percentile(p)
            true = _exact_percentile(svals, p)
            tol = abs(true) * (sk.alpha + ALPHA_EPS) + 1e-12
            if abs(est - true) > tol:
                problems.append(
                    f"seed {seed}: metric {metric} p{p:g}: sketch "
                    f"{est} vs exact {true} (tol {tol})")


def _rollup_cost_probe():
    """Scripted drive at 1 vs 4 replicas/cell: per-poll rollup work
    must stay inside the same fixed row budget (metric + tenant +
    version keys), with replica count nowhere in the equation."""
    from deepspeed_tpu.resilience.clock import SimClock, use_clock
    from deepspeed_tpu.resilience.dst import SimConfig, SimEngine
    from deepspeed_tpu.serving import Region

    cells = 2
    bound = (cells + 1) * 15
    out = {}
    for replicas in (1, 4):
        clock = SimClock()
        with use_clock(clock):
            region = Region(
                lambda: SimEngine(SimConfig()),
                {"cells": cells, "cell_ring_vnodes": 16},
                {"replicas": replicas, "router": "prefix_affinity",
                 "respawn": False},
                {"policy": "slo", "stuck_tick_timeout_s": 0.0,
                 "drain_timeout_s": 600.0, "poll_interval_s": 0.25},
                start=False, clock=clock)
            reqs = [region.submit([i, i + 1, 5], max_new_tokens=2,
                                  deadline_s=300.0,
                                  tenant=f"tenant-{i % 3}")
                    for i in range(1, 13)]
            work = []
            for _ in range(400):
                region.step()
                work.append(region.rollup_work_last)
                clock.advance(1.0)
                if all(r.is_terminal for r in reqs):
                    break
            done = all(r.is_terminal for r in reqs)
            clock.pump = region.step
            region.close(timeout=30.0)
            clock.pump = None
        out[replicas] = {"max_work": max(work), "done": done}
    return {
        "bound": bound,
        "replicas_1": out[1], "replicas_4": out[4],
        "ok": (out[1]["done"] and out[4]["done"]
               and 0 < out[1]["max_work"] <= bound
               and 0 < out[4]["max_work"] <= bound),
    }


def _burst_trace_once():
    """Deterministic two-tenant burst: tenant 'burny' misses every
    deadline during the burst, tenant 'calm' stays healthy; then burny
    goes quiet and its alerts must auto-clear. Returns the full alert
    log blob (fire/clear rows with virtual timestamps)."""
    from deepspeed_tpu.resilience.clock import SimClock, use_clock
    from deepspeed_tpu.resilience.dst import SimConfig, SimEngine
    from deepspeed_tpu.serving import Region

    clock = SimClock()
    with use_clock(clock):
        region = Region(
            lambda: SimEngine(SimConfig()),
            {"cells": 2, "cell_ring_vnodes": 16,
             # tight objective so a 6-request burst trips the page, and
             # a non-unit cadence so the rollup_every path is exercised
             "telemetry_rollup_every": 2,
             "slo_target": 0.5, "slo_window_s": 40.0,
             "slo_fast_window_s": 40.0, "slo_slow_window_s": 80.0,
             "slo_fast_burn": 1.5, "slo_slow_burn": 1.2,
             "slo_min_samples": 2},
            {"replicas": 1, "router": "prefix_affinity",
             "respawn": False},
            {"policy": "slo", "stuck_tick_timeout_s": 0.0,
             "drain_timeout_s": 600.0, "poll_interval_s": 0.25},
            start=False, clock=clock)
        reqs = []
        for i in range(1, 7):
            reqs.append(region.submit([i, 2, 9], max_new_tokens=2,
                                      deadline_s=0.001, tenant="burny"))
            reqs.append(region.submit([i, 3, 9], max_new_tokens=2,
                                      deadline_s=500.0, tenant="calm"))
        for _ in range(400):
            region.step()
            clock.advance(1.0)
            if all(r.is_terminal for r in reqs):
                break
        # burst over: advance past the slow window so burny's rows age
        # out and the active alerts auto-clear
        for _ in range(100):
            region.step()
            clock.advance(1.0)
        log = list(region.slo_alert_log)
        active = region.slo.active_alerts()
        fast_burn = region.slo.has_fast_burn()
        clock.pump = region.step
        region.close(timeout=30.0)
        clock.pump = None
    fired = [(r["tenant"], r["window"]) for r in log
             if r["state"] == "firing"]
    cleared = [(r["tenant"], r["window"]) for r in log
               if r["state"] == "clear"]
    return {
        "blob": json.dumps(log, sort_keys=True),
        "transitions": len(log),
        "fired": fired,
        "cleared": cleared,
        "only_burny_fired": bool(fired) and all(
            t == "burny" for t, _ in fired),
        "both_windows_fired": {w for _, w in fired} == {"fast", "slow"},
        "auto_cleared": {w for _, w in cleared} == {"fast", "slow"},
        "nothing_left_active": not active and not fast_burn,
    }


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--schedules", type=int, default=200,
                    help="number of seeded schedules (gate: >= 200)")
    ap.add_argument("--seed-base", type=int, default=0)
    ap.add_argument("--verbose", action="store_true")
    args = ap.parse_args()
    if not args.verbose:
        logging.disable(logging.WARNING)   # the faults ARE the workload

    from deepspeed_tpu.telemetry import digest as digest_mod

    # instrument the plane's single write entry point: every sketch
    # observation also lands in a pooled ground-truth stream keyed by
    # metric — the conservation/accuracy oracle
    observed = {}
    orig_observe = digest_mod.DigestSource.observe

    def recording_observe(self, metric, v):
        if v is not None:
            observed.setdefault(metric, []).append(float(v))
        orig_observe(self, metric, v)

    digest_mod.DigestSource.observe = recording_observe

    t0 = time.monotonic()
    seeds = range(args.seed_base, args.seed_base + args.schedules)
    problems = []          # conservation/accuracy findings
    run_failures = []      # (seed, violations) from the DST auditor
    witness = {}           # seed -> (trace, span, rollup, alert) hashes
    totals = {"observations": 0, "rollups": 0, "alert_transitions": 0,
              "alert_seeds": 0, "slo_judged": 0.0}
    try:
        for seed in seeds:
            report, region = _run_seed(seed, observed)
            if not report.ok:
                run_failures.append((seed, report.violations))
            _check_sketches(seed, region, observed, problems)
            witness[seed] = (
                report.trace_hash, report.span_hash, region.rollup_hash,
                hashlib.sha256(
                    _alert_log_blob(region).encode()).hexdigest())
            totals["observations"] += sum(
                len(v) for v in observed.values())
            totals["rollups"] += region.rollup_count
            n_alerts = len(region.slo_alert_log)
            totals["alert_transitions"] += n_alerts
            totals["alert_seeds"] += 1 if n_alerts else 0
            totals["slo_judged"] += region._tel_rollup.counter(
                "slo_judged")

        replayed = 0
        mismatches = []
        for seed in range(args.seed_base,
                          args.seed_base + args.schedules, REPLAY_STRIDE):
            replayed += 1
            report, region = _run_seed(seed, observed)
            again = (report.trace_hash, report.span_hash,
                     region.rollup_hash,
                     hashlib.sha256(
                         _alert_log_blob(region).encode()).hexdigest())
            if again != witness[seed]:
                mismatches.append(seed)

        burst_a = _burst_trace_once()
        burst_b = _burst_trace_once()
    finally:
        digest_mod.DigestSource.observe = orig_observe
    cost = _rollup_cost_probe()
    wall = time.monotonic() - t0

    gates = {
        "enough_schedules": args.schedules >= 200,
        "zero_invariant_violations": not run_failures,
        "sketch_conservation_and_accuracy": not problems,
        "digest_stream_deterministic": not mismatches,
        "rollup_cost_replica_independent": cost["ok"],
        "burn_alerts_fire_for_burning_tenant_only":
            burst_a["only_burny_fired"] and burst_a["both_windows_fired"],
        "burn_alerts_auto_clear": (burst_a["auto_cleared"]
                                   and burst_a["nothing_left_active"]),
        "burst_trace_bit_identical": burst_a["blob"] == burst_b["blob"],
        # tripwire: the schedules must actually exercise the plane
        "plane_exercised": (totals["observations"] > 0
                            and totals["rollups"] > 0
                            and totals["slo_judged"] > 0),
    }
    report = {
        "metric": "region_telemetry_plane_gate_failures_over_seeds",
        "schedules": args.schedules,
        "seed_base": args.seed_base,
        "replayed_for_determinism": replayed,
        "replay_mismatch_seeds": mismatches,
        "gated_percentiles": list(GATED_PERCENTILES),
        "problems": problems[:20],
        "totals": totals,
        "rollup_cost": cost,
        "burst_trace": {k: v for k, v in burst_a.items() if k != "blob"},
        "failing_seeds": [s for s, _ in run_failures],
        "wall_s": round(wall, 2),
        "gates": gates,
        "value": len(problems) + len(mismatches) + len(run_failures),
    }
    from _artifact import write_artifact

    path = write_artifact("SLO", report, device="host-sim")
    print(f"[slo-lane] {args.schedules} schedules, "
          f"{totals['observations']} pooled observations, "
          f"{totals['rollups']} digest rollups, "
          f"{int(totals['slo_judged'])} SLO verdicts, "
          f"{totals['alert_transitions']} alert transitions over "
          f"{totals['alert_seeds']} seeds in {wall:.1f}s")
    print(f"[slo-lane] burst trace: fired={burst_a['fired']} "
          f"cleared={burst_a['cleared']}")
    print(f"[slo-lane] artifact: {path}")
    failed = [g for g, ok in gates.items() if not ok]
    if failed:
        for pr in problems[:10]:
            print(f"[slo-lane] problem: {pr}")
        print(f"slo lane: FAILED gates {failed}")
        return 1
    print(f"slo lane: OK — region sketch percentiles within the "
          f"documented error bound of pooled truth on every seed, "
          f"digest + alert streams bit-identical on replay, rollup "
          f"cost replica-independent, per-tenant burn alerts fire and "
          f"clear deterministically")
    return 0


if __name__ == "__main__":
    sys.exit(main())
