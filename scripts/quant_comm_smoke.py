"""Quant-comm gate (CPU evidence lane, docs/communication.md).

Gates the compressed-collectives facade + T3 staged schedule on a
virtual 8-device mesh:

1. **Bit-exact overlap** — the staged schedule with compression OFF must
   produce bit-identical losses and parameters in serial vs overlapped
   issue order (same dataflow, different issue position).
2. **Wire-byte ratios** — per the bytes-on-wire ledger, the int8 weight
   all-gather must cut wire volume >= 2x and the int4 inter-slice
   gradient hop >= 4x vs the uncompressed payload.
3. **Error bound** — the traced quantization round-trip error must stay
   within the documented QuantSpec bound (0.5/qmax of the block absmax).
4. **Zero recompiles** — the staged compressed path inside the fused
   train_steps(k) scan traces each program exactly once across repeated
   calls (train/recompiles stays 0).
5. **NORTHSTAR projection** — the committed NORTHSTAR artifact's
   overlapped zero3 comm exposure must be cut >= 50% vs the serial
   booking (the ROADMAP item-1 claim, modeled with the same
   comm.compressed.modeled_exposure the projection uses).

Exits nonzero on any violation. Wired into run_tests.sh.
Usage: python scripts/quant_comm_smoke.py
"""

from __future__ import annotations

import glob
import json
import os
import subprocess
import sys

HERE = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, HERE)

_CHILD = "_DST_QUANT_COMM_CHILD"


def _fail(msg: str) -> None:
    print(f"[quant-comm] GATE FAIL: {msg}", flush=True)
    sys.exit(1)


def _check_northstar() -> dict:
    """Newest committed NORTHSTAR artifact carrying the overlapped comm
    projection; its exposure reduction is the gated claim."""
    cands = sorted(glob.glob(os.path.join(HERE, "NORTHSTAR_r*.json")))
    for path in reversed(cands):
        with open(path) as fh:
            report = json.load(fh)
        rows = [c for c in report.get("configs", [])
                if isinstance(c.get("comm_compression"), dict)]
        if not rows:
            continue
        worst = min(r["comm_compression"]["exposure_reduction_vs_serial"]
                    for r in rows)
        if worst < 0.5:
            _fail(f"{os.path.basename(path)}: overlapped zero3 comm "
                  f"exposure reduced only {worst:.0%} (< 50%) vs the "
                  f"serial booking")
        # r07+: the fused kernel-backend projection must sit STRICTLY
        # below the per-layer block-schedule number per config, and the
        # committed decode MLP A/B must show fused < unfused
        fused_rows = [r for r in rows
                      if isinstance(r.get("comm_compression_fused"), dict)]
        for r in fused_rows:
            per_layer = r["comm_compression"]["overlapped_compressed_s"]
            per_tile = r["comm_compression_fused"]["overlapped_compressed_s"]
            if not per_tile < per_layer:
                _fail(f"{os.path.basename(path)} [{r['name']}]: fused "
                      f"per-tile exposure {per_tile} not strictly below "
                      f"the per-layer number {per_layer}")
        ab = report.get("decode_mlp_ab")
        if fused_rows and not ab:
            _fail(f"{os.path.basename(path)}: fused projection present "
                  f"but no decode_mlp_ab committed")
        if ab:
            for leg in ("dense", "int8"):
                row = ab.get(leg, {})
                if not (row.get("decode_mlp_fused_s", 1e9)
                        < row.get("decode_mlp_unfused_s", 0.0)):
                    _fail(f"{os.path.basename(path)}: decode MLP A/B "
                          f"({leg}) shows no fused win: {row}")
        print(f"[quant-comm] {os.path.basename(path)}: exposure reduction "
              f">= {worst:.0%} across {len(rows)} configs"
              + (f"; fused per-tile < per-layer on {len(fused_rows)} "
                 f"configs + decode A/B" if fused_rows else ""),
              flush=True)
        return {"artifact": os.path.basename(path),
                "min_exposure_reduction": worst}
    _fail("no NORTHSTAR_r*.json with a comm_compression projection found")


def _run_child() -> None:
    import jax

    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    from deepspeed_tpu.comm import compressed as cc
    from deepspeed_tpu.telemetry import MetricsRegistry, set_registry

    sys.path.insert(0, os.path.join(HERE, "scripts"))
    from _comm_lane import build_comm_engine, run_comm_ab

    assert len(jax.devices()) >= 8, len(jax.devices())
    reg = set_registry(MetricsRegistry())

    # -- legs 1+2: the shared A/B (scripts/_comm_lane.py — same lane the
    # MULTICHIP dryrun drives): serial-vs-overlapped bit-exactness with
    # compression off, then the compressed engine + ledger ratios
    try:
        ab = run_comm_ab(batch_size=32, steps_bitexact=4,
                         steps_compressed=4, seed=0)
    except AssertionError as e:
        _fail(str(e))
    print(f"[quant-comm] overlap bit-exact over 4 steps: "
          f"{ab['overlap_bitexact_losses']}", flush=True)
    w_ratio = ab["ratios"]["weight_allgather"]
    g_ratio = ab["ratios"]["grad_inter_slice"]
    if w_ratio < 2.0:
        _fail(f"weight all-gather wire reduction {w_ratio:.2f}x < 2x")
    if g_ratio < 4.0:
        _fail(f"inter-slice gradient hop wire reduction {g_ratio:.2f}x < 4x")

    # -- leg 3: error bound (fresh engine with stats on)
    batch = ab["batch"]
    e_c = build_comm_engine({"enabled": True, "weight_bits": 8,
                             "grad_bits": 4, "error_stats": True,
                             "overlap": "staged"}, batch_size=32, seed=0)
    m = e_c.train_batch(batch)
    err = float(m["quant_rel_err"])
    bound = cc.QuantSpec(4, 256).rel_error_bound
    if not 0.0 <= err <= bound + 1e-6:
        _fail(f"quant rel error {err:.4f} outside documented bound {bound:.4f}")

    # -- leg 4: one-trace fused scan + recompile guard
    e_c.train_steps([batch, batch])
    e_c.train_steps([batch, batch])
    if e_c.trace_count("train_steps_2") != 1:
        _fail(f"staged fused scan retraced: "
              f"{e_c.trace_count('train_steps_2')} traces")
    if reg.counter("train/recompiles").value != 0:
        _fail("recompile guard tripped in the staged scan")
    print(json.dumps({
        "weight_allgather_wire_reduction": round(w_ratio, 2),
        "grad_interhost_wire_reduction": round(g_ratio, 2),
        "quant_rel_err": round(err, 5),
        "quant_rel_err_bound": round(bound, 5),
        "losses_compressed": [round(l, 5)
                              for l in ab["compressed_losses"]],
        "fused_scan_traces": e_c.trace_count("train_steps_2"),
    }), flush=True)


def main() -> int:
    if os.environ.get(_CHILD) == "1":
        _run_child()
        return 0
    # the NORTHSTAR check needs no devices — do it in the parent
    _check_northstar()
    from __graft_entry__ import cpu_child_env

    env = cpu_child_env(8)
    env[_CHILD] = "1"
    proc = subprocess.run([sys.executable, os.path.abspath(__file__)],
                          env=env, cwd=HERE, timeout=900)
    if proc.returncode == 0:
        print("[quant-comm] gate PASS", flush=True)
    return proc.returncode


if __name__ == "__main__":
    sys.exit(main())
